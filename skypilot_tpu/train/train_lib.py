"""Sharded training step (pjit/GSPMD) for the model zoo.

This is what the reference delegates to torch-xla + HF Trainer in its TPU
recipe (examples/tpu/v6e/README.md, docs/source/reference/tpu.rst:100-118);
here it is native: one jitted SPMD step with donated state, fp32 master
params + bf16 compute, optax AdamW, sharded by the same logical rules as the
model.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from skypilot_tpu import models as models_lib
from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.parallel import sharding as sharding_lib

Batch = Dict[str, jnp.ndarray]


def _zigzag_seq_shards(cfg, mesh: Mesh) -> int:
    """0 when the config doesn't use zigzag ring attention; otherwise the
    'sequence' mesh size (>=1) — the zigzag layout then applies to
    tokens/targets/positions even at size 1 (identity permutation), so the
    model's explicit-positions guard is always satisfied."""
    if (getattr(cfg, 'attention_impl', '') == 'ring' and
            getattr(cfg, 'ring_layout', 'seq') == 'zigzag'):
        return max(1, dict(mesh.shape).get('sequence', 1))
    return 0


def _zigzag_shift(tokens, mask, n_seq: int):
    """Shift tokens into (inputs, targets) and lay the sequence dim out in
    zigzag order so every 'sequence' shard does equal causal ring work
    (ops/ring_attention.py). Returns (inputs, targets, mask, positions);
    positions are the original sequence positions each layout slot holds —
    forward() feeds them to RoPE, so the permutation is invisible to the
    math (CE loss is a masked mean over positions, permutation-invariant).
    n_seq == 0 means "not zigzag": no permutation, default positions.
    """
    from skypilot_tpu.ops import ring_attention as ring_lib
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    if n_seq == 0:
        return inputs, targets, mask, None
    perm = ring_lib.zigzag_positions(inputs.shape[1], n_seq)
    inputs = jnp.take(inputs, perm, axis=1)
    targets = jnp.take(targets, perm, axis=1)
    if mask is not None:
        mask = jnp.take(mask, perm, axis=1)
    return inputs, targets, mask, perm


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any


def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray,
                       mask: Optional[jnp.ndarray] = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mean next-token CE over masked positions. logits fp32 [B,S,V]."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None],
                               axis=-1)[..., 0]
    nll = logz - gold
    if mask is None:
        mask = jnp.ones_like(nll)
    mask = mask.astype(jnp.float32)
    denom = jnp.maximum(mask.sum(), 1.0)
    return (nll * mask).sum() / denom, denom


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      total_steps: int = 10000,
                      max_grad_norm: float = 1.0) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def state_shardings(cfg: 'llama.LlamaConfig', mesh: Mesh,
                    tx: optax.GradientTransformation,
                    rules: Optional[sharding_lib.Rules] = None) -> TrainState:
    """TrainState-shaped pytree of NamedShardings (for jit in/out)."""
    rules = rules or sharding_lib.Rules()
    mod = models_lib.module_for(cfg)
    specs = mod.param_specs(cfg, rules)
    p_shard = sharding_lib.tree_shardings(mesh, specs)
    param_shapes = jax.eval_shape(
        functools.partial(mod.init_params, cfg=cfg),
        jax.random.PRNGKey(0))
    opt_shapes = jax.eval_shape(tx.init, param_shapes)
    leaf_to_sharding = sharding_lib.shardings_like(mesh, specs, param_shapes)
    opt_shard = jax.tree.map(leaf_to_sharding, opt_shapes)
    return TrainState(step=NamedSharding(mesh, PartitionSpec()),
                      params=p_shard, opt_state=opt_shard)


def init_train_state(rng: jax.Array, cfg: 'llama.LlamaConfig', mesh: Mesh,
                     tx: optax.GradientTransformation,
                     rules: Optional[sharding_lib.Rules] = None) -> TrainState:
    """Materialise params + opt state directly sharded on the mesh."""
    shardings = state_shardings(cfg, mesh, tx, rules)

    mod = models_lib.module_for(cfg)

    def _init(r):
        params = mod.init_params(r, cfg)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=tx.init(params))

    out_shardings = TrainState(step=shardings.step, params=shardings.params,
                               opt_state=shardings.opt_state)
    with mesh_lib.use_mesh(mesh):
        return jax.jit(_init, out_shardings=out_shardings)(rng)


def make_train_step(cfg: 'llama.LlamaConfig', mesh: Mesh,
                    tx: optax.GradientTransformation,
                    rules: Optional[sharding_lib.Rules] = None,
                    grad_accum_steps: int = 1
                    ) -> Callable[[TrainState, Batch],
                                  Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """Jitted (state, batch) → (state, metrics); donates state.

    batch: {'tokens': int32 [B, S+1]} — shifted internally;
    optional 'loss_mask' [B, S] masks the *target* positions.

    grad_accum_steps > 1 splits the batch into that many microbatches and
    accumulates grads in one `lax.scan` before a single optimizer update —
    the global batch stays on the loader/step contract, only peak
    activation memory shrinks (activations live for one microbatch at a
    time). Accumulation is token-weighted (each microbatch's mean-grad
    scaled by its target-token count, normalized by the total), so the
    update equals the dense step even when loss_mask counts differ across
    microbatches (asserted in tests/unit_tests/test_llama.py).
    """
    rules = rules or sharding_lib.Rules()
    shardings = state_shardings(cfg, mesh, tx, rules)
    mod = models_lib.module_for(cfg)

    n_zigzag = _zigzag_seq_shards(cfg, mesh)

    def _grads_of(params, tokens, mask):
        inputs, targets, mask, positions = _zigzag_shift(tokens, mask,
                                                         n_zigzag)

        def loss_fn(p):
            if getattr(mod, 'HAS_AUX', False):
                logits, aux = mod.forward(p, inputs, cfg, rules,
                                          positions=positions,
                                          return_aux=True)
            else:
                logits, aux = mod.forward(p, inputs, cfg, rules,
                                          positions=positions), 0.0
            loss, denom = cross_entropy_loss(logits, targets, mask)
            return loss + aux, (loss, denom)

        (_, (loss, denom)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        return grads, loss, denom

    def step_fn(state: TrainState, batch: Batch):
        tokens = batch['tokens']
        mask = batch.get('loss_mask')
        a = grad_accum_steps
        if a == 1:
            grads, loss, denom = _grads_of(state.params, tokens, mask)
        else:
            b = tokens.shape[0]
            if b % a != 0:
                raise ValueError(f'batch {b} not divisible by '
                                 f'grad_accum_steps {a}')
            tok_m = tokens.reshape(a, b // a, *tokens.shape[1:])
            mask_m = (mask.reshape(a, b // a, *mask.shape[1:])
                      if mask is not None else None)

            def micro(carry, xs):
                g_sum, l_sum, d_sum = carry
                if mask_m is None:
                    t, m = xs, None
                else:
                    t, m = xs
                g, loss, denom = _grads_of(state.params, t, m)
                # Token-weighted: each microbatch's mean-grad re-scales by
                # its own target-token count so the final grads equal the
                # dense full-batch mean — equal weighting per MICROBATCH
                # would over-weight sparsely-masked microbatches' tokens.
                g_sum = jax.tree.map(lambda s, gi: s + gi * denom, g_sum, g)
                return (g_sum, l_sum + loss * denom, d_sum + denom), None

            g0 = jax.tree.map(jnp.zeros_like, state.params)
            xs = tok_m if mask_m is None else (tok_m, mask_m)
            (g_sum, l_sum, d_sum), _ = jax.lax.scan(
                micro, (g0, jnp.zeros(()), jnp.zeros(())), xs)
            d_safe = jnp.maximum(d_sum, 1.0)
            grads = jax.tree.map(lambda g: g / d_safe, g_sum)
            loss = l_sum / d_safe
            denom = d_sum
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        metrics = {'loss': loss, 'grad_norm': gnorm,
                   'tokens': denom, 'step': state.step}
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt), metrics

    jitted = jax.jit(
        step_fn,
        donate_argnums=(0,),
        out_shardings=(shardings, NamedSharding(mesh, PartitionSpec())),
    )

    def wrapped(state, batch):
        with mesh_lib.use_mesh(mesh):
            return jitted(state, batch)

    return wrapped


def make_eval_step(cfg: 'llama.LlamaConfig', mesh: Mesh,
                   rules: Optional[sharding_lib.Rules] = None
                   ) -> Callable[[Any, Batch], jnp.ndarray]:
    """Jitted forward-only loss: (params, batch) → scalar mean CE.

    The held-out metric for the trainer's --eval-data loop; shares the
    model forward and sharding rules with the train step (no dropout /
    no optimizer, so eval loss is deterministic given the batch)."""
    rules = rules or sharding_lib.Rules()
    mod = models_lib.module_for(cfg)

    n_zigzag = _zigzag_seq_shards(cfg, mesh)

    def eval_fn(params, batch: Batch):
        tokens = batch['tokens']
        inputs, targets, mask, positions = _zigzag_shift(
            tokens, batch.get('loss_mask'), n_zigzag)
        if getattr(mod, 'HAS_AUX', False):
            logits, _ = mod.forward(params, inputs, cfg, rules,
                                    positions=positions, return_aux=True)
        else:
            logits = mod.forward(params, inputs, cfg, rules,
                                 positions=positions)
        loss, _ = cross_entropy_loss(logits, targets, mask)
        return loss

    jitted = jax.jit(eval_fn,
                     out_shardings=NamedSharding(mesh, PartitionSpec()))

    def wrapped(params, batch):
        with mesh_lib.use_mesh(mesh):
            return jitted(params, batch)

    return wrapped


def synthetic_batch(rng: jax.Array, batch_size: int, seq_len: int,
                    vocab_size: int) -> Batch:
    tokens = jax.random.randint(rng, (batch_size, seq_len + 1), 0, vocab_size,
                                dtype=jnp.int32)
    return {'tokens': tokens}
