"""Training layer: sharded train state, pjit train step, data pipeline."""
from skypilot_tpu.train.train_lib import (TrainState, cross_entropy_loss,
                                          make_train_step, init_train_state)

__all__ = ['TrainState', 'cross_entropy_loss', 'make_train_step',
           'init_train_state']
