"""Fold trained LoRA adapters into base weights for serving.

    python -m skypilot_tpu.train.lora_merge \
        --hf-dir ~/ckpts/Llama-3.2-1B --lora-dir ~/ft/adapters \
        --out ~/ft/merged

The output is a standard HF checkpoint directory (weights + tokenizer
sidecars) — serve it directly:

    python -m skypilot_tpu.serve.engine --hf-dir ~/ft/merged

(The reference's finetune recipes end the same way: torchtune writes an
HF-format dir that vLLM then serves, llm/llama-3_1-finetuning/.)
"""
from __future__ import annotations

import argparse

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger('skypilot_tpu.train.lora_merge')


def main() -> None:
    from skypilot_tpu.utils import jax_utils
    jax_utils.pin_platform_from_env()
    parser = argparse.ArgumentParser(prog='skytpu-lora-merge')
    parser.add_argument('--hf-dir', required=True,
                        help='Base HF checkpoint the adapters were '
                             'trained against.')
    parser.add_argument('--lora-dir', required=True,
                        help='Directory holding adapters.npz + lora.json '
                             '(trainer --lora-dir).')
    parser.add_argument('--out', required=True,
                        help='Output HF checkpoint directory.')
    args = parser.parse_args()

    from skypilot_tpu.models import hf_export, hf_import, llama
    from skypilot_tpu.train import lora

    # Fail BEFORE the (possibly multi-GB) weight load: export
    # round-trips the dense Llama/Qwen2 family only.
    cfg_only = hf_import.load_hf_config(args.hf_dir)
    if type(cfg_only) is not llama.LlamaConfig:
        raise SystemExit(
            f'lora_merge exports the dense Llama/Qwen2 family only; '
            f'{args.hf_dir} is {type(cfg_only).__name__}. (Serve MoE '
            f'LoRA runs by loading base + adapters directly.)')
    # dtype=None keeps the base's stored dtype (bf16 stays bf16 — the
    # merge itself happens in fp32 inside merge_into, and the export
    # keeps the artifact the same size as the base).
    cfg, base = hf_import.load_hf_checkpoint(args.hf_dir, dtype=None)
    adapters, lcfg, step, _ = lora.load_adapters(args.lora_dir)
    merged = lora.merge_into(base, adapters, lcfg)
    out = hf_export.save_hf_checkpoint(merged, cfg, args.out,
                                       source_dir=args.hf_dir)
    logger.info(f'Merged rank-{lcfg.rank} adapters (step {step}) into '
                f'{out}; serve with --hf-dir {out}')


if __name__ == '__main__':
    main()
