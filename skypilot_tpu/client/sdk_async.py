"""Asyncio client SDK for the API server.

Reference analog: sky/client/sdk_async.py (asyncio variant of sdk.py).
Same request model as `client/sdk.py` — every call POSTs to
`/api/v1/<name>`, gets a request id, then awaits the persisted request —
but non-blocking, so a notebook or an async service (e.g. the serve load
balancer) can multiplex many control-plane calls on one event loop.

Endpoint/auth resolution is shared with the sync SDK (`api_server_url`,
`_headers`), so both SDKs always talk to the same server with the same
token.
"""
from __future__ import annotations

import asyncio
import sys
from typing import Any, Dict, List, Optional

import aiohttp

from skypilot_tpu.client import sdk as sync_sdk
from skypilot_tpu.client.sdk import ApiError, RequestFailedError
from skypilot_tpu.server import requests_lib as server_requests

__all__ = [
    'ApiError', 'RequestFailedError', 'submit', 'get', 'stream_and_get',
    'api_cancel', 'api_list_requests', 'launch', 'exec', 'status', 'queue',
    'down', 'stop', 'start', 'cancel', 'tail_logs',
]


async def _url(url: Optional[str]) -> str:
    if url:
        return url
    # api_server_url probes /api/v1/health with a synchronous
    # requests.get (2 s timeout) and may read the endpoint file — run
    # it in a worker thread so endpoint resolution never stalls every
    # other coroutine on the loop.
    return await asyncio.to_thread(sync_sdk.api_server_url, required=True)


async def submit(name: str, payload: Dict[str, Any],
                 url: Optional[str] = None) -> str:
    url = await _url(url)
    payload = sync_sdk.prepare_payload(payload)
    async with aiohttp.ClientSession() as session:
        async with session.post(f'{url}/api/v1/{name}', json=payload,
                                headers=sync_sdk._headers(),
                                timeout=aiohttp.ClientTimeout(
                                    total=30)) as r:
            if r.status != 200:
                raise ApiError(f'{name}: HTTP {r.status}: {await r.text()}')
            return (await r.json())['request_id']


async def get(request_id: str, url: Optional[str] = None) -> Any:
    """Await request completion; return its result (or raise)."""
    url = await _url(url)
    async with aiohttp.ClientSession() as session:
        while True:
            async with session.get(
                    f'{url}/api/v1/get',
                    params={'request_id': request_id, 'wait': '1'},
                    headers=sync_sdk._headers(),
                    timeout=aiohttp.ClientTimeout(total=300)) as r:
                if r.status == 404:
                    raise ApiError(f'no request {request_id}')
                if r.status != 200:
                    raise ApiError(f'get: HTTP {r.status}: '
                                   f'{await r.text()}')
                rec = await r.json()
            status = server_requests.RequestStatus(rec['status'])
            if status.is_terminal():
                break
    if status == server_requests.RequestStatus.SUCCEEDED:
        return rec['result']
    if status == server_requests.RequestStatus.CANCELLED:
        raise ApiError(f'request {request_id} was cancelled')
    raise RequestFailedError(request_id, rec.get('error') or '')


async def stream_and_get(request_id: str, url: Optional[str] = None,
                         out=None) -> Any:
    url = await _url(url)
    out = out or sys.stdout
    async with aiohttp.ClientSession() as session:
        async with session.get(
                f'{url}/api/v1/stream',
                params={'request_id': request_id},
                headers=sync_sdk._headers(),
                timeout=aiohttp.ClientTimeout(total=None)) as r:
            async for chunk in r.content.iter_any():
                out.write(chunk.decode('utf-8', errors='replace'))
                out.flush()
    return await get(request_id, url)


async def api_cancel(request_id: str, url: Optional[str] = None) -> bool:
    url = await _url(url)
    async with aiohttp.ClientSession() as session:
        async with session.post(f'{url}/api/v1/request_cancel',
                                json={'request_id': request_id},
                                headers=sync_sdk._headers(),
                                timeout=aiohttp.ClientTimeout(
                                    total=30)) as r:
            if r.status != 200:
                raise ApiError(f'cancel: HTTP {r.status}: '
                               f'{await r.text()}')
            return bool((await r.json()).get('cancelled'))


async def api_list_requests(url: Optional[str] = None
                            ) -> List[Dict[str, Any]]:
    url = await _url(url)
    async with aiohttp.ClientSession() as session:
        async with session.get(f'{url}/api/v1/requests',
                               headers=sync_sdk._headers(),
                               timeout=aiohttp.ClientTimeout(
                                   total=30)) as r:
            if r.status != 200:
                raise ApiError(f'requests: HTTP {r.status}: '
                               f'{await r.text()}')
            return await r.json()


# ---------------------------------------------------------------------------
# Typed RPCs
# ---------------------------------------------------------------------------

async def launch(task, cluster_name: Optional[str] = None, *,
                 detach_run: bool = True, down_: bool = False,
                 dryrun: bool = False, retry_until_up: bool = False,
                 stream: bool = True) -> Any:
    payload = {'task': task.to_yaml_config(), 'cluster_name': cluster_name,
               'detach_run': detach_run, 'down': down_, 'dryrun': dryrun,
               'retry_until_up': retry_until_up}
    rid = await submit('launch', payload)
    return await (stream_and_get(rid) if stream else get(rid))


async def exec(task, cluster_name: str, *,  # pylint: disable=redefined-builtin
               detach_run: bool = True) -> Any:
    rid = await submit('exec', {'task': task.to_yaml_config(),
                                'cluster_name': cluster_name,
                                'detach_run': detach_run})
    return await get(rid)


async def status(cluster_names: Optional[List[str]] = None,
                 refresh: bool = False, all_workspaces: bool = False) -> Any:
    from skypilot_tpu import workspaces
    return await get(await submit('status', {
        'cluster_names': cluster_names,
        'refresh': refresh,
        'all_workspaces': all_workspaces,
        'workspace': workspaces.get_active_workspace(),
    }))


async def queue(cluster_name: str) -> Any:
    return await get(await submit('queue', {'cluster_name': cluster_name}))


async def down(cluster_name: str) -> Any:
    return await get(await submit('down', {'cluster_name': cluster_name}))


async def stop(cluster_name: str) -> Any:
    return await get(await submit('stop', {'cluster_name': cluster_name}))


async def start(cluster_name: str) -> Any:
    return await get(await submit('start', {'cluster_name': cluster_name}))


async def cancel(cluster_name: str,
                 job_ids: Optional[List[int]] = None) -> Any:
    return await get(await submit('cancel', {'cluster_name': cluster_name,
                                             'job_ids': job_ids}))


async def tail_logs(cluster_name: str, job_id: Optional[int] = None,
                    follow: bool = True) -> Any:
    rid = await submit('logs', {'cluster_name': cluster_name,
                                'job_id': job_id, 'follow': follow})
    return await stream_and_get(rid)
