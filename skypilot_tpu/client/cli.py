"""The `skytpu` CLI.

Reference analog: sky/client/cli/command.py (launch:1009, exec:1200,
status:1710, queue:2171, logs:2258, cancel:2397, stop:2524, start:2734,
down:2944, check:3482, show_gpus:3547 → show-tpus here). Commands route
through the local SDK by default; `--server` routes through a running API
server (client/sdk.py).
"""
from __future__ import annotations

import os
import sys
from typing import List, Optional, Tuple

import click

import skypilot_tpu as sky
from skypilot_tpu import check as check_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.catalog import tpu_catalog
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)


def _parse_env_overrides(env: Tuple[str, ...]) -> dict:
    env_overrides = {}
    for item in env:
        if '=' not in item:
            raise click.UsageError(f'--env expects KEY=VALUE, got {item!r}')
        k, v = item.split('=', 1)
        env_overrides[k] = v
    return env_overrides


def _load_task(entrypoint: str, env: Tuple[str, ...],
               overrides: dict) -> sky.Task:
    env_overrides = _parse_env_overrides(env)
    try:
        if entrypoint.endswith(('.yaml', '.yml')) and os.path.exists(
                entrypoint):
            task = sky.Task.from_yaml(entrypoint, env_overrides or None)
        else:
            # Inline command entrypoint.
            task = sky.Task(run=entrypoint, envs=env_overrides or None)
        if overrides:
            task.set_resources_override(
                {k: v for k, v in overrides.items() if v is not None})
    except (exceptions.SkyTpuError, ValueError) as e:
        raise click.ClickException(str(e)) from e
    return task


def _resource_options(fn):
    fn = click.option('--accelerators', '--tpu', 'accelerators',
                      default=None,
                      help='TPU slice, e.g. tpu-v5p-128.')(fn)
    fn = click.option('--cloud', default=None)(fn)
    fn = click.option('--region', default=None)(fn)
    fn = click.option('--zone', default=None)(fn)
    fn = click.option('--use-spot/--no-use-spot', 'use_spot', default=None)(fn)
    return fn


@click.group()
@click.version_option(sky.__version__, '--version', '-v')
def cli():
    """skytpu: TPU-native cloud AI orchestration."""


@cli.command()
@click.argument('entrypoint', required=True)
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--dryrun', is_flag=True, default=False)
@click.option('--down', is_flag=True, default=False,
              help='Tear down the cluster when the job finishes.')
@click.option('--env', multiple=True, help='KEY=VALUE task env overrides.')
@click.option('--retry-until-up', is_flag=True, default=False)
@click.option('--no-setup', is_flag=True, default=False)
@_resource_options
def launch(entrypoint: str, cluster: Optional[str], detach_run: bool,
           dryrun: bool, down: bool, env: Tuple[str, ...],
           retry_until_up: bool, no_setup: bool, **overrides):
    """Launch a task (provision a TPU slice if needed) from YAML or command."""
    task = _load_task(entrypoint, env, overrides)
    try:
        job_id, handle = sky.launch(task, cluster_name=cluster,
                                    dryrun=dryrun, detach_run=detach_run,
                                    down=down, retry_until_up=retry_until_up,
                                    no_setup=no_setup)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e
    if handle is not None and job_id is not None:
        click.echo(f'Job {job_id} on cluster {handle.cluster_name!r}.')


@cli.command(name='exec')
@click.argument('cluster', required=True)
@click.argument('entrypoint', required=True)
@click.option('--detach-run', '-d', is_flag=True, default=False)
@click.option('--env', multiple=True)
def exec_cmd(cluster: str, entrypoint: str, detach_run: bool,
             env: Tuple[str, ...]):
    """Run a task on an existing cluster (no provision/setup)."""
    task = _load_task(entrypoint, env, {})
    try:
        job_id, _ = sky.exec(task, cluster, detach_run=detach_run)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e
    click.echo(f'Job {job_id} on cluster {cluster!r}.')


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--refresh', '-r', is_flag=True, default=False)
@click.option('--all-workspaces', '-u', is_flag=True, default=False,
              help='Show clusters from every workspace.')
def status(clusters: Tuple[str, ...], refresh: bool, all_workspaces: bool):
    """Show clusters (active workspace only; see `workspace:` config)."""
    records = sky.status(list(clusters) or None, refresh=refresh,
                         all_workspaces=all_workspaces)
    if not records:
        click.echo('No existing clusters.')
        return
    rows = []
    import time
    for r in records:
        handle = r.get('handle') or {}
        res_cfg = handle.get('launched_resources') or {}
        acc = res_cfg.get('accelerators', '-')
        spot = ' [spot]' if res_cfg.get('use_spot') else ''
        age = common_utils.format_duration(
            max(0.0, time.time() - (r.get('launched_at') or 0)))
        rows.append((r['name'], f"{handle.get('cloud', '-')}", f'{acc}{spot}',
                     r.get('handle', {}).get('zone') or '-', age,
                     r['status'].colored_str()))
    header = ('NAME', 'CLOUD', 'RESOURCES', 'ZONE', 'AGE', 'STATUS')
    widths = [max(len(header[i]), *(len(str(r[i])) for r in rows))
              for i in range(len(header))]
    click.echo('  '.join(h.ljust(w) for h, w in zip(header, widths)))
    for r in rows:
        click.echo('  '.join(str(c).ljust(w) for c, w in zip(r, widths)))


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def down(clusters: Tuple[str, ...], yes: bool):
    """Terminate cluster(s)."""
    if not yes:
        click.confirm(f'Terminate {", ".join(clusters)}?', abort=True)
    for name in clusters:
        try:
            sky.down(name)
        except exceptions.SkyTpuError as e:
            raise click.ClickException(str(e)) from e


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def stop(clusters: Tuple[str, ...], yes: bool):
    """Stop cluster(s) (TPU generations that support stop)."""
    if not yes:
        click.confirm(f'Stop {", ".join(clusters)}?', abort=True)
    for name in clusters:
        try:
            sky.stop(name)
        except exceptions.SkyTpuError as e:
            raise click.ClickException(str(e)) from e


@cli.command()
@click.argument('cluster', required=True)
def start(cluster: str):
    """Restart a stopped cluster."""
    try:
        sky.start(cluster)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e


@cli.command()
@click.argument('cluster', required=True)
@click.option('--idle-minutes', '-i', type=int, default=5)
@click.option('--down', 'down_after', is_flag=True, default=False)
@click.option('--cancel', 'cancel_flag', is_flag=True, default=False,
              help='Disable autostop.')
def autostop(cluster: str, idle_minutes: int, down_after: bool,
             cancel_flag: bool):
    """Configure idleness autostop for a cluster."""
    try:
        sky.autostop(cluster, None if cancel_flag else idle_minutes,
                     down_after)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e


@cli.command()
@click.argument('cluster', required=True)
def queue(cluster: str):
    """Show the job queue of a cluster."""
    try:
        jobs = sky.queue(cluster)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e
    if not jobs:
        click.echo('No jobs.')
        return
    header = ('ID', 'NAME', 'USER', 'STATUS', 'HOSTS')
    click.echo('  '.join(header))
    for j in jobs:
        click.echo(f"{j['job_id']}  {j['job_name']}  {j['username']}  "
                   f"{j['status']}  {j['num_hosts']}")


@cli.command()
@click.argument('cluster', required=True)
@click.argument('job_id', required=False, type=int)
@click.option('--no-follow', is_flag=True, default=False)
def logs(cluster: str, job_id: Optional[int], no_follow: bool):
    """Tail the logs of a job."""
    try:
        rc = sky.tail_logs(cluster, job_id, follow=not no_follow)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e
    sys.exit(rc)


@cli.command()
@click.argument('cluster', required=True)
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', '-a', 'all_jobs', is_flag=True, default=False)
def cancel(cluster: str, job_ids: Tuple[int, ...], all_jobs: bool):
    """Cancel job(s)."""
    if not job_ids and not all_jobs:
        raise click.UsageError('Pass job ids or --all.')
    try:
        done = sky.cancel(cluster, None if all_jobs else list(job_ids))
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e
    click.echo(f'Cancelled: {done}')


@cli.command()
def check():
    """Probe cloud credentials and show enabled clouds."""
    enabled = check_lib.check()
    if not enabled:
        click.echo('No cloud enabled.')
        sys.exit(1)


@cli.command(name='show-tpus')
@click.option('--name-filter', default=None)
@click.option('--region', default=None)
@click.option('--all-regions', is_flag=True, default=False)
def show_tpus(name_filter: Optional[str], region: Optional[str],
              all_regions: bool):
    """List TPU slice offerings and pricing (analog: sky show-gpus)."""
    offerings = tpu_catalog.list_accelerators(name_filter=name_filter,
                                              region_filter=region)
    header = ('SLICE', 'CHIPS', 'TOPOLOGY', 'HOSTS', 'REGION',
              '$/HR', 'SPOT $/HR')
    click.echo('  '.join(h.ljust(12) for h in header))
    for name in sorted(offerings,
                       key=lambda n: (offerings[n][0].generation,
                                      offerings[n][0].num_chips)):
        infos = offerings[name]
        shown = infos if all_regions else infos[:1]
        for info in shown:
            row = (name, str(info.num_chips), info.topology,
                   str(info.num_hosts), info.region,
                   f'{info.price:.2f}', f'{info.spot_price:.2f}')
            click.echo('  '.join(c.ljust(12) for c in row))


@cli.command(name='show-models')
def show_models():
    """List the native model presets (trainer --model / engine --model)."""
    from skypilot_tpu import models as models_lib
    header = ('PRESET', 'FAMILY', 'PARAMS', 'LAYERS', 'DIM', 'MAX SEQ')
    click.echo('  '.join(h.ljust(18) for h in header))
    for name in models_lib.list_presets():
        cfg = models_lib.get_config(name)
        family = models_lib.module_for(cfg).__name__.rsplit('.', 1)[-1]
        n = cfg.num_params
        params = (f'{n/1e9:.1f}B' if n >= 1e9 else
                  f'{n/1e6:.0f}M' if n >= 1e7 else f'{n/1e6:.1f}M')
        row = (name, family, params, str(cfg.n_layers), str(cfg.dim),
               str(cfg.max_seq_len))
        click.echo('  '.join(c.ljust(18) for c in row))


@cli.command(name='cost-report')
def cost_report():
    """Show the cost of past clusters."""
    rows = sky.cost_report()
    if not rows:
        click.echo('No history.')
        return
    for r in rows:
        dur = common_utils.format_duration(r.get('duration_seconds') or 0)
        click.echo(f"{r['name']}: {dur}, ${r.get('cost') or 0:.2f}")


@cli.group()
def jobs():
    """Managed jobs: auto-recovery from TPU spot preemption
    (reference: `sky jobs`)."""


@jobs.command(name='launch')
@click.argument('entrypoint', required=True)
@click.option('--name', '-n', default=None, help='Managed job name.')
@click.option('--env', multiple=True, help='KEY=VALUE task env overrides.')
@click.option('--detach-run', '-d', is_flag=True, default=False,
              help='Return immediately instead of streaming logs.')
@click.option('--pool', '-p', default=None,
              help='Run on a worker of this pool (see `jobs pool apply`) '
                   'instead of a dedicated cluster.')
@_resource_options
def jobs_launch(entrypoint: str, name: Optional[str], env: Tuple[str, ...],
                detach_run: bool, pool: Optional[str], **overrides):
    """Submit a managed job — single task, or a multi-document YAML
    pipeline (stages run in order, one recovery-managed job)."""
    from skypilot_tpu import jobs as jobs_lib
    entry = None
    if entrypoint.endswith(('.yaml', '.yml')) and os.path.exists(entrypoint):
        with open(entrypoint, 'r', encoding='utf-8') as f:
            is_pipeline = f.read().count('\n---') > 0
        if is_pipeline:
            from skypilot_tpu import dag as dag_lib
            env_overrides = _parse_env_overrides(env)
            try:
                entry = dag_lib.load_chain_dag_from_yaml(
                    entrypoint, env_overrides or None)
                active = {k: v for k, v in overrides.items()
                          if v is not None}
                if active:
                    for t in entry.tasks:
                        t.set_resources_override(active)
            except (exceptions.SkyTpuError, ValueError) as e:
                raise click.ClickException(str(e)) from e
    if entry is None:
        entry = _load_task(entrypoint, env, overrides)
    try:
        job_id = jobs_lib.launch(entry, name=name, pool=pool)
    except (exceptions.SkyTpuError, ValueError) as e:
        raise click.ClickException(str(e)) from e
    click.echo(f'Managed job {job_id} submitted.')
    if not detach_run:
        jobs_lib.tail_logs(job_id, follow=True)


@jobs.command(name='queue')
@click.option('--skip-finished', '-s', is_flag=True, default=False)
def jobs_queue(skip_finished: bool):
    """Show managed jobs."""
    from skypilot_tpu import jobs as jobs_lib
    rows = jobs_lib.queue(skip_finished=skip_finished)
    if not rows:
        click.echo('No managed jobs.')
        return
    import time as time_lib
    header = ('ID', 'NAME', 'STATUS', 'CLUSTER', '#RECOVERIES', 'AGE')
    click.echo('  '.join(h.ljust(12) for h in header))
    for j in rows:
        age = common_utils.format_duration(
            max(0.0, time_lib.time() - (j['submitted_at'] or 0)))
        # Pad by the *visible* status width; colored_str adds ANSI escapes.
        status_cell = (j['status'].colored_str() +
                       ' ' * max(0, 12 - len(j['status'].value)))
        click.echo('  '.join((str(j['job_id']).ljust(12),
                              str(j['name']).ljust(12), status_cell,
                              str(j['cluster_name'] or '-').ljust(12),
                              str(j['recovery_count']).ljust(12), age)))


@jobs.command(name='logs')
@click.argument('job_id', required=False, type=int)
@click.option('--no-follow', is_flag=True, default=False)
@click.option('--controller', is_flag=True, default=False,
              help="Show the job's controller log instead.")
def jobs_logs(job_id: Optional[int], no_follow: bool, controller: bool):
    """Tail a managed job's logs (survives preemption/teardown)."""
    from skypilot_tpu import jobs as jobs_lib
    try:
        rc = jobs_lib.tail_logs(job_id, follow=not no_follow,
                                controller=controller)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e
    sys.exit(rc)


@jobs.command(name='cancel')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--name', '-n', default=None)
@click.option('--all', '-a', 'all_jobs', is_flag=True, default=False)
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_cancel(job_ids: Tuple[int, ...], name: Optional[str],
                all_jobs: bool, yes: bool):
    """Cancel managed job(s)."""
    from skypilot_tpu import jobs as jobs_lib
    if not (job_ids or name or all_jobs):
        raise click.UsageError('Pass job ids, --name, or --all.')
    if not yes:
        what = 'ALL managed jobs' if all_jobs else (
            f'jobs {list(job_ids)}{f" named {name!r}" if name else ""}')
        click.confirm(f'Cancel {what}?', abort=True)
    try:
        done = jobs_lib.cancel(job_ids=list(job_ids) or None, name=name,
                               all_jobs=all_jobs)
    except (exceptions.SkyTpuError, ValueError) as e:
        raise click.ClickException(str(e)) from e
    click.echo(f'Cancellation requested: {done}')


@jobs.group(name='pool')
def jobs_pool():
    """Worker pools: pre-provisioned clusters managed jobs exec onto
    (reference: `sky jobs pool`)."""


@jobs_pool.command(name='apply')
@click.argument('entrypoint', required=True)
@click.option('--pool-name', '-p', default=None, help='Pool name.')
@click.option('--workers', '-w', type=int, default=None,
              help='Worker count (overrides the YAML pool.workers).')
def jobs_pool_apply(entrypoint: str, pool_name: Optional[str],
                    workers: Optional[int]):
    """Create or resize a pool from a task YAML with a `pool:` section."""
    from skypilot_tpu.jobs import pool as pool_lib
    task = _load_task(entrypoint, (), {})
    try:
        result = pool_lib.apply(task, pool_name=pool_name, workers=workers)
    except (exceptions.SkyTpuError, ValueError) as e:
        raise click.ClickException(str(e)) from e
    click.echo(f"Pool {result['name']!r} applied "
               f'(watch: skytpu jobs pool status).')


@jobs_pool.command(name='status')
@click.argument('pool_names', nargs=-1)
def jobs_pool_status(pool_names: Tuple[str, ...]):
    """Show pools and their workers (busy workers show the job id)."""
    from skypilot_tpu.jobs import pool as pool_lib
    records = pool_lib.status(list(pool_names) or None)
    if not records:
        click.echo('No pools.')
        return
    for r in records:
        click.echo(f"{r['name']}  {r['status'].colored_str()}  "
                   f"{len(r['replicas'])} worker(s)")
        for rep in r['replicas']:
            busy = (f"  job {rep['job_id']}" if rep.get('job_id') is not None
                    else '  idle')
            click.echo(f"  worker {rep['replica_id']}  "
                       f"{rep['status'].colored_str()}{busy}  "
                       f"({rep['cluster_name']})")


@jobs_pool.command(name='down')
@click.argument('pool_name', required=True)
@click.option('--purge', is_flag=True, default=False,
              help='Also remove the pool record.')
@click.option('--yes', '-y', is_flag=True, default=False)
def jobs_pool_down(pool_name: str, purge: bool, yes: bool):
    """Tear down a pool and its workers."""
    from skypilot_tpu.jobs import pool as pool_lib
    if not yes:
        click.confirm(f'Tear down pool {pool_name!r}?', abort=True)
    try:
        pool_lib.down(pool_name, purge=purge)
    except (exceptions.SkyTpuError, ValueError) as e:
        raise click.ClickException(str(e)) from e
    click.echo(f'Pool {pool_name!r} torn down.')


@cli.group()
def serve():
    """Replicated serving with autoscaling (reference: `sky serve`)."""


@serve.command(name='up')
@click.argument('entrypoint', required=True)
@click.option('--service-name', '-n', default=None)
@click.option('--env', multiple=True)
def serve_up(entrypoint: str, service_name: Optional[str],
             env: Tuple[str, ...]):
    """Bring up a service from a task YAML with a `service:` section."""
    from skypilot_tpu import serve as serve_lib
    task = _load_task(entrypoint, env, {})
    try:
        info = serve_lib.up(task, service_name=service_name)
    except (exceptions.SkyTpuError, ValueError) as e:
        raise click.ClickException(str(e)) from e
    click.echo(f"Service {info['name']!r} starting at {info['endpoint']} "
               f"(watch: skytpu serve status).")


@serve.command(name='update')
@click.argument('service_name', required=True)
@click.argument('entrypoint', required=True)
@click.option('--mode', type=click.Choice(['rolling', 'blue_green']),
              default='rolling', show_default=True,
              help='rolling replaces replicas one at a time; blue_green '
                   'brings up a full new set before cutting traffic over.')
@click.option('--env', multiple=True)
def serve_update(service_name: str, entrypoint: str, mode: str,
                 env: Tuple[str, ...]):
    """Migrate a live service to a new task YAML version."""
    from skypilot_tpu import serve as serve_lib
    task = _load_task(entrypoint, env, {})
    try:
        info = serve_lib.update(task, service_name, mode=mode)
    except (exceptions.SkyTpuError, ValueError) as e:
        raise click.ClickException(str(e)) from e
    click.echo(f"Service {info['name']!r} updating to version "
               f"{info['version']} ({info['mode']}).")


@serve.command(name='status')
@click.argument('service_names', nargs=-1)
def serve_status(service_names: Tuple[str, ...]):
    """Show services and their replicas."""
    from skypilot_tpu import serve as serve_lib
    records = serve_lib.status(list(service_names) or None)
    if not records:
        click.echo('No services.')
        return
    for r in records:
        click.echo(f"{r['name']}  {r['status'].colored_str()}  "
                   f"{r['endpoint']}  v{r.get('version', 1)}")
        for rep in r['replicas']:
            click.echo(f"  replica {rep['replica_id']}  "
                       f"v{rep.get('version', 1)}  "
                       f"{rep['status'].colored_str()}  {rep['url']}  "
                       f"({rep['cluster_name']})")
        if r.get('failure_reason'):
            click.echo(f"  failure: {r['failure_reason']}")


@serve.command(name='down')
@click.argument('service_name', required=True)
@click.option('--purge', is_flag=True, default=False,
              help='Also delete the service record.')
@click.option('--yes', '-y', is_flag=True, default=False)
def serve_down(service_name: str, purge: bool, yes: bool):
    """Tear down a service and all its replicas."""
    from skypilot_tpu import serve as serve_lib
    if not yes:
        click.confirm(f'Tear down service {service_name!r}?', abort=True)
    try:
        serve_lib.down(service_name, purge=purge)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e


@cli.command(name='tunnel')
@click.argument('cluster', required=True)
@click.option('--port', '-p', type=int, default=22, show_default=True,
              help='Remote port on the cluster head.')
@click.option('--local-port', '-l', type=int, required=True,
              help='Local listen port.')
def tunnel_cmd(cluster: str, port: int, local_port: int):
    """Tunnel a cluster port through the API server (websocket proxy).

    Example: `skytpu tunnel mycluster -p 22 -l 2222 &` then
    `ssh -p 2222 user@127.0.0.1`.
    """
    from skypilot_tpu.client import tunnel as tunnel_lib
    tunnel_lib.run_tunnel(cluster, port, local_port)


@cli.group()
def ssh():
    """BYO-machine SSH node pools (reference: `sky ssh`). Pools are
    declared in ~/.skytpu/ssh_node_pools.yaml and launched with
    `--cloud ssh`."""


@ssh.command(name='list')
def ssh_list():
    """Show configured SSH node pools and their hosts."""
    from skypilot_tpu.clouds import ssh as ssh_cloud
    pools = ssh_cloud.load_pools()
    if not pools:
        raise click.ClickException(
            f'No pools configured in {ssh_cloud.POOLS_PATH}.')
    from skypilot_tpu.provision.ssh import instance as ssh_instance
    state = ssh_instance.load_allocations()
    host_to_cluster = {}
    for cluster, alloc in state.get('allocations', {}).items():
        for h in alloc.get('hosts', []):
            host_to_cluster[str(h)] = cluster
    for name, cfg in pools.items():
        hosts = cfg.get('hosts') or []
        click.echo(f'{name}  ({len(hosts)} host(s), accelerator: '
                   f"{cfg.get('accelerator', '-')})")
        for h in hosts:
            used = host_to_cluster.get(str(h))
            click.echo(f'  {h}  '
                       f'{f"in use by {used}" if used else "free"}')


@ssh.command(name='check')
def ssh_check():
    """Probe SSH connectivity to every pool host."""
    from skypilot_tpu.clouds import ssh as ssh_cloud
    ok, reason = ssh_cloud.Ssh.check_credentials()
    if not ok:
        raise click.ClickException(reason or 'ssh pools unavailable')
    click.echo('SSH node pools configured and reachable.')


@cli.group()
def storage():
    """Storage buckets registered with the framework
    (reference: `sky storage`)."""


@storage.command(name='ls')
def storage_ls():
    """List registered storage objects."""
    from skypilot_tpu import global_state
    rows = global_state.get_storages()
    if not rows:
        click.echo('No storage objects.')
        return
    for r in rows:
        h = r['handle'] or {}
        click.echo(f"{r['name']}  {h.get('store_type', '?')}  "
                   f"{h.get('mode', '?')}  {h.get('source', '?')}  "
                   f"{r['status']}")


@storage.command(name='delete')
@click.argument('name', required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def storage_delete(name: str, yes: bool):
    """Deregister a storage object (does not delete bucket contents)."""
    from skypilot_tpu import global_state
    if global_state.get_storage(name) is None:
        raise click.ClickException(f'Storage {name!r} not found.')
    if not yes:
        click.confirm(f'Deregister storage {name!r}?', abort=True)
    global_state.remove_storage(name)
    click.echo(f'Storage {name!r} deregistered.')


@storage.command(name='transfer')
@click.argument('src', required=True)
@click.argument('dst', required=True)
@click.option('--dryrun', is_flag=True, default=False,
              help='Print the transfer command without running it.')
def storage_transfer(src: str, dst: str, dryrun: bool):
    """Sync SRC bucket/dir into DST (gs://, s3://, r2://, local paths).

    MIRRORS the source: files in DST that are not in SRC are DELETED
    (rsync --delete / gsutil -d / aws s3 sync --delete semantics).
    """
    from skypilot_tpu.data import data_transfer
    try:
        cmd = data_transfer.transfer(src, dst, dryrun=dryrun)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e
    click.echo(cmd if dryrun else f'Transferred {src} -> {dst}.')


@cli.group()
def volumes():
    """Network volumes (persistent disks) for clusters
    (reference: `sky volume`)."""


@volumes.command(name='apply')
@click.argument('name', required=True)
@click.option('--size', type=int, required=True, help='Size in GiB.')
@click.option('--zone', required=True)
@click.option('--type', 'disk_type', default='pd-balanced')
def volumes_apply(name: str, size: int, zone: str, disk_type: str):
    """Create (or adopt) a persistent disk."""
    from skypilot_tpu import volumes as volumes_lib
    try:
        info = volumes_lib.apply(name, size, zone, disk_type)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e
    click.echo(f"Volume {info['name']!r}: {info['size_gb']} GiB "
               f"{info['disk_type']} in {info['zone']}.")


@volumes.command(name='ls')
def volumes_ls():
    """List volumes."""
    from skypilot_tpu import volumes as volumes_lib
    rows = volumes_lib.ls()
    if not rows:
        click.echo('No volumes.')
        return
    for r in rows:
        h = r['handle'] or {}
        click.echo(f"{r['name']}  {h.get('size_gb', '?')}GiB  "
                   f"{h.get('disk_type', '?')}  {h.get('zone', '?')}  "
                   f"{r['status']}")


@volumes.command(name='delete')
@click.argument('name', required=True)
@click.option('--yes', '-y', is_flag=True, default=False)
def volumes_delete(name: str, yes: bool):
    """Delete a volume."""
    from skypilot_tpu import volumes as volumes_lib
    if not yes:
        click.confirm(f'Delete volume {name!r}?', abort=True)
    try:
        volumes_lib.delete(name)
    except exceptions.SkyTpuError as e:
        raise click.ClickException(str(e)) from e


@cli.group()
def api():
    """Manage the API server (reference: `sky api`)."""


@api.command(name='start')
@click.option('--host', default='127.0.0.1')
@click.option('--port', type=int, default=46580)
@click.option('--foreground', is_flag=True, default=False)
def api_start(host: str, port: int, foreground: bool):
    """Start the API server (daemonized unless --foreground)."""
    from skypilot_tpu.client import sdk
    url = sdk.api_start(host, port, foreground=foreground)
    click.echo(f'API server running at {url}')


@api.command(name='stop')
def api_stop():
    """Stop the API server."""
    from skypilot_tpu.client import sdk
    click.echo('stopped' if sdk.api_stop() else 'not running')


@api.command(name='status')
@click.option('--limit', type=int, default=30)
def api_status(limit: int):
    """Show the API server and its recent requests."""
    from skypilot_tpu.client import sdk
    info = sdk.api_info()
    click.echo(f"server: {info.get('status')} "
               f"{info.get('url', '')} {info.get('version', '')}")
    if info.get('status') != 'healthy':
        return
    for r in sdk.api_list_requests()[:limit]:
        click.echo(f"{r['request_id']}  {r['name']:<18} {r['status']}")


@api.command(name='login')
@click.argument('url', required=True)
@click.option('--token', default=None,
              help='Bearer token the server requires (helm chart: the '
                   '<release>-skytpu-token secret).')
def api_login(url: str, token: str):
    """Point this client at a (remote) API server and persist it."""
    from skypilot_tpu.client import sdk
    try:
        sdk.login(url, token)
    except sdk.ApiError as e:
        raise click.ClickException(str(e))
    click.echo(f'Logged in to {url.rstrip("/")}.')


@api.command(name='logs')
@click.argument('request_id', required=True)
def api_logs(request_id: str):
    """Stream a request's log."""
    from skypilot_tpu.client import sdk
    try:
        sdk.stream_and_get(request_id)
    except sdk.RequestFailedError as e:
        raise click.ClickException(str(e))


@api.command(name='cancel')
@click.argument('request_id', required=True)
def api_cancel(request_id: str):
    """Cancel a queued/running request."""
    from skypilot_tpu.client import sdk
    click.echo('cancelled' if sdk.api_cancel(request_id) else
               'not cancellable')


def main():
    return cli()


if __name__ == '__main__':
    main()
