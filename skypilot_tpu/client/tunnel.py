"""Client side of the TCP-over-websocket tunnel to cluster ports.

Reference analog: sky/templates/websocket_proxy.py — the ProxyCommand
script that carries ssh over the API server's websocket endpoint. Here:
a local TCP listener; every accepted connection gets its own websocket
to `/api/v1/tunnel?cluster=...&port=...` (authenticated with the same
bearer token as the SDK) and the two byte streams are pumped in both
directions. Usable as:

    skytpu tunnel mycluster --port 22 --local-port 2222 &
    ssh -p 2222 user@127.0.0.1
"""
from __future__ import annotations

import asyncio
from typing import Optional

import aiohttp

from skypilot_tpu import sky_logging
from skypilot_tpu.client import sdk as sync_sdk

logger = sky_logging.init_logger(__name__)


async def _pump_one(local_reader: asyncio.StreamReader,
                    local_writer: asyncio.StreamWriter,
                    server_url: str, cluster: str, port: int) -> None:
    ws_url = (f'{server_url}/api/v1/tunnel'
              f'?cluster={cluster}&port={port}')
    async with aiohttp.ClientSession() as session:
        try:
            ws = await session.ws_connect(ws_url,
                                          headers=sync_sdk._headers(),
                                          max_msg_size=4 * 1024 * 1024)
        except aiohttp.ClientError as e:
            logger.warning(f'tunnel connect failed: {e}')
            local_writer.close()
            return

        async def up() -> None:            # local tcp → ws
            while True:
                data = await local_reader.read(65536)
                if not data:
                    break
                await ws.send_bytes(data)
            await ws.close()

        async def down() -> None:          # ws → local tcp
            async for msg in ws:
                if msg.type == aiohttp.WSMsgType.BINARY:
                    local_writer.write(msg.data)
                    await local_writer.drain()
                elif msg.type in (aiohttp.WSMsgType.CLOSED,
                                  aiohttp.WSMsgType.ERROR):
                    break
            local_writer.close()

        await asyncio.gather(up(), down(), return_exceptions=True)


async def serve_tunnel(cluster: str, port: int, local_port: int,
                       url: Optional[str] = None,
                       ready_event: Optional[asyncio.Event] = None) -> None:
    """Listen on 127.0.0.1:local_port and proxy each connection."""
    # api_server_url does a synchronous health probe (requests.get,
    # 2 s timeout) — resolve it in a worker thread so an in-flight
    # tunnel on the same loop never stalls behind it.
    server_url = url or await asyncio.to_thread(
        sync_sdk.api_server_url, required=True)

    async def on_conn(reader, writer):
        await _pump_one(reader, writer, server_url, cluster, port)

    server = await asyncio.start_server(on_conn, '127.0.0.1', local_port)
    logger.info(f'tunnel: 127.0.0.1:{local_port} -> {cluster}:{port} '
                f'(via {server_url})')
    if ready_event is not None:
        ready_event.set()
    async with server:
        await server.serve_forever()


def run_tunnel(cluster: str, port: int, local_port: int,
               url: Optional[str] = None) -> None:
    """Blocking entry point (the CLI's)."""
    try:
        asyncio.run(serve_tunnel(cluster, port, local_port, url=url))
    except KeyboardInterrupt:
        pass
