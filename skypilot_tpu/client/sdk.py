"""Client SDK for the API server (reference analog: sky/client/sdk.py —
every call → HTTP POST → RequestId; stream_and_get to follow).

Two modes:
  - direct (default): the top-level `skypilot_tpu.*` functions run
    in-process — hermetic, no server needed.
  - server: these functions POST to a running API server and poll/stream
    the persisted request. Activated by SKYTPU_API_SERVER_URL or a healthy
    endpoint recorded by `skytpu api start`.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

import requests as requests_http

from skypilot_tpu.utils import knobs

from skypilot_tpu import sky_logging
from skypilot_tpu.server import requests_lib as server_requests

logger = sky_logging.init_logger(__name__)

DEFAULT_URL = 'http://127.0.0.1:46580'


def _token_path() -> str:
    return os.path.expanduser('~/.skytpu/api_token')


def _headers() -> dict:
    """Bearer auth when the server requires it (server/_api_token)."""
    token = knobs.get_str('SKYTPU_API_TOKEN')
    if not token:
        try:
            with open(_token_path(), 'r', encoding='utf-8') as f:
                token = f.read().strip()
        except OSError:
            token = ''
    return {'Authorization': f'Bearer {token}'} if token else {}


class ApiError(Exception):
    pass


class RequestFailedError(ApiError):
    def __init__(self, request_id: str, error: str):
        super().__init__(f'request {request_id} failed:\n{error}')
        self.request_id = request_id
        self.server_error = error


def endpoint_file() -> str:
    return os.path.join(server_requests.server_dir(), 'endpoint')


def login(url: str, token: Optional[str] = None) -> None:
    """Point this client at an API server persistently (the deploy story
    for the helm chart: `skytpu api login <url> --token <...>`).

    Writes the endpoint file every later CLI/SDK call resolves, and the
    bearer token to ~/.skytpu/api_token (0600). Health-checked first so a
    typo'd URL fails here, not on the next launch."""
    url = url.rstrip('/')
    if not _healthy(url):
        raise ApiError(f'No healthy API server at {url} '
                       f'(GET {url}/api/v1/health failed).')
    os.makedirs(os.path.dirname(endpoint_file()), exist_ok=True)
    with open(endpoint_file(), 'w', encoding='utf-8') as f:
        f.write(url)
    if token:
        os.makedirs(os.path.dirname(_token_path()), exist_ok=True)
        fd = os.open(_token_path(), os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                     0o600)
        with os.fdopen(fd, 'w', encoding='utf-8') as f:
            f.write(token)
    else:
        # Token-less login must CLEAR any previous server's token — it
        # would otherwise keep riding along to the new host.
        try:
            os.remove(_token_path())
        except OSError:
            pass


def api_server_url(required: bool = False) -> Optional[str]:
    url = knobs.get_str('SKYTPU_API_SERVER_URL')
    if not url and os.path.exists(endpoint_file()):
        with open(endpoint_file(), 'r', encoding='utf-8') as f:
            url = f.read().strip()
    if url and _healthy(url):
        return url
    if required:
        raise ApiError(
            'No healthy API server. Start one with `skytpu api start` or '
            'set SKYTPU_API_SERVER_URL.')
    return None


def _healthy(url: str) -> bool:
    try:
        r = requests_http.get(f'{url}/api/v1/health', timeout=2)
        return r.status_code == 200
    except requests_http.RequestException:
        return False


# ---------------------------------------------------------------------------
# Server lifecycle
# ---------------------------------------------------------------------------

def api_start(host: str = '127.0.0.1', port: int = 46580,
              foreground: bool = False) -> str:
    url = f'http://{host}:{port}'
    if _healthy(url):
        return url
    if foreground:
        from skypilot_tpu.server import server as server_lib
        server_lib.run(host, port)
        return url
    log = os.path.join(server_requests.server_dir(), 'server.log')
    with open(log, 'a', encoding='utf-8') as f:
        subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.server.server',
             '--host', host, '--port', str(port)],
            stdout=f, stderr=f, start_new_session=True)
    for _ in range(50):
        if _healthy(url):
            return url
        time.sleep(0.2)
    raise ApiError(f'API server failed to start; see {log}')


def api_stop() -> bool:
    pid_file = os.path.join(server_requests.server_dir(), 'server.pid')
    if not os.path.exists(pid_file):
        return False
    with open(pid_file, 'r', encoding='utf-8') as f:
        pid = int(f.read().strip() or 0)
    try:
        os.kill(pid, 15)
    except (OSError, ProcessLookupError):
        return False
    for p in (pid_file, endpoint_file()):
        try:
            os.remove(p)
        except OSError:
            pass
    return True


def api_info() -> Dict[str, Any]:
    url = api_server_url()
    if url is None:
        return {'status': 'stopped'}
    r = requests_http.get(f'{url}/api/v1/health', timeout=5)
    info = r.json()
    info['url'] = url
    return info


# ---------------------------------------------------------------------------
# Request plumbing
# ---------------------------------------------------------------------------

def prepare_payload(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Attach the client's config view to a request payload.

    The server runs with ITS config; the request must carry the client's
    view so e.g. a client-side `workspace:`/`kubernetes:` setting governs
    the request (per-request isolation happens in the runner subprocess).
    Shared by the sync and async SDKs so their request protocol can't
    diverge."""
    if '_config_overrides' not in payload:
        from skypilot_tpu import config as config_lib
        client_cfg = config_lib.to_dict()
        if client_cfg:
            payload = dict(payload, _config_overrides=client_cfg)
    return payload


def submit(name: str, payload: Dict[str, Any],
           url: Optional[str] = None) -> str:
    url = url or api_server_url(required=True)
    payload = prepare_payload(payload)
    r = requests_http.post(f'{url}/api/v1/{name}', json=payload,
                            headers=_headers(), timeout=30)
    if r.status_code != 200:
        raise ApiError(f'{name}: HTTP {r.status_code}: {r.text}')
    return r.json()['request_id']


def get(request_id: str, url: Optional[str] = None) -> Any:
    """Block until the request finishes; return its result (or raise)."""
    url = url or api_server_url(required=True)
    while True:
        r = requests_http.get(f'{url}/api/v1/get',
                              params={'request_id': request_id, 'wait': '1'},
                              headers=_headers(), timeout=300)
        if r.status_code == 404:
            raise ApiError(f'no request {request_id}')
        if r.status_code != 200:
            raise ApiError(f'get: HTTP {r.status_code}: {r.text}')
        rec = r.json()
        status = server_requests.RequestStatus(rec['status'])
        if status.is_terminal():
            break
    if status == server_requests.RequestStatus.SUCCEEDED:
        return rec['result']
    if status == server_requests.RequestStatus.CANCELLED:
        raise ApiError(f'request {request_id} was cancelled')
    raise RequestFailedError(request_id, rec.get('error') or '')


def stream_and_get(request_id: str, url: Optional[str] = None,
                   out=None) -> Any:
    """Stream the request's log to `out` (stdout default), then get()."""
    url = url or api_server_url(required=True)
    out = out or sys.stdout
    with requests_http.get(f'{url}/api/v1/stream',
                           params={'request_id': request_id},
                           headers=_headers(), stream=True,
                           timeout=None) as r:
        for chunk in r.iter_content(chunk_size=None, decode_unicode=True):
            if chunk:
                out.write(chunk)
                out.flush()
    return get(request_id, url)


def api_cancel(request_id: str, url: Optional[str] = None) -> bool:
    url = url or api_server_url(required=True)
    r = requests_http.post(f'{url}/api/v1/request_cancel',
                           json={'request_id': request_id},
                           headers=_headers(), timeout=30)
    if r.status_code != 200:
        raise ApiError(f'cancel: HTTP {r.status_code}: {r.text}')
    return bool(r.json().get('cancelled'))


def api_list_requests(url: Optional[str] = None) -> List[Dict[str, Any]]:
    url = url or api_server_url(required=True)
    r = requests_http.get(f'{url}/api/v1/requests', headers=_headers(),
                          timeout=30)
    if r.status_code != 200:
        raise ApiError(f'requests: HTTP {r.status_code}: {r.text}')
    return r.json()


# ---------------------------------------------------------------------------
# Typed RPCs (server-mode equivalents of the top-level SDK calls)
# ---------------------------------------------------------------------------

def launch(task, cluster_name: Optional[str] = None, *,
           detach_run: bool = True, down: bool = False, dryrun: bool = False,
           retry_until_up: bool = False, stream: bool = True) -> Any:
    payload = {'task': task.to_yaml_config(), 'cluster_name': cluster_name,
               'detach_run': detach_run, 'down': down, 'dryrun': dryrun,
               'retry_until_up': retry_until_up}
    rid = submit('launch', payload)
    return stream_and_get(rid) if stream else get(rid)


def exec(task, cluster_name: str, *,  # pylint: disable=redefined-builtin
         detach_run: bool = True) -> Any:
    rid = submit('exec', {'task': task.to_yaml_config(),
                          'cluster_name': cluster_name,
                          'detach_run': detach_run})
    return get(rid)


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False, all_workspaces: bool = False) -> Any:
    from skypilot_tpu import workspaces
    return get(submit('status', {
        'cluster_names': cluster_names,
        'refresh': refresh,
        'all_workspaces': all_workspaces,
        # The server filters by the CLIENT's workspace, not its own env.
        'workspace': workspaces.get_active_workspace(),
    }))


def queue(cluster_name: str) -> Any:
    return get(submit('queue', {'cluster_name': cluster_name}))


def down(cluster_name: str) -> Any:
    return get(submit('down', {'cluster_name': cluster_name}))


def stop(cluster_name: str) -> Any:
    return get(submit('stop', {'cluster_name': cluster_name}))


def start(cluster_name: str) -> Any:
    return get(submit('start', {'cluster_name': cluster_name}))


def cancel(cluster_name: str, job_ids: Optional[List[int]] = None) -> Any:
    return get(submit('cancel', {'cluster_name': cluster_name,
                                 'job_ids': job_ids}))


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True) -> Any:
    rid = submit('logs', {'cluster_name': cluster_name, 'job_id': job_id,
                          'follow': follow})
    return stream_and_get(rid)
