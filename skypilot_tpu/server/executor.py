"""Request scheduler: claims NEW requests and spawns runner subprocesses.

Reference analog: sky/server/requests/executor.py (RequestQueue:112,
RequestWorker:168, LONG/SHORT schedule types with guaranteed+burstable
parallelism executor.py:173-188). Here: a scheduler thread per schedule
type; LONG requests (launch/down/...) get a bounded pool so provisioning
bursts cannot starve the box, SHORT requests (status/queue/...) a wider one.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List

from skypilot_tpu import sky_logging
from skypilot_tpu.server import requests_lib
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger(__name__)

LONG_PARALLELISM = max(2, min(8, (os.cpu_count() or 4) // 2))
SHORT_PARALLELISM = 16

# 'process' (default): one runner subprocess per request — isolation,
# per-request logs, kill()-based cancel, per-request config overrides.
# 'thread': run handlers on scheduler-owned threads in the server process —
# the consolidation mode for low-footprint deployments and load tests;
# trades process isolation (and mid-flight cancel) for ~100x cheaper
# request startup. Reference analog: consolidation mode
# (sky/serve/serve_utils.py is_consolidation_mode).
EXECUTOR_MODE_ENV = 'SKYTPU_EXECUTOR_MODE'


class _InlineJob:
    """Popen-compatible (poll) wrapper for a thread-mode request."""

    def __init__(self, rec: Dict) -> None:
        self._thread = threading.Thread(target=self._run, args=(rec,),
                                        daemon=True)
        self._thread.start()

    def poll(self):
        return None if self._thread.is_alive() else 0

    @staticmethod
    def _run(rec: Dict) -> None:
        import traceback
        from skypilot_tpu import config as config_lib
        from skypilot_tpu.observe import spans
        from skypilot_tpu.observe import trace
        from skypilot_tpu.server import registry
        # pid 0, NOT os.getpid(): the recorded pid is cancel_request's
        # kill target, and in thread mode that would be the API server
        # itself. 0 marks "no killable process" (cancel then refuses).
        requests_lib.set_running(rec['request_id'], 0)
        handler, _ = registry.HANDLERS[rec['name']]
        # Contextvar only (NOT trace.adopt / spans.adopt_parent): the
        # env is shared with every sibling request thread in this
        # process, so writing it would cross-contaminate their traces
        # and span parentage. Threads start with a fresh context, so
        # the sets below scope to this request.
        if rec.get('trace_id'):
            trace.set_trace(rec['trace_id'])
        spans.set_parent(rec['request_id'])
        try:
            with spans.span('server.run', attrs={'name': rec['name'],
                                                 'mode': 'thread'}):
                payload = rec['payload']
                with config_lib.override(
                        payload.get('_config_overrides') or {}):
                    result = handler(payload)
        except BaseException:  # pylint: disable=broad-except
            requests_lib.set_failed(rec['request_id'],
                                    traceback.format_exc())
            return
        requests_lib.set_result(rec['request_id'], result)


class Scheduler:

    def __init__(self) -> None:
        self._procs: Dict[str, List[subprocess.Popen]] = {
            requests_lib.LONG: [], requests_lib.SHORT: []}
        self._limits = {requests_lib.LONG: LONG_PARALLELISM,
                        requests_lib.SHORT: SHORT_PARALLELISM}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def start(self) -> None:
        for sched_type in (requests_lib.LONG, requests_lib.SHORT):
            t = threading.Thread(target=self._loop, args=(sched_type,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    def _loop(self, sched_type: str) -> None:
        procs = self._procs[sched_type]
        limit = self._limits[sched_type]
        while not self._stop.is_set():
            procs[:] = [p for p in procs if p.poll() is None]
            spawned = False
            if len(procs) < limit:
                rec = requests_lib.next_pending(sched_type)
                if rec is not None:
                    procs.append(self._spawn(rec))
                    spawned = True
            if not spawned:
                time.sleep(0.2)

    def _spawn(self, rec):
        logger.info(f'request {rec["request_id"]} ({rec["name"]}) starting')
        if knobs.get_enum(EXECUTOR_MODE_ENV) == 'thread':
            return _InlineJob(rec)
        return subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.server.request_runner',
             '--request-id', rec['request_id']],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True)


def cancel_request(request_id: str) -> bool:
    """Kill the runner (if running) and mark the record CANCELLED.

    Thread-mode requests (pid recorded as 0) have no killable process:
    once RUNNING they are uncancellable and this returns False; queued
    ones cancel normally."""
    rec = requests_lib.get(request_id)
    if rec is None:
        return False
    status = requests_lib.RequestStatus(rec['status'])
    if status.is_terminal():
        return False
    pid = rec.get('pid')
    if status is requests_lib.RequestStatus.RUNNING and not pid:
        return False
    if pid:
        try:
            os.killpg(pid, signal.SIGTERM)
        except (OSError, ProcessLookupError):
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
    requests_lib.set_cancelled(rec['request_id'])
    return True
