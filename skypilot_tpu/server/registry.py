"""Request name → handler mapping (reference analog: the per-endpoint
bodies in sky/server/server.py routed into sky/execution.py / sky/core.py).

Handlers take the JSON payload dict and return a JSON-able result. They run
inside the per-request runner subprocess, so blocking is fine.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

from skypilot_tpu.server import requests_lib


def _launch(payload: Dict[str, Any]) -> Dict[str, Any]:
    import skypilot_tpu as sky
    task = sky.Task.from_yaml_config(payload['task'])
    job_id, handle = sky.launch(
        task,
        cluster_name=payload.get('cluster_name'),
        dryrun=payload.get('dryrun', False),
        detach_run=payload.get('detach_run', True),
        down=payload.get('down', False),
        retry_until_up=payload.get('retry_until_up', False),
        no_setup=payload.get('no_setup', False),
    )
    return {'job_id': job_id,
            'cluster_name': handle.cluster_name if handle else None}


def _exec(payload: Dict[str, Any]) -> Dict[str, Any]:
    import skypilot_tpu as sky
    task = sky.Task.from_yaml_config(payload['task'])
    job_id, handle = sky.exec(task, payload['cluster_name'],
                              detach_run=payload.get('detach_run', True))
    return {'job_id': job_id,
            'cluster_name': handle.cluster_name if handle else None}


def _status(payload: Dict[str, Any]) -> Any:
    from skypilot_tpu import core
    records = core.status(payload.get('cluster_names'),
                          refresh=payload.get('refresh', False),
                          all_workspaces=payload.get('all_workspaces',
                                                     False),
                          workspace=payload.get('workspace'))
    out = []
    for r in records:
        r = dict(r)
        r.pop('handle', None)          # not JSON-able; CLI renders the rest
        out.append(r)
    return out


def _start(payload):
    from skypilot_tpu import core
    core.start(payload['cluster_name'])
    return {'cluster_name': payload['cluster_name']}


def _stop(payload):
    from skypilot_tpu import core
    core.stop(payload['cluster_name'])
    return {'cluster_name': payload['cluster_name']}


def _down(payload):
    from skypilot_tpu import core
    core.down(payload['cluster_name'])
    return {'cluster_name': payload['cluster_name']}


def _autostop(payload):
    from skypilot_tpu import core
    core.autostop(payload['cluster_name'], payload.get('idle_minutes'),
                  payload.get('down', False))
    return {}


def _queue(payload):
    from skypilot_tpu import core
    return core.queue(payload['cluster_name'])


def _cancel(payload):
    from skypilot_tpu import core
    return {'cancelled': core.cancel(payload['cluster_name'],
                                     payload.get('job_ids'))}


def _logs(payload):
    """Job logs print to this request's own log file; the client streams
    them via /api/v1/stream?request_id=... (reference: sky api logs)."""
    from skypilot_tpu import core
    rc = core.tail_logs(payload['cluster_name'], payload.get('job_id'),
                        follow=payload.get('follow', False))
    return {'returncode': rc}


def _check(payload):
    from skypilot_tpu import check as check_lib
    clouds = check_lib.check(quiet=True)
    return {'enabled_clouds': [str(c) for c in clouds]}


def _cost_report(payload):
    from skypilot_tpu import core
    return core.cost_report()


def _jobs_launch(payload):
    import skypilot_tpu as sky
    from skypilot_tpu import jobs
    task = sky.Task.from_yaml_config(payload['task'])
    job_id = jobs.launch(task, name=payload.get('name'))
    return {'job_id': job_id}


def _jobs_queue(payload):
    from skypilot_tpu import jobs
    rows = jobs.queue(name=payload.get('name'),
                      skip_finished=payload.get('skip_finished', False))
    out = []
    for r in rows:
        r = dict(r)
        r['status'] = r['status'].value
        r.pop('task_config', None)
        out.append(r)
    return out


def _jobs_cancel(payload):
    from skypilot_tpu import jobs
    return {'cancelled': jobs.cancel(job_ids=payload.get('job_ids'),
                                     name=payload.get('name'),
                                     all_jobs=payload.get('all', False))}


def _jobs_logs(payload):
    from skypilot_tpu import jobs
    rc = jobs.tail_logs(payload.get('job_id'),
                        follow=payload.get('follow', False),
                        controller=payload.get('controller', False))
    return {'returncode': rc}


def _serve_up(payload):
    import skypilot_tpu as sky
    from skypilot_tpu import serve
    task = sky.Task.from_yaml_config(payload['task'])
    return serve.up(task, service_name=payload.get('service_name'))


def _serve_status(payload):
    from skypilot_tpu import serve
    out = []
    for r in serve.status(payload.get('service_names')):
        r = dict(r)
        r['status'] = r['status'].value
        r['replicas'] = [dict(rep, status=rep['status'].value)
                         for rep in r['replicas']]
        out.append(r)
    return out


def _serve_update(payload):
    import skypilot_tpu as sky
    from skypilot_tpu import serve
    task = sky.Task.from_yaml_config(payload['task'])
    return serve.update(task, payload['service_name'],
                        mode=payload.get('mode', 'rolling'))


def _serve_down(payload):
    from skypilot_tpu import serve
    serve.down(payload['service_name'], purge=payload.get('purge', False))
    return {'service_name': payload['service_name']}


def _list_accelerators(payload):
    import dataclasses
    from skypilot_tpu.catalog import tpu_catalog
    offers = tpu_catalog.list_accelerators(
        name_filter=payload.get('name_filter'),
        region_filter=payload.get('region_filter'),
        max_chips=payload.get('max_chips'))
    return {name: [dataclasses.asdict(o) for o in infos]
            for name, infos in offers.items()}


# name -> (handler, schedule_type)
HANDLERS: Dict[str, Tuple[Callable[[Dict[str, Any]], Any], str]] = {
    'launch': (_launch, requests_lib.LONG),
    'exec': (_exec, requests_lib.LONG),
    'start': (_start, requests_lib.LONG),
    'stop': (_stop, requests_lib.LONG),
    'down': (_down, requests_lib.LONG),
    'status': (_status, requests_lib.SHORT),
    'autostop': (_autostop, requests_lib.SHORT),
    'queue': (_queue, requests_lib.SHORT),
    'cancel': (_cancel, requests_lib.SHORT),
    'logs': (_logs, requests_lib.SHORT),
    'check': (_check, requests_lib.SHORT),
    'cost_report': (_cost_report, requests_lib.SHORT),
    'list_accelerators': (_list_accelerators, requests_lib.SHORT),
    # Managed jobs plane (reference: sky/jobs/server/ routes). jobs_launch is
    # SHORT because it only writes the DB row and spawns the controller —
    # provisioning happens in the controller process, not the request worker.
    'jobs_launch': (_jobs_launch, requests_lib.SHORT),
    'jobs_queue': (_jobs_queue, requests_lib.SHORT),
    'jobs_cancel': (_jobs_cancel, requests_lib.SHORT),
    'jobs_logs': (_jobs_logs, requests_lib.SHORT),
    # Serve plane (reference: sky/serve/server/ routes). serve_up only
    # records state + spawns the controller, so SHORT; serve_down tears
    # down replicas synchronously, so LONG.
    'serve_up': (_serve_up, requests_lib.SHORT),
    'serve_status': (_serve_status, requests_lib.SHORT),
    'serve_update': (_serve_update, requests_lib.SHORT),
    'serve_down': (_serve_down, requests_lib.LONG),
}
