"""API server plane: persisted async requests over aiohttp.

Reference analog: sky/server/ (FastAPI app server.py:702-2087, request
executor sky/server/requests/executor.py). Same architecture, TPU-repo
dependencies: aiohttp instead of FastAPI/uvicorn, one subprocess per
request (isolation + per-request logs + kill-based cancellation), sqlite
request records so `skytpu api logs/get` can replay any request.
"""
