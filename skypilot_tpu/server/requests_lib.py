"""Persisted API request records (reference analog: sky/server/requests/requests.py).

Each RPC becomes a row; the executor runs it in a subprocess; the row
carries status, JSON payload/result, the runner pid (for cancellation) and
the per-request log path (for `skytpu api logs`).
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import time
import uuid
from typing import Any, Dict, List, Optional

from skypilot_tpu.observe import journal as journal_lib
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.observe import spans as spans_lib
from skypilot_tpu.observe import trace as trace_lib
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import sqlite_utils

SHORT = 'SHORT'
LONG = 'LONG'

# How long a request sat NEW in the queue before a dispatcher claimed
# it — the first thing to look at when "the server feels slow": a tall
# tail here means the LONG pool is saturated, not that handlers got
# slower. Label values are the two schedule types — bounded.
_QUEUE_WAIT = metrics_lib.histogram(
    'skytpu_server_queue_wait_seconds',
    'Wait between request creation and dispatcher claim.',
    labels={'schedule_type': (LONG, SHORT)})


class RequestStatus(str, enum.Enum):
    NEW = 'NEW'
    RUNNING = 'RUNNING'
    SUCCEEDED = 'SUCCEEDED'
    FAILED = 'FAILED'
    CANCELLED = 'CANCELLED'

    def is_terminal(self) -> bool:
        return self in (RequestStatus.SUCCEEDED, RequestStatus.FAILED,
                        RequestStatus.CANCELLED)


def server_dir() -> str:
    d = os.path.expanduser(knobs.get_str('SKYTPU_SERVER_DIR'))
    os.makedirs(d, exist_ok=True)
    os.makedirs(os.path.join(d, 'logs'), exist_ok=True)
    return d


def _db_path() -> str:
    return os.path.join(server_dir(), 'requests.db')


def _conn() -> sqlite3.Connection:
    conn = sqlite_utils.connect_wal(_db_path())
    conn.execute("""CREATE TABLE IF NOT EXISTS requests (
        request_id TEXT PRIMARY KEY,
        name TEXT,
        payload TEXT,
        status TEXT,
        schedule_type TEXT,
        result TEXT,
        error TEXT,
        pid INTEGER,
        user TEXT,
        created_at REAL,
        started_at REAL,
        finished_at REAL,
        trace_id TEXT)""")
    try:
        conn.execute('ALTER TABLE requests ADD COLUMN trace_id TEXT')
    except sqlite3.OperationalError:
        pass   # pre-observability DB already migrated
    return conn


def log_path(request_id: str) -> str:
    return os.path.join(server_dir(), 'logs', f'{request_id}.log')


def create(name: str, payload: Dict[str, Any], schedule_type: str = LONG,
           user: str = '', trace_id: Optional[str] = None) -> str:
    """Persist a request row. This is trace INGRESS: every request gets
    a correlation id here (caller-provided, ambient, or freshly minted)
    that then follows the work through the runner subprocess, the
    managed-job controller, recovery, and down to the slice driver's
    gang env — the join key across journal, timeline and usage."""
    request_id = uuid.uuid4().hex[:16]
    trace_id = trace_id or trace_lib.get() or trace_lib.new_trace_id()
    with _conn() as conn:
        conn.execute(
            'INSERT INTO requests (request_id, name, payload, status, '
            'schedule_type, user, created_at, trace_id) '
            'VALUES (?,?,?,?,?,?,?,?)',
            (request_id, name, json.dumps(payload), RequestStatus.NEW.value,
             schedule_type, user, time.time(), trace_id))
    journal_lib.record_event('api_request', entity=request_id,
                             trace_id=trace_id, data={'name': name})
    return request_id


def get(request_id: str) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        row = conn.execute(
            'SELECT request_id, name, payload, status, schedule_type, '
            'result, error, pid, user, created_at, started_at, '
            'finished_at, trace_id '
            'FROM requests WHERE request_id LIKE ?',
            (request_id + '%',)).fetchone()
    if row is None:
        return None
    keys = ['request_id', 'name', 'payload', 'status', 'schedule_type',
            'result', 'error', 'pid', 'user', 'created_at', 'started_at',
            'finished_at', 'trace_id']
    rec = dict(zip(keys, row))
    rec['payload'] = json.loads(rec['payload']) if rec['payload'] else {}
    rec['result'] = json.loads(rec['result']) if rec['result'] else None
    return rec


def list_requests(limit: int = 100) -> List[Dict[str, Any]]:
    with _conn() as conn:
        rows = conn.execute(
            'SELECT request_id, name, status, user, created_at, finished_at '
            'FROM requests ORDER BY created_at DESC LIMIT ?',
            (limit,)).fetchall()
    keys = ['request_id', 'name', 'status', 'user', 'created_at',
            'finished_at']
    return [dict(zip(keys, r)) for r in rows]


def next_pending(schedule_type: str) -> Optional[Dict[str, Any]]:
    """Atomically claim the oldest unclaimed NEW request of this type.

    Claimed = started_at set (NEW→RUNNING happens later, in the runner).
    The claim must not race: a SELECT-then-guarded-UPDATE that can land
    on a just-claimed row returns None while work is still queued, and
    the scheduler's idle backoff then paces a busy queue at 5 claims/s
    (caught by tests/load_tests/test_load_on_server.py).
    sqlite_utils.immediate takes sqlite's single write lock before the
    SELECT (and fails loudly on an already-open transaction), so no
    other dispatcher can claim between our SELECT and UPDATE — same
    atomicity as the previous UPDATE...RETURNING form, but portable to
    sqlite < 3.35."""
    conn = _conn()
    now = time.time()
    with sqlite_utils.immediate(conn):
        row = conn.execute(
            'SELECT request_id, created_at FROM requests WHERE status=? '
            'AND schedule_type=? AND started_at IS NULL '
            'ORDER BY created_at LIMIT 1',
            (RequestStatus.NEW.value, schedule_type)).fetchone()
        if row is None:
            return None
        conn.execute('UPDATE requests SET started_at=? '
                     'WHERE request_id=?', (now, row[0]))
    rec = get(row[0])
    if row[1] is not None:
        _QUEUE_WAIT.observe(max(0.0, now - row[1]),
                            schedule_type=schedule_type)
        # The queue wait starts in the API server's ingress and ends in
        # this dispatcher thread — a retroactive span (the scoped form
        # cannot cross the gap). Parent = the request's root span,
        # whose id IS the request id by contract, so no cross-process
        # id exchange is needed.
        spans_lib.record('server.queue_wait', start_wall=row[1],
                         duration=max(0.0, now - row[1]),
                         trace_id=rec.get('trace_id') if rec else None,
                         parent_id=row[0],
                         attrs={'schedule_type': schedule_type})
    return rec


def set_running(request_id: str, pid: int) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE requests SET status=?, pid=? WHERE request_id=?',
            (RequestStatus.RUNNING.value, pid, request_id))


def _journal_finished(request_id: str, status: RequestStatus,
                      reason: Optional[str] = None) -> None:
    journal_lib.record_event('api_request_finished', entity=request_id,
                             reason=reason,
                             data={'status': status.value})
    # The request's ROOT span, recorded retroactively at the terminal
    # write (its endpoints span the server and runner processes).
    # span_id == request_id by contract: the dispatcher's queue-wait
    # span and the runner's server.run span parent under it from other
    # processes with no id exchange.
    # Targeted read: get() would deserialize the payload AND the
    # result blob set_result just serialized — an extra multi-MB JSON
    # parse per finished request for three scalar columns.
    with _conn() as conn:
        row = conn.execute(
            'SELECT created_at, name, trace_id FROM requests '
            'WHERE request_id = ?', (request_id,)).fetchone()
    if row is None or not row[0]:
        return
    spans_lib.record('api.request', start_wall=row[0],
                     duration=max(0.0, time.time() - row[0]),
                     trace_id=row[2],
                     span_id=request_id,
                     attrs={'name': row[1],
                            'status': status.value})


def set_result(request_id: str, result: Any) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE requests SET status=?, result=?, finished_at=? '
            'WHERE request_id=?',
            (RequestStatus.SUCCEEDED.value, json.dumps(result), time.time(),
             request_id))
    _journal_finished(request_id, RequestStatus.SUCCEEDED)


def set_failed(request_id: str, error: str) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE requests SET status=?, error=?, finished_at=? '
            'WHERE request_id=?',
            (RequestStatus.FAILED.value, error, time.time(), request_id))
    # The full traceback stays on the row; the journal gets its last
    # line — the exception itself — enough to class the failure when
    # scanning a trace.
    last_line = ((error or '').strip().splitlines() or [''])[-1][:200]
    _journal_finished(request_id, RequestStatus.FAILED,
                      reason=last_line or None)


def set_cancelled(request_id: str) -> None:
    with _conn() as conn:
        conn.execute(
            'UPDATE requests SET status=?, finished_at=? WHERE request_id=?',
            (RequestStatus.CANCELLED.value, time.time(), request_id))
    _journal_finished(request_id, RequestStatus.CANCELLED)


def gc_requests(max_age_seconds: float = 24 * 3600) -> int:
    """Drop terminal request rows (and their logs) older than max_age.

    Reference analog: the server's request GC (VERDICT r1 weak item 10 —
    without it the requests DB and log dir grow forever).
    """
    cutoff = time.time() - max_age_seconds
    terminal = tuple(s.value for s in RequestStatus if s.is_terminal())
    ph = ','.join('?' * len(terminal))
    with _conn() as conn:
        rows = conn.execute(
            f'SELECT request_id FROM requests WHERE status IN ({ph}) '
            f'AND finished_at IS NOT NULL AND finished_at < ?',
            (*terminal, cutoff)).fetchall()
        ids = [r[0] for r in rows]
        # Chunk: sqlite caps SQL variables (999 traditionally); the first
        # GC pass on a long-lived server can see thousands of rows.
        for i in range(0, len(ids), 500):
            chunk = ids[i:i + 500]
            idph = ','.join('?' * len(chunk))
            conn.execute(
                f'DELETE FROM requests WHERE request_id IN ({idph})', chunk)
    for rid in ids:
        try:
            os.remove(log_path(rid))
        except OSError:
            pass
    return len(ids)


def metrics_snapshot() -> Dict[str, Any]:
    """Aggregates for the /metrics endpoint."""
    with _conn() as conn:
        counts = conn.execute(
            'SELECT name, status, COUNT(*) FROM requests '
            'GROUP BY name, status').fetchall()
        durs = conn.execute(
            'SELECT name, COUNT(*), SUM(finished_at - started_at) '
            'FROM requests WHERE finished_at IS NOT NULL AND '
            'started_at IS NOT NULL GROUP BY name').fetchall()
    return {
        'counts': [(n, s, c) for n, s, c in counts],
        'durations': [(n, c, t or 0.0) for n, c, t in durs],
    }
