"""Per-request runner subprocess (reference analog: the executor worker
process in sky/server/requests/executor.py — here one process per request,
which gives isolation, per-request logs and kill()-based cancellation).
"""
from __future__ import annotations

import argparse
import os
import sys
import traceback


def main() -> None:
    parser = argparse.ArgumentParser(prog='request_runner')
    parser.add_argument('--request-id', required=True)
    args = parser.parse_args()

    from skypilot_tpu.server import registry, requests_lib

    rec = requests_lib.get(args.request_id)
    if rec is None:
        print(f'unknown request {args.request_id}', file=sys.stderr)
        sys.exit(2)

    # Adopt the trace minted at ingress: contextvar for this process's
    # journal/timeline/usage calls, env for every subprocess the handler
    # spawns (jobs controller, serve controller, backend runners).
    from skypilot_tpu.observe import trace
    trace.adopt(rec.get('trace_id'))

    log = open(requests_lib.log_path(rec['request_id']), 'a', buffering=1,
               encoding='utf-8')
    os.dup2(log.fileno(), sys.stdout.fileno())
    os.dup2(log.fileno(), sys.stderr.fileno())

    requests_lib.set_running(rec['request_id'], os.getpid())
    handler, _ = registry.HANDLERS[rec['name']]
    # The executor-run span: everything the handler does (optimizer,
    # provisioning, the slice driver via subprocess env) parents under
    # it, and it parents under the request's root span (span id ==
    # request id). adopt_parent exports the env carrier — this is a
    # dedicated per-request process, so process-wide adoption is safe
    # (the thread-mode executor must NOT do this; see executor.py).
    from skypilot_tpu.observe import spans
    try:
        with spans.span('server.run', parent_id=rec['request_id'],
                        attrs={'name': rec['name']}) as run_span:
            spans.adopt_parent(run_span.span_id)
            # Per-request config isolation (reference analog:
            # sky/utils/context.py contextvars): the client's config
            # overrides apply to THIS request only — the subprocess
            # boundary guarantees no bleed into sibling requests.
            from skypilot_tpu import config as config_lib
            payload = rec['payload']
            with config_lib.override(
                    payload.get('_config_overrides') or {}):
                result = handler(payload)
    except SystemExit as e:
        if e.code in (None, 0):
            requests_lib.set_result(rec['request_id'], None)
            spans.flush(timeout=2.0)
            return
        requests_lib.set_failed(rec['request_id'], f'exit code {e.code}')
        spans.flush(timeout=2.0)
        raise
    except BaseException:  # pylint: disable=broad-except
        requests_lib.set_failed(rec['request_id'], traceback.format_exc())
        # The write-behind span queue lives on a daemon thread: drain
        # it before the dedicated runner process exits or the run's
        # spans die with it.
        spans.flush(timeout=2.0)
        sys.exit(1)
    requests_lib.set_result(rec['request_id'], result)
    spans.flush(timeout=2.0)


if __name__ == '__main__':
    main()
