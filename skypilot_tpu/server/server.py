"""aiohttp API server (reference analog: sky/server/server.py FastAPI app).

Endpoints (all JSON):
  GET  /api/v1/health                  — liveness + version
  POST /api/v1/{name}                  — enqueue request → {request_id}
  GET  /api/v1/get?request_id=&wait=1  — request record (optionally block)
  GET  /api/v1/stream?request_id=      — chunked log streaming (follows
                                         until the request finishes)
  GET  /api/v1/requests                — list request records
  POST /api/v1/request_cancel          — cancel {request_id}

Run: `skytpu api start` (daemonized) or
`python -m skypilot_tpu.server.server --port 46580` (foreground).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
from typing import Any

from aiohttp import web

import skypilot_tpu
from skypilot_tpu import sky_logging
from skypilot_tpu.server import executor, registry, requests_lib
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger(__name__)

DEFAULT_PORT = 46580
_SERVER_START_TIME = 0.0
_GC_INTERVAL_SECONDS = 3600.0


def _json(data: Any, status: int = 200) -> web.Response:
    return web.json_response(data, status=status)


def _api_token() -> str:
    """Optional bearer-token auth (reference analog: sky/server/auth/).

    Empty string = auth disabled (the local single-user default). Set
    SKYTPU_API_TOKEN (or write ~/.skytpu/api_token) when exposing the
    server beyond localhost.
    """
    token = knobs.get_str('SKYTPU_API_TOKEN')
    if token:
        return token
    path = os.path.expanduser('~/.skytpu/api_token')
    try:
        with open(path, 'r', encoding='utf-8') as f:
            return f.read().strip()
    except OSError:
        return ''


@web.middleware
async def auth_middleware(request: web.Request, handler):
    # The HTML shell is public (it holds no data); its data endpoint and
    # everything else stays behind auth (/dashboard?token=... wires the
    # header in client-side).
    open_paths = ('/api/v1/health', '/dashboard')
    got = request.headers.get('Authorization', '')
    if not got:
        # Dashboard cookie (set by /dashboard?token= once, HttpOnly):
        # authenticates exactly like a bearer header in both auth modes.
        cookie = request.cookies.get('skytpu_dash', '')
        if cookie:
            got = f'Bearer {cookie}'

    # Two identity-resolving modes share one enforcement tail below:
    #  - SSO header trust (reference analog: sky/server/auth/ with
    #    oauth2-proxy): SKYTPU_AUTH_USER_HEADER names a header an
    #    authenticating reverse proxy in front sets (e.g.
    #    X-Auth-Request-Email). ONLY enable when the server is reachable
    #    exclusively through that proxy — the header is trusted as-is.
    #    The identity maps to the users-file entry of that name; unknown
    #    identities get SKYTPU_AUTH_DEFAULT_ROLE (default: no access).
    #  - Multi-user bearer tokens (users file present): token → user.
    trust_header = knobs.get_str('SKYTPU_AUTH_USER_HEADER')
    users = request.app['users']
    if trust_header or users:
        if request.path in open_paths:
            return await handler(request)
        from skypilot_tpu.users import rbac
        user = None
        if trust_header:
            identity = request.headers.get(trust_header, '')
            if identity:
                user = next((u for u in (users or {}).values()
                             if u.name == identity), None)
                if user is None:
                    raw = knobs.get_str('SKYTPU_AUTH_DEFAULT_ROLE')
                    if raw:
                        try:
                            user = rbac.User(name=identity,
                                             role=rbac.Role(raw.lower()))
                        except ValueError:
                            # A typo'd default role must read as "no
                            # default", not 500 every request.
                            logger.warning(
                                f'SKYTPU_AUTH_DEFAULT_ROLE={raw!r} is not '
                                f'a valid role; rejecting unknown '
                                f'identities.')
        else:
            user = rbac.resolve_user(got, users)
        if user is None:
            return _json({'error': 'unauthorized'}, status=401)
        if request.method == 'POST':
            # Fixed-path mutations (request_cancel) gate exactly like named
            # request submissions — a viewer must not cancel others' work.
            name = request.match_info.get('name') or \
                request.path.rsplit('/', 1)[-1]
            if not user.role.may_submit(name):
                return _json({'error': f'role {user.role.value!r} may not '
                                       f'submit {name!r}'}, status=403)
        request['user'] = user
        return await handler(request)

    # Single shared-token mode.
    token = request.app['api_token']
    if token and request.path not in open_paths:
        import hmac
        if not hmac.compare_digest(got, f'Bearer {token}'):
            return _json({'error': 'unauthorized'}, status=401)
    return await handler(request)


async def health(request: web.Request) -> web.Response:
    return _json({'status': 'healthy', 'version': skypilot_tpu.__version__,
                  'commit': knobs.get_str('SKYTPU_COMMIT')})


async def submit(request: web.Request) -> web.Response:
    name = request.match_info['name']
    if name not in registry.HANDLERS:
        return _json({'error': f'unknown request name {name!r}'}, status=404)
    try:
        payload = await request.json()
    except json.JSONDecodeError:
        payload = {}
    _, sched_type = registry.HANDLERS[name]
    user = request.get('user')
    user_name = user.name if user else request.headers.get('X-User', '')
    # Trace ingress: the id minted (or honored, via X-Skytpu-Trace-Id)
    # here follows the request through runner → controller → recovery →
    # slice driver, and is the join key for /v1/events. Validation
    # lives with the trace semantics (observe/trace.py): garbage falls
    # back to a minted id rather than propagating into DB rows and
    # child-process environments.
    from skypilot_tpu.observe import trace as trace_lib
    offered = request.headers.get('X-Skytpu-Trace-Id', '')
    trace_id = (offered if trace_lib.is_valid_trace_id(offered)
                else trace_lib.new_trace_id())
    # Off-loop: create() writes the requests DB and the shared journal
    # — both sqlite files other processes contend on.
    request_id = await asyncio.to_thread(
        requests_lib.create, name, payload, sched_type,
        user=user_name, trace_id=trace_id)
    return _json({'request_id': request_id, 'trace_id': trace_id})


async def get_request(request: web.Request) -> web.Response:
    request_id = request.query.get('request_id', '')
    wait = request.query.get('wait', '0') == '1'
    # Off-loop: every requests-DB read opens a sqlite connection (with
    # a retried WAL pragma that can sleep under contention) — polled
    # here per waiting client, it must never run on the event loop.
    rec = await asyncio.to_thread(requests_lib.get, request_id)
    if rec is None:
        return _json({'error': f'no request {request_id!r}'}, status=404)
    # Adaptive backoff: snappy for short requests, 1 Hz for long ones —
    # a fixed 0.2s poll per waiting client hammers sqlite under load.
    delay = 0.1
    while wait and not requests_lib.RequestStatus(rec['status']).is_terminal():
        await asyncio.sleep(delay)
        delay = min(delay * 1.5, 1.0)
        rec = await asyncio.to_thread(requests_lib.get, request_id)
    return _json(rec)


async def stream(request: web.Request) -> web.StreamResponse:
    request_id = request.query.get('request_id', '')
    rec = await asyncio.to_thread(requests_lib.get, request_id)
    if rec is None:
        return _json({'error': f'no request {request_id!r}'}, status=404)
    request_id = rec['request_id']
    path = requests_lib.log_path(request_id)

    resp = web.StreamResponse(
        headers={'Content-Type': 'text/plain; charset=utf-8'})
    await resp.prepare(request)
    pos = 0
    delay = 0.1
    while True:
        chunk = b''
        if os.path.exists(path):
            with open(path, 'rb') as f:
                f.seek(pos)
                chunk = f.read()
                pos = f.tell()
        if chunk:
            await resp.write(chunk)
        rec = await asyncio.to_thread(requests_lib.get, request_id)
        if rec is None or requests_lib.RequestStatus(
                rec['status']).is_terminal():
            # Drain whatever arrived between the read and the status check.
            if os.path.exists(path):
                with open(path, 'rb') as f:
                    f.seek(pos)
                    tail = f.read()
                if tail:
                    await resp.write(tail)
            break
        # Back off while idle; reset to snappy when bytes flow.
        if chunk:
            delay = 0.1
        else:
            delay = min(delay * 1.5, 1.0)
        await asyncio.sleep(delay)
    await resp.write_eof()
    return resp


async def list_requests(request: web.Request) -> web.Response:
    limit = int(request.query.get('limit', '100'))
    return _json(await asyncio.to_thread(requests_lib.list_requests,
                                         limit))


async def metrics(request: web.Request) -> web.Response:
    """Prometheus text exposition (reference: sky/metrics/utils.py:47-146).

    Two sources concatenated: DB-derived aggregates (request counts and
    durations survive process restarts because the requests table does)
    and the in-process observe registry (queue-wait histograms and
    whatever else this process instrumented). Served at both
    ``/metrics`` (scraper convention) and ``/api/v1/metrics``."""
    del request
    import time as time_lib
    # Off-loop: the snapshot is sqlite aggregation over the requests
    # table and must not stall in-flight handlers on a busy DB.
    snap = await asyncio.to_thread(requests_lib.metrics_snapshot)
    lines = [
        '# HELP skytpu_uptime_seconds API server uptime.',
        '# TYPE skytpu_uptime_seconds gauge',
        f'skytpu_uptime_seconds {time_lib.time() - _SERVER_START_TIME:.1f}',
        '# HELP skytpu_requests_total API requests by name and status.',
        '# TYPE skytpu_requests_total counter',
    ]
    for name, status, count in snap['counts']:
        lines.append(f'skytpu_requests_total{{name="{name}",'
                     f'status="{status}"}} {count}')
    lines += [
        '# HELP skytpu_request_duration_seconds_sum Total request runtime.',
        '# TYPE skytpu_request_duration_seconds_sum counter',
    ]
    for name, count, total in snap['durations']:
        lines.append(
            f'skytpu_request_duration_seconds_sum{{name="{name}"}} '
            f'{total:.3f}')
        lines.append(
            f'skytpu_request_duration_seconds_count{{name="{name}"}} '
            f'{count}')
    from skypilot_tpu.observe import metrics as metrics_lib
    registry_text = metrics_lib.render()
    body = '\n'.join(lines) + '\n' + registry_text
    return web.Response(text=body, content_type='text/plain')


async def events(request: web.Request) -> web.Response:
    """Trace-correlated event journal query (``/v1/events``): status
    transitions published by the guarded setters plus request and
    provisioning milestones, filterable by machine/entity/trace_id/
    kind/since/limit."""
    from skypilot_tpu.observe import journal as journal_lib
    try:
        kwargs = journal_lib.filters_from_query(request.query)
    except ValueError:
        return _json({'error': 'since/limit must be numbers'}, status=400)
    # Off-loop: the journal scan is sqlite I/O and can be large —
    # blocking here would stall every other in-flight handler.
    result = await asyncio.to_thread(journal_lib.query, **kwargs)
    return _json({'events': result})


async def traces(request: web.Request) -> web.Response:
    """One request's latency decomposition (``/v1/traces/<trace_id>``):
    the rooted span tree — ingress → queue wait → executor run →
    optimizer → per-zone provisioning on the control plane; LB pick →
    upstream → engine queue/prefill/decode on the serving plane —
    assembled from the ``spans`` table keyed by the trace id
    (docs/OBSERVABILITY.md#span-trees)."""
    from skypilot_tpu.observe import spans as spans_lib
    trace_id = request.match_info.get('trace_id', '')
    from skypilot_tpu.observe import trace as trace_lib
    if not trace_lib.is_valid_trace_id(trace_id):
        return _json({'error': f'bad trace id {trace_id!r}'}, status=400)
    # Off-loop: the tree read flushes the write-behind queue and scans
    # sqlite — neither may stall in-flight handlers.
    result = await asyncio.to_thread(spans_lib.tree, trace_id)
    return _json(result)


async def dashboard_page(request: web.Request) -> web.Response:
    # Token hygiene: ?token=... lands in access logs and browser history,
    # so it is accepted exactly once — swapped for an HttpOnly cookie and
    # stripped from the URL with a redirect. (Deprecated entry path; use
    # the cookie or an Authorization header directly.)
    token = request.query.get('token')
    if token:
        logger.warning('/dashboard?token=... is deprecated (tokens leak '
                       'into logs/history); the token was moved into an '
                       'HttpOnly cookie.')
        resp = web.Response(status=303, headers={'Location': '/dashboard'})
        resp.set_cookie('skytpu_dash', token, httponly=True,
                        samesite='Strict', path='/dashboard')
        return resp
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'dashboard', 'index.html')
    with open(path, 'r', encoding='utf-8') as f:
        return web.Response(text=f.read(), content_type='text/html')


async def dashboard_summary(request: web.Request) -> web.Response:
    """Read-only snapshot for the dashboard: direct sqlite reads (fast, no
    request queue round-trip) — each one runs off-loop, because every
    state-DB read opens a sqlite connection whose WAL pragma can
    retry-sleep under contention."""
    del request
    from skypilot_tpu import global_state
    clusters = []
    for r in await asyncio.to_thread(global_state.get_clusters):
        handle = r.get('handle') or {}
        res = handle.get('launched_resources') or {}
        clusters.append({
            'name': r['name'],
            'resources': res.get('accelerators', '-') + (
                ' [spot]' if res.get('use_spot') else ''),
            'cloud': handle.get('cloud', '-'),
            'zone': handle.get('zone') or '-',
            'status': r['status'].value,
            'launched_at': r.get('launched_at'),
        })
    from skypilot_tpu.jobs import state as jobs_state
    jobs = [{
        'job_id': j['job_id'], 'name': j['name'],
        'status': j['status'].value, 'cluster_name': j['cluster_name'],
        'recovery_count': j['recovery_count'],
        'submitted_at': j['submitted_at'],
    } for j in (await asyncio.to_thread(jobs_state.get_jobs))[:50]]
    from skypilot_tpu.serve import serve_state
    services = []
    for s in await asyncio.to_thread(serve_state.get_services):
        reps = await asyncio.to_thread(serve_state.get_replicas,
                                       s['name'])
        is_pool = bool((s['spec'] or {}).get('pool'))
        services.append({
            'name': s['name'], 'status': s['status'].value,
            'endpoint': (None if is_pool else
                         f"http://127.0.0.1:{s['lb_port']}"),
            'pool': is_pool,
            'version': int(s.get('version') or 1),
            'ready_replicas': sum(
                1 for r in reps
                if r['status'] is serve_state.ReplicaStatus.READY),
            'total_replicas': len(reps),
        })
    return _json({
        'clusters': clusters,
        'jobs': jobs,
        'services': services,
        'requests': await asyncio.to_thread(requests_lib.list_requests,
                                            20),
    })


def _tail_file(path: str, lines: int) -> str:
    import collections
    try:
        with open(path, 'r', encoding='utf-8', errors='replace') as f:
            # deque keeps only the last N lines in memory — these logs
            # can be huge and this runs on every dashboard poll.
            return ''.join(collections.deque(f, maxlen=lines))
    except OSError:
        return ''


def _parse_lines(request: web.Request) -> int:
    """`lines` query param, clamped to [1, 2000] (the payload guard);
    garbage raises ValueError → the caller 400s."""
    return max(1, min(int(request.query.get('lines', '200')), 2000))


async def dashboard_cluster(request: web.Request) -> web.Response:
    """Drill-down: one cluster's handle facts + its ON-CLUSTER job queue
    (the `skytpu queue` surface, reachable in the browser — reference
    parity with the SPA's per-cluster pages, sky/server/server.py:2053)."""
    from skypilot_tpu import global_state
    from skypilot_tpu.backends import slice_backend
    name = request.query.get('name', '')
    record = await asyncio.to_thread(global_state.get_cluster, name)
    if record is None or not record.get('handle'):
        return _json({'error': f'no cluster {name!r} (or no handle '
                               f'recorded yet)'}, status=404)
    handle = slice_backend.SliceResourceHandle.from_dict(record['handle'])

    def fetch():
        try:
            return slice_backend.TpuSliceBackend().queue(handle)
        except Exception as e:  # pylint: disable=broad-except
            return [{'error': str(e)}]

    jobs = await asyncio.to_thread(fetch)
    res = (record.get('handle') or {}).get('launched_resources') or {}
    return _json({
        'name': name,
        'status': record['status'].value,
        'cloud': handle.cloud, 'region': handle.region,
        'zone': handle.zone,
        'resources': res.get('accelerators', '-'),
        'launched_at': record.get('launched_at'),
        'jobs': jobs,
    })


async def dashboard_cluster_log(request: web.Request) -> web.Response:
    """Tail one on-cluster job's log (non-follow; the page polls — live
    tailing without holding a remote stream open per browser tab)."""
    from skypilot_tpu import global_state
    from skypilot_tpu.backends import slice_backend
    name = request.query.get('name', '')
    try:
        job_id = int(request.query.get('job_id', ''))
        lines = _parse_lines(request)
    except ValueError:
        return _json({'error': 'job_id/lines must be integers'},
                     status=400)
    record = await asyncio.to_thread(global_state.get_cluster, name)
    if record is None or not record.get('handle'):
        return _json({'error': f'no cluster {name!r} (or no handle '
                               f'recorded yet)'}, status=404)
    handle = slice_backend.SliceResourceHandle.from_dict(record['handle'])
    backend = slice_backend.TpuSliceBackend()
    try:
        text = await asyncio.to_thread(backend.capture_logs, handle,
                                       job_id, lines)
    except Exception as e:  # pylint: disable=broad-except
        return _json({'error': str(e)}, status=500)
    return _json({'name': name, 'job_id': job_id, 'log': text})


async def dashboard_job(request: web.Request) -> web.Response:
    """Drill-down: one MANAGED job — record + mirrored run log + its
    controller log (the `skytpu jobs logs` surface in the browser)."""
    from skypilot_tpu.jobs import state as jobs_state
    try:
        job_id = int(request.query.get('job_id', ''))
        lines = _parse_lines(request)
    except ValueError:
        return _json({'error': 'job_id/lines must be integers'},
                     status=400)
    rec = next((j for j in await asyncio.to_thread(jobs_state.get_jobs)
                if j['job_id'] == job_id), None)
    if rec is None:
        return _json({'error': f'no managed job {job_id}'}, status=404)
    return _json({
        'job': {'job_id': rec['job_id'], 'name': rec['name'],
                'status': rec['status'].value,
                'cluster_name': rec['cluster_name'],
                'recovery_count': rec['recovery_count'],
                'submitted_at': rec['submitted_at']},
        'run_log': _tail_file(jobs_state.job_log_path(job_id), lines),
        'controller_log': _tail_file(
            jobs_state.controller_log_path(job_id), lines),
    })


async def dashboard_service(request: web.Request) -> web.Response:
    """Drill-down: one service — replica table with probe state (status,
    consecutive probe failures, version, age) + the controller log (the
    `skytpu serve status` surface plus logs, in the browser)."""
    from skypilot_tpu.serve import serve_state
    name = request.query.get('name', '')
    try:
        lines = _parse_lines(request)
    except ValueError:
        return _json({'error': 'lines must be an integer'}, status=400)
    rec = await asyncio.to_thread(serve_state.get_service, name)
    if rec is None:
        return _json({'error': f'no service {name!r}'}, status=404)
    replicas = [{
        'replica_id': r['replica_id'],
        'cluster_name': r['cluster_name'],
        'status': r['status'].value,
        'url': r['url'],
        'version': r.get('version') or 1,
        'probe_failures': r.get('consecutive_failures') or 0,
        'launched_at': r.get('launched_at'),
    } for r in await asyncio.to_thread(serve_state.get_replicas, name)]
    return _json({
        'name': name,
        'status': rec['status'].value,
        'version': int(rec.get('version') or 1),
        'failure_reason': rec.get('failure_reason'),
        'lb_port': rec.get('lb_port'),
        'replicas': replicas,
        'controller_log': _tail_file(
            serve_state.controller_log_path(name), lines),
    })


async def tunnel(request: web.Request) -> web.WebSocketResponse:
    """Bidirectional TCP-over-websocket proxy to a cluster's head host.

    Reference analog: the API server's websocket ssh proxy
    (sky/server/server.py:1845 + sky/templates/websocket_proxy.py) — the
    client keeps one authenticated HTTP(S) connection to the API server
    and reaches cluster ports (ssh, debuggers, TensorBoard) without the
    cluster being directly routable from the client.

    GET /api/v1/tunnel?cluster=<name>&port=<port> (websocket upgrade);
    binary frames carry the raw TCP bytes in both directions.
    """
    from skypilot_tpu import global_state
    from skypilot_tpu.backends import slice_backend
    cluster = request.query.get('cluster', '')
    port = int(request.query.get('port', 22))
    record = await asyncio.to_thread(global_state.get_cluster, cluster)
    if record is None:
        raise web.HTTPNotFound(text=f'cluster {cluster!r} not found')
    handle = slice_backend.SliceResourceHandle.from_dict(record['handle'])
    head = handle.get_cluster_info().ordered_instances()[0]
    ip = head.external_ip or head.internal_ip

    ws = web.WebSocketResponse(max_msg_size=4 * 1024 * 1024)
    await ws.prepare(request)
    try:
        reader, writer = await asyncio.open_connection(ip, port)
    except OSError as e:
        await ws.close(code=1011, message=str(e).encode()[:120])
        return ws

    async def pump_up() -> None:           # ws → tcp
        async for msg in ws:
            if msg.type == web.WSMsgType.BINARY:
                writer.write(msg.data)
                await writer.drain()
            elif msg.type in (web.WSMsgType.CLOSE, web.WSMsgType.ERROR):
                break
        writer.close()

    async def pump_down() -> None:         # tcp → ws
        while True:
            data = await reader.read(65536)
            if not data:
                break
            await ws.send_bytes(data)
        await ws.close()

    await asyncio.gather(pump_up(), pump_down(), return_exceptions=True)
    return ws


async def _gc_loop(app: web.Application) -> None:
    while True:
        try:
            n = await asyncio.to_thread(requests_lib.gc_requests)
            if n:
                logger.info(f'request GC: pruned {n} old records')
            from skypilot_tpu import observe
            pruned = await asyncio.to_thread(observe.gc)
            if any(pruned.values()):
                logger.info(f'observe GC: pruned {pruned["events"]} '
                            f'event(s), {pruned["spans"]} span(s)')
        except asyncio.CancelledError:
            return
        except Exception as e:  # pylint: disable=broad-except
            # e.g. transient 'database is locked': never let one bad pass
            # kill GC for the server's lifetime.
            logger.warning(f'request GC pass failed (will retry): {e}')
        try:
            await asyncio.sleep(_GC_INTERVAL_SECONDS)
        except asyncio.CancelledError:
            return


async def request_cancel(request: web.Request) -> web.Response:
    payload = await request.json()
    # Off-loop: the cancel path writes the requests DB and journals.
    ok = await asyncio.to_thread(executor.cancel_request,
                                 payload.get('request_id', ''))
    return _json({'cancelled': ok})


def build_app() -> web.Application:
    global _SERVER_START_TIME
    import time as time_lib
    _SERVER_START_TIME = time_lib.time()
    app = web.Application(middlewares=[auth_middleware])
    app['api_token'] = _api_token()
    from skypilot_tpu.users import rbac
    app['users'] = rbac.load_users()
    app.router.add_get('/api/v1/health', health)
    app.router.add_get('/api/v1/get', get_request)
    app.router.add_get('/api/v1/stream', stream)
    app.router.add_get('/api/v1/requests', list_requests)
    app.router.add_get('/api/v1/metrics', metrics)
    app.router.add_get('/metrics', metrics)
    app.router.add_get('/api/v1/events', events)
    app.router.add_get('/v1/events', events)
    app.router.add_get('/api/v1/traces/{trace_id}', traces)
    app.router.add_get('/v1/traces/{trace_id}', traces)
    app.router.add_get('/api/v1/tunnel', tunnel)
    app.router.add_post('/api/v1/request_cancel', request_cancel)
    app.router.add_get('/dashboard', dashboard_page)
    app.router.add_get('/dashboard/api/summary', dashboard_summary)
    app.router.add_get('/dashboard/api/cluster', dashboard_cluster)
    app.router.add_get('/dashboard/api/cluster_log',
                       dashboard_cluster_log)
    app.router.add_get('/dashboard/api/job', dashboard_job)
    app.router.add_get('/dashboard/api/service', dashboard_service)
    app.router.add_post('/api/v1/{name}', submit)

    async def _start_gc(app_):
        app_['gc_task'] = asyncio.create_task(_gc_loop(app_))

    async def _stop_gc(app_):
        app_['gc_task'].cancel()

    app.on_startup.append(_start_gc)
    app.on_cleanup.append(_stop_gc)
    return app


def run(host: str = '127.0.0.1', port: int = DEFAULT_PORT) -> None:
    sched = executor.Scheduler()
    sched.start()
    app = build_app()
    d = requests_lib.server_dir()
    with open(os.path.join(d, 'endpoint'), 'w', encoding='utf-8') as f:
        f.write(f'http://{host}:{port}')
    with open(os.path.join(d, 'server.pid'), 'w', encoding='utf-8') as f:
        f.write(str(os.getpid()))
    logger.info(f'API server on http://{host}:{port}')
    web.run_app(app, host=host, port=port, print=None)


def main() -> None:
    parser = argparse.ArgumentParser(prog='skytpu-api-server')
    parser.add_argument('--host', default='127.0.0.1')
    parser.add_argument('--port', type=int, default=DEFAULT_PORT)
    args = parser.parse_args()
    run(args.host, args.port)


if __name__ == '__main__':
    main()
