"""Token→user resolution + role enforcement for the API server.

Reference analog: sky/users/ (casbin RBAC, RoleName at sky/users/rbac.py:43)
— redesigned to a declarative users file, no policy engine:

~/.skytpu/server_users.yaml:
    users:
      - name: alice
        token: a-long-random-string
        role: admin          # admin | user | viewer
      - name: bob
        token: another-long-random-string
        role: viewer

Roles: admin = everything; user = everything except user management;
viewer = read-only requests. When the users file is absent, the server
falls back to the single shared token (SKYTPU_API_TOKEN) or open local
mode — multi-user is opt-in.
"""
from __future__ import annotations

import dataclasses
import enum
import hmac
import os
from typing import Dict, Optional

USERS_PATH = '~/.skytpu/server_users.yaml'

# Handler names a viewer may invoke (read-only surface).
READ_ONLY_REQUESTS = frozenset({
    'status', 'queue', 'logs', 'check', 'cost_report', 'list_accelerators',
    'jobs_queue', 'jobs_logs', 'serve_status',
})


class Role(enum.Enum):
    ADMIN = 'admin'
    USER = 'user'
    VIEWER = 'viewer'

    def may_submit(self, request_name: str) -> bool:
        if self in (Role.ADMIN, Role.USER):
            return True
        return request_name in READ_ONLY_REQUESTS


@dataclasses.dataclass(frozen=True)
class User:
    name: str
    role: Role


def load_users(path: Optional[str] = None) -> Dict[str, User]:
    """{token: User} from the users file; {} when multi-user is off."""
    import yaml
    path = os.path.expanduser(path or USERS_PATH)
    if not os.path.exists(path):
        return {}
    with open(path, 'r', encoding='utf-8') as f:
        data = yaml.safe_load(f) or {}
    out: Dict[str, User] = {}
    for entry in data.get('users') or []:
        token = str(entry.get('token', ''))
        if not token:
            continue
        raw_role = str(entry.get('role', 'user')).lower()
        try:
            role = Role(raw_role)
        except ValueError as e:
            raise ValueError(
                f'{USERS_PATH}: user {entry.get("name", "?")!r} has '
                f'unknown role {raw_role!r}; valid: '
                f'{[r.value for r in Role]}') from e
        out[token] = User(name=str(entry.get('name', 'unnamed')), role=role)
    return out


def resolve_user(authorization_header: str,
                 users: Optional[Dict[str, User]] = None) -> Optional[User]:
    """Bearer token → User (constant-time compare), or None."""
    if users is None:
        users = load_users()
    if not authorization_header.startswith('Bearer '):
        return None
    token = authorization_header[len('Bearer '):]
    for known, user in users.items():
        if hmac.compare_digest(token, known):
            return user
    return None
