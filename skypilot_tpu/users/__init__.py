"""Users + role-based access control (reference analog: sky/users/)."""
from skypilot_tpu.users.rbac import Role
from skypilot_tpu.users.rbac import resolve_user

__all__ = ['Role', 'resolve_user']
