"""Data-service dispatcher: worker registry + split assignment.

The dispatcher is the control plane of the input service — it never
touches a batch. It tracks workers (heartbeats → ALIVE/LOST), owns the
dataset spec of the job it serves, and maintains the split-assignment
state machine: step space is partitioned round-robin into
``num_splits`` splits (split ``s`` owns steps with
``step % num_splits == s``) and every split is assigned to exactly one
ALIVE worker. Because a batch is a pure function of ``(spec, step)``
(data_service/spec.py), reassignment is *at-least-once by
construction*: handing a dead worker's splits to a survivor — or to a
worker that turns out to still be alive — can duplicate work but never
change a byte of the stream.

State lives in WAL sqlite (``utils/sqlite_utils``; sqlite-3.34-safe,
no RETURNING). All status writes go through the guarded setters
``set_worker_status`` / ``set_split_status`` (declared in
``analysis/state_machines.py``, enforced by the skylint
``state-machine`` checker) inside ``BEGIN IMMEDIATE`` transactions,
journaling ``data_worker_join`` / ``data_worker_lost`` /
``data_worker_reassign`` events exactly once per winning write.
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.analysis import state_machines
from skypilot_tpu.data_service import protocol
from skypilot_tpu.data_service import spec as spec_lib
from skypilot_tpu.observe import journal
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import sqlite_utils

logger = sky_logging.init_logger(__name__)

DEFAULT_NUM_SPLITS = 8
DEFAULT_HEARTBEAT_TIMEOUT = knobs.get_float('SKYTPU_DATA_HEARTBEAT_TIMEOUT')


class DataWorkerStatus(enum.Enum):
    """Registry state of one data worker (docs/DATA_SERVICE.md)."""
    ALIVE = 'ALIVE'
    LOST = 'LOST'


class DataSplitStatus(enum.Enum):
    """Assignment state of one step-space split."""
    UNASSIGNED = 'UNASSIGNED'
    ASSIGNED = 'ASSIGNED'


_WORKERS_UP = metrics_lib.gauge(
    'skytpu_data_workers_up',
    'Data-service workers currently ALIVE in the dispatcher registry')
_REQUESTS = metrics_lib.counter(
    'skytpu_data_requests_total',
    'Dispatcher protocol requests by operation',
    labels={'op': ('register', 'heartbeat', 'routes', 'put_spec',
                   'stats', 'other')})


def _connect(path: str) -> sqlite3.Connection:
    conn = sqlite_utils.connect_wal(path)
    conn.execute("""
        CREATE TABLE IF NOT EXISTS workers (
            worker_id TEXT PRIMARY KEY,
            addr TEXT,
            status TEXT,
            last_heartbeat REAL,
            joined_ts REAL
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS splits (
            split_id INTEGER PRIMARY KEY,
            status TEXT,
            worker_id TEXT,
            assigned_ts REAL
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS meta (
            key TEXT PRIMARY KEY,
            value TEXT
        )""")
    conn.commit()
    return conn


# ----------------------------------------------------- guarded setters

def set_worker_status(conn: sqlite3.Connection, worker_id: str,
                      new: DataWorkerStatus, *,
                      addr: Optional[str] = None,
                      reason: Optional[str] = None,
                      require_heartbeat_before: Optional[float] = None,
                      ) -> Tuple[Optional[str], bool]:
    """THE worker-status write path (state-machine checker contract).

    Returns ``(old_status, changed)``. A missing row is created only
    for ``new == ALIVE`` (registration is the machine's entry point).
    ``require_heartbeat_before`` makes the reaper's LOST write
    conditional: a heartbeat that lands between the reaper's scan and
    this transaction keeps the worker ALIVE (no stale kill).
    Journals ``data_worker_join`` / ``data_worker_lost`` exactly once
    per winning edge, inside the transaction.
    """
    now = time.time()
    with sqlite_utils.immediate(conn):
        row = conn.execute(
            'SELECT status, last_heartbeat FROM workers '
            'WHERE worker_id = ?', (worker_id,)).fetchone()
        if row is None:
            if new is not DataWorkerStatus.ALIVE:
                return None, False
            conn.execute(
                'INSERT INTO workers (worker_id, addr, status, '
                'last_heartbeat, joined_ts) VALUES (?, ?, ?, ?, ?)',
                (worker_id, addr, new.value, now, now))
            journal.record_event('data_worker_join', worker_id,
                                 reason=reason or 'register',
                                 data={'addr': addr})
            return None, True
        old, last_hb = row
        if require_heartbeat_before is not None and \
                last_hb is not None and \
                last_hb >= require_heartbeat_before:
            return old, False
        if not state_machines.can_transition(
                state_machines.DATA_WORKER_TRANSITIONS, old, new.value):
            return old, False
        if old == new.value:
            # Self-loop: refresh liveness facts, no journal.
            conn.execute(
                'UPDATE workers SET addr = COALESCE(?, addr), '
                'last_heartbeat = ? WHERE worker_id = ?',
                (addr, now, worker_id))
            return old, False
        conn.execute(
            'UPDATE workers SET status = ?, addr = COALESCE(?, addr), '
            'last_heartbeat = ? WHERE worker_id = ?',
            (new.value, addr, now, worker_id))
        if new is DataWorkerStatus.ALIVE:
            journal.record_event('data_worker_join', worker_id,
                                 reason=reason or 'rejoin',
                                 data={'old': old, 'addr': addr})
        else:
            journal.record_event('data_worker_lost', worker_id,
                                 reason=reason,
                                 data={'old': old, 'addr': addr})
        return old, True


def set_split_status(conn: sqlite3.Connection,
                     assignment: Dict[int, Optional[str]],
                     ) -> List[Tuple[int, Optional[str], Optional[str]]]:
    """THE split-status write path: bulk (re)assignment in ONE
    transaction. ``assignment`` maps split_id → worker_id (None =
    UNASSIGNED). Owner changes within ASSIGNED are legal self-loops of
    the status machine — the at-least-once reassignment contract rests
    on batches being pure functions of step, not on exclusivity.
    Returns the applied ``(split_id, old_worker, new_worker)`` edges.
    """
    applied: List[Tuple[int, Optional[str], Optional[str]]] = []
    now = time.time()
    with sqlite_utils.immediate(conn):
        for split_id, worker_id in sorted(assignment.items()):
            row = conn.execute(
                'SELECT status, worker_id FROM splits WHERE split_id = ?',
                (split_id,)).fetchone()
            if row is None:
                continue
            old_status, old_worker = row
            new_status = (DataSplitStatus.ASSIGNED if worker_id
                          else DataSplitStatus.UNASSIGNED).value
            if not state_machines.can_transition(
                    state_machines.DATA_SPLIT_TRANSITIONS, old_status,
                    new_status):
                continue
            if old_status == new_status and old_worker == worker_id:
                continue
            conn.execute(
                'UPDATE splits SET status = ?, worker_id = ?, '
                'assigned_ts = ? WHERE split_id = ?',
                (new_status, worker_id, now, split_id))
            applied.append((split_id, old_worker, worker_id))
    return applied


class Dispatcher:
    """TCP front + sqlite state + heartbeat reaper."""

    def __init__(self, db_path: str, *, host: str = '127.0.0.1',
                 port: int = 0,
                 num_splits: int = DEFAULT_NUM_SPLITS,
                 heartbeat_timeout: float = DEFAULT_HEARTBEAT_TIMEOUT,
                 reset_spec: bool = False):
        self._db_path = db_path
        self._heartbeat_timeout = heartbeat_timeout
        self._local = threading.local()
        self._stop = threading.Event()
        # Serializes every read-plan-apply assignment sequence
        # (register handlers + the reaper). The split writes alone are
        # transactional, but a plan computed from a stale read and
        # committed LAST could strand splits on a LOST worker or leave
        # a new worker idle — and this process is the DB's only
        # writer, so a process lock makes the whole sequence atomic.
        self._assign_lock = threading.Lock()
        conn = self._conn()
        if reset_spec:
            # New job, same DB path (`--fresh`): drop the served spec
            # so the next put_spec wins. Split geometry stays — and
            # workers cache their spec in memory, so restart them too
            # (their fetches would refuse the new fingerprint loudly).
            with sqlite_utils.immediate(conn):
                conn.execute("DELETE FROM meta WHERE key IN "
                             "('spec', 'spec_fp')")
            logger.info('dispatcher spec reset (--fresh): the next '
                        'put_spec defines the served pipeline.')
        with sqlite_utils.immediate(conn):
            row = conn.execute(
                "SELECT value FROM meta WHERE key = 'num_splits'"
            ).fetchone()
            if row is None:
                conn.execute(
                    "INSERT INTO meta (key, value) VALUES "
                    "('num_splits', ?)", (str(num_splits),))
                conn.executemany(
                    'INSERT INTO splits (split_id, status, worker_id, '
                    'assigned_ts) VALUES (?, ?, NULL, NULL)',
                    [(i, DataSplitStatus.UNASSIGNED.value)
                     for i in range(num_splits)])
                self.num_splits = num_splits
            else:
                # An existing DB owns the split geometry: step→split
                # routing must not change across dispatcher restarts.
                self.num_splits = int(row[0])
                if self.num_splits != num_splits:
                    logger.warning(
                        f'dispatcher DB {db_path} was created with '
                        f'num_splits={self.num_splits}; ignoring '
                        f'requested {num_splits}.')
        self._server = protocol.FramedServer(host, port, self._handle,
                                             name='data-dispatcher')
        self.addr = self._server.addr
        self._reaper = threading.Thread(target=self._reap_loop,
                                        name='data-dispatcher-reaper',
                                        daemon=True)

    # ------------------------------------------------------- lifecycle

    def start(self) -> 'Dispatcher':
        self._server.start()
        self._reaper.start()
        logger.info(f'data-service dispatcher on {self.addr[0]}:'
                    f'{self.addr[1]} (db={self._db_path}, '
                    f'num_splits={self.num_splits}, heartbeat_timeout='
                    f'{self._heartbeat_timeout}s)')
        return self

    def stop(self) -> None:
        self._stop.set()
        self._server.stop()
        self._reaper.join(timeout=5.0)

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, 'conn', None)
        if conn is None:
            conn = _connect(self._db_path)
            self._local.conn = conn
        return conn

    # -------------------------------------------------------- handlers

    def _handle(self, obj: Dict[str, Any], arrays: protocol.Arrays
                ) -> Tuple[Dict[str, Any], Optional[protocol.Arrays]]:
        op = str(obj.get('op', ''))
        _REQUESTS.inc(op=op if op in ('register', 'heartbeat', 'routes',
                                      'put_spec', 'stats') else 'other')
        if failpoints.ACTIVE:
            failpoints.fire('data.dispatch')
        if op == 'register':
            return self._op_register(obj), None
        if op == 'heartbeat':
            return self._op_heartbeat(obj), None
        if op == 'routes':
            return self._routes(), None
        if op == 'put_spec':
            return self._op_put_spec(obj), None
        if op == 'stats':
            return self._op_stats(), None
        raise protocol.RemoteError(f'unknown op {op!r}', kind='bad_op')

    def _op_register(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        worker_id = str(obj['worker_id'])
        addr = str(obj['addr'])
        conn = self._conn()
        # Status write OUTSIDE _assign_lock: the setter is its own
        # BEGIN IMMEDIATE transaction, and holding the lock across a
        # commit would stall every other handler thread behind
        # sqlite's WAL-contention retry sleep. The lock only
        # serializes plan *computation*; applying the plan is safe
        # unlocked because set_split_status is per-row guarded and
        # reassignment is at-least-once by construction.
        old, changed = set_worker_status(
            conn, worker_id, DataWorkerStatus.ALIVE, addr=addr)
        with self._assign_lock:
            plan = self._plan_rebalance(conn)
        if plan:
            set_split_status(conn, plan)
        reply = self._routes()
        reply.update(ok=True, rejoined=bool(old is not None and changed))
        return reply

    def _op_heartbeat(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        worker_id = str(obj['worker_id'])
        conn = self._conn()
        # `status IN (?)`: reads the column, never writes it — the
        # state-machine lint's raw-SQL rule keys on `status =` anywhere
        # in an UPDATE, and a WHERE-clause equality would false-positive.
        cur = conn.execute(
            'UPDATE workers SET last_heartbeat = ? '
            'WHERE worker_id = ? AND status IN (?)',
            (time.time(), worker_id, DataWorkerStatus.ALIVE.value))
        conn.commit()
        if cur.rowcount == 0:
            # Unknown or LOST: tell the worker to re-register — its
            # splits were reassigned, rejoining gets it new ones.
            return {'ok': False, 'resync': True}
        reply: Dict[str, Any] = {'ok': True, 'spec_fp': self._spec_fp()}
        if not obj.get('have_spec'):
            # Spec rides the next beat after put_spec, so workers load
            # the corpus OFF the fetch path (a multi-minute tokenize
            # must burn heartbeat time, not the client's fetch budget).
            row = conn.execute(
                "SELECT value FROM meta WHERE key = 'spec'").fetchone()
            if row:
                reply['spec'] = json.loads(row[0])
                reply['num_splits'] = self.num_splits
        return reply

    def _op_put_spec(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        try:
            spec = spec_lib.DatasetSpec.from_json(obj['spec'])
        except (ValueError, TypeError) as e:
            # Schema skew is a CONFIG refusal ('spec' kind — clients
            # never retry it), not an 'internal' error they would
            # retry for the whole stall budget.
            raise protocol.RemoteError(f'cannot parse dataset spec: '
                                       f'{e}', kind='spec') from e
        fp = spec.fingerprint()
        conn = self._conn()
        with sqlite_utils.immediate(conn):
            row = conn.execute(
                "SELECT value FROM meta WHERE key = 'spec_fp'").fetchone()
            if row is None:
                conn.execute(
                    "INSERT INTO meta (key, value) VALUES ('spec', ?), "
                    "('spec_fp', ?)",
                    (json.dumps(spec.to_json()), fp))
            elif row[0] != fp:
                raise protocol.RemoteError(
                    f'dispatcher already serves spec {row[0]}, client '
                    f'sent {fp} — one dispatcher serves one dataset '
                    f'spec; start another, or restart this one with '
                    f'--fresh (and fresh workers) for a new pipeline',
                    kind='spec_mismatch')
        return {'ok': True, 'spec_fp': fp,
                'num_splits': self.num_splits}

    def _op_stats(self) -> Dict[str, Any]:
        conn = self._conn()
        workers = conn.execute(
            'SELECT status, COUNT(*) FROM workers GROUP BY status'
        ).fetchall()
        splits = conn.execute(
            'SELECT status, COUNT(*) FROM splits GROUP BY status'
        ).fetchall()
        return {'ok': True, 'workers': dict(workers),
                'splits': dict(splits), 'num_splits': self.num_splits,
                'spec_fp': self._spec_fp()}

    def _routes(self) -> Dict[str, Any]:
        conn = self._conn()
        workers = dict(conn.execute(
            'SELECT worker_id, addr FROM workers WHERE status = ?',
            (DataWorkerStatus.ALIVE.value,)).fetchall())
        assignments = {
            str(split_id): worker_id
            for split_id, worker_id in conn.execute(
                'SELECT split_id, worker_id FROM splits '
                'WHERE status = ?',
                (DataSplitStatus.ASSIGNED.value,)).fetchall()
            if worker_id in workers
        }
        row = conn.execute(
            "SELECT value FROM meta WHERE key = 'spec'").fetchone()
        return {'workers': workers, 'assignments': assignments,
                'num_splits': self.num_splits,
                'spec': json.loads(row[0]) if row else None,
                'spec_fp': self._spec_fp()}

    def _spec_fp(self) -> Optional[str]:
        row = self._conn().execute(
            "SELECT value FROM meta WHERE key = 'spec_fp'").fetchone()
        return row[0] if row else None

    # ----------------------------------------------------- assignment

    def _plan_rebalance(self, conn: sqlite3.Connection
                        ) -> Dict[int, str]:
        """Plan (do not apply): assign every orphaned/UNASSIGNED
        split to the least-loaded ALIVE worker, then level the load
        (a freshly joined worker must take splits from the
        incumbents — input capacity scales only if assignments follow
        the pool). Deterministic (sorted ids, stable moves) so
        concurrent rebalances converge to the same layout; batches
        being pure functions of step makes every interim
        double-ownership harmless. Pure reads + compute so callers
        can run it under ``_assign_lock`` without holding the lock
        across a commit; the plan is applied OUTSIDE the lock via
        ``set_split_status`` (its own guarded transaction)."""
        alive = [w for (w,) in conn.execute(
            'SELECT worker_id FROM workers WHERE status = ? '
            'ORDER BY worker_id',
            (DataWorkerStatus.ALIVE.value,)).fetchall()]
        if not alive:
            return {}
        owned: Dict[str, List[int]] = {w: [] for w in alive}
        unassigned: List[int] = []
        for split_id, status, worker_id in conn.execute(
                'SELECT split_id, status, worker_id FROM splits '
                'ORDER BY split_id').fetchall():
            if status == DataSplitStatus.ASSIGNED.value and \
                    worker_id in owned:
                owned[worker_id].append(split_id)
            else:
                unassigned.append(split_id)
        plan: Dict[int, str] = {}
        for split_id in unassigned:
            target = min(alive, key=lambda w: (len(owned[w]), w))
            plan[split_id] = target
            owned[target].append(split_id)
        while True:
            most = max(alive, key=lambda w: (len(owned[w]), w))
            least = min(alive, key=lambda w: (len(owned[w]), w))
            if len(owned[most]) - len(owned[least]) <= 1:
                break
            moved = owned[most].pop()   # highest id: stable choice
            plan[moved] = least
            owned[least].append(moved)
        return plan

    def _reap_loop(self) -> None:
        interval = max(0.05, self._heartbeat_timeout / 4.0)
        while not self._stop.wait(interval):
            try:
                self._reap_once()
            except Exception as e:  # noqa: BLE001 — reaper must survive
                logger.warning(f'dispatcher reaper pass failed: {e}')

    def _reap_once(self) -> None:
        conn = self._conn()
        # Orphan sweep: splits still assigned to a non-ALIVE worker.
        # Normally the LOST write and the rebalance land in the same
        # pass, but a dispatcher restart between the two (or right
        # after a crash mid-register) would otherwise strand those
        # splits forever — survivors only heartbeat, never re-register,
        # so no other path re-runs the rebalance.
        with self._assign_lock:
            orphans = conn.execute(
                'SELECT COUNT(*) FROM splits WHERE status = ? AND '
                'worker_id NOT IN (SELECT worker_id FROM workers '
                'WHERE status = ?)',
                (DataSplitStatus.ASSIGNED.value,
                 DataWorkerStatus.ALIVE.value)).fetchone()[0]
            plan = self._plan_rebalance(conn) if orphans else {}
        # Apply + journal outside the lock: both commit to sqlite and
        # can sleep on WAL contention; a register RPC must not stall
        # behind the reaper's bookkeeping.
        if plan:
            set_split_status(conn, plan)
            journal.record_event(
                'data_worker_reassign', 'dispatcher',
                reason='orphan_sweep',
                data={'to': {str(k): v for k, v in plan.items()}})
        cutoff = time.time() - self._heartbeat_timeout
        stale = [w for (w,) in conn.execute(
            'SELECT worker_id FROM workers WHERE status = ? AND '
            'last_heartbeat < ?',
            (DataWorkerStatus.ALIVE.value, cutoff)).fetchall()]
        for worker_id in stale:
            # The LOST write needs no lock: require_heartbeat_before
            # makes it a compare-and-set inside the setter's own
            # transaction, so a concurrent heartbeat wins cleanly.
            _, changed = set_worker_status(
                conn, worker_id, DataWorkerStatus.LOST,
                reason='heartbeat_timeout',
                require_heartbeat_before=cutoff)
            if not changed:
                continue
            with self._assign_lock:
                orphaned = [s for (s,) in conn.execute(
                    'SELECT split_id FROM splits WHERE worker_id = ?',
                    (worker_id,)).fetchall()]
                plan = self._plan_rebalance(conn)
            if plan:
                set_split_status(conn, plan)
            journal.record_event(
                'data_worker_reassign', worker_id,
                reason='heartbeat_timeout',
                data={'splits': orphaned,
                      'to': {str(k): v for k, v in plan.items()}})
            logger.warning(
                f'data worker {worker_id} lost (no heartbeat for '
                f'{self._heartbeat_timeout}s); reassigned splits '
                f'{orphaned} -> {plan}')
        _WORKERS_UP.set(float(self._conn().execute(
            'SELECT COUNT(*) FROM workers WHERE status = ?',
            (DataWorkerStatus.ALIVE.value,)).fetchone()[0]))
