"""Trainer-side data-service client: a prefetching, reconnecting
step-ordered batch iterator.

The client is where the service's failure containment meets the
trainer's determinism contract: batches are yielded strictly in step
order, each fetched from whichever worker currently owns the step's
split, and EVERY failure mode — dead worker, dispatcher blip, injected
``data.fetch`` fault — is handled by refreshing the routing table and
retrying under a seeded :class:`~skypilot_tpu.utils.backoff.Backoff`,
never by skipping or reordering a step. A worker death therefore
stalls the stream for at most the heartbeat-timeout + backoff budget
and changes nothing about its contents.

The prefetch thread keeps a BOUNDED queue of upcoming batches
(``prefetch_depth``); ``next()`` pops from it, so fetch latency
overlaps the train step instead of serializing with it. The stall
budget (``stall_budget_s``) is the loud-failure bound: a stream that
cannot make progress for that long raises ``DataServiceStallError``
instead of hanging the job silently.
"""
from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from typing import Any, Dict, Iterator, Optional, Tuple

import numpy as np

from skypilot_tpu import sky_logging
from skypilot_tpu.data_service import protocol
from skypilot_tpu.data_service import spec as spec_lib
from skypilot_tpu.data_service import telemetry
from skypilot_tpu.utils import backoff as backoff_lib
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import failpoints

logger = sky_logging.init_logger(__name__)


class DataServiceStallError(RuntimeError):
    """The stream made no progress within the stall budget."""


class DataServiceClient:
    """Iterator of ``{name: ndarray}`` batches for steps
    ``start_step, start_step+1, ...``."""

    def __init__(self, addr: str, spec: spec_lib.DatasetSpec, *,
                 start_step: int = 0,
                 prefetch_depth: int = 4,
                 fetch_timeout: Optional[float] = None,
                 stall_budget_s: Optional[float] = None):
        # Env-tunable (the trainer exposes no per-knob flags): a corpus
        # whose worker-side load/tokenize takes minutes needs a bigger
        # budget than the echo-fast default.
        if fetch_timeout is None:
            fetch_timeout = knobs.get_float('SKYTPU_DATA_FETCH_TIMEOUT')
        if stall_budget_s is None:
            stall_budget_s = knobs.get_float('SKYTPU_DATA_STALL_BUDGET')
        self._dispatcher_addr = protocol.parse_addr(addr)
        self.spec = spec
        self._spec_fp = spec.fingerprint()
        self._start_step = start_step
        self._fetch_timeout = fetch_timeout
        self._stall_budget_s = stall_budget_s
        self._stop = threading.Event()
        self._queue: 'queue.Queue[Tuple[int, Any]]' = queue.Queue(
            maxsize=max(1, prefetch_depth))
        self._routes: Dict[str, Any] = {}
        self._failure: Optional[BaseException] = None
        # Persistent connections, all owned by the prefetch thread
        # (start() touches the dispatcher one before the thread runs):
        # a batch fetch per train step must not pay a TCP handshake —
        # FramedServer keeps connections open for exactly this.
        self._dispatcher = protocol.FramedClient(self._dispatcher_addr)
        self._worker_conns: Dict[str, protocol.FramedClient] = {}
        self._thread = threading.Thread(
            target=self._prefetch_loop, daemon=True,
            name='data-service-prefetch')
        self._started = False

    # ------------------------------------------------------- lifecycle

    def start(self) -> 'DataServiceClient':
        """Register the spec with the dispatcher and start prefetching.
        Retries until the dispatcher answers (it may still be booting
        when the trainer comes up) within the stall budget."""
        deadline = time.monotonic() + self._stall_budget_s
        boff = backoff_lib.Backoff(base=0.2, cap=2.0,
                                   seed=self.spec.seed)
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                self._dispatcher.request(
                    {'op': 'put_spec', 'spec': self.spec.to_json()},
                    timeout=self._fetch_timeout)
                self._thread.start()
                self._started = True
                return self
            except protocol.RemoteError as e:
                if e.kind in ('spec', 'spec_mismatch'):
                    raise   # config error: retrying cannot heal it
                last_err = e
                boff.sleep()
            except (protocol.ProtocolError, OSError) as e:
                last_err = e
                boff.sleep()
        raise DataServiceStallError(
            f'dispatcher at {self._dispatcher_addr} unreachable for '
            f'{self._stall_budget_s}s: {last_err}')

    def close(self) -> None:
        # The prefetcher's put() polls at 0.2s against _stop, so no
        # queue drain is needed to unblock it.
        self._stop.set()
        if self._started:
            self._thread.join(timeout=5.0)
        self._dispatcher.close()
        for conn in self._worker_conns.values():
            conn.close()
        self._worker_conns.clear()

    def __enter__(self) -> 'DataServiceClient':
        return self if self._started else self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------- iterator

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        if not self._started:
            self.start()
        deadline = time.monotonic() + self._stall_budget_s
        while True:
            if self._failure is not None:
                raise self._failure
            try:
                _, batch = self._queue.get(timeout=0.2)
            except queue.Empty:
                if self._stop.is_set():
                    raise StopIteration
                if time.monotonic() >= deadline:
                    raise DataServiceStallError(
                        f'no batch within the {self._stall_budget_s}s '
                        f'stall budget') from None
                continue
            telemetry.BATCHES.inc(role='client')
            telemetry.QUEUE_DEPTH.set(float(self._queue.qsize()),
                                      role='client')
            return batch

    # -------------------------------------------------------- fetching

    def _prefetch_loop(self) -> None:
        try:
            for step in itertools.count(self._start_step):
                if self._stop.is_set():
                    return
                batch = self._fetch(step)
                while not self._stop.is_set():
                    try:
                        self._queue.put((step, batch), timeout=0.2)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # noqa: BLE001 — surfaced at next()
            self._failure = e

    def _refresh_routes(self) -> None:
        reply, _ = self._dispatcher.request({'op': 'routes'},
                                            timeout=self._fetch_timeout)
        self._routes = reply
        # Prune connections to addresses that left the routable set
        # (keyed by ADDRESS: a rejoined worker id may move).
        alive = set((reply.get('workers') or {}).values())
        for addr_text in list(self._worker_conns):
            if addr_text not in alive:
                self._worker_conns.pop(addr_text).close()

    def _fetch(self, step: int) -> Dict[str, np.ndarray]:
        """Fetch ONE step's batch, retrying across worker/dispatcher
        failures until the stall budget runs out. Seeded backoff: a
        chaos schedule reproduces the same retry timeline."""
        deadline = time.monotonic() + self._stall_budget_s
        boff = backoff_lib.Backoff(base=0.1, cap=2.0,
                                   seed=self.spec.seed ^ step)
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline and not self._stop.is_set():
            try:
                if failpoints.ACTIVE:
                    failpoints.fire('data.fetch')
                t0 = time.perf_counter()
                batch = self._fetch_once(step)
                telemetry.FETCH_SECONDS.observe(time.perf_counter() - t0)
                return batch
            except protocol.RemoteError as e:
                if e.kind in ('spec', 'spec_mismatch'):
                    raise   # config refusal: fail the run loudly
                last_err = e
            except (protocol.ProtocolError, OSError, KeyError,
                    failpoints.FailpointError) as e:
                last_err = e
            # The route we just used failed us: drop the cache so the
            # retry re-asks the dispatcher (which reassigns a dead
            # worker's splits after its heartbeat timeout).
            self._routes = {}
            boff.sleep()
        raise DataServiceStallError(
            f'step {step}: no worker served the batch within the '
            f'{self._stall_budget_s}s stall budget (last error: '
            f'{last_err})')

    def _fetch_once(self, step: int) -> Dict[str, np.ndarray]:
        num_splits = int(self._routes.get('num_splits') or 0)
        if not num_splits or not self._routes.get('workers'):
            self._refresh_routes()
            num_splits = int(self._routes.get('num_splits') or 0)
        split = step % num_splits if num_splits else 0
        worker_id = self._routes.get('assignments', {}).get(str(split))
        addr_text = self._routes.get('workers', {}).get(worker_id)
        if addr_text is None:
            self._refresh_routes()
            worker_id = self._routes.get('assignments', {}).get(
                str(split))
            addr_text = self._routes.get('workers', {}).get(worker_id)
            if addr_text is None:
                raise protocol.ProtocolError(
                    f'no ALIVE worker owns split {split} yet')
        conn = self._worker_conns.get(addr_text)
        if conn is None:
            conn = protocol.FramedClient(protocol.parse_addr(addr_text))
            self._worker_conns[addr_text] = conn
        reply, arrays = conn.request(
            {'op': 'get_batch', 'step': step, 'spec_fp': self._spec_fp},
            timeout=self._fetch_timeout)
        if int(reply.get('step', -1)) != step:
            raise protocol.ProtocolError(
                f'worker answered step {reply.get("step")} for step '
                f'{step}')
        if not arrays:
            raise protocol.ProtocolError('batch reply carried no arrays')
        # A failed fetch against THIS worker invalidates the cached
        # route at the next retry via _refresh_routes; a succeeded one
        # keeps it (the common path costs one dispatcher round-trip
        # only at startup and after churn).
        return arrays
