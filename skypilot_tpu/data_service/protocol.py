"""Framed TCP protocol for the input-data service.

The wire format — versioned length-prefixed frames carrying a JSON
control object plus npy-encoded arrays, with a deadline on every
socket op — moved to :mod:`skypilot_tpu.utils.framed` when the
disaggregated serving plane started shipping KV pages over the same
idiom (ROADMAP item 2's named refactor). This module re-exports the
whole surface unchanged so every data-service caller (and its tests)
keeps importing ``data_service.protocol``; the framing semantics are
documented, tested and evolved in ``utils/framed.py`` from here on.
"""
from __future__ import annotations

# Back-compat surface: the data service's modules and tests import
# these names from here. The private helpers (_encode_payload,
# _HEADER, ...) are re-exported too — the protocol tests forge frames
# with them.
from skypilot_tpu.utils.framed import (  # noqa: F401
    MAGIC,
    MAX_FRAME_BYTES,
    VERSION,
    Arrays,
    Deadline,
    FramedClient,
    FramedServer,
    ProtocolError,
    ProtocolTimeout,
    RemoteError,
    VersionMismatchError,
    _HEADER,
    _U32,
    _decode_payload,
    _encode_payload,
    _recv_exact,
    parse_addr,
    raise_if_error,
    recv_msg,
    request,
    send_msg,
)

__all__ = [
    'MAGIC', 'MAX_FRAME_BYTES', 'VERSION', 'Arrays', 'Deadline',
    'FramedClient', 'FramedServer', 'ProtocolError', 'ProtocolTimeout',
    'RemoteError', 'VersionMismatchError', 'parse_addr',
    'raise_if_error', 'recv_msg', 'request', 'send_msg',
]
