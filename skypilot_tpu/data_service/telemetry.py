"""Shared metric declarations for the data service.

One definition per metric (the promtext precedent): worker and client
both move ``batches_total``/``queue_depth`` under different ``role``
labels, and the registry refuses conflicting redeclarations at import
time — two copy-pasted literals drifting apart would break whichever
module imports second. Catalog: docs/OBSERVABILITY.md.
"""
from __future__ import annotations

from skypilot_tpu.observe import metrics as metrics_lib

BATCHES = metrics_lib.counter(
    'skytpu_data_batches_total',
    'Batches served (worker) / consumed (client) by the data service',
    labels={'role': ('worker', 'client')})
QUEUE_DEPTH = metrics_lib.gauge(
    'skytpu_data_queue_depth',
    'Bounded prefetch-buffer occupancy (worker cache / client queue)',
    labels={'role': ('worker', 'client')})
FETCH_SECONDS = metrics_lib.histogram(
    'skytpu_data_fetch_seconds',
    'Client-observed latency of one batch fetch, retries included')
