"""Elastic wiring for the data-service worker pool (docs/ELASTIC.md).

The paper's core economic claim ("A Case for Disaggregating ML Input
Data Processing", PAPERS.md) is that the CPU input pool should track
what the TPUs actually need — and the signal for that is already on
the telemetry plane: ``skytpu_train_batch_wait_seconds``, the time
the train step loop blocks in ``next()``. This module declares the
pool's ElasticSpec:

  * signal — batch-wait BURN (seconds blocked per wall second; a
    share in [0, 1] for one trainer) from a scraper
    (:func:`batch_wait_burn_signal`) or any in-process probe;
  * target — a hold band (`SKYTPU_ELASTIC_DATA_WAIT_LOW/HIGH`):
    above it the trainer is input-stalled → add a worker; below it
    the pool is overprovisioned → drain one. Band mode, not
    proportional: wait share does not map linearly onto worker count;
  * hooks — ``scale_up`` spawns a worker (a CPU Task in production,
    a DataWorker object in the bench/tests); ``scale_down`` drains
    one. DRAIN = :func:`drain_one`: STOP HEARTBEATING the chosen
    worker and let the dispatcher's reassignment machinery (PR 10)
    rebalance its splits — batches are pure functions of
    ``(spec, step)``, so the training stream stays bit-identical
    across the scale event.

Safety is the uniform elastic contract: a dead scrape plane or a
not-yet-measuring trainer is NO SIGNAL → hold (there is no sane
fallback reducer for input starvation, so none is declared).
"""
from __future__ import annotations

from typing import Callable, List, Optional, TypeVar

from skypilot_tpu.elastic import signals
from skypilot_tpu.elastic import spec as elastic_spec
from skypilot_tpu.utils import knobs

_Worker = TypeVar('_Worker')


def batch_wait_burn_signal(scraper) -> signals.SignalFn:
    """Batch-wait burn from the fleet telemetry plane (the scraper
    must have the trainer's /metrics endpoint as a target)."""
    return signals.scraped_burn(scraper,
                                'skytpu_train_batch_wait_seconds')


def worker_pool_spec(
        signal: signals.SignalFn, *,
        scale_up: Callable[[int], None],
        scale_down: Callable[[int], None],
        min_workers: int = 1,
        max_workers: Optional[int] = None,
        initial_workers: Optional[int] = None,
        band: Optional[tuple] = None,
        upscale_delay_seconds: float = 0.0,
        downscale_delay_seconds: float = 0.0,
) -> elastic_spec.ElasticSpec:
    """The data-worker pool's declared elastic contract. Knobs fill
    the band/cooldown/flap-resistance defaults; callers override for
    tests and benches (synthetic clocks, tight cadences)."""
    if band is None:
        band = (knobs.get_float('SKYTPU_ELASTIC_DATA_WAIT_LOW'),
                knobs.get_float('SKYTPU_ELASTIC_DATA_WAIT_HIGH'))
    return elastic_spec.ElasticSpec(
        pool='data_workers',
        signal=signal,
        band=band,
        min_units=min_workers,
        max_units=max_workers,
        initial_units=initial_workers,
        upscale_delay_seconds=upscale_delay_seconds,
        downscale_delay_seconds=downscale_delay_seconds,
        cooldown_seconds=knobs.get_float(
            'SKYTPU_ELASTIC_COOLDOWN_SECONDS'),
        clean_rounds=knobs.get_int('SKYTPU_ELASTIC_CLEAN_ROUNDS'),
        stale_after=knobs.get_float('SKYTPU_ELASTIC_STALE_SECONDS'),
        scale_up=scale_up,
        scale_down=scale_down)


def drain_one(workers: List[_Worker]) -> Optional[_Worker]:
    """Drain the NEWEST worker from a live pool list (LIFO: the
    longest-lived workers keep their warm source caches) by stopping
    it — which stops its heartbeat, so the dispatcher's reaper marks
    it LOST and reassigns its splits bit-identically. Returns the
    drained worker (already stopped), or None for an empty pool."""
    if not workers:
        return None
    worker = workers.pop()
    worker.stop()
    return worker
