"""CLI: ``python -m skypilot_tpu.data_service dispatcher|worker``.

Data workers are just CPU Tasks to the control plane — see
examples/data-service-train.yaml for the gang wiring. Both
subcommands print one JSON readiness line to stdout (address,
identity) so a supervising task — or a chaos test — can harvest the
endpoint, then serve until SIGTERM/SIGINT.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
from typing import List, Optional

from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import knobs


def _serve_until_signal() -> None:
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()


def main(argv: Optional[List[str]] = None) -> int:
    failpoints.load_env()
    parser = argparse.ArgumentParser(
        prog='python -m skypilot_tpu.data_service',
        description='Disaggregated input-data service '
                    '(docs/DATA_SERVICE.md).')
    sub = parser.add_subparsers(dest='cmd', required=True)

    disp = sub.add_parser('dispatcher', help='worker registry + '
                                             'split assignment')
    disp.add_argument('--host', default='0.0.0.0')
    disp.add_argument('--port', type=int, default=8470)
    disp.add_argument('--db', default='~/.skytpu/data_service/'
                                      'dispatcher.db')
    disp.add_argument('--num-splits', type=int, default=8)
    disp.add_argument('--heartbeat-timeout', type=float,
                      default=knobs.get_float(
                          'SKYTPU_DATA_HEARTBEAT_TIMEOUT'))
    disp.add_argument('--fresh', action='store_true',
                      help='drop the previously served dataset spec '
                           '(new job, same --db; restart workers too)')

    work = sub.add_parser('worker', help='stateless CPU batch worker')
    work.add_argument('--dispatcher', required=True,
                      help='dispatcher host:port')
    work.add_argument('--host', default='0.0.0.0')
    work.add_argument('--port', type=int, default=0,
                      help='0 = ephemeral')
    work.add_argument('--advertise-host', default=None,
                      help='address clients/dispatcher reach this '
                           'worker at (default: the bound host)')
    work.add_argument('--worker-id', default=None)
    work.add_argument('--queue-depth', type=int, default=8)
    work.add_argument('--heartbeat-interval', type=float, default=2.0)

    args = parser.parse_args(argv)
    if args.cmd == 'dispatcher':
        from skypilot_tpu.data_service import dispatcher as disp_lib
        db = os.path.expanduser(args.db)
        os.makedirs(os.path.dirname(db) or '.', exist_ok=True)
        svc = disp_lib.Dispatcher(
            db, host=args.host, port=args.port,
            num_splits=args.num_splits,
            heartbeat_timeout=args.heartbeat_timeout,
            reset_spec=args.fresh).start()
        print(json.dumps({'role': 'dispatcher',
                          'addr': f'{svc.addr[0]}:{svc.addr[1]}',
                          'num_splits': svc.num_splits}), flush=True)
        _serve_until_signal()
        svc.stop()
        return 0
    from skypilot_tpu.data_service import protocol
    from skypilot_tpu.data_service import worker as worker_lib
    w = worker_lib.DataWorker(
        protocol.parse_addr(args.dispatcher),
        host=args.host, port=args.port,
        advertise_host=args.advertise_host,
        worker_id=args.worker_id, queue_depth=args.queue_depth,
        heartbeat_interval=args.heartbeat_interval).start()
    print(json.dumps({'role': 'worker', 'worker_id': w.worker_id,
                      'addr': f'{w.addr[0]}:{w.addr[1]}'}), flush=True)
    _serve_until_signal()
    w.stop()
    return 0


if __name__ == '__main__':
    sys.exit(main())
