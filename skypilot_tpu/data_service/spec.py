"""DatasetSpec + pure step-indexed batch sources.

The determinism contract of the whole data service lives here: a
:class:`DatasetSpec` fully describes an input pipeline, and
:func:`load_source` builds a *pure* ``step -> batch`` function from it
using the existing ``data/`` loader/sft/tokenizer pipelines. Every
consumer — the trainer's in-process iterator, every data-service
worker, the bench harness — runs the SAME source code over the same
spec, which is what makes the batch at step N a pure function of
``(seed, corpus, step)``: identical for 1 vs 3 workers, across worker
deaths, and across checkpoint-resume.

Specs are fingerprinted (sha256 of the canonical JSON); the client
sends its fingerprint with every fetch and a worker refuses a
mismatch loudly — two processes silently disagreeing about the
pipeline is exactly the garbage-batch failure the service must not
ship to the TPU.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import time
from typing import Any, Dict, Optional

import numpy as np

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass(frozen=True)
class DatasetSpec:
    """Everything a stateless worker needs to recreate the pipeline.

    Paths must resolve on every worker (shared storage / baked image —
    the same contract checkpoints place on ``--ckpt-dir``). ``seed``
    feeds the synthetic stream (and any future shuffling); the
    on-disk corpus paths feed the deterministic indexers in
    ``data/loader.py`` / ``data/sft.py``.
    """
    batch_size: int
    seq_len: int
    vocab_size: int
    seed: int = 0
    data_path: Optional[str] = None
    # HF tokenizer name (plain corpus) or tokenizer.json path (SFT) —
    # the same double duty TrainerConfig.tokenizer serves.
    tokenizer: Optional[str] = None
    sft_data_path: Optional[str] = None
    chat_family: Optional[str] = None
    # Bench knob (SKYTPU_BENCH_METRIC=train_input): an artificial
    # per-batch preprocess cost, so "input scales independently" is
    # measurable on CPU without a heavyweight real pipeline. Affects
    # timing only, never batch content.
    preprocess_delay_s: float = 0.0

    def __post_init__(self):
        if self.batch_size < 1 or self.seq_len < 1:
            raise ValueError(f'batch_size={self.batch_size} and '
                             f'seq_len={self.seq_len} must be >= 1')
        if self.vocab_size < 1:
            raise ValueError(f'vocab_size={self.vocab_size} must be >= 1')
        if self.data_path and self.sft_data_path:
            raise ValueError('data_path and sft_data_path are exclusive')

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: Dict[str, Any]) -> 'DatasetSpec':
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(obj) - known
        if unknown:
            raise ValueError(f'unknown DatasetSpec fields {sorted(unknown)}'
                             f' — client and worker disagree about the '
                             f'spec schema; upgrade the older side')
        return cls(**obj)

    def fingerprint(self) -> str:
        text = json.dumps(self.to_json(), sort_keys=True,
                          separators=(',', ':'))
        return hashlib.sha256(text.encode('utf-8')).hexdigest()[:16]


class Source:
    """A loaded pipeline: ``batch_at_step`` is pure in ``step``."""

    def __init__(self, spec: DatasetSpec):
        self.spec = spec

    def _compute(self, step: int) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def batch_at_step(self, step: int) -> Dict[str, np.ndarray]:
        if self.spec.preprocess_delay_s > 0:
            time.sleep(self.spec.preprocess_delay_s)
        return self._compute(step)


class _PlainSource(Source):
    """Contiguous-window LM batches over a token corpus."""

    def __init__(self, spec: DatasetSpec, tokens):
        super().__init__(spec)
        self._tokens = tokens

    def _compute(self, step: int) -> Dict[str, np.ndarray]:
        from skypilot_tpu.data import loader
        return {'tokens': loader.batch_at_step(
            self._tokens, step, self.spec.batch_size, self.spec.seq_len)}


class _SftSource(Source):
    """Conversation batches with assistant-only loss masks."""

    def __init__(self, spec: DatasetSpec, tokens: np.ndarray,
                 masks: np.ndarray):
        super().__init__(spec)
        self._tokens = tokens
        self._masks = masks

    def _compute(self, step: int) -> Dict[str, np.ndarray]:
        from skypilot_tpu.data import sft
        return sft.batch_at_step(self._tokens, self._masks, step,
                                 self.spec.batch_size)


def synthetic_tokens(spec: DatasetSpec) -> np.ndarray:
    """The seeded synthetic corpus (no data path): the stream every
    smoke-test trainer run consumes, reproducible from the spec alone."""
    rng = np.random.default_rng(spec.seed)
    base = rng.integers(
        0, spec.vocab_size,
        size=(max(4 * spec.batch_size * spec.seq_len, spec.seq_len + 2),),
        dtype=np.int64)
    return base.astype(np.int32)


def load_source(spec: DatasetSpec) -> Source:
    """Materialize the pipeline a spec describes.

    Raises ``ValueError`` on a tokenizer/model vocab mismatch
    (``data/loader.validate_vocab``) — a worker built from a bad spec
    must refuse at load, not ship garbage batches to the TPU.
    """
    from skypilot_tpu.data import loader
    if spec.sft_data_path:
        from skypilot_tpu.data import sft
        from skypilot_tpu.data import tokenizer as tokenizer_lib
        if spec.tokenizer:
            tokenizer = tokenizer_lib.load_tokenizer(spec.tokenizer)
        else:
            tokenizer = tokenizer_lib.ByteTokenizer()
        family = spec.chat_family or tokenizer.chat_family
        tokens, masks = sft.load_sft_dataset(spec.sft_data_path, tokenizer,
                                             family, spec.seq_len)
        loader.validate_vocab(tokens, spec.vocab_size,
                              context='SFT corpus')
        logger.info(f'SFT: {tokens.shape[0]} conversations '
                    f'({family} template), '
                    f'{float(masks.sum()):.0f} trainable tokens.')
        return _SftSource(spec, tokens, masks)
    if spec.data_path is not None:
        tokens = loader.load_tokens(spec.data_path, spec.tokenizer)
        loader.validate_vocab(tokens, spec.vocab_size, context='Corpus')
        return _PlainSource(spec, tokens)
    return _PlainSource(spec, synthetic_tokens(spec))
