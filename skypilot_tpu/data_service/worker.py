"""Stateless CPU data worker: spec in, batches out.

A worker holds NO state the stream depends on: its only inputs are the
:class:`~skypilot_tpu.data_service.spec.DatasetSpec` it pulls from the
dispatcher and the step numbers clients ask for, and
``spec.load_source`` makes the batch for step N a pure function of
both. Killing a worker mid-run therefore changes nothing about the
token stream — the dispatcher reassigns its splits and the survivors
compute the identical batches (the chaos suite's load-bearing
invariant, tests/chaos/test_data_service.py).

Buffering is BOUNDED everywhere: one prefetch thread computes at most
``queue_depth`` batches ahead into a step-keyed cache, and a full
precompute queue drops work instead of growing — backpressure, never
an unbounded buffer (the tf.data-service lesson: input workers that
buffer unboundedly just move the OOM from the trainer to the pool).

A worker built from a mismatched spec (token ids outside the model
vocab — ``data/loader.validate_vocab``) refuses EVERY fetch with a
``spec``-kinded error instead of shipping garbage batches to the TPU.
"""
from __future__ import annotations

import collections
import queue
import socket
import threading
import time
import uuid
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.data_service import protocol
from skypilot_tpu.data_service import spec as spec_lib
from skypilot_tpu.data_service import telemetry
from skypilot_tpu.utils import backoff as backoff_lib
from skypilot_tpu.utils import failpoints

logger = sky_logging.init_logger(__name__)


# THE seed derivation for worker-style loops (shared with the rollout
# worker; utils/backoff owns it so the planes can't drift).
stable_seed = backoff_lib.stable_seed


def _routable_host(bound_host: str,
                   dispatcher_addr: Tuple[str, int]) -> str:
    """A peer-reachable address for a wildcard bind: registering
    '0.0.0.0' with the dispatcher would route every client to ITSELF
    (connection refused on any multi-node deployment).

    The UDP-connect trick asks the kernel which interface egresses
    toward the dispatcher — unlike ``gethostbyname(gethostname())``,
    which on stock Debian-family hosts resolves to the /etc/hosts
    loopback entry (127.0.1.1) and would advertise an unroutable
    address. A loopback answer is CORRECT when the dispatcher itself
    is loopback (single-box tests)."""
    if bound_host not in ('0.0.0.0', '::', ''):
        return bound_host
    probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    probe.settimeout(1.0)
    try:
        probe.connect(dispatcher_addr)   # routes only; no packet sent
        return probe.getsockname()[0]
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return socket.gethostname()
    finally:
        probe.close()


class DataWorker:
    """One stateless worker process/thread: serve + heartbeat loops."""

    def __init__(self, dispatcher_addr: Tuple[str, int], *,
                 host: str = '127.0.0.1', port: int = 0,
                 worker_id: Optional[str] = None,
                 advertise_host: Optional[str] = None,
                 queue_depth: int = 8,
                 heartbeat_interval: float = 2.0,
                 register_timeout: float = 60.0,
                 rpc_timeout: float = 10.0):
        self.worker_id = worker_id or f'dw-{uuid.uuid4().hex[:8]}'
        self._dispatcher_addr = dispatcher_addr
        self._queue_depth = max(1, queue_depth)
        self._heartbeat_interval = heartbeat_interval
        self._register_timeout = register_timeout
        self._rpc_timeout = rpc_timeout
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._spec: Optional[spec_lib.DatasetSpec] = None
        self._spec_fp: Optional[str] = None
        self._source: Optional[spec_lib.Source] = None
        self._spec_error: Optional[str] = None
        self._loader_thread: Optional[threading.Thread] = None
        self._num_splits: Optional[int] = None
        # step -> batch, bounded to queue_depth entries (oldest out).
        self._cache: 'collections.OrderedDict[int, Dict[str, Any]]' = (
            collections.OrderedDict())
        self._precompute: 'queue.Queue[int]' = queue.Queue(
            maxsize=self._queue_depth)
        self._server = protocol.FramedServer(
            host, port, self._handle, name=f'data-worker-{self.worker_id}')
        adv = advertise_host or _routable_host(self._server.addr[0],
                                               dispatcher_addr)
        self.addr = (adv, self._server.addr[1])
        self._seed = stable_seed(self.worker_id)
        # Owned by the heartbeat thread (and by start() before it runs):
        # one persistent connection carries every heartbeat instead of a
        # handshake + dispatcher thread + sqlite connection per beat.
        self._dispatcher = protocol.FramedClient(dispatcher_addr)
        self._heartbeat_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f'{self.worker_id}-heartbeat')
        self._prefetch_thread = threading.Thread(
            target=self._prefetch_loop, daemon=True,
            name=f'{self.worker_id}-prefetch')

    # ------------------------------------------------------- lifecycle

    def start(self) -> 'DataWorker':
        self._server.start()
        self._register(deadline_s=self._register_timeout)
        self._heartbeat_thread.start()
        self._prefetch_thread.start()
        logger.info(f'data worker {self.worker_id} serving on '
                    f'{self.addr[0]}:{self.addr[1]}, dispatcher '
                    f'{self._dispatcher_addr[0]}:'
                    f'{self._dispatcher_addr[1]}')
        return self

    def stop(self) -> None:
        self._stop.set()
        self._server.stop()
        self._heartbeat_thread.join(timeout=5.0)
        self._prefetch_thread.join(timeout=5.0)
        if self._loader_thread is not None:
            self._loader_thread.join(timeout=5.0)
        self._dispatcher.close()

    # ---------------------------------------------------- registration

    def _register(self, deadline_s: float) -> None:
        deadline = time.monotonic() + deadline_s
        boff = backoff_lib.Backoff(base=0.2, cap=2.0, seed=self._seed)
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline and not self._stop.is_set():
            try:
                reply, _ = self._dispatcher.request(
                    {'op': 'register', 'worker_id': self.worker_id,
                     'addr': f'{self.addr[0]}:{self.addr[1]}'},
                    timeout=self._rpc_timeout)
                self._adopt_routes(reply)
                return
            except (protocol.ProtocolError, protocol.RemoteError,
                    OSError) as e:
                last_err = e
                boff.sleep()
        raise TimeoutError(
            f'worker {self.worker_id} could not register with '
            f'dispatcher at {self._dispatcher_addr} within '
            f'{deadline_s}s: {last_err}')

    def _adopt_routes(self, reply: Dict[str, Any]) -> None:
        with self._lock:
            self._adopt_routes_locked(reply)

    def _set_spec(self, spec: spec_lib.DatasetSpec) -> None:
        """Adopt a spec and start loading its source on a DEDICATED
        thread. Caller holds ``_lock``. The load may take minutes
        (tokenizing a real corpus) and must starve neither heartbeats
        (a loading worker reaped as LOST would churn splits among
        equally-loading peers) nor the serve loop — fetches during the
        load get a retriable ``loading`` error instead."""
        self._spec = spec
        self._spec_fp = spec.fingerprint()
        self._loader_thread = threading.Thread(
            target=self._load_source, args=(spec,), daemon=True,
            name=f'{self.worker_id}-load')
        self._loader_thread.start()

    def _load_source(self, spec: spec_lib.DatasetSpec) -> None:
        try:
            source = spec_lib.load_source(spec)
            error = None
        except (ValueError, OSError) as e:
            # Config refusal (vocab mismatch, unreadable corpus):
            # permanent for this spec — every fetch answers kind=spec.
            source, error = None, str(e)
            logger.error(f'worker {self.worker_id} refuses spec '
                         f'{spec.fingerprint()}: {e}')
        with self._lock:
            self._source = source
            self._spec_error = error

    def _ensure_source(self) -> spec_lib.Source:
        with self._lock:
            if self._source is not None:
                return self._source
            if self._spec_error is not None:
                raise protocol.RemoteError(self._spec_error, kind='spec')
            have_spec = self._spec is not None
        if not have_spec:
            # No spec yet: pull it from the dispatcher (put there by
            # the client before its first fetch).
            reply, _ = protocol.request(self._dispatcher_addr,
                                        {'op': 'routes'},
                                        timeout=self._rpc_timeout)
            with self._lock:
                if self._spec is None:
                    if reply.get('spec') is None:
                        raise protocol.RemoteError(
                            'dispatcher has no dataset spec yet',
                            kind='no_spec')
                    self._adopt_routes_locked(reply)
        with self._lock:
            if self._source is not None:
                return self._source
            if self._spec_error is not None:
                raise protocol.RemoteError(self._spec_error, kind='spec')
        # Loader thread still running: transient — the client retries
        # under its stall budget while heartbeats keep this worker
        # ALIVE through the load.
        raise protocol.RemoteError('dataset source still loading',
                                   kind='loading')

    def _adopt_routes_locked(self, reply: Dict[str, Any]) -> None:
        self._num_splits = int(reply.get('num_splits') or 0) or None
        if self._spec is None and self._spec_error is None and \
                reply.get('spec') is not None:
            try:
                spec = spec_lib.DatasetSpec.from_json(reply['spec'])
            except (ValueError, TypeError) as e:
                # Version skew: refuse LOUDLY and keep beating — a
                # raise here would kill the heartbeat thread and brick
                # the process silently; instead every fetch answers a
                # permanent 'spec'-kinded error carrying the message.
                self._spec_error = f'cannot parse dataset spec: {e}'
                logger.error(f'worker {self.worker_id}: '
                             f'{self._spec_error}')
                return
            self._set_spec(spec)

    # -------------------------------------------------------- serving

    def _handle(self, obj: Dict[str, Any], arrays: protocol.Arrays
                ) -> Tuple[Dict[str, Any], Optional[protocol.Arrays]]:
        op = str(obj.get('op', ''))
        if op == 'get_batch':
            return self._op_get_batch(obj)
        if op == 'ping':
            return {'ok': True, 'worker_id': self.worker_id}, None
        raise protocol.RemoteError(f'unknown op {op!r}', kind='bad_op')

    def _op_get_batch(self, obj: Dict[str, Any]
                      ) -> Tuple[Dict[str, Any], protocol.Arrays]:
        if failpoints.ACTIVE:
            failpoints.fire('data.worker_batch')
        step = int(obj['step'])
        source = self._ensure_source()
        want_fp = obj.get('spec_fp')
        if want_fp is not None and want_fp != self._spec_fp:
            raise protocol.RemoteError(
                f'worker serves spec {self._spec_fp}, client asked for '
                f'{want_fp} — pipelines diverged; restart the older '
                f'side', kind='spec_mismatch')
        with self._lock:
            # get, not pop: in a multi-host gang EVERY host fetches
            # step N — one computation must serve all of them.
            batch = self._cache.get(step)
        if batch is None:
            batch = source.batch_at_step(step)
            with self._lock:
                # Cache the inline result too (same multi-host
                # contract); the size bound evicts oldest.
                self._cache[step] = batch
                while len(self._cache) > self._queue_depth:
                    self._cache.popitem(last=False)
        self._schedule_prefetch(step)
        telemetry.BATCHES.inc(role='worker')
        with self._lock:
            telemetry.QUEUE_DEPTH.set(float(len(self._cache)),
                                      role='worker')
        return {'ok': True, 'step': step, 'spec_fp': self._spec_fp}, batch

    # ------------------------------------------------------- prefetch

    def _schedule_prefetch(self, served_step: int) -> None:
        """Precompute the steps this worker will most likely serve
        next: the same split's following steps. Non-blocking put — a
        full queue means we are already queue_depth ahead, so DROP
        (bounded buffering is the contract, not throughput)."""
        stride = self._num_splits or 1
        for ahead in range(1, self._queue_depth + 1):
            try:
                self._precompute.put_nowait(served_step + ahead * stride)
            except queue.Full:
                return

    def _prefetch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                step = self._precompute.get(timeout=0.2)
            except queue.Empty:
                continue
            with self._lock:
                source = self._source
                have = step in self._cache
            if source is None or have:
                continue
            try:
                batch = source.batch_at_step(step)
            except Exception as e:  # noqa: BLE001 — prefetch is advisory
                logger.warning(f'worker {self.worker_id} prefetch of '
                               f'step {step} failed: {e}')
                continue
            with self._lock:
                self._cache[step] = batch
                while len(self._cache) > self._queue_depth:
                    self._cache.popitem(last=False)

    # ------------------------------------------------------ heartbeats

    def _heartbeat_loop(self) -> None:
        boff = backoff_lib.Backoff(base=0.2, cap=5.0, seed=self._seed)
        while not self._stop.wait(self._heartbeat_interval):
            try:
                if failpoints.ACTIVE:
                    # Chaos hook: a firing skips beats, so the
                    # dispatcher sees exactly the silence a hung or
                    # partitioned worker would produce.
                    failpoints.fire('data.heartbeat')
                with self._lock:
                    have_spec = self._spec is not None
                reply, _ = self._dispatcher.request(
                    {'op': 'heartbeat', 'worker_id': self.worker_id,
                     'have_spec': have_spec},
                    timeout=self._rpc_timeout)
                if not have_spec and reply.get('spec') is not None:
                    # Load the source NOW (heartbeat thread), so the
                    # first get_batch finds it ready instead of paying
                    # the corpus load inside the client's fetch budget.
                    self._adopt_routes(reply)
                if reply.get('resync'):
                    # Dispatcher declared us LOST: rejoin for fresh
                    # splits. At-least-once reassignment means the
                    # interim double-ownership was harmless.
                    self._register(deadline_s=self._register_timeout)
                boff.reset()
            except failpoints.FailpointError:
                continue
            except (protocol.ProtocolError, protocol.RemoteError,
                    OSError, TimeoutError) as e:
                logger.warning(f'worker {self.worker_id} heartbeat '
                               f'failed: {e}')
                # Jittered pause on top of the interval: a dispatcher
                # restart must not see a thundering herd of beats.
                boff.sleep()
