"""Disaggregated input-data service: dispatcher + CPU workers + client.

The tf.data-service architecture (PAPERS.md: "A Case for Disaggregating
ML Input Data Processing") adapted to this framework's determinism
contract: input preprocessing runs on a pool of CPU-only workers that
scale independently of the TPU count, while the batch at step N stays a
pure function of ``(seed, corpus, step)`` — identical for 1 vs 3
workers, across worker deaths, and across the checkpoint-resume path
(train/checkpoints.py). Workers are *stateless compute*: worker churn,
like mesh churn, changes nothing about the token stream.

Pieces (each its own module, docs/DATA_SERVICE.md for the wiring):

  * :mod:`protocol`  — versioned length-prefixed framed TCP (stdlib
    sockets, a deadline on every socket op) carrying npy-encoded
    fixed-shape batches;
  * :mod:`spec`      — the ``DatasetSpec`` both sides fingerprint and
    the pure step→batch sources built from the existing ``data/``
    tokenizer/sft/loader pipelines;
  * :mod:`dispatcher`— worker registry with heartbeats and a
    split-assignment state machine in WAL-sqlite, reassigning a dead
    worker's splits at-least-once;
  * :mod:`worker`    — stateless CPU worker serving batches under a
    bounded prefetch queue (backpressure, never unbounded buffering);
  * :mod:`client`    — trainer-side prefetching iterator with
    backoff reconnects (``--data-service <addr>`` on the trainer).

Run the services with ``python -m skypilot_tpu.data_service
dispatcher|worker ...`` — data workers are just CPU Tasks to the
control plane (examples/data-service-train.yaml).
"""
