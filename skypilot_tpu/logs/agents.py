"""Log-shipping agents: stream job logs off the cluster hosts.

Reference analog: sky/logs/{gcp,aws}.py — fluentbit configs installed at
provision time (instance_setup.setup_logging_on_cluster:610). Same hook
here (provisioner.post_provision_runtime_setup): when the user configures

    logs:
      store: gcp            # or aws
      # optional extra labels attached to every record
      labels: {team: ml}

every host gets a fluent-bit tail → cloud-logging pipeline over
~/.skytpu_runtime/logs/**. Hosts without fluent-bit log a warning and
continue — shipping is best-effort observability, never a launch blocker.
"""
from __future__ import annotations

import shlex
from typing import Any, Dict, Optional

_FLUENTBIT_CONF = """\
[SERVICE]
    flush 5
    daemon off
[INPUT]
    name tail
    path {log_glob}
    tag skytpu.*
    refresh_interval 10
[FILTER]
    name record_modifier
    match *
    record cluster {cluster_name}
{extra_records}
[OUTPUT]
{output}
"""

_GCP_OUTPUT = """\
    name stackdriver
    match *
    resource global
"""

_AWS_OUTPUT = """\
    name cloudwatch_logs
    match *
    region {region}
    log_group_name skytpu-{cluster_name}
    log_stream_prefix host-
    auto_create_group true
"""


def _conf(store: str, cluster_name: str,
          labels: Optional[Dict[str, Any]] = None,
          region: str = 'us-central1') -> str:
    extra = '\n'.join(f'    record {k} {v}'
                      for k, v in (labels or {}).items())
    if store == 'gcp':
        output = _GCP_OUTPUT
    elif store == 'aws':
        output = _AWS_OUTPUT.format(region=region,
                                    cluster_name=cluster_name)
    else:
        raise ValueError(f'Unknown log store {store!r}; '
                         f"supported: 'gcp', 'aws'.")
    return _FLUENTBIT_CONF.format(
        # Placeholder expanded by the shell at install time — fluent-bit
        # does not expand $HOME in config values.
        log_glob='__SKYTPU_HOME__/.skytpu_runtime/logs/*/*.log',
        cluster_name=cluster_name,
        extra_records=extra,
        output=output)


def setup_command_for_config(config: Optional[Dict[str, Any]],
                             cluster_name: str) -> Optional[str]:
    """The per-host command installing + starting the shipping agent, or
    None when `logs:` is not configured."""
    if not config or not config.get('store'):
        return None
    conf = _conf(str(config['store']).lower(), cluster_name,
                 labels=config.get('labels'),
                 region=str(config.get('region', 'us-central1')))
    conf_q = shlex.quote(conf)
    # [f]luent-bit: the bracket keeps pkill from matching (and killing)
    # the shell executing this very command.
    return (
        'if command -v fluent-bit >/dev/null 2>&1; then '
        f'  printf %s {conf_q} | sed "s|__SKYTPU_HOME__|$HOME|g" '
        '    > $HOME/.skytpu_fluentbit.conf && '
        '  pkill -f "[f]luent-bit.*skytpu_fluentbit" 2>/dev/null; '
        '  nohup fluent-bit -c $HOME/.skytpu_fluentbit.conf '
        '    > /tmp/skytpu_fluentbit.log 2>&1 & '
        'else '
        '  echo "[skytpu] fluent-bit not installed; log shipping skipped" '
        '    >&2; '
        'fi')
