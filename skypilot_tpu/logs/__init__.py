"""External log shipping (reference analog: sky/logs/)."""
from skypilot_tpu.logs.agents import setup_command_for_config

__all__ = ['setup_command_for_config']
