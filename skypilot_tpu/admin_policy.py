"""Pluggable org policy hook: mutate/validate every user request.

Reference analog: sky/admin_policy.py (`UserRequest` → `MutatedUserRequest`
through a deployment-configured policy class). Configured via
`admin_policy: mypkg.mymodule.MyPolicy` in ~/.skytpu/config.yaml; applied
at the entry of launch/exec/jobs-launch/serve-up, before the optimizer.

Typical uses: force spot for cost control, pin regions for data residency,
inject labels for billing attribution, reject oversized slices.

Policies MUST be idempotent (same contract as the reference): recovery and
replica relaunches re-enter execution.launch, so a policy may see a task it
already mutated.
"""
from __future__ import annotations

import dataclasses
import importlib
import typing
from typing import Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass
class UserRequest:
    """What the policy sees: the task plus the operation being requested."""
    task: 'task_lib.Task'
    operation: str                 # 'launch' | 'exec' | 'jobs.launch' | ...
    cluster_name: Optional[str] = None
    dryrun: bool = False


@dataclasses.dataclass
class MutatedUserRequest:
    task: 'task_lib.Task'


class AdminPolicy:
    """Subclass and point `admin_policy:` config at it."""

    def validate_and_mutate(self, request: UserRequest) -> MutatedUserRequest:
        raise NotImplementedError


class PolicyRejectedError(exceptions.SkyTpuError):
    """Raised by policies to reject a request outright."""


def _load_policy() -> Optional[AdminPolicy]:
    from skypilot_tpu import config as config_lib
    path = config_lib.get_nested(('admin_policy',), None)
    if not path:
        return None
    module_name, _, cls_name = str(path).rpartition('.')
    if not module_name:
        raise ValueError(
            f'admin_policy must be a full dotted path, got {path!r}')
    try:
        module = importlib.import_module(module_name)
        cls = getattr(module, cls_name)
    except (ImportError, AttributeError) as e:
        raise ValueError(f'Cannot load admin_policy {path!r}: {e}') from e
    policy = cls()
    if not isinstance(policy, AdminPolicy):
        raise ValueError(f'{path} is not an AdminPolicy subclass.')
    return policy


def apply(task: 'task_lib.Task', operation: str,
          cluster_name: Optional[str] = None,
          dryrun: bool = False) -> 'task_lib.Task':
    """Run the configured policy (no-op when none is configured)."""
    policy = _load_policy()
    if policy is None:
        return task
    request = UserRequest(task=task, operation=operation,
                          cluster_name=cluster_name, dryrun=dryrun)
    mutated = policy.validate_and_mutate(request)
    if not isinstance(mutated, MutatedUserRequest):
        raise ValueError(
            f'{type(policy).__name__}.validate_and_mutate must return a '
            f'MutatedUserRequest, got {type(mutated).__name__}.')
    logger.debug(f'admin policy {type(policy).__name__} applied to '
                 f'{operation}.')
    return mutated.task
