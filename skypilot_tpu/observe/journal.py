"""Durable structured event journal (sqlite, WAL).

The runtime half of docs/STATE_MACHINES.md: every guarded status
setter (``jobs/state.set_status_nonterminal``/``set_terminal``,
``serve/serve_state.set_replica_status``/``set_service_status``,
``skylet/job_lib.set_status``) publishes its winning transition here —
old→new, reason, timestamp, trace id — so the declared state machines
are *observable* at runtime, not just enforced. Provisioning and
request milestones land as generic events in the same table.

Write contract:

  * exactly once per WINNING write — callers journal inside their
    guarded BEGIN IMMEDIATE transaction, right after the UPDATE (the
    journal is a separate DB file, so no deadlock), which also makes
    journal order match commit order; never for self-loop re-writes
    (a re-assertion of the current status is not a transition);
  * never in the way — journal I/O failures are swallowed
    (``record_*`` return False); telemetry must not fail the
    control-plane write it describes;
  * trace-correlated — ``trace_id`` defaults to the active
    :mod:`skypilot_tpu.observe.trace` id, so journal rows join against
    timeline spans, usage events and the API request that caused them.

The DB is one WAL-mode sqlite file (``SKYTPU_OBSERVE_DB``, default
``~/.skytpu/observe/journal.db``) — INSERT-only, no read-modify-write,
so plain autocommit inserts are race-free under sqlite's write lock.
sqlite-3.34-safe: no RETURNING, connections via
``utils/sqlite_utils.connect_wal``.
"""
from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Mapping, Optional

from skypilot_tpu.utils import jsonl_utils
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import sqlite_utils

from skypilot_tpu.observe import trace

_DB_PATH_ENV = 'SKYTPU_OBSERVE_DB'
_DISABLE_ENV = 'SKYTPU_DISABLE_JOURNAL'

KIND_TRANSITION = 'transition'
KIND_ENTRY = 'entry'


def db_path() -> str:
    """Pure path resolution — no filesystem side effects. _conn()
    creates the directory on its cache-miss branch; keeping this pure
    means the per-event cache-key comparison costs no syscalls."""
    return os.path.expanduser(knobs.get_str(_DB_PATH_ENV))


def _enabled() -> bool:
    return not knobs.get_bool(_DISABLE_ENV)


# Per-thread connection cache (the global_state._conn pattern): the
# journal sits on hot paths — every API request and status transition
# — so paying connect + WAL pragma + DDL per event would multiply
# sqlite lock traffic. Keyed by path: tests repoint SKYTPU_OBSERVE_DB
# per case and must not inherit a stale connection.
_local = threading.local()


def _conn() -> sqlite3.Connection:
    path = db_path()
    cached = getattr(_local, 'conn', None)
    if cached is not None and getattr(_local, 'path', None) == path:
        return cached
    os.makedirs(os.path.dirname(path), exist_ok=True)
    conn = sqlite_utils.connect_wal(path)
    conn.execute("""
        CREATE TABLE IF NOT EXISTS events (
            event_id INTEGER PRIMARY KEY AUTOINCREMENT,
            ts REAL,
            kind TEXT,
            machine TEXT,
            entity TEXT,
            old_status TEXT,
            new_status TEXT,
            reason TEXT,
            trace_id TEXT,
            pid INTEGER,
            data TEXT
        )""")
    conn.execute('CREATE INDEX IF NOT EXISTS idx_events_trace '
                 'ON events (trace_id)')
    conn.execute('CREATE INDEX IF NOT EXISTS idx_events_entity '
                 'ON events (machine, entity)')
    conn.commit()
    _local.conn = conn
    _local.path = path
    return conn


def _insert(kind: str, machine: Optional[str], entity: Optional[str],
            old: Optional[str], new: Optional[str],
            reason: Optional[str], trace_id: Optional[str],
            data: Optional[Dict[str, Any]]) -> bool:
    if not _enabled():
        return False
    if trace_id is None:
        trace_id = trace.get()
    try:
        with _conn() as conn:
            conn.execute(
                'INSERT INTO events (ts, kind, machine, entity, '
                'old_status, new_status, reason, trace_id, pid, data) '
                'VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)',
                (time.time(), kind, machine, entity, old, new, reason,
                 trace_id, os.getpid(),
                 json.dumps(data) if data else None))
        return True
    except (sqlite3.Error, OSError):
        # Best-effort by contract: the state write this describes
        # already committed and must not be failed retroactively.
        return False


def record_transition(machine: str, entity: str, old: Optional[str],
                      new: str, *, reason: Optional[str] = None,
                      trace_id: Optional[str] = None,
                      data: Optional[Dict[str, Any]] = None) -> bool:
    """One status-machine edge. ``old is None`` marks the entity's
    ENTRY into its state machine (row creation), not a transition."""
    kind = KIND_TRANSITION if old is not None else KIND_ENTRY
    return _insert(kind, machine, entity, old, new, reason, trace_id,
                   data)


def record_event(kind: str, entity: Optional[str] = None, *,
                 machine: Optional[str] = None,
                 reason: Optional[str] = None,
                 trace_id: Optional[str] = None,
                 data: Optional[Dict[str, Any]] = None) -> bool:
    """A non-transition milestone (provision attempt, request finish...)."""
    return _insert(kind, machine, entity, None, None, reason, trace_id,
                   data)


# ---------------------------------------------------------------- reads

_COLUMNS = ('event_id', 'ts', 'kind', 'machine', 'entity', 'old_status',
            'new_status', 'reason', 'trace_id', 'pid', 'data')


def _row_to_dict(row) -> Dict[str, Any]:
    d = dict(zip(_COLUMNS, row))
    if d.get('data'):
        try:
            d['data'] = json.loads(d['data'])
        except ValueError:
            pass
    return d


def filters_from_query(params: Mapping[str, str],
                       max_limit: int = 10000) -> Dict[str, Any]:
    """HTTP query params -> ``query()`` kwargs — ONE parser for every
    events endpoint (API server ``/v1/events``, LB ``/-/lb/events``),
    so the filter surface cannot silently diverge. Accepts ``machine``
    / ``entity`` / ``kind`` / ``trace_id`` (alias ``trace``) /
    ``since`` / ``limit``; raises ValueError on non-numeric
    since/limit (callers turn that into a 400)."""
    kwargs: Dict[str, Any] = {}
    for key in ('machine', 'entity', 'kind'):
        value = params.get(key)
        if value:
            kwargs[key] = value
    trace_id = params.get('trace_id') or params.get('trace')
    if trace_id:
        kwargs['trace_id'] = trace_id
    if params.get('since'):
        kwargs['since'] = float(params['since'])
    kwargs['limit'] = min(int(params.get('limit', '200')), max_limit)
    return kwargs


def entity_scope_clause(entity_scope: str) -> 'tuple[str, List[str]]':
    """SQL predicate restricting rows to one entity subtree: the
    entity equals the scope (the service row itself) or lives under it
    (``scope/<replica_id>``). LIKE metachars in the scope ('_' is
    common in service names) must not act as wildcards — that would
    leak OTHER services' rows through the user-facing scoped LB
    endpoints. One definition shared by the events and spans readers
    so the escaping (a security boundary) cannot drift between them."""
    escaped = (entity_scope.replace('\\', '\\\\')
               .replace('%', '\\%').replace('_', '\\_'))
    return ("(entity = ? OR entity LIKE ? || '/%' ESCAPE '\\')",
            [entity_scope, escaped])


def query(*, machine: Optional[str] = None, entity: Optional[str] = None,
          trace_id: Optional[str] = None, kind: Optional[str] = None,
          since: Optional[float] = None, limit: int = 1000,
          entity_scope: Optional[str] = None) -> List[Dict[str, Any]]:
    """Filtered events, oldest first.

    ``entity_scope='svc'`` restricts to entities belonging to that
    name: ``entity == 'svc'`` (the service row itself) or entities
    under it (``'svc/<replica_id>'``) — what a per-service endpoint
    may expose without leaking the rest of the shared journal.
    """
    clauses, params = [], []
    for col, val in (('machine', machine), ('entity', entity),
                     ('trace_id', trace_id), ('kind', kind)):
        if val is not None:
            clauses.append(f'{col} = ?')
            params.append(val)
    if entity_scope is not None:
        clause, scope_params = entity_scope_clause(entity_scope)
        clauses.append(clause)
        params.extend(scope_params)
    if since is not None:
        clauses.append('ts >= ?')
        params.append(since)
    where = (' WHERE ' + ' AND '.join(clauses)) if clauses else ''
    sql = (f'SELECT {", ".join(_COLUMNS)} FROM events{where} '
           f'ORDER BY event_id LIMIT ?')
    params.append(max(1, int(limit)))
    try:
        with _conn() as conn:
            rows = conn.execute(sql, params).fetchall()
    except (sqlite3.Error, OSError):
        return []
    return [_row_to_dict(r) for r in rows]


def tail(n: int = 20) -> List[Dict[str, Any]]:
    """The most recent ``n`` events, oldest first."""
    try:
        with _conn() as conn:
            rows = conn.execute(
                f'SELECT {", ".join(_COLUMNS)} FROM events '
                f'ORDER BY event_id DESC LIMIT ?',
                (max(1, int(n)),)).fetchall()
    except (sqlite3.Error, OSError):
        return []
    return [_row_to_dict(r) for r in reversed(rows)]


def gc_events(max_age_seconds: float = 7 * 24 * 3600,
              max_rows: int = 500_000) -> int:
    """Retention: drop events older than ``max_age_seconds`` and, if
    the table still exceeds ``max_rows``, the oldest overflow — the
    journal is INSERT-only on hot paths (every API request and status
    transition), so without this it grows until the disk fills. The
    API server's hourly GC loop calls it alongside gc_requests; it is
    also safe to run from any process sharing the DB."""
    try:
        conn = _conn()
        with sqlite_utils.immediate(conn):
            cur = conn.execute('DELETE FROM events WHERE ts < ?',
                               (time.time() - max_age_seconds,))
            deleted = cur.rowcount
            # Row cap by the (max_rows+1)-th NEWEST id — never by
            # max_id arithmetic: AUTOINCREMENT ids are sparse after
            # age-based deletes, and `max_id - max_rows` would wipe
            # live rows far beyond the intended overflow.
            row = conn.execute(
                'SELECT event_id FROM events '
                'ORDER BY event_id DESC LIMIT 1 OFFSET ?',
                (max_rows,)).fetchone()
            if row is not None:
                cur = conn.execute(
                    'DELETE FROM events WHERE event_id <= ?', (row[0],))
                deleted += cur.rowcount
        return max(0, deleted)
    except (sqlite3.Error, OSError):
        return 0


def export_jsonl(path: str, max_bytes: float = float('inf'),
                 **filters: Any) -> int:
    """Dump matching events as JSONL through the shared writer
    (utils/jsonl_utils — the one usage telemetry appends through).
    Returns the number of lines written.

    Rotation is OFF by default (``max_bytes=inf``): a one-shot export
    that rotated mid-dump would silently keep only the newest chunk
    while reporting the full count. Pass a finite ``max_bytes`` only
    for an append-forever streaming export.
    """
    writer = jsonl_utils.RotatingJsonlWriter(path, max_bytes)
    written = 0
    for event in query(**filters):
        if writer.write(event):
            written += 1
    return written
