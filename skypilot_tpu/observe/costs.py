"""Cost attribution: metered dollars from catalog pricing to per-token
joins — the economic axis of the fleet plane.

Every other signal the observe plane tracks (latency, goodput, burn
rates) already flows scrape → tsdb → SLO; dollars were the one axis
living in an ad-hoc helper. This module is the single place price math
is allowed to happen:

  * a :class:`CostMeter` prices every pool's runtime from the catalog
    layer (``catalog.get_hourly_cost``, per replica, keyed by slice
    topology and price class spot|on_demand). The price is resolved
    ONCE per replica lifetime and journaled as a ``cost_price`` event
    — later catalog drift cannot rewrite a run's history;
  * each scrape round, :meth:`CostMeter.accrue` turns wall-clock since
    the last round into metered replica-seconds and dollars, persisted
    into a ``costs`` table in the journal DB (same write contract as
    tsdb samples: best-effort, one transaction per round, retention
    via :func:`gc_costs` wired into the shared ``observe.gc()``);
  * the metered dollars JOIN against the already-scraped
    ``skytpu_engine_tokens_total`` / goodput counters to derive
    ``skytpu_cost_usd_total{pool,price_class}``,
    ``skytpu_cost_per_token_usd{pool}`` and
    ``skytpu_cost_per_request_usd{cls}`` gauges;
  * declarative :class:`CostBudget` specs (``SKYTPU_COST_BUDGETS``
    JSON, refused loudly when malformed) evaluate per round with
    fast/slow burn-rate windows and ``cost_budget_ok|warning|breach``
    journal events — observe/slo.py's multi-window hysteresis idiom
    applied to spend rate instead of error fraction: burn = measured
    $/hour over the window divided by the budgeted $/hour.

Alongside the reference rate each replica also resolves its ON-DEMAND
price once: the accrual rows carry both, so ``spot_discount`` (what
the same replica-seconds would have cost on-demand ÷ what they did
cost) is a first-class, journal-backed column rather than a separate
pricing run — the loadgen scorecard's spot-vs-on-demand A/B.

Entity scoping follows the journal: cost rows key on the replica's
journal entity (``<svc>/<rid>`` or ``<svc>/<role>/<rid>``), and every
reader takes the same ``entity_scope`` predicate the scoped LB
endpoints use — a shared observe DB must not leak one service's spend
into another's ``/-/fleet/costs``.

The catalog import is function-level on purpose: observe (layer 3)
sits below catalog (layer 4); pricing is a sanctioned runtime bridge,
not a module-level dependency.
"""
from __future__ import annotations

import dataclasses
import os
import sqlite3
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import sqlite_utils

from skypilot_tpu.observe import journal
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.observe import promtext
from skypilot_tpu.observe import request_class
from skypilot_tpu.observe import tsdb

logger = sky_logging.init_logger(__name__)

# Closed metric-label vocabularies (the breaker-state precedent): one
# value per priceable pool. Superset of elastic/spec.py POOLS (which
# observe must not import — layering) plus the rollout plane's stable
# learner; test_costs pins the subset relation so the two cannot
# silently drift.
POOLS: Tuple[str, ...] = ('serve', 'prefill', 'decode', 'data_workers',
                          'rollout', 'learner')
PRICE_CLASSES: Tuple[str, ...] = ('on_demand', 'spot')
# Budget scope label: a budget covers one pool or the whole fleet.
BUDGET_POOLS: Tuple[str, ...] = POOLS + ('fleet',)
STATES = ('ok', 'warning', 'breach')
_STATE_CODE = {'ok': 0, 'warning': 1, 'breach': 2}

TOKENS_FAMILY = 'skytpu_engine_tokens_total'
GOODPUT_FAMILY = 'skytpu_engine_goodput_total'
# Per-class decode-token proxy: the class TPOT histogram observes one
# sample per decoded token beyond the first, so its _count delta is
# the closest per-class token share the fleet plane records.
CLASS_TOKENS_FAMILY = 'skytpu_engine_class_tpot_seconds_count'

_M_USD_TOTAL = metrics_lib.gauge(
    'skytpu_cost_usd_total',
    'Metered dollars accrued by this process\'s cost meter since '
    'start, per pool and price class.',
    labels={'pool': POOLS, 'price_class': PRICE_CLASSES})
_M_PER_TOKEN = metrics_lib.gauge(
    'skytpu_cost_per_token_usd',
    'Windowed $/generated-token per pool: metered dollars over the '
    'join window divided by the fleet token-counter delta.',
    labels={'pool': POOLS})
_M_PER_REQUEST = metrics_lib.gauge(
    'skytpu_cost_per_request_usd',
    'Windowed $/finished-request per request class (dollars '
    'apportioned by each class\'s decode-token share).',
    labels={'cls': request_class.CLASSES})
_M_BURN = metrics_lib.gauge(
    'skytpu_cost_burn_rate',
    'Cost-budget burn rate per budget pool and window (1.0 = spending '
    'exactly the budgeted $/hour).',
    labels={'pool': BUDGET_POOLS, 'window': ('fast', 'slow')})
_M_STATE = metrics_lib.gauge(
    'skytpu_cost_budget_state',
    'Cost-budget state per budget pool: 0 ok, 1 warning, 2 breach.',
    labels={'pool': BUDGET_POOLS})


# --------------------------------------------------------------- pricing

def hourly_rate(accelerator: str, price_class: str) -> float:
    """$/hour for one replica of ``accelerator`` at ``price_class`` —
    THE price resolution every consumer (serve meter, rollout harness,
    elastic projections, scorecards) goes through. Lazy catalog import:
    observe sits below catalog in the layer order."""
    if price_class not in PRICE_CLASSES:
        raise ValueError(f'unknown price class {price_class!r}; '
                         f'valid: {PRICE_CLASSES}')
    from skypilot_tpu import catalog
    from skypilot_tpu.tpu import topology
    tpu_slice = topology.parse_tpu_accelerator(accelerator)
    return catalog.get_hourly_cost(tpu_slice,
                                   use_spot=price_class == 'spot')


def default_accelerator() -> str:
    return knobs.get_str('SKYTPU_COST_ACCELERATOR')


def default_price_class() -> str:
    return knobs.get_enum('SKYTPU_COST_PRICE_CLASS')


# ------------------------------------------------------------- the table

_local = threading.local()


def _conn() -> sqlite3.Connection:
    path = journal.db_path()
    cached = getattr(_local, 'conn', None)
    if cached is not None and getattr(_local, 'path', None) == path:
        return cached
    os.makedirs(os.path.dirname(path), exist_ok=True)
    conn = sqlite_utils.connect_wal(path)
    conn.execute("""
        CREATE TABLE IF NOT EXISTS costs (
            cost_id INTEGER PRIMARY KEY AUTOINCREMENT,
            ts REAL,
            entity TEXT,
            pool TEXT,
            price_class TEXT,
            hourly_usd REAL,
            seconds REAL,
            usd REAL,
            reference_usd REAL
        )""")
    conn.execute('CREATE INDEX IF NOT EXISTS idx_costs_ts '
                 'ON costs (ts)')
    conn.execute('CREATE INDEX IF NOT EXISTS idx_costs_entity '
                 'ON costs (entity, ts)')
    conn.commit()
    _local.conn = conn
    _local.path = path
    return conn


def insert_costs(rows: List[Tuple[float, str, str, str, float, float,
                                  float, float]]) -> int:
    """One accrual round's rows ``(ts, entity, pool, price_class,
    hourly_usd, seconds, usd, reference_usd)`` in ONE transaction
    (all-or-nothing per round, like a tsdb scrape round). Best-effort:
    a failed persist must never wedge the scrape loop."""
    if not rows:
        return 0
    try:
        conn = _conn()
        with conn:
            conn.executemany(
                'INSERT INTO costs (ts, entity, pool, price_class, '
                'hourly_usd, seconds, usd, reference_usd) '
                'VALUES (?, ?, ?, ?, ?, ?, ?, ?)', rows)
        return len(rows)
    except (sqlite3.Error, OSError):
        return 0


def window_spend(window: float, now: Optional[float] = None,
                 entity_scope: Optional[str] = None
                 ) -> Dict[Tuple[str, str], Dict[str, float]]:
    """Aggregated spend inside the window, grouped per (pool,
    price_class): ``{'usd', 'reference_usd', 'seconds'}``. The
    ``entity_scope`` predicate is journal.entity_scope_clause — the
    same escaped-LIKE security boundary the scoped LB endpoints use.
    Best-effort ({} on failure)."""
    now = time.time() if now is None else now
    clauses = ['ts > ?', 'ts <= ?']
    params: List[Any] = [now - window, now]
    if entity_scope is not None:
        clause, scope_params = journal.entity_scope_clause(entity_scope)
        clauses.append(clause)
        params.extend(scope_params)
    sql = ('SELECT pool, price_class, SUM(usd), SUM(reference_usd), '
           'SUM(seconds) FROM costs WHERE ' + ' AND '.join(clauses) +
           ' GROUP BY pool, price_class')
    try:
        with _conn() as conn:
            rows = conn.execute(sql, params).fetchall()
    except (sqlite3.Error, OSError):
        return {}
    return {(pool, pc): {'usd': usd or 0.0,
                         'reference_usd': ref or 0.0,
                         'seconds': secs or 0.0}
            for pool, pc, usd, ref, secs in rows}


def gc_costs(max_age_seconds: float = 7 * 24 * 3600,
             max_rows: int = 500_000) -> int:
    """Retention, same discipline as tsdb.gc_samples: age window plus
    a row cap keyed on the Nth-NEWEST row id (never max-id arithmetic
    — AUTOINCREMENT ids go sparse after age deletes). Long-lived
    controllers accrue one row per replica per scrape round; without
    this the costs table leaks forever."""
    try:
        conn = _conn()
        with sqlite_utils.immediate(conn):
            cur = conn.execute('DELETE FROM costs WHERE ts < ?',
                               (time.time() - max_age_seconds,))
            deleted = cur.rowcount
            row = conn.execute(
                'SELECT cost_id FROM costs '
                'ORDER BY cost_id DESC LIMIT 1 OFFSET ?',
                (max_rows,)).fetchone()
            if row is not None:
                cur = conn.execute(
                    'DELETE FROM costs WHERE cost_id <= ?', (row[0],))
                deleted += cur.rowcount
        return max(0, deleted)
    except (sqlite3.Error, OSError):
        return 0


# -------------------------------------------------------------- budgets

@dataclasses.dataclass
class CostBudget:
    """One spend objective: ``hourly_usd`` is the budgeted $/hour for
    ``pool`` ('fleet' = every metered pool). Burn over a window is the
    measured spend rate divided by the budget — 1.0 means spending
    exactly the budgeted dollars; a FAST window catches a runaway
    scale-up, a SLOW window confirms it is sustained (a breach
    requires BOTH, exactly the SLO engine's multi-window recipe)."""
    hourly_usd: float
    pool: str = 'fleet'
    name: str = ''
    fast_window: float = 300.0
    slow_window: float = 3600.0
    fast_burn: float = 2.0
    slow_burn: float = 1.2
    clear_rounds: int = 3

    def __post_init__(self) -> None:
        if self.pool not in BUDGET_POOLS:
            raise ValueError(f'unknown budget pool {self.pool!r}; '
                             f'valid: {BUDGET_POOLS}')
        if not self.hourly_usd > 0.0:
            raise ValueError('hourly_usd must be > 0 — a zero budget '
                             'makes every metered second a breach')
        if not self.name:
            self.name = f'cost_{self.pool}'


def default_budgets() -> List[CostBudget]:
    """Budgets from ``SKYTPU_COST_BUDGETS`` — a JSON list of
    :class:`CostBudget` kwargs dicts (docs/OBSERVABILITY.md "Cost
    attribution" shows the format). Malformed raises at startup: a
    silently-dropped budget is unmonitored spend. No stock budgets —
    unlike latency objectives, a dollar ceiling is deployment policy
    with no sane universal default."""
    cfg = knobs.get_json('SKYTPU_COST_BUDGETS')
    if cfg is None:
        return []
    try:
        if not isinstance(cfg, list):
            raise ValueError('expected a JSON list')
        return [CostBudget(**item) for item in cfg]
    except (ValueError, TypeError) as e:
        raise ValueError(
            f'SKYTPU_COST_BUDGETS is malformed ({e}); expected a JSON '
            f'list of cost budget objects, e.g. '
            f'[{{"pool": "serve", "hourly_usd": 40.0}}]') from e


@dataclasses.dataclass
class BudgetEvaluation:
    budget: CostBudget
    state: str
    burn_fast: Optional[float]
    burn_slow: Optional[float]
    rate_usd_per_hour: Optional[float] = None   # slow-window spend rate
    transitioned: bool = False


# ------------------------------------------------------------ the meter

@dataclasses.dataclass
class _Replica:
    entity: str
    pool: str
    accelerator: str
    price_class: str
    hourly_usd: float        # resolved ONCE, at registration
    reference_usd: float     # the on-demand rate, resolved at the same
    last_accrued: float      # instant — drift-proof like hourly_usd


class CostMeter:
    """Prices a fleet's runtime and joins it against its traffic.

    Owned like the SLO engine: the service controller (or the loadgen
    LocalStack) constructs one per service, registers/deregisters
    replicas as the routable set changes, and calls ``accrue()`` +
    ``evaluate()`` from the scrape loop's ``on_round`` hook. ``entity``
    scopes journal events, cost rows and tsdb joins to the owning
    service — the shared-DB reality that made /-/lb/events scoped."""

    def __init__(self, entity: Optional[str] = None,
                 budgets: Optional[List[CostBudget]] = None,
                 join_window: Optional[float] = None):
        self.entity = entity
        self.budgets = (list(budgets) if budgets is not None
                        else default_budgets())
        names = [b.name for b in self.budgets]
        if len(set(names)) != len(names):
            raise ValueError(f'duplicate cost budget names: {names}')
        self.join_window = (knobs.get_float('SKYTPU_COST_JOIN_WINDOW')
                            if join_window is None else join_window)
        self._replicas: Dict[str, _Replica] = {}
        self._totals: Dict[Tuple[str, str], float] = {}
        self._reference_totals: Dict[str, float] = {}   # per pool
        self._state: Dict[str, str] = {b.name: 'ok'
                                       for b in self.budgets}
        self._clean_rounds: Dict[str, int] = {b.name: 0
                                              for b in self.budgets}
        self._last_evals: List[BudgetEvaluation] = []
        self._publish_states()

    # -------------------------------------------------- registration
    def register(self, entity: str, pool: str, *,
                 accelerator: Optional[str] = None,
                 price_class: Optional[str] = None,
                 now: Optional[float] = None) -> None:
        """Start metering one replica. The price resolves HERE, once,
        and rides a ``cost_price`` journal event — the run's pricing
        history survives later catalog edits. Idempotent for an
        unchanged (accelerator, price_class); a changed price class
        (spot replica replaced by on-demand) closes the old meter at
        ``now`` and opens a fresh one, so a mid-window flip accrues
        each side at its own rate."""
        if pool not in POOLS:
            raise ValueError(f'unknown cost pool {pool!r}; '
                             f'valid: {POOLS}')
        now = time.time() if now is None else now
        accelerator = accelerator or default_accelerator()
        price_class = price_class or default_price_class()
        current = self._replicas.get(entity)
        if current is not None:
            if (current.accelerator == accelerator and
                    current.price_class == price_class and
                    current.pool == pool):
                return
            self.deregister(entity, now=now)
        rate = hourly_rate(accelerator, price_class)
        reference = (rate if price_class == 'on_demand'
                     else hourly_rate(accelerator, 'on_demand'))
        self._replicas[entity] = _Replica(
            entity=entity, pool=pool, accelerator=accelerator,
            price_class=price_class, hourly_usd=rate,
            reference_usd=reference, last_accrued=now)
        journal.record_event(
            'cost_price', entity=entity,
            reason=f'{accelerator}@{price_class}',
            data={'pool': pool, 'accelerator': accelerator,
                  'price_class': price_class, 'hourly_usd': rate,
                  'reference_hourly_usd': reference})

    def deregister(self, entity: str,
                   now: Optional[float] = None) -> None:
        """Final accrual up to ``now``, then stop metering."""
        replica = self._replicas.pop(entity, None)
        if replica is None:
            return
        now = time.time() if now is None else now
        self._accrue_rows([replica], now)

    def replicas(self) -> Dict[str, str]:
        """{entity: price_class} of currently metered replicas."""
        return {e: r.price_class for e, r in self._replicas.items()}

    # ------------------------------------------------------- accrual
    def _accrue_rows(self, replicas: List[_Replica],
                     now: float) -> None:
        rows = []
        for r in replicas:
            dt = now - r.last_accrued
            if dt <= 0:
                continue
            usd = r.hourly_usd * dt / 3600.0
            ref = r.reference_usd * dt / 3600.0
            rows.append((now, r.entity, r.pool, r.price_class,
                         r.hourly_usd, dt, usd, ref))
            r.last_accrued = now
            key = (r.pool, r.price_class)
            self._totals[key] = self._totals.get(key, 0.0) + usd
            self._reference_totals[r.pool] = (
                self._reference_totals.get(r.pool, 0.0) + ref)
        insert_costs(rows)

    def charge(self, entity: str, seconds: float,
               now: Optional[float] = None) -> float:
        """Manual accrual of measured busy-seconds for a registered
        replica (the rollout harness's path — it meters compute time,
        not wall-clock between scrape rounds). Returns the dollars
        charged."""
        replica = self._replicas[entity]
        now = time.time() if now is None else now
        usd = replica.hourly_usd * seconds / 3600.0
        ref = replica.reference_usd * seconds / 3600.0
        insert_costs([(now, replica.entity, replica.pool,
                       replica.price_class, replica.hourly_usd,
                       seconds, usd, ref)])
        key = (replica.pool, replica.price_class)
        self._totals[key] = self._totals.get(key, 0.0) + usd
        self._reference_totals[replica.pool] = (
            self._reference_totals.get(replica.pool, 0.0) + ref)
        return usd

    def accrue(self, now: Optional[float] = None) -> int:
        """One metering round (scrape-loop thread): wall-clock since
        each replica's last accrual becomes replica-seconds and
        dollars, persisted and folded into the gauges; then the
        token/request joins republish. Returns the number of metered
        replicas."""
        now = time.time() if now is None else now
        live = list(self._replicas.values())
        self._accrue_rows(live, now)
        for (pool, price_class), usd in self._totals.items():
            _M_USD_TOTAL.set(usd, pool=pool, price_class=price_class)
        try:
            self._publish_joins(now)
        except Exception:  # pylint: disable=broad-except
            # The joins read tsdb (shared sqlite) — a failed join must
            # not kill the metering itself; dollars stay accrued.
            logger.warning('cost join publish failed:', exc_info=True)
        return len(live)

    # --------------------------------------------------------- joins
    def _scoped_targets(self, now: float, window: float) -> List[str]:
        if self.entity is None:
            return tsdb.targets(since=now - window)
        prefix = f'{self.entity}/'
        return [t for t in tsdb.targets(since=now - window)
                if t == self.entity or t.startswith(prefix)]

    def _target_pool(self, target: str) -> str:
        """A scrape target's cost pool from its entity shape:
        ``<svc>/<role>/<rid>`` carries its pool in the role segment
        (the disagg tagging convention); anything else is the
        monolithic serve pool."""
        parts = target.split('/')
        if len(parts) >= 3 and parts[-2] in POOLS:
            return parts[-2]
        return 'serve'

    def _publish_joins(self, now: float) -> None:
        window = self.join_window
        spend = window_spend(window, now, entity_scope=self.entity)
        usd_by_pool: Dict[str, float] = {}
        for (pool, _), agg in spend.items():
            usd_by_pool[pool] = usd_by_pool.get(pool, 0.0) + agg['usd']
        total_usd = sum(usd_by_pool.values())
        tokens_by_pool: Dict[str, float] = {}
        class_tokens: Dict[str, float] = {}
        class_requests: Dict[str, float] = {}
        for target in self._scoped_targets(now, window):
            pool = self._target_pool(target)
            tokens_by_pool[pool] = (tokens_by_pool.get(pool, 0.0) +
                                    _counter_window_sum(
                                        TOKENS_FAMILY, target, window,
                                        now))
            for cls in request_class.CLASSES:
                cls_labels = promtext.labels_text((('cls', cls),))
                class_tokens[cls] = (
                    class_tokens.get(cls, 0.0) +
                    _counter_window_sum(CLASS_TOKENS_FAMILY, target,
                                        window, now,
                                        labels=cls_labels))
                for outcome in ('good', 'slow'):
                    key = promtext.labels_text(
                        (('cls', cls), ('outcome', outcome)))
                    class_requests[cls] = (
                        class_requests.get(cls, 0.0) +
                        _counter_window_sum(GOODPUT_FAMILY, target,
                                            window, now, labels=key))
        for pool, usd in usd_by_pool.items():
            tokens = tokens_by_pool.get(pool, 0.0)
            if tokens > 0:
                _M_PER_TOKEN.set(usd / tokens, pool=pool)
        # Per-request cost: apportion the window's dollars by each
        # class's decode-token share (its actual compute draw), then
        # divide by its finished requests. With no per-class token
        # data yet, fall back to request share — uniform per request,
        # honest about what IS known.
        token_total = sum(class_tokens.values())
        request_total = sum(class_requests.values())
        for cls in request_class.CLASSES:
            finished = class_requests.get(cls, 0.0)
            if finished <= 0 or total_usd <= 0:
                continue
            if token_total > 0:
                share = class_tokens.get(cls, 0.0) / token_total
            elif request_total > 0:
                share = finished / request_total
            else:
                continue
            _M_PER_REQUEST.set(total_usd * share / finished, cls=cls)

    # ------------------------------------------------------ budgets
    def _pool_rates(self, budget: CostBudget, now: float
                    ) -> Tuple[Optional[float], Optional[float]]:
        """(fast, slow) spend rates in $/hour for one budget's scope.
        None with no cost rows in the window — no data must HOLD the
        state (the meter may simply not have accrued yet), never read
        as zero spend."""
        out: List[Optional[float]] = []
        for window in (budget.fast_window, budget.slow_window):
            spend = window_spend(window, now, entity_scope=self.entity)
            rows = [agg for (pool, _), agg in spend.items()
                    if budget.pool == 'fleet' or pool == budget.pool]
            if not rows:
                out.append(None)
                continue
            usd = sum(agg['usd'] for agg in rows)
            out.append(usd / window * 3600.0)
        return out[0], out[1]

    @staticmethod
    def _target_state(budget: CostBudget, burn_fast: Optional[float],
                      burn_slow: Optional[float]) -> Optional[str]:
        if burn_fast is None and burn_slow is None:
            return None
        bf = burn_fast or 0.0
        bs = burn_slow or 0.0
        if bf >= budget.fast_burn and bs >= budget.slow_burn:
            return 'breach'
        if bf >= budget.fast_burn or bs >= 1.0:
            return 'warning'
        return 'ok'

    def evaluate(self, now: Optional[float] = None
                 ) -> List[BudgetEvaluation]:
        """One budget round (scrape-loop thread, after accrue()):
        escalation immediate, de-escalation after ``clear_rounds``
        consecutive cleaner rounds — a spend rate hovering at the
        threshold cannot strobe ok/breach."""
        now = time.time() if now is None else now
        out: List[BudgetEvaluation] = []
        burn_by_pool: Dict[Tuple[str, str], float] = {}
        for budget in self.budgets:
            try:
                rate_fast, rate_slow = self._pool_rates(budget, now)
            except Exception:  # pylint: disable=broad-except
                # Per-budget containment, the SLO engine's idiom: one
                # budget's read blowing up must not kill the others.
                logger.warning(
                    f'cost budget {budget.name!r} evaluation failed; '
                    f'holding state {self._state[budget.name]!r}:',
                    exc_info=True)
                out.append(BudgetEvaluation(
                    budget=budget, state=self._state[budget.name],
                    burn_fast=None, burn_slow=None))
                continue
            burn_fast = (None if rate_fast is None
                         else rate_fast / budget.hourly_usd)
            burn_slow = (None if rate_slow is None
                         else rate_slow / budget.hourly_usd)
            for window, burn in (('fast', burn_fast),
                                 ('slow', burn_slow)):
                if burn is None:
                    continue       # no data is NOT a zero burn
                key = (budget.pool, window)
                burn_by_pool[key] = max(burn_by_pool.get(key, 0.0),
                                        burn)
            target = self._target_state(budget, burn_fast, burn_slow)
            current = self._state[budget.name]
            transitioned = False
            if target is not None and target != current:
                if _STATE_CODE[target] > _STATE_CODE[current]:
                    transitioned = self._transition(
                        budget, current, target, burn_fast, burn_slow,
                        rate_slow)
                else:
                    self._clean_rounds[budget.name] += 1
                    if self._clean_rounds[budget.name] >= \
                            budget.clear_rounds:
                        transitioned = self._transition(
                            budget, current, target, burn_fast,
                            burn_slow, rate_slow)
            else:
                self._clean_rounds[budget.name] = 0
            out.append(BudgetEvaluation(
                budget=budget, state=self._state[budget.name],
                burn_fast=burn_fast, burn_slow=burn_slow,
                rate_usd_per_hour=rate_slow,
                transitioned=transitioned))
        for (pool, window), burn in burn_by_pool.items():
            _M_BURN.set(burn, pool=pool, window=window)
        self._publish_states()
        self._last_evals = out
        return out

    def _transition(self, budget: CostBudget, old: str, new: str,
                    burn_fast: Optional[float],
                    burn_slow: Optional[float],
                    rate_slow: Optional[float]) -> bool:
        self._state[budget.name] = new
        self._clean_rounds[budget.name] = 0
        logger.warning(f'Cost budget {budget.name!r}: {old} -> {new} '
                       f'(burn fast={burn_fast}, slow={burn_slow})')
        journal.record_event(
            f'cost_budget_{new}', entity=self.entity,
            reason=f'{old}->{new}',
            data={'budget': budget.name, 'pool': budget.pool,
                  'hourly_usd': budget.hourly_usd,
                  'burn_fast': burn_fast, 'burn_slow': burn_slow,
                  'rate_usd_per_hour': rate_slow})
        return True

    def _publish_states(self) -> None:
        per_pool: Dict[str, int] = {}
        for budget in self.budgets:
            code = _STATE_CODE[self._state[budget.name]]
            per_pool[budget.pool] = max(per_pool.get(budget.pool, 0),
                                        code)
        for pool, code in per_pool.items():
            _M_STATE.set(code, pool=pool)

    def states(self) -> Dict[str, str]:
        return dict(self._state)

    def budget_summary(self) -> Dict[str, Dict[str, Any]]:
        """Per-budget snapshot of the last evaluate() round (the
        /-/fleet/costs budget rows). Empty before the first round."""
        out: Dict[str, Dict[str, Any]] = {}
        for ev in self._last_evals:
            out[ev.budget.name] = {
                'pool': ev.budget.pool,
                'hourly_usd': ev.budget.hourly_usd,
                'state': ev.state,
                'burn_fast': ev.burn_fast,
                'burn_slow': ev.burn_slow,
                'rate_usd_per_hour': ev.rate_usd_per_hour,
            }
        return out

    # ---------------------------------------------------- projections
    def pool_hourly_usd(self, pool: str) -> Optional[float]:
        """Current metered $/hour of one pool's live replicas (None
        when nothing is registered there)."""
        rates = [r.hourly_usd for r in self._replicas.values()
                 if r.pool == pool]
        return sum(rates) if rates else None

    def projector(self, pool: str
                  ) -> Callable[[int, int], Optional[float]]:
        """A ``(old_units, new_units) -> projected $/hour delta``
        closure for the elastic controller's decision journal — the
        price math stays HERE, the controller only carries the
        number. Projects at the pool's mean per-replica rate; None
        before the first replica registers (nothing to price from)."""
        def project(old: int, new: int) -> Optional[float]:
            rates = [r.hourly_usd for r in self._replicas.values()
                     if r.pool == pool]
            if not rates:
                return None
            return (new - old) * (sum(rates) / len(rates))
        return project

    # ------------------------------------------------------- summary
    def summary(self, window: Optional[float] = None,
                now: Optional[float] = None) -> Dict[str, Any]:
        """One JSON-able doc merging the metered window, the live
        rates, the joins and the budget states — the /-/fleet/costs
        body and the scorecard's cost section."""
        now = time.time() if now is None else now
        window = self.join_window if window is None else window
        doc = window_summary(window, now=now, entity_scope=self.entity)
        doc['entity'] = self.entity
        live: Dict[str, Any] = {}
        for r in self._replicas.values():
            row = live.setdefault(r.pool, {'replicas': 0,
                                           'hourly_usd': 0.0,
                                           'price_classes': set()})
            row['replicas'] += 1
            row['hourly_usd'] = round(row['hourly_usd'] +
                                      r.hourly_usd, 6)
            row['price_classes'].add(r.price_class)
        for row in live.values():
            row['price_classes'] = sorted(row['price_classes'])
        doc['live'] = live
        doc['budgets'] = self.budget_summary()
        return doc


# ------------------------------------------------------- offline reads

def _counter_window_sum(name: str, target: str, window: float,
                        now: float,
                        labels: Optional[str] = None) -> float:
    """One target's windowed counter delta, summed across label sets
    (or restricted to one canonical ``labels`` rendering). The
    counter-restart rule is slo.py's: a negative delta means the
    replica relaunched inside the window, and the latest ABSOLUTE
    value is the honest lower bound."""
    latest = tsdb.latest_round(name, target)
    if not latest:
        return 0.0
    anchor = tsdb.round_at_or_before(name, target, now - window)
    total = 0.0
    for labels_key, (_, value) in latest.items():
        if labels is not None and labels_key != labels:
            continue
        prev = anchor.get(labels_key, (0.0, 0.0))[1]
        total += value - prev if value >= prev else value
    return total


def window_summary(window: float, now: Optional[float] = None,
                   entity_scope: Optional[str] = None
                   ) -> Dict[str, Any]:
    """The metered window from the DB alone — no meter object needed
    (the ``observe cost --db`` offline path and the live summary's
    shared core): per-pool dollars/seconds/per-token joins, totals and
    the spot discount. ``entity_scope`` restricts a shared DB to one
    service's subtree."""
    now = time.time() if now is None else now
    spend = window_spend(window, now, entity_scope=entity_scope)
    pools: Dict[str, Dict[str, Any]] = {}
    for (pool, price_class), agg in spend.items():
        row = pools.setdefault(pool, {'usd': 0.0, 'reference_usd': 0.0,
                                      'replica_seconds': 0.0,
                                      'by_price_class': {}})
        row['usd'] += agg['usd']
        row['reference_usd'] += agg['reference_usd']
        row['replica_seconds'] += agg['seconds']
        row['by_price_class'][price_class] = round(agg['usd'], 9)
    # Token joins per pool over the same window and scope.
    targets = tsdb.targets(since=now - window)
    if entity_scope is not None:
        prefix = f'{entity_scope}/'
        targets = [t for t in targets
                   if t == entity_scope or t.startswith(prefix)]
    tokens_by_pool: Dict[str, float] = {}
    requests = 0.0
    for target in targets:
        parts = target.split('/')
        pool = (parts[-2] if len(parts) >= 3 and parts[-2] in POOLS
                else 'serve')
        tokens_by_pool[pool] = (tokens_by_pool.get(pool, 0.0) +
                                _counter_window_sum(
                                    TOKENS_FAMILY, target, window,
                                    now))
        requests += _counter_window_sum('skytpu_engine_requests_total',
                                        target, window, now)
    total_usd = 0.0
    total_ref = 0.0
    total_tokens = 0.0
    for pool, row in pools.items():
        tokens = tokens_by_pool.get(pool, 0.0)
        row['tokens'] = tokens
        if tokens > 0:
            row['cost_per_token_usd'] = round(row['usd'] / tokens, 12)
        total_usd += row['usd']
        total_ref += row['reference_usd']
        total_tokens += tokens
        row['usd'] = round(row['usd'], 9)
        row['reference_usd'] = round(row['reference_usd'], 9)
        row['replica_seconds'] = round(row['replica_seconds'], 3)
    totals: Dict[str, Any] = {
        'usd': round(total_usd, 9),
        'reference_usd': round(total_ref, 9),
    }
    if total_tokens > 0 and total_usd > 0:
        totals['cost_per_token_usd'] = round(total_usd / total_tokens,
                                             12)
    if requests > 0 and total_usd > 0:
        totals['cost_per_request_usd'] = round(total_usd / requests,
                                               12)
    if total_usd > 0:
        # What the same replica-seconds would have cost on-demand,
        # over what they did cost: the spot discount (1.0 when every
        # replica already runs on-demand).
        totals['spot_discount'] = round(total_ref / total_usd, 4)
    return {'window_seconds': window, 'pools': pools,
            'totals': totals}


# --------------------------------------------------- rollout cost path

def cost_per_sample(samples: int, learner_busy_s: float,
                    worker_busy_s: float, *,
                    accelerator: str = 'v5litepod-8',
                    workers_spot: bool = True) -> Dict[str, Any]:
    """$/sample for a rollout run: stable learner at on-demand price,
    rollout fleet at spot (harvested) or on-demand (control) — the
    rollout harness's historical contract (key set, rounding and all:
    RL_HARVEST_LAST_GOOD.json pins the numbers), re-priced through the
    one CostMeter code path instead of its own catalog math."""
    meter = CostMeter(entity='rollout_cost', budgets=[])
    meter.register('rollout_cost/learner', 'learner',
                   accelerator=accelerator, price_class='on_demand')
    meter.register(
        'rollout_cost/workers', 'rollout', accelerator=accelerator,
        price_class='spot' if workers_spot else 'on_demand')
    learner_rate = meter._replicas[  # pylint: disable=protected-access
        'rollout_cost/learner'].hourly_usd
    worker_rate = meter._replicas[  # pylint: disable=protected-access
        'rollout_cost/workers'].hourly_usd
    learner_cost = meter.charge('rollout_cost/learner', learner_busy_s)
    worker_cost = meter.charge('rollout_cost/workers', worker_busy_s)
    total = learner_cost + worker_cost
    return {
        'accelerator': accelerator,
        'workers_spot': workers_spot,
        'learner_hourly_usd': learner_rate,
        'worker_hourly_usd': worker_rate,
        'learner_cost_usd': round(learner_cost, 6),
        'worker_cost_usd': round(worker_cost, 6),
        'total_cost_usd': round(total, 6),
        'cost_per_sample_usd': (round(total / samples, 9)
                                if samples else None),
    }
