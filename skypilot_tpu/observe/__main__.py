"""Observability CLI: ``python -m skypilot_tpu.observe <cmd>``.

Commands:
  tail     — last N journal events, human-readable or --json
  events   — filtered journal query (--machine/--entity/--trace/
             --kind/--since/--limit)
  trace    — one request's latency decomposition: the rooted span
             tree with durations and % of parent. ``--url`` fetches a
             live ``/v1/traces/<id>`` (API server) or
             ``/-/lb/trace/<id>`` (serve LB) endpoint; without it the
             local journal DB is read directly (``--db`` overrides
             the path)
  metrics  — dump Prometheus exposition: --url fetches a live
             ``/metrics`` endpoint (API server, serve LB); without
             --url, renders THIS process's registry (useful from
             tests/REPLs, empty in a fresh CLI process)
  export   — write matching journal events as JSONL through the
             shared rotating writer; ``--chrome`` writes the span
             tables merged with any timeline capture as Chrome
             trace-event JSON instead (load in Perfetto)
  cost     — the cost-attribution view (observe/costs.py): per-pool
             metered dollars, $/token join, spot discount and budget
             states. ``--url`` asks a live serve LB's
             ``/-/fleet/costs``; without it the costs/tsdb tables this
             process can see are read (``--db`` repoints,
             ``--window`` bounds the metered window)
  fleet    — the fleet view: per-replica scrape/saturation table +
             merged fleet TTFT/TPOT p50/p95 (the shared
             promtext.histogram_quantile) + the per-class table
             (goodput, miss fraction, class p95s, SLO burn/state) —
             every registered class renders, sample-less ones as
             ``-`` cells. ``--url`` asks a live serve LB
             (``/-/fleet/status`` + ``/-/fleet/metrics``); without
             it the local scraped-samples table is read (``--db``
             repoints, ``--window`` bounds the quantile window)

Exit codes: 0 ok, 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.observe import journal
from skypilot_tpu.observe import metrics
from skypilot_tpu.observe import spans as spans_lib
from skypilot_tpu.utils import knobs


def _fmt_event(e: Dict[str, Any]) -> str:
    ts = time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(e['ts']))
    trace_part = f' trace={e["trace_id"]}' if e.get('trace_id') else ''
    if e['kind'] == journal.KIND_TRANSITION:
        body = (f'{e["machine"]} {e["entity"]}: '
                f'{e["old_status"]} -> {e["new_status"]}')
    elif e['kind'] == journal.KIND_ENTRY:
        body = f'{e["machine"]} {e["entity"]}: entered {e["new_status"]}'
    else:
        body = f'{e["kind"]} {e.get("entity") or ""}'.strip()
    reason = f' ({e["reason"]})' if e.get('reason') else ''
    return f'{ts} [{e["event_id"]}] {body}{reason}{trace_part}'


def _print_events(events: List[Dict[str, Any]], as_json: bool) -> None:
    if as_json:
        print(json.dumps(events, indent=2))
        return
    for e in events:
        print(_fmt_event(e))


def _query_args(args: argparse.Namespace) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key in ('machine', 'entity', 'kind'):
        val = getattr(args, key, None)
        if val is not None:
            out[key] = val
    if getattr(args, 'trace', None) is not None:
        out['trace_id'] = args.trace
    if getattr(args, 'since', None) is not None:
        out['since'] = args.since
    if getattr(args, 'limit', None) is not None:
        out['limit'] = args.limit
    return out


def _fetch_tree(trace_id: str, url: Optional[str],
                db: Optional[str]) -> Dict[str, Any]:
    """The span tree for one trace: from a live endpoint (--url: an
    API server's /v1/traces or a serve LB's /-/lb/trace — a bare
    host:port gets the API-server path) or straight from the journal
    DB this process can see (--db repoints it)."""
    if url is not None:
        from urllib import request as urlrequest
        target = url if '://' in url else f'http://{url}'
        if not target.rstrip('/').endswith(trace_id):
            target = f'{target.rstrip("/")}/v1/traces/{trace_id}'
        with urlrequest.urlopen(target, timeout=10) as resp:
            return json.loads(resp.read().decode('utf-8'))
    if db is not None:
        knobs.export('SKYTPU_OBSERVE_DB', db)
    return spans_lib.tree(trace_id)


def _fetch_metrics(url: Optional[str]) -> str:
    if url is None:
        return metrics.render()
    from urllib import request as urlrequest
    target = url if '://' in url else f'http://{url}'
    if not target.rstrip('/').endswith('/metrics'):
        target = target.rstrip('/') + '/metrics'
    with urlrequest.urlopen(target, timeout=10) as resp:
        return resp.read().decode('utf-8', errors='replace')


_FLEET_QUANTILES = ((0.50, 'p50'), (0.95, 'p95'))
_FLEET_FAMILIES = (('skytpu_engine_ttft_seconds', 'ttft'),
                   ('skytpu_engine_tpot_seconds', 'tpot'))


def _http_json(url: str) -> Dict[str, Any]:
    from urllib import request as urlrequest
    with urlrequest.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read().decode('utf-8'))


def _http_text(url: str) -> str:
    from urllib import request as urlrequest
    with urlrequest.urlopen(url, timeout=10) as resp:
        return resp.read().decode('utf-8', errors='replace')


def _fleet_doc(url: Optional[str], db: Optional[str],
               window: float) -> Dict[str, Any]:
    """The fleet view as one JSON-able doc: per-replica rows + merged
    quantiles. Live (--url → a serve LB's /-/fleet/ endpoints) or
    offline (the scraped-samples table this process can see)."""
    from skypilot_tpu.observe import promtext
    if url is not None:
        base = (url if '://' in url else f'http://{url}').rstrip('/')
        doc = _http_json(base + '/-/fleet/status')
        # /-/fleet/metrics legitimately answers 503 before the first
        # scrape or during a full outage (every replica stale) — the
        # per-replica status table we ALREADY have is the operator's
        # diagnostic in exactly that moment, so degrade to it instead
        # of aborting the whole view on the metrics fetch.
        try:
            text = _http_text(base + '/-/fleet/metrics')
        except OSError as e:
            doc['fleet_quantiles'] = {}
            doc['metrics_error'] = str(e)
            return doc
        quantiles: Dict[str, float] = {}
        for family, short in _FLEET_FAMILIES:
            for q, suffix in _FLEET_QUANTILES:
                v = promtext.quantile_from_text(text, family, q)
                if v == v:                       # not NaN
                    quantiles[f'{short}_{suffix}_ms'] = round(v * 1e3,
                                                              2)
        doc['fleet_quantiles'] = quantiles
        return doc
    if db is not None:
        knobs.export('SKYTPU_OBSERVE_DB', db)
    from skypilot_tpu.observe import request_class
    from skypilot_tpu.observe import slo as slo_lib
    from skypilot_tpu.observe import tsdb
    now = time.time()
    replicas = []
    for target in tsdb.targets(since=now - window):
        row: Dict[str, Any] = {'entity': target}
        up = tsdb.latest_round('skytpu_scrape_up', target)
        if up:
            ts, val = next(iter(up.values()))
            row['last_success_age'] = (round(now - ts, 1)
                                       if val >= 0.5 else None)
            row['up'] = val >= 0.5
        for name, key in (('skytpu_engine_queue_depth', 'queue_depth'),
                          ('skytpu_engine_in_flight', 'in_flight'),
                          ('skytpu_engine_kv_pages_free',
                           'kv_pages_free')):
            latest = tsdb.latest_round(name, target)
            if latest:
                row[key] = next(iter(latest.values()))[1]
        replicas.append(row)
    quantiles = {}
    for family, short in _FLEET_FAMILIES:
        hist = slo_lib.windowed_histogram(family, window, now)
        for q, suffix in _FLEET_QUANTILES:
            v = promtext.histogram_quantile(hist, q)
            if v == v:
                quantiles[f'{short}_{suffix}_ms'] = round(v * 1e3, 2)
    # Per-class scorecard columns from the same scraped samples. Every
    # lookup degrades to "no row entries" for a class with no samples
    # yet — a freshly declared class must render, not KeyError.
    classes = {}
    for cls in request_class.CLASSES:
        row = {}
        fast, slow, measured = slo_lib.goodput_fractions(
            cls, window, window, now)
        del fast
        if measured is not None:
            row['goodput'] = round(measured, 4)
            # Burn is objective-relative; offline (no SLOEngine, no
            # specs) reports the raw miss fraction instead — the live
            # path's status doc carries real burn_fast/burn_slow.
            row['miss_fraction'] = round(slow, 4)
        cls_filter = promtext.labels_text((('cls', cls),))
        for family, short in (
                ('skytpu_engine_class_ttft_seconds', 'ttft'),
                ('skytpu_engine_class_tpot_seconds', 'tpot')):
            hist = slo_lib.windowed_histogram(
                family, window, now, label_filter=cls_filter)
            v = promtext.histogram_quantile(hist, 0.95)
            if v == v:
                row[f'{short}_p95_ms'] = round(v * 1e3, 2)
        classes[cls] = row
    return {'replicas': replicas, 'fleet_quantiles': quantiles,
            'classes': classes, 'window_seconds': window}


def _cost_doc(url: Optional[str], db: Optional[str],
              window: float) -> Dict[str, Any]:
    """The cost view as one JSON-able doc. Live (--url → a serve LB's
    /-/fleet/costs, the attached meter's summary with its entity
    scope and live rates) or offline (costs.window_summary over the
    tables this process can see — metered history only; no live
    replica rates without a meter)."""
    if url is not None:
        base = (url if '://' in url else f'http://{url}').rstrip('/')
        return _http_json(base + '/-/fleet/costs')
    if db is not None:
        knobs.export('SKYTPU_OBSERVE_DB', db)
    from skypilot_tpu.observe import costs
    return costs.window_summary(window)


def _print_cost(doc: Dict[str, Any]) -> None:
    pools = doc.get('pools') or {}
    if pools:
        cols = ('pool', 'usd', 'reference_usd', 'replica_seconds',
                'tokens', 'cost_per_token_usd')
        rows = [{'pool': pool, **(row if isinstance(row, dict) else {})}
                for pool, row in sorted(pools.items())]
        present = [c for c in cols
                   if any(r.get(c) is not None for r in rows)]
        widths = {c: max(len(c), *(len(_cell(r.get(c)))
                                   for r in rows))
                  for c in present}
        print('  '.join(c.ljust(widths[c]) for c in present))
        for r in rows:
            print('  '.join(_cell(r.get(c)).ljust(widths[c])
                            for c in present))
    else:
        print('(no metered cost rows in the window)')
    totals = doc.get('totals') or {}
    if totals:
        print('totals: ' + '  '.join(
            f'{k}={_cell(v)}' for k, v in sorted(totals.items())))
    budgets = doc.get('budgets') or {}
    for name, row in sorted(budgets.items()):
        print(f'budget {name}: ' + '  '.join(
            f'{k}={_cell(v)}' for k, v in sorted(row.items())))


def _cell(value: Any) -> str:
    """One class-table cell: None (no samples for this class yet)
    renders as '-', floats round-trip compactly."""
    if value is None:
        return '-'
    if isinstance(value, float):
        return f'{value:g}'
    return str(value)


def _print_fleet(doc: Dict[str, Any]) -> None:
    replicas = doc.get('replicas') or []
    cols = ('entity', 'url', 'up', 'last_success_age', 'queue_depth',
            'in_flight', 'kv_pages_free', 'stale', 'error')
    present = [c for c in cols
               if any(c in r and r[c] is not None for r in replicas)]
    if replicas and present:
        widths = {c: max(len(c), *(len(str(r.get(c, '')))
                                   for r in replicas))
                  for c in present}
        print('  '.join(c.ljust(widths[c]) for c in present))
        for r in replicas:
            print('  '.join(str(r.get(c, '')).ljust(widths[c])
                            for c in present))
    else:
        print('(no replicas scraped)')
    slo_states = doc.get('slo')
    if slo_states:
        print('slo: ' + '  '.join(f'{k}={v}'
                                  for k, v in sorted(slo_states.items())))
    classes = doc.get('classes') or {}
    if classes:
        # Every cell via .get: a class with no samples yet renders as
        # blanks, never a KeyError on a missing label set.
        ccols = ('cls', 'goodput', 'good', 'slow', 'miss_fraction',
                 'ttft_p95_ms', 'tpot_p95_ms', 'state', 'burn_fast',
                 'burn_slow')
        rows = [{'cls': cls, **(row if isinstance(row, dict) else {})}
                for cls, row in sorted(classes.items())]
        present = [c for c in ccols
                   if any(r.get(c) is not None for r in rows)]
        if present:
            widths = {c: max(len(c), *(len(_cell(r.get(c)))
                                       for r in rows))
                      for c in present}
            print('  '.join(c.ljust(widths[c]) for c in present))
            for r in rows:
                print('  '.join(_cell(r.get(c)).ljust(widths[c])
                                for c in present))
    quantiles = doc.get('fleet_quantiles') or {}
    if quantiles:
        print('fleet: ' + '  '.join(f'{k}={v}'
                                    for k, v in sorted(quantiles.items())))
    elif doc.get('metrics_error'):
        print(f'fleet: (metrics unavailable: {doc["metrics_error"]})')
    else:
        print('fleet: (no histogram samples yet)')


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog='python -m skypilot_tpu.observe',
        description='Tail/query the event journal; dump metrics.')
    sub = parser.add_subparsers(dest='cmd', required=True)

    p_tail = sub.add_parser('tail', help='last N journal events')
    p_tail.add_argument('-n', type=int, default=20)
    p_tail.add_argument('--json', action='store_true')

    p_events = sub.add_parser('events', help='filtered journal query')
    p_events.add_argument('--machine')
    p_events.add_argument('--entity')
    p_events.add_argument('--trace')
    p_events.add_argument('--kind')
    p_events.add_argument('--since', type=float,
                          help='unix timestamp lower bound')
    p_events.add_argument('--limit', type=int, default=1000)
    p_events.add_argument('--json', action='store_true')

    p_trace = sub.add_parser('trace',
                             help='span tree for one trace id')
    p_trace.add_argument('trace_id')
    p_trace.add_argument('--url', default=None,
                         help='fetch a live trace endpoint (host:port '
                              'or full URL; bare hosts get '
                              '/v1/traces/<id> appended)')
    p_trace.add_argument('--db', default=None,
                         help='read this journal DB instead of the '
                              'default local one (no --url)')
    p_trace.add_argument('--json', action='store_true')

    p_metrics = sub.add_parser('metrics',
                               help='Prometheus exposition dump')
    p_metrics.add_argument('--url', default=None,
                           help='fetch a live /metrics endpoint '
                                '(host:port or full URL)')

    p_export = sub.add_parser('export', help='journal -> JSONL')
    p_export.add_argument('--out', required=True)
    p_export.add_argument('--chrome', action='store_true',
                          help='write Chrome trace-event JSON (spans '
                               'merged with any timeline capture) '
                               'instead of journal JSONL')
    p_export.add_argument('--machine')
    p_export.add_argument('--entity')
    p_export.add_argument('--trace')
    p_export.add_argument('--kind')
    p_export.add_argument('--since', type=float)
    p_export.add_argument('--limit', type=int, default=100000)

    p_fleet = sub.add_parser(
        'fleet', help='per-replica table + merged fleet quantiles')
    p_fleet.add_argument('--url', default=None,
                         help='a live serve LB (host:port or URL); '
                              'fetches /-/fleet/status + '
                              '/-/fleet/metrics')
    p_fleet.add_argument('--db', default=None,
                         help='read this observe DB instead of the '
                              'default local one (no --url)')
    p_fleet.add_argument('--window', type=float, default=3600.0,
                         help='quantile window in seconds for the '
                              'offline (tsdb) path')
    p_fleet.add_argument('--json', action='store_true')

    p_cost = sub.add_parser(
        'cost', help='per-pool metered dollars + $/token joins + '
                     'budget states')
    p_cost.add_argument('--url', default=None,
                        help='a live serve LB (host:port or URL); '
                             'fetches /-/fleet/costs')
    p_cost.add_argument('--db', default=None,
                        help='read this observe DB instead of the '
                             'default local one (no --url)')
    p_cost.add_argument('--window', type=float, default=3600.0,
                        help='metered window in seconds for the '
                             'offline path')
    p_cost.add_argument('--json', action='store_true')
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == 'tail':
        _print_events(journal.tail(args.n), args.json)
    elif args.cmd == 'events':
        _print_events(journal.query(**_query_args(args)), args.json)
    elif args.cmd == 'metrics':
        try:
            sys.stdout.write(_fetch_metrics(args.url))
        except OSError as e:
            print(f'observe: could not fetch metrics: {e}',
                  file=sys.stderr)
            return 2
    elif args.cmd == 'trace':
        try:
            result = _fetch_tree(args.trace_id, args.url, args.db)
        except (OSError, ValueError) as e:
            print(f'observe: could not fetch trace: {e}',
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(result, indent=2))
        else:
            print(spans_lib.format_tree(result))
    elif args.cmd == 'fleet':
        try:
            doc = _fleet_doc(args.url, args.db, args.window)
        except (OSError, ValueError) as e:
            print(f'observe: could not fetch fleet view: {e}',
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(doc, indent=2))
        else:
            _print_fleet(doc)
    elif args.cmd == 'cost':
        try:
            doc = _cost_doc(args.url, args.db, args.window)
        except (OSError, ValueError) as e:
            print(f'observe: could not fetch cost view: {e}',
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(doc, indent=2))
        else:
            _print_cost(doc)
    elif args.cmd == 'export':
        if args.chrome:
            # chrome_trace filters by trace id only — refuse the other
            # filters instead of writing the whole table while the
            # user believes it was narrowed.
            ignored = [f'--{k}' for k in
                       ('machine', 'entity', 'kind', 'since')
                       if getattr(args, k, None) is not None]
            if ignored:
                print(f'observe: --chrome supports --trace only '
                      f'(got {", ".join(ignored)})', file=sys.stderr)
                return 2
            doc = spans_lib.chrome_trace(trace_id=args.trace,
                                         limit=args.limit)
            with open(args.out, 'w', encoding='utf-8') as f:
                json.dump(doc, f)
            note = (' (hit --limit: oldest spans dropped)'
                    if len(doc['traceEvents']) >= args.limit else '')
            print(f'observe: wrote {len(doc["traceEvents"])} trace '
                  f'event(s) to {args.out}{note}', file=sys.stderr)
        else:
            n = journal.export_jsonl(args.out, **_query_args(args))
            print(f'observe: wrote {n} event(s) to {args.out}',
                  file=sys.stderr)
    return 0


if __name__ == '__main__':
    sys.exit(main())
