"""Trace/correlation IDs, carried on a contextvar + SKYTPU_TRACE_ID.

One trace ID is minted per API request at ingress
(``server/requests_lib.create``) and threaded through everything that
request causes: the runner subprocess, the managed-job controller, the
recovery strategy, the backend and finally the slice driver's gang env
— so a preempted replica's journal entries, timeline spans and usage
events can all be joined back to the request that launched it.

Two carriers, checked in order:

  * the :mod:`contextvars` variable — same-process propagation (async
    handlers, ``with trace_context(...)`` scopes). NOTE: plain
    ``threading.Thread`` targets start with an EMPTY context, so a
    thread that must carry the trace either re-sets it or relies on
    the env carrier below.
  * the ``SKYTPU_TRACE_ID`` environment variable — cross-process
    propagation. ``adopt()`` writes both, which is what dedicated
    per-entity processes (request runner, job controller, serve
    controller, slice driver) call at startup so every child process
    they spawn inherits the trace for free.

Stdlib-only; safe to import from any layer.
"""
from __future__ import annotations

import contextlib
import contextvars
import re
import uuid

from skypilot_tpu.utils import knobs
from typing import Dict, Iterator, Optional

ENV_VAR = 'SKYTPU_TRACE_ID'

_HEX_RE = re.compile(r'[0-9a-fA-F]{8,64}')

_TRACE: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    'skytpu_trace_id', default=None)


def new_trace_id() -> str:
    """A fresh 16-hex-char correlation id."""
    return uuid.uuid4().hex[:16]


def is_valid_trace_id(candidate: str) -> bool:
    """Is this an acceptable EXTERNALLY-supplied trace id?

    One definition for every ingress (API header today, future LB/CLI
    surfaces): hex with optional uuid-style dashes, 8-64 hex chars
    total. The value lands in DB rows, journal indexes and
    child-process environments, so anything else must be rejected in
    favor of a minted id.
    """
    if not candidate or len(candidate) > 64:
        return False
    return bool(_HEX_RE.fullmatch(candidate.replace('-', '')))


def get() -> Optional[str]:
    """The active trace id: contextvar first, then the env carrier."""
    tid = _TRACE.get()
    if tid:
        return tid
    return knobs.get_str(ENV_VAR) or None


def set_trace(trace_id: Optional[str]) -> 'contextvars.Token':
    """Bind ``trace_id`` in the current context; returns the reset token."""
    return _TRACE.set(trace_id)


def reset(token: 'contextvars.Token') -> None:
    _TRACE.reset(token)


@contextlib.contextmanager
def trace_context(trace_id: Optional[str] = None) -> Iterator[str]:
    """Scope a trace id (minting one when none is given)."""
    tid = trace_id or new_trace_id()
    token = _TRACE.set(tid)
    try:
        yield tid
    finally:
        _TRACE.reset(token)


def adopt(trace_id: Optional[str]) -> None:
    """Make ``trace_id`` this PROCESS's trace: contextvar + env.

    Called at the top of dedicated per-entity processes (request
    runner, jobs controller, serve controller, slice driver) so that
    (a) every journal/metric/timeline call in the process carries it
    and (b) every subprocess inherits it through the environment.
    """
    if not trace_id:
        return
    _TRACE.set(trace_id)
    knobs.export(ENV_VAR, trace_id)


def env_with_trace(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """A copy of ``env`` (default: empty) with the active trace stamped
    in — for subprocess spawns that build their env explicitly."""
    out = dict(env or {})
    tid = get()
    if tid:
        out[ENV_VAR] = tid
    return out
