"""Scraped-sample time-series store (sqlite, WAL) — the fleet plane's
memory.

The controller scraper (observe/scrape.py) persists a curated set of
every replica's metrics here each round; the SLO engine (observe/slo.py)
evaluates burn-rate windows over it and the ``observe fleet`` CLI reads
it directly when no live endpoint is reachable. Same DB file as the
journal (``SKYTPU_OBSERVE_DB``) — one retention loop, one place to
look — in its own ``samples`` table.

Schema (one row per sample per target per scrape round):

    samples(sample_id AUTOINCREMENT, ts REAL, target TEXT,
            name TEXT, labels TEXT, value REAL)

``target`` is the scraped entity (``<service>/<replica_id>``);
``labels`` is the canonical sorted ``k="v"`` rendering of the sample's
label set ('' for none) so histogram bucket series round-trip exactly.

Write contract (same as the journal): INSERT-only on the hot path,
best-effort — a sample that fails to persist must never wedge the
scrape loop; sqlite-3.34-safe (no RETURNING, ``connect_wal``);
retention via :func:`gc_samples` (age window + Nth-newest-id row cap),
wired into the shared ``observe.gc()``.
"""
from __future__ import annotations

import os
import sqlite3
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from skypilot_tpu.utils import sqlite_utils

from skypilot_tpu.observe import journal

# (name, labels, value) — labels already canonically rendered.
SampleRow = Tuple[str, str, float]

_local = threading.local()


def _conn() -> sqlite3.Connection:
    path = journal.db_path()
    cached = getattr(_local, 'conn', None)
    if cached is not None and getattr(_local, 'path', None) == path:
        return cached
    os.makedirs(os.path.dirname(path), exist_ok=True)
    conn = sqlite_utils.connect_wal(path)
    conn.execute("""
        CREATE TABLE IF NOT EXISTS samples (
            sample_id INTEGER PRIMARY KEY AUTOINCREMENT,
            ts REAL,
            target TEXT,
            name TEXT,
            labels TEXT,
            value REAL
        )""")
    conn.execute('CREATE INDEX IF NOT EXISTS idx_samples_name_ts '
                 'ON samples (name, ts)')
    conn.execute('CREATE INDEX IF NOT EXISTS idx_samples_target '
                 'ON samples (target, name, ts)')
    conn.commit()
    _local.conn = conn
    _local.path = path
    return conn


def insert_samples(target: str, rows: Iterable[SampleRow],
                   ts: Optional[float] = None) -> int:
    """One scrape round's samples for one target, in ONE transaction
    (a round is all-or-nothing per target: a half-written round would
    make windowed bucket deltas lie). Best-effort: returns the number
    of rows written, 0 on any sqlite/OS failure."""
    rows = list(rows)
    if not rows:
        return 0
    stamp = time.time() if ts is None else ts
    try:
        conn = _conn()
        with conn:
            conn.executemany(
                'INSERT INTO samples (ts, target, name, labels, value) '
                'VALUES (?, ?, ?, ?, ?)',
                [(stamp, target, name, labels, float(value))
                 for name, labels, value in rows])
        return len(rows)
    except (sqlite3.Error, OSError):
        return 0


_COLUMNS = ('sample_id', 'ts', 'target', 'name', 'labels', 'value')


def query(*, name: Optional[str] = None, target: Optional[str] = None,
          since: Optional[float] = None, until: Optional[float] = None,
          limit: int = 100000) -> List[Dict[str, Any]]:
    """Filtered samples, oldest first. Best-effort ([] on failure)."""
    clauses, params = [], []
    for col, val in (('name', name), ('target', target)):
        if val is not None:
            clauses.append(f'{col} = ?')
            params.append(val)
    if since is not None:
        clauses.append('ts >= ?')
        params.append(since)
    if until is not None:
        clauses.append('ts <= ?')
        params.append(until)
    where = (' WHERE ' + ' AND '.join(clauses)) if clauses else ''
    sql = (f'SELECT {", ".join(_COLUMNS)} FROM samples{where} '
           f'ORDER BY sample_id LIMIT ?')
    params.append(max(1, int(limit)))
    try:
        with _conn() as conn:
            rows = conn.execute(sql, params).fetchall()
    except (sqlite3.Error, OSError):
        return []
    return [dict(zip(_COLUMNS, r)) for r in rows]


def targets(since: Optional[float] = None) -> List[str]:
    """Distinct targets with samples (optionally only recent ones) —
    what the CLI's per-replica table iterates."""
    clauses, params = [], []
    if since is not None:
        clauses.append('ts >= ?')
        params.append(since)
    where = (' WHERE ' + ' AND '.join(clauses)) if clauses else ''
    try:
        with _conn() as conn:
            rows = conn.execute(
                f'SELECT DISTINCT target FROM samples{where} '
                f'ORDER BY target', params).fetchall()
    except (sqlite3.Error, OSError):
        return []
    return [r[0] for r in rows]


def latest_round(name: str, target: str) -> Dict[str, Tuple[float, float]]:
    """The NEWEST scrape round's series for (name, target):
    ``{labels: (ts, value)}``. A round shares one ts (insert_samples
    stamps the batch), so "newest round" = all rows at the max ts."""
    try:
        with _conn() as conn:
            row = conn.execute(
                'SELECT MAX(ts) FROM samples WHERE name = ? AND '
                'target = ?', (name, target)).fetchone()
            if row is None or row[0] is None:
                return {}
            ts = row[0]
            rows = conn.execute(
                'SELECT labels, value FROM samples WHERE name = ? AND '
                'target = ? AND ts = ?', (name, target, ts)).fetchall()
    except (sqlite3.Error, OSError):
        return {}
    return {labels: (ts, value) for labels, value in rows}


def round_at_or_before(name: str, target: str,
                       ts: float) -> Dict[str, Tuple[float, float]]:
    """The newest round at or before ``ts`` — the window-start anchor
    for cumulative-series deltas (burn-rate windows)."""
    try:
        with _conn() as conn:
            row = conn.execute(
                'SELECT MAX(ts) FROM samples WHERE name = ? AND '
                'target = ? AND ts <= ?', (name, target, ts)).fetchone()
            if row is None or row[0] is None:
                return {}
            anchor = row[0]
            rows = conn.execute(
                'SELECT labels, value FROM samples WHERE name = ? AND '
                'target = ? AND ts = ?',
                (name, target, anchor)).fetchall()
    except (sqlite3.Error, OSError):
        return {}
    return {labels: (anchor, value) for labels, value in rows}


def gc_samples(max_age_seconds: float = 7 * 24 * 3600,
               max_rows: int = 500_000) -> int:
    """Retention, same discipline as journal.gc_events: age window
    plus a row cap keyed on the Nth-NEWEST row id (never max-id
    arithmetic — AUTOINCREMENT ids go sparse after age deletes). The
    scraper writes dozens of rows per replica per round; without this
    the samples table outgrows every other journal table combined."""
    try:
        conn = _conn()
        with sqlite_utils.immediate(conn):
            cur = conn.execute('DELETE FROM samples WHERE ts < ?',
                               (time.time() - max_age_seconds,))
            deleted = cur.rowcount
            row = conn.execute(
                'SELECT sample_id FROM samples '
                'ORDER BY sample_id DESC LIMIT 1 OFFSET ?',
                (max_rows,)).fetchone()
            if row is not None:
                cur = conn.execute(
                    'DELETE FROM samples WHERE sample_id <= ?',
                    (row[0],))
                deleted += cur.rowcount
        return max(0, deleted)
    except (sqlite3.Error, OSError):
        return 0
