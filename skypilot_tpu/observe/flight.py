"""Engine flight recorder: a fixed-size, lock-free hot-loop event ring.

The decode hot loop (serve/engine.py batch loop) must not take a
sqlite write — or even a dict-allocating span — per token: at target
TPOT (a few ms) that is telemetry stealing double-digit percentages of
the serving budget. This is the hot path's recorder instead: a
PREALLOCATED ring of ``(monotonic_ns, event_code, slot, seq)`` tuples.
The record path is one atomic counter bump (``itertools.count`` —
CPython's C-level iterator, no lock) plus one list-slot store (a
pointer swap under the GIL): no locks, no sqlite, no syscalls beyond
the vDSO clock read, and — critically — NO device sync.

Consumers:

  * ``GET /debug/flight`` on the engine dumps the ring (newest events,
    decoded codes) for live "what was the loop doing" inspection;
  * ``_fail_all`` / ``_reset_device_state`` snapshot the ring into the
    event journal automatically, so every engine failure ships its
    last ~64k hot-loop events alongside the reset event;
  * per-request TTFT/TPOT are derived from ring-aligned host
    timestamps at collect/publish time (never inside the per-token
    loop) and surface as ``skytpu_engine_ttft_seconds`` /
    ``skytpu_engine_tpot_seconds`` histograms plus request-span attrs.

Multi-host: followers run the same engine methods at the same
op-stream points (serve/multihost.py), so each process's ring mirrors
the leader's dispatch/collect interleaving — comparing rings across
hosts shows where a follower fell behind.

Stdlib-only; safe to import from any layer.
"""
from __future__ import annotations

import itertools
import time

from skypilot_tpu.utils import knobs
from typing import Any, Dict, List, Optional, Tuple

# Event codes (ints in the ring; names only at dump time).
DISPATCH = 1        # fused step enqueued; seq = k (step width)
COLLECT = 2         # fused step consumed; seq = k
ADMIT = 3           # a request prefilled into `slot`; seq = bucket
FINISH = 4          # `slot` finished; seq = tokens generated
SPEC = 5            # speculative verify round; seq = accepted tokens
RESET = 6           # device-state rebuild (failure path)
CANCEL = 7          # a cancel applied to `slot`
CHUNK = 8           # a prefill chunk ran for `slot`; seq = tokens done

CODE_NAMES: Dict[int, str] = {
    DISPATCH: 'dispatch', COLLECT: 'collect', ADMIT: 'admit',
    FINISH: 'finish', SPEC: 'spec', RESET: 'reset', CANCEL: 'cancel',
    CHUNK: 'chunk',
}

_CAPACITY_ENV = 'SKYTPU_FLIGHT_CAPACITY'
DEFAULT_CAPACITY = 65536


class FlightRecorder:
    """Fixed-capacity ring of hot-loop events.

    Concurrent writers are safe with no lock: each ``record`` claims a
    unique monotonically-increasing index from the shared counter and
    stores one immutable tuple into its slot — overwrites only ever
    replace the OLDEST entries (index mod capacity), so a wraparound
    loses nothing but them. ``snapshot`` reads a point-in-time copy of
    the slots; an entry being concurrently replaced is seen as either
    its old or its new tuple, never a torn value.
    """

    __slots__ = ('capacity', '_buf', '_ctr')

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = knobs.get_int(_CAPACITY_ENV)
        if capacity < 1:
            raise ValueError('flight ring needs capacity >= 1')
        self.capacity = capacity
        self._buf: List[Optional[Tuple[int, int, int, int]]] = \
            [None] * capacity
        self._ctr = itertools.count()

    def record(self, code: int, slot: int = 0, seq: int = 0) -> None:
        """THE hot-path call: one counter bump + one slot store."""
        i = next(self._ctr)
        self._buf[i % self.capacity] = (time.monotonic_ns(), code, slot,
                                        seq)

    def snapshot(self) -> List[Tuple[int, int, int, int]]:
        """Point-in-time copy, oldest first (by monotonic timestamp —
        ring order is index order, but a concurrent writer may have
        replaced a slot between the copy's first and last element)."""
        entries = [e for e in list(self._buf) if e is not None]
        entries.sort(key=lambda e: e[0])
        return entries

    def dump(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Decoded events for the /debug/flight endpoint (newest-last;
        ``limit`` keeps only the newest N)."""
        entries = self.snapshot()
        if limit is not None and limit > 0:
            entries = entries[-limit:]
        return [{'t_ns': ns, 'event': CODE_NAMES.get(code, str(code)),
                 'slot': slot, 'seq': seq}
                for ns, code, slot, seq in entries]

    def clear(self) -> None:
        """Drop every entry (tests; post-snapshot resets keep the ring
        by default — overlapping failures should still see history)."""
        self._buf = [None] * self.capacity
        self._ctr = itertools.count()


def snapshot_to_journal(recorder: FlightRecorder, *,
                        reason: Optional[str] = None,
                        entity: Optional[str] = None,
                        max_events: Optional[int] = None) -> bool:
    """Persist the ring into the event journal (kind=flight_snapshot)
    — called from the engine's failure paths so a post-mortem has the
    hot loop's last moments without anyone having scraped /debug/flight
    in time. Best-effort like every journal write."""
    entries = recorder.snapshot()
    if not entries:
        return False
    if max_events is not None and max_events > 0:
        entries = entries[-max_events:]
    from skypilot_tpu.observe import journal
    return journal.record_event(
        'flight_snapshot', entity=entity, reason=reason,
        data={'events': [list(e) for e in entries],
              'columns': ['t_ns', 'code', 'slot', 'seq'],
              'codes': {str(k): v for k, v in CODE_NAMES.items()}})
