"""Declarative SLOs evaluated over scraped fleet samples: burn rates,
multi-window alerting, hysteresis.

The measurement half of "handles production traffic": PR 9's scraper
(observe/scrape.py) persists every replica's availability and latency
histograms into tsdb; this module turns them into *objectives* — "99.9%
of scrapes up", "95% of requests first-token under 2s" — evaluated
every scrape round the way serving-scale playbooks do (the
Google-SRE-style multi-window, multi-burn-rate recipe):

  * the ERROR BUDGET is ``1 - objective``;
  * the BURN RATE over a window is ``error_fraction / budget`` — 1.0
    means exactly spending the budget, 14x means spending a month's
    budget in ~2 days;
  * a FAST window (minutes) catches cliffs, a SLOW window (hour+)
    confirms they are real — a breach requires BOTH, so a single bad
    scrape round cannot page;
  * transitions carry HYSTERESIS: escalation (ok→warning→breach) is
    immediate, de-escalation requires ``clear_rounds`` consecutive
    clean evaluations — a flapping replica cannot strobe the state.

States export as ``skytpu_slo_state{slo=<kind>}`` (0 ok / 1 warning /
2 breach) and ``skytpu_slo_burn_rate{slo=<kind>,window=fast|slow}``;
every transition journals an ``slo_<new_state>`` event with the burn
rates and the measured quantile in ``data``. SLO *kinds* are a closed
set (the metric-label cardinality contract); custom spec NAMES ride
the journal events.

Latency SLOs evaluate from CUMULATIVE bucket deltas over the window
(latest round minus the round at the window start, merged across
replicas bucket-wise via promtext) — the same math a Prometheus
recording rule would do, no per-request state anywhere.
"""
from __future__ import annotations

import dataclasses
import json
import math
import time
from typing import Dict, List, Mapping, Optional, Tuple

from skypilot_tpu import sky_logging

from skypilot_tpu.observe import journal
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.observe import promtext
from skypilot_tpu.observe import request_class
from skypilot_tpu.observe import tsdb
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger(__name__)

# The closed set of SLO kinds — the declared, bounded metric label.
# The per-class goodput kinds (goodput_<cls>, one per closed request
# class) evaluate the engine's skytpu_engine_goodput_total counter as
# windowed deltas: error fraction = slow / (good + slow) inside the
# window — "what share of this class's finished requests missed their
# class latency objective" — run through the same multi-window
# burn-rate machinery as every other kind.
#
# The PER-STAGE kinds serve the disaggregated pools (serve/disagg):
# ``prefill_queue`` evaluates the admission-wait histogram over the
# PREFILL pool's targets only (the saturation a long-prompt burst
# builds up — the prefill autoscaler's alerting mirror) and
# ``decode_ttft`` evaluates the TTFT histogram over the DECODE pool's
# targets only (adoption → first streamed token — the latency-shaped
# phase disaggregation protects). Same windowed-delta burn machinery;
# the only difference is the target filter: a controller tags disagg
# scrape targets ``<service>/<role>/<replica_id>``, and these kinds
# restrict to their role segment.
KINDS = (('availability', 'ttft_p95', 'tpot_p95',
          'prefill_queue', 'decode_ttft') +
         request_class.GOODPUT_KINDS)
STATES = ('ok', 'warning', 'breach')
_STATE_CODE = {'ok': 0, 'warning': 1, 'breach': 2}

_KIND_FAMILY = {
    'ttft_p95': 'skytpu_engine_ttft_seconds',
    'tpot_p95': 'skytpu_engine_tpot_seconds',
    'prefill_queue': 'skytpu_engine_admission_wait_seconds',
    'decode_ttft': 'skytpu_engine_ttft_seconds',
}
# Pool-scoped kinds: evaluated only over targets whose entity carries
# the role segment (``<service>/<role>/<replica_id>``).
_KIND_POOL = {
    'prefill_queue': 'prefill',
    'decode_ttft': 'decode',
}
GOODPUT_FAMILY = 'skytpu_engine_goodput_total'
# scrape.UP_SERIES without importing scrape (slo must stay importable
# standalone for the CLI; both modules pin this literal and
# test_fleet asserts they agree).
_UP_SERIES = 'skytpu_scrape_up'

_M_BURN = metrics_lib.gauge(
    'skytpu_slo_burn_rate',
    'Error-budget burn rate per SLO kind and window (1.0 = spending '
    'exactly the budget).',
    labels={'slo': KINDS, 'window': ('fast', 'slow')})
_M_STATE = metrics_lib.gauge(
    'skytpu_slo_state',
    'SLO state per kind: 0 ok, 1 warning, 2 breach.',
    labels={'slo': KINDS})


@dataclasses.dataclass
class SLOSpec:
    """One objective. ``kind`` must be one of :data:`KINDS`;
    ``name`` defaults to the kind (custom names appear in journal
    events; metrics label by kind). ``objective`` is the good
    fraction; latency kinds also take ``threshold_seconds`` (a request
    is good when at/under it — align it with a declared histogram
    bucket bound, or the bucketed good-count rounds down)."""
    kind: str
    name: str = ''
    objective: float = 0.999
    threshold_seconds: float = 2.0
    fast_window: float = 300.0
    slow_window: float = 3600.0
    fast_burn: float = 14.0
    slow_burn: float = 6.0
    clear_rounds: int = 3

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f'unknown SLO kind {self.kind!r}; '
                             f'valid: {KINDS}')
        if not (0.0 < self.objective < 1.0):
            raise ValueError('objective must be in (0, 1) — an '
                             'objective of 1.0 has a zero error '
                             'budget and every error is a breach')
        if not self.name:
            self.name = self.kind

    @property
    def budget(self) -> float:
        return 1.0 - self.objective


def default_specs() -> List[SLOSpec]:
    """The stock objectives, overridable via ``SKYTPU_SLO_SPECS`` — a
    JSON list of :class:`SLOSpec` kwargs dicts (docs/OBSERVABILITY.md
    "Fleet" section shows the format). A malformed env var raises at
    controller startup: a silently-dropped SLO is an unmonitored
    fleet."""
    cfg = knobs.get_json('SKYTPU_SLO_SPECS')
    if cfg is not None:
        try:
            if not isinstance(cfg, list):
                raise ValueError('expected a JSON list')
            return [SLOSpec(**item) for item in cfg]
        except (ValueError, TypeError) as e:
            raise ValueError(
                f'SKYTPU_SLO_SPECS is malformed ({e}); expected a '
                f'JSON list of SLO spec objects, e.g. '
                f'[{{"kind": "availability", "objective": 0.999}}]'
            ) from e
    return [
        SLOSpec(kind='availability', objective=0.999),
        SLOSpec(kind='ttft_p95', objective=0.95, threshold_seconds=2.5),
        SLOSpec(kind='tpot_p95', objective=0.95, threshold_seconds=0.25),
    ] + [
        # Per-class goodput: 99% of each class's finished requests
        # meet their class latency objective (the objective itself —
        # the TTFT/TPOT cut — lives in request_class.OBJECTIVES; this
        # spec only sets how much missing is tolerable).
        SLOSpec(kind=kind, objective=0.99)
        for kind in request_class.GOODPUT_KINDS
    ]


# ------------------------------------------------------------ window math

def _split_le(labels: str) -> Tuple[Optional[str], Optional[float]]:
    """A stored bucket-series label string → (canonical label string
    WITHOUT le, the le bound). (None, None) on a malformed string."""
    if not labels:
        return None, None
    try:
        pairs = promtext._parse_labels(labels)  # pylint: disable=protected-access
    except ValueError:
        return None, None
    le = None
    rest = []
    for k, v in pairs:
        if k == 'le':
            le = math.inf if v == '+Inf' else float(v)
        else:
            rest.append((k, v))
    if le is None:
        return None, None
    return promtext.labels_text(tuple(rest)), le


def _series_delta(latest: Mapping[str, Tuple[float, float]],
                  anchor: Mapping[str, Tuple[float, float]]
                  ) -> Dict[str, float]:
    """Cumulative-series window delta per label set. A negative delta
    means the counter restarted inside the window (replica relaunch):
    the latest ABSOLUTE value is the honest lower bound of the
    window's activity, so use it."""
    out: Dict[str, float] = {}
    for labels, (_, value) in latest.items():
        prev = anchor.get(labels, (0.0, 0.0))[1]
        out[labels] = value - prev if value >= prev else value
    return out


def _target_window_hist(latest_b, latest_c, latest_s, family: str,
                        target: str, start: float,
                        label_filter: Optional[str] = None
                        ) -> Optional[promtext.HistogramData]:
    """One target's windowed histogram from its (already fetched)
    latest cumulative rounds and the anchor rounds at the window
    start. Grouped PER LABEL SET (minus le): a labeled family
    (foo_seconds{cls=...}) has one cumulative bucket series per label
    set — concatenating them would interleave duplicate le bounds into
    one garbage bucket list. Each label set's series is its own
    histogram; within one family they share the declared layout, so
    they merge bucket-wise."""
    anchor_b = tsdb.round_at_or_before(f'{family}_bucket', target,
                                       start)
    deltas = _series_delta(latest_b, anchor_b)
    count_d = _series_delta(
        latest_c,
        tsdb.round_at_or_before(f'{family}_count', target, start))
    sum_d = _series_delta(
        latest_s,
        tsdb.round_at_or_before(f'{family}_sum', target, start))
    groups: Dict[str, List[Tuple[float, float]]] = {}
    for labels, delta in deltas.items():
        rest_key, le = _split_le(labels)
        if le is not None:
            groups.setdefault(rest_key, []).append((le, delta))
    if label_filter is not None:
        # Restrict to ONE label set (canonical labels_text rendering,
        # e.g. 'cls="interactive"') — the per-class quantile path.
        groups = ({label_filter: groups[label_filter]}
                  if label_filter in groups else {})
    per_label: List[promtext.HistogramData] = []
    for rest_key, buckets in groups.items():
        buckets.sort(key=lambda b: b[0])
        hist = promtext.HistogramData(
            buckets=buckets,
            sum=sum_d.get(rest_key, 0.0),
            count=count_d.get(rest_key, buckets[-1][1]))
        if hist.buckets[-1][0] != math.inf:
            hist.buckets.append((math.inf, hist.count))
        per_label.append(hist)
    if not per_label:
        return None
    return promtext.merge_histograms(per_label)


def windowed_histograms(family: str, windows: List[float],
                        now: Optional[float] = None,
                        targets: Optional[List[str]] = None,
                        label_filter: Optional[str] = None
                        ) -> List[promtext.HistogramData]:
    """The fleet's histogram of ``family`` observations inside EACH
    window: per target, latest cumulative round minus the round at the
    window start; shards merged bucket-wise (mismatched layouts refuse
    loudly in promtext). The latest rounds are window-independent and
    fetched ONCE per target — the SLO engine evaluates a fast and a
    slow window every scrape round, and doubling the sqlite reads per
    round per replica would be pure waste. Empty HistogramData entries
    where nothing was scraped."""
    now = time.time() if now is None else now
    if targets is None:
        targets = tsdb.targets(since=now - max(windows))
    per_window: List[List[promtext.HistogramData]] = [
        [] for _ in windows]
    for target in targets:
        latest_b = tsdb.latest_round(f'{family}_bucket', target)
        if not latest_b:
            continue
        latest_c = tsdb.latest_round(f'{family}_count', target)
        latest_s = tsdb.latest_round(f'{family}_sum', target)
        for i, window in enumerate(windows):
            hist = _target_window_hist(latest_b, latest_c, latest_s,
                                       family, target, now - window,
                                       label_filter)
            if hist is not None:
                per_window[i].append(hist)
    return [promtext.merge_histograms(shards) if shards else
            promtext.HistogramData(buckets=[(math.inf, 0.0)])
            for shards in per_window]


def windowed_histogram(family: str, window: float,
                       now: Optional[float] = None,
                       targets: Optional[List[str]] = None,
                       label_filter: Optional[str] = None
                       ) -> promtext.HistogramData:
    """Single-window convenience over :func:`windowed_histograms`
    (the fleet CLI's offline path)."""
    return windowed_histograms(family, [window], now, targets,
                               label_filter)[0]


def availability_error_fraction(window: float,
                                now: Optional[float] = None,
                                targets: Optional[List[str]] = None
                                ) -> Optional[float]:
    """Fraction of per-target scrape rounds in the window that were
    DOWN (the ``skytpu_scrape_up`` series the scraper writes every
    round, success or failure). ``targets`` restricts to one service's
    replicas on a SHARED observe DB (two co-located controllers must
    not count each other's outages). None with no rounds recorded —
    "no data" must not read as "perfectly available"."""
    fast, _ = _availability_fractions(window, window, now, targets)
    return fast


def _availability_fractions(fast_window: float, slow_window: float,
                            now: Optional[float] = None,
                            targets: Optional[List[str]] = None
                            ) -> Tuple[Optional[float],
                                       Optional[float]]:
    """(fast, slow) error fractions from ONE query over the slow
    window (the superset) — the fast window is a timestamp filter of
    rows already in hand, not a second sqlite scan per round."""
    now = time.time() if now is None else now
    rows = tsdb.query(name=_UP_SERIES, since=now - slow_window,
                      until=now)
    if targets is not None:
        allowed = set(targets)
        rows = [r for r in rows if r['target'] in allowed]

    def frac(subset) -> Optional[float]:
        if not subset:
            return None
        return sum(1 for r in subset if r['value'] < 0.5) / len(subset)

    fast_cut = now - fast_window
    return frac([r for r in rows if r['ts'] >= fast_cut]), frac(rows)


def goodput_fractions(cls: str, fast_window: float, slow_window: float,
                      now: Optional[float] = None,
                      targets: Optional[List[str]] = None
                      ) -> Tuple[Optional[float], Optional[float],
                                 Optional[float]]:
    """(fast_error, slow_error, measured_goodput) for one request
    class from windowed deltas of the engine goodput counter: error
    fraction = slow / (good + slow) finished inside the window, i.e.
    the share of the class's completed requests that missed their
    latency objective. None with no finishes in the window — a silent
    class has no goodput, good or bad. ``measured`` is the goodput
    (good) fraction over the SLOW window, the scorecard column."""
    now = time.time() if now is None else now
    if targets is None:
        targets = tsdb.targets(since=now - slow_window)
    good_key = promtext.labels_text((('cls', cls), ('outcome', 'good')))
    slow_key = promtext.labels_text((('cls', cls), ('outcome', 'slow')))
    windows = (fast_window, slow_window)
    sums = {w: [0.0, 0.0] for w in windows}          # [good, slow]
    for target in targets:
        latest = tsdb.latest_round(GOODPUT_FAMILY, target)
        if not latest:
            continue
        for window in windows:
            deltas = _series_delta(
                latest,
                tsdb.round_at_or_before(GOODPUT_FAMILY, target,
                                        now - window))
            acc = sums[window]
            acc[0] += deltas.get(good_key, 0.0)
            acc[1] += deltas.get(slow_key, 0.0)

    def err(acc) -> Optional[float]:
        total = acc[0] + acc[1]
        return (acc[1] / total) if total > 0 else None

    fast, slow = err(sums[fast_window]), err(sums[slow_window])
    return fast, slow, (None if slow is None else 1.0 - slow)


def latency_error_fraction(hist: promtext.HistogramData,
                           threshold: float) -> Optional[float]:
    """Fraction of windowed observations ABOVE the threshold. The
    good count is the cumulative bucket at the largest finite bound at
    or under the threshold (bucketed data can only answer at bucket
    resolution — rounding DOWN the good side is the conservative
    choice). None with no observations."""
    if hist.count <= 0:
        return None
    good = 0.0
    for le, cum in hist.buckets:
        if le == math.inf or le > threshold:
            break
        good = cum
    return max(0.0, 1.0 - good / hist.count)


# --------------------------------------------------------------- engine

@dataclasses.dataclass
class Evaluation:
    spec: SLOSpec
    state: str
    burn_fast: Optional[float]
    burn_slow: Optional[float]
    measured: Optional[float] = None     # p95 / availability fraction
    transitioned: bool = False


class SLOEngine:
    """Holds per-spec state machines; ``evaluate()`` runs once per
    scrape round (the controller wires it into the scrape loop's
    ``on_round`` hook). ``entity`` scopes journal events to the owning
    service so the LB's scoped /-/lb/events shows them."""

    def __init__(self, specs: Optional[List[SLOSpec]] = None,
                 entity: Optional[str] = None):
        self.specs = list(specs) if specs is not None else default_specs()
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f'duplicate SLO spec names: {names}')
        self.entity = entity
        self._state: Dict[str, str] = {s.name: 'ok' for s in self.specs}
        self._clean_rounds: Dict[str, int] = {s.name: 0
                                              for s in self.specs}
        self._last_evals: List[Evaluation] = []
        self._publish_states()

    # ------------------------------------------------------------ query
    def state(self, name: str) -> str:
        return self._state[name]

    def states(self) -> Dict[str, str]:
        return dict(self._state)

    # ------------------------------------------------------- evaluation
    def _scoped_targets(self, now: float,
                        window: float) -> Optional[List[str]]:
        """The tsdb targets THIS engine may evaluate: with a bound
        entity, only ``<entity>/...`` replicas — the observe DB is
        shared (two co-located controllers write the same file, the
        reality that made /-/lb/events entity-scoped), so an unscoped
        engine would count a sibling service's outages and latencies
        in this service's burn rates. None (= all targets) only
        without an entity — a standalone evaluator owning its DB."""
        if self.entity is None:
            return None
        prefix = f'{self.entity}/'
        return [t for t in tsdb.targets(since=now - window)
                if t == self.entity or t.startswith(prefix)]

    def _error_fractions(self, spec: SLOSpec, now: float
                         ) -> Tuple[Optional[float], Optional[float],
                                    Optional[float]]:
        """(fast_fraction, slow_fraction, measured)."""
        targets = self._scoped_targets(now, spec.slow_window)
        if spec.kind == 'availability':
            fast, slow = _availability_fractions(
                spec.fast_window, spec.slow_window, now, targets)
            measured = None if slow is None else 1.0 - slow
            return fast, slow, measured
        if spec.kind.startswith('goodput_'):
            return goodput_fractions(
                spec.kind[len('goodput_'):], spec.fast_window,
                spec.slow_window, now, targets)
        family = _KIND_FAMILY[spec.kind]
        pool = _KIND_POOL.get(spec.kind)
        if pool is not None:
            # Per-pool delta windows: restrict to the role's scrape
            # targets. With no pool-tagged targets (a monolithic
            # service evaluating a disagg kind) the windows are empty
            # → no data → the state machine HOLDS, it never breaches.
            if targets is None:
                targets = tsdb.targets(since=now - spec.slow_window)
            targets = [t for t in targets if f'/{pool}/' in t]
        fast_h, slow_h = windowed_histograms(
            family, [spec.fast_window, spec.slow_window], now, targets)
        fast = latency_error_fraction(fast_h, spec.threshold_seconds)
        slow = latency_error_fraction(slow_h, spec.threshold_seconds)
        measured = promtext.histogram_quantile(slow_h, 0.95)
        if math.isnan(measured):
            measured = None
        return fast, slow, measured

    @staticmethod
    def _target_state(spec: SLOSpec, burn_fast: Optional[float],
                      burn_slow: Optional[float]) -> Optional[str]:
        """What the burn rates say RIGHT NOW (hysteresis applied by
        the caller). None = no data, hold the current state."""
        if burn_fast is None and burn_slow is None:
            return None
        bf = burn_fast or 0.0
        bs = burn_slow or 0.0
        if bf >= spec.fast_burn and bs >= spec.slow_burn:
            return 'breach'
        if bf >= spec.fast_burn or bs >= 1.0:
            return 'warning'
        return 'ok'

    def evaluate(self, now: Optional[float] = None) -> List[Evaluation]:
        now = time.time() if now is None else now
        out: List[Evaluation] = []
        # The burn gauge labels by KIND (bounded); when several specs
        # share a kind the WORST burn wins — same aggregation as the
        # state gauge, or a relaxed spec evaluated later would
        # silently overwrite a strict spec's 20x burn with 0.
        burn_by_kind: Dict[Tuple[str, str], float] = {}
        for spec in self.specs:
            try:
                fast_frac, slow_frac, measured = self._error_fractions(
                    spec, now)
            except Exception:  # pylint: disable=broad-except
                # PER-SPEC containment: one spec's evaluation blowing
                # up (e.g. BucketMismatchError during a rolling update
                # where old/new engine versions declare different
                # bucket layouts) must not kill the OTHER specs —
                # losing availability alerting in a mixed-version
                # window is losing it exactly when an outage is most
                # likely. The broken spec holds its state and reports
                # no burn until the fleet converges.
                logger.warning(f'SLO {spec.name!r} evaluation failed; '
                               f'holding state '
                               f'{self._state[spec.name]!r}:',
                               exc_info=True)
                out.append(Evaluation(
                    spec=spec, state=self._state[spec.name],
                    burn_fast=None, burn_slow=None))
                continue
            burn_fast = (None if fast_frac is None
                         else fast_frac / spec.budget)
            burn_slow = (None if slow_frac is None
                         else slow_frac / spec.budget)
            for window, burn in (('fast', burn_fast),
                                 ('slow', burn_slow)):
                if burn is None:
                    # No data is NOT a zero burn: writing 0.0 here
                    # would clear an operator's burn-rate alert at the
                    # exact moment telemetry went missing. The gauge
                    # holds its last value; the scrape staleness gauge
                    # says why.
                    continue
                key = (spec.kind, window)
                burn_by_kind[key] = max(burn_by_kind.get(key, 0.0),
                                        burn)
            target = self._target_state(spec, burn_fast, burn_slow)
            current = self._state[spec.name]
            transitioned = False
            if target is not None and target != current:
                if _STATE_CODE[target] > _STATE_CODE[current]:
                    # Escalate immediately — a breach must not wait
                    # out the hysteresis.
                    transitioned = self._transition(
                        spec, current, target, burn_fast, burn_slow,
                        measured)
                else:
                    # De-escalate only after clear_rounds consecutive
                    # cleaner evaluations (hysteresis: a flapping
                    # signal cannot strobe ok/breach).
                    self._clean_rounds[spec.name] += 1
                    if self._clean_rounds[spec.name] >= \
                            spec.clear_rounds:
                        transitioned = self._transition(
                            spec, current, target, burn_fast,
                            burn_slow, measured)
            else:
                self._clean_rounds[spec.name] = 0
            out.append(Evaluation(
                spec=spec, state=self._state[spec.name],
                burn_fast=burn_fast, burn_slow=burn_slow,
                measured=measured, transitioned=transitioned))
        for (kind, window), burn in burn_by_kind.items():
            _M_BURN.set(burn, slo=kind, window=window)
        self._publish_states()
        self._last_evals = out
        return out

    def burn_summary(self) -> Dict[str, Dict[str, object]]:
        """Per-KIND snapshot of the last ``evaluate()`` round —
        ``{kind: {state, burn_fast, burn_slow, measured}}`` — for the
        /-/fleet/status per-class columns and the loadgen scorecard.
        When several specs share a kind the worst state wins and burns
        take the max, mirroring the gauge aggregation. Empty before
        the first evaluation."""
        out: Dict[str, Dict[str, object]] = {}
        for ev in self._last_evals:
            kind = ev.spec.kind
            row = out.get(kind)
            if row is None:
                row = {'state': ev.state, 'burn_fast': ev.burn_fast,
                       'burn_slow': ev.burn_slow,
                       'measured': ev.measured}
                out[kind] = row
                continue
            if _STATE_CODE[ev.state] > _STATE_CODE[row['state']]:
                row['state'] = ev.state
            for field, value in (('burn_fast', ev.burn_fast),
                                 ('burn_slow', ev.burn_slow)):
                if value is not None and (row[field] is None or
                                          value > row[field]):
                    row[field] = value
            if row['measured'] is None:
                row['measured'] = ev.measured
        return out

    def _transition(self, spec: SLOSpec, old: str, new: str,
                    burn_fast: Optional[float],
                    burn_slow: Optional[float],
                    measured: Optional[float]) -> bool:
        self._state[spec.name] = new
        self._clean_rounds[spec.name] = 0
        logger.warning(f'SLO {spec.name!r}: {old} -> {new} '
                       f'(burn fast={burn_fast}, slow={burn_slow})')
        journal.record_event(
            f'slo_{new}', entity=self.entity, reason=f'{old}->{new}',
            data={'slo': spec.name, 'kind': spec.kind,
                  'objective': spec.objective,
                  'burn_fast': burn_fast, 'burn_slow': burn_slow,
                  'measured': measured})
        return True

    def _publish_states(self) -> None:
        # Per KIND (bounded label): when several specs share a kind,
        # the worst state wins the gauge; names disambiguate in the
        # journal.
        per_kind: Dict[str, int] = {}
        for spec in self.specs:
            code = _STATE_CODE[self._state[spec.name]]
            per_kind[spec.kind] = max(per_kind.get(spec.kind, 0), code)
        for kind, code in per_kind.items():
            _M_STATE.set(code, slo=kind)
