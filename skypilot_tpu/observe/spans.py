"""Timed span trees: request-level latency decomposition.

The journal (observe/journal.py) answers "what happened" — this module
answers "where did the time go". A span is one timed hop of a request
(queue wait, optimizer plan, a per-zone provision attempt, an LB
upstream call, engine prefill), keyed by the existing trace IDs
(observe/trace.py) and parented into a tree so one slow request
decomposes across the control and serving planes
(``/v1/traces/<trace_id>``; docs/OBSERVABILITY.md).

Recording surfaces:

  * ``with spans.span('server.queue_wait', attrs...):`` — the scoped
    form. Parentage is contextvar-first (nested spans in one process),
    then the ``SKYTPU_PARENT_SPAN_ID`` env carrier (a child process
    parents its spans under whatever its parent exported — the same
    two-carrier contract trace IDs use). ``spans.start(...)`` is the
    same object un-sugared; the skylint ``span-discipline`` checker
    flags a ``start`` that is not used as a context manager (a leaked
    span never records its end).
  * ``spans.record(name, start_wall=..., duration=...)`` — the
    RETROACTIVE form, for hops whose endpoints live in different
    processes (a queue wait starts in the API server and ends in a
    scheduler thread) or that must not write telemetry on their hot
    path (the engine records request spans from ring-buffer deltas
    after the request finishes — see observe/flight.py).

Persistence is WRITE-BEHIND by contract: a finished span is enqueued
onto an in-process queue and a daemon thread batches it into a
``spans`` table in the journal DB (same file, same BEGIN IMMEDIATE /
sqlite-3.34-safe discipline). The traced work never blocks on — and
can never be failed by — telemetry I/O; readers (``tree()``,
``query_spans()``) flush the queue first so a just-finished request is
immediately decomposable.

Durations pair a wall-clock start (cross-process alignment) with a
monotonic interval (immune to clock steps). Stdlib-only.
"""
from __future__ import annotations

import atexit
import contextlib
import contextvars
import functools
import json
import os
import queue
import sqlite3
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import sqlite_utils

from skypilot_tpu.observe import journal
from skypilot_tpu.observe import trace

ENV_PARENT = 'SKYTPU_PARENT_SPAN_ID'
_DISABLE_ENV = 'SKYTPU_DISABLE_SPANS'

# The active span id — parent for any span opened in this context.
_CURRENT: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    'skytpu_span_id', default=None)

# Sampling: True while the current request was sampled OUT — scoped
# spans still nest (cheap objects, parentage intact) but nothing is
# enqueued for persistence.
_SUPPRESSED: contextvars.ContextVar[bool] = contextvars.ContextVar(
    'skytpu_span_suppress', default=False)


def suppressed() -> bool:
    """True when span persistence is off in this context (an unsampled
    request). Callers that export carriers (headers, env) should skip
    them for a suppressed request so downstream processes don't persist
    spans the sampler dropped."""
    return _SUPPRESSED.get()


@contextlib.contextmanager
def suppress():
    """Run a request with span persistence suppressed (sampling)."""
    token = _SUPPRESSED.set(True)
    try:
        yield
    finally:
        _SUPPRESSED.reset(token)


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def _enabled() -> bool:
    return not knobs.get_bool(_DISABLE_ENV)


def current() -> Optional[str]:
    """The parent for a new span: contextvar first (same-process
    nesting), then the env carrier (a parent process exported it)."""
    sid = _CURRENT.get()
    if sid:
        return sid
    return knobs.get_str(ENV_PARENT) or None


def set_parent(span_id: Optional[str]) -> 'contextvars.Token':
    """Bind a parent span id in THIS context only (thread-mode
    executors: the env is shared with sibling request threads, so only
    the contextvar may carry per-request parentage)."""
    return _CURRENT.set(span_id)


def adopt_parent(span_id: Optional[str]) -> None:
    """Make ``span_id`` this PROCESS's parent span: contextvar + env,
    so every subprocess spawned from here parents its spans under it
    (mirrors trace.adopt). Call only from dedicated per-entity
    processes (request runner, slice driver) — never from a shared
    server process."""
    if not span_id:
        return
    _CURRENT.set(span_id)
    knobs.export(ENV_PARENT, span_id)


def env_with_span(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """A copy of ``env`` with the active span stamped in as the
    cross-process parent carrier."""
    out = dict(env or {})
    sid = current()
    if sid:
        out[ENV_PARENT] = sid
    return out


class Span:
    """One timed hop. Context-manager use records start on ``with``
    entry (already done by ``start()``) and the duration + persistence
    on exit; the span is also the parent scope for spans opened inside
    the ``with`` body."""

    __slots__ = ('span_id', 'trace_id', 'parent_id', 'name', 'entity',
                 'start_wall', '_start_mono', 'attrs', '_token',
                 '_finished')

    def __init__(self, name: str, *, trace_id: Optional[str],
                 parent_id: Optional[str], entity: Optional[str],
                 attrs: Optional[Dict[str, Any]]):
        self.span_id = new_span_id()
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.entity = entity
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.start_wall = time.time()
        self._start_mono = time.monotonic()
        self._token: Optional[contextvars.Token] = None
        self._finished = False

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def finish(self) -> None:
        """Record the end and enqueue for write-behind persistence.
        Idempotent — a double finish records once."""
        if self._finished:
            return
        self._finished = True
        duration = time.monotonic() - self._start_mono
        _enqueue_row(self.span_id, self.trace_id, self.parent_id,
                     self.name, self.entity, self.start_wall, duration,
                     self.attrs)

    def __enter__(self) -> 'Span':
        self._token = _CURRENT.set(self.span_id)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        if exc is not None and 'error' not in self.attrs:
            self.attrs['error'] = f'{type(exc).__name__}: {exc}'
        self.finish()


def start(name: str, *, trace_id: Optional[str] = None,
          parent_id: Optional[str] = None, entity: Optional[str] = None,
          attrs: Optional[Dict[str, Any]] = None, **extra: Any) -> Span:
    """Begin a span. Use as a context manager (``with spans.start(...)
    as s:``) — a bare start with no paired finish leaks the span, and
    skylint's ``span-discipline`` checker flags that shape. For spans
    whose endpoints are not lexically scoped, use ``record()``."""
    if trace_id is None:
        trace_id = trace.get()
    if parent_id is None:
        parent_id = current()
    merged = dict(attrs or {})
    merged.update(extra)
    return Span(name, trace_id=trace_id, parent_id=parent_id,
                entity=entity, attrs=merged)


# `span` is the documented context-manager spelling; `start` exists so
# the lint rule has an explicit escape-hatch name to police.
span = start


def traced(name: str) -> Callable:
    """Decorator form: record the wrapped call as a span (the timeline
    ``@timeline.event`` idiom, but persisted and tree-shaped)."""

    def _decorate(fn: Callable) -> Callable:

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with start(name):
                return fn(*args, **kwargs)

        return wrapper

    return _decorate


def record(name: str, *, start_wall: float, duration: float,
           trace_id: Optional[str] = None,
           parent_id: Optional[str] = None,
           span_id: Optional[str] = None,
           entity: Optional[str] = None,
           attrs: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Record an already-measured span retroactively (cross-process
    hops; hot paths that derive timings after the fact). ``span_id``
    may be supplied when the id must be known ahead of time (e.g. the
    API request root span is the request id, so the claim site in
    another process can parent under it without coordination). Returns
    the span id, or None when spans are disabled."""
    if not _enabled():
        return None
    if trace_id is None:
        trace_id = trace.get()
    sid = span_id or new_span_id()
    _enqueue_row(sid, trace_id, parent_id, name, entity, start_wall,
                 max(0.0, float(duration)), dict(attrs or {}))
    return sid


# ------------------------------------------------------------ persistence

_COLUMNS = ('span_id', 'trace_id', 'parent_id', 'name', 'entity',
            'start_ts', 'duration', 'pid', 'attrs')

# Write-behind queue: span finish is an enqueue (never sqlite I/O on
# the traced path); one daemon worker drains it in batches. Each item
# carries the DB path RESOLVED AT FINISH TIME so tests that repoint
# SKYTPU_OBSERVE_DB per case stay deterministic.
_QUEUE: 'queue.SimpleQueue' = queue.SimpleQueue()
_WORKER_LOCK = threading.Lock()
_WORKER: Optional[threading.Thread] = None
_BATCH_MAX = 256


def _enqueue_row(span_id: str, trace_id: Optional[str],
                 parent_id: Optional[str], name: str,
                 entity: Optional[str], start_wall: float,
                 duration: float, attrs: Dict[str, Any]) -> None:
    if not _enabled() or _SUPPRESSED.get():
        return
    row = (span_id, trace_id, parent_id, name, entity, start_wall,
           duration, os.getpid(),
           json.dumps(attrs, default=str) if attrs else None)
    _QUEUE.put((journal.db_path(), row))
    _ensure_worker()


_ATEXIT_ARMED = False


def _ensure_worker() -> None:
    global _WORKER, _ATEXIT_ARMED
    if _WORKER is not None and _WORKER.is_alive():
        return
    with _WORKER_LOCK:
        if _WORKER is not None and _WORKER.is_alive():
            return
        if not _ATEXIT_ARMED:
            # The worker is a daemon: a short-lived process (the CLI's
            # hermetic local mode) can exit with spans still queued.
            # atexit handlers run while daemon threads are still
            # schedulable, so a bounded flush drains them.
            atexit.register(flush, 2.0)
            _ATEXIT_ARMED = True
        _WORKER = threading.Thread(target=_worker_loop,
                                   name='skytpu-span-writer',
                                   daemon=True)
        _WORKER.start()


def _ensure_table(conn: sqlite3.Connection) -> None:
    conn.execute("""
        CREATE TABLE IF NOT EXISTS spans (
            row_id INTEGER PRIMARY KEY AUTOINCREMENT,
            span_id TEXT,
            trace_id TEXT,
            parent_id TEXT,
            name TEXT,
            entity TEXT,
            start_ts REAL,
            duration REAL,
            pid INTEGER,
            attrs TEXT
        )""")
    conn.execute('CREATE INDEX IF NOT EXISTS idx_spans_trace '
                 'ON spans (trace_id)')
    conn.commit()


def _write_batch(path: str, rows: List[tuple]) -> None:
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        conn = sqlite_utils.connect_wal(path)
        try:
            _ensure_table(conn)
            with sqlite_utils.immediate(conn):
                conn.executemany(
                    'INSERT INTO spans (span_id, trace_id, parent_id, '
                    'name, entity, start_ts, duration, pid, attrs) '
                    'VALUES (?,?,?,?,?,?,?,?,?)', rows)
        finally:
            conn.close()
    except (sqlite3.Error, OSError):
        # Best-effort by contract: spans describe work that already
        # happened and must never fail (or retry-storm) it.
        pass


def _worker_loop() -> None:
    while True:
        item = _QUEUE.get()
        taken = 0
        events: List[threading.Event] = []
        by_path: Dict[str, List[tuple]] = {}
        while True:
            if isinstance(item, threading.Event):
                events.append(item)
            else:
                path, row = item
                by_path.setdefault(path, []).append(row)
                taken += 1
            if taken >= _BATCH_MAX:
                break
            try:
                item = _QUEUE.get_nowait()
            except queue.Empty:
                break
        for path, rows in by_path.items():
            _write_batch(path, rows)
        for ev in events:
            ev.set()


def flush(timeout: float = 5.0) -> bool:
    """Block until everything enqueued so far is committed (readers
    call this so a just-finished span is immediately visible). Returns
    False on timeout — never raises."""
    if _WORKER is None and _QUEUE.empty():
        return True
    done = threading.Event()
    _QUEUE.put(done)
    _ensure_worker()
    return done.wait(timeout)


# ------------------------------------------------------------------ reads

def _conn_ro() -> sqlite3.Connection:
    path = journal.db_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    conn = sqlite_utils.connect_wal(path)
    _ensure_table(conn)
    return conn


def _row_to_dict(row) -> Dict[str, Any]:
    d = dict(zip(_COLUMNS, row))
    if d.get('attrs'):
        try:
            d['attrs'] = json.loads(d['attrs'])
        except ValueError:
            pass
    return d


def query_spans(*, trace_id: Optional[str] = None,
                name: Optional[str] = None,
                entity_scope: Optional[str] = None,
                since: Optional[float] = None,
                limit: int = 5000,
                newest_first: bool = False) -> List[Dict[str, Any]]:
    """Filtered spans, oldest-start first. ``entity_scope`` restricts
    like journal.query's: the span's entity equals the scope or lives
    under it (``scope/...``) — what a user-facing per-service endpoint
    may expose from the shared DB. ``newest_first`` makes the LIMIT
    keep the NEWEST rows instead of the oldest (results still return
    oldest-first) — for unfiltered exports, where truncating away the
    most recent requests would hide exactly what's being debugged."""
    flush(timeout=2.0)
    clauses, params = [], []
    for col, val in (('trace_id', trace_id), ('name', name)):
        if val is not None:
            clauses.append(f'{col} = ?')
            params.append(val)
    if entity_scope is not None:
        clause, scope_params = journal.entity_scope_clause(entity_scope)
        clauses.append(clause)
        params.extend(scope_params)
    if since is not None:
        clauses.append('start_ts >= ?')
        params.append(since)
    where = (' WHERE ' + ' AND '.join(clauses)) if clauses else ''
    order = ('start_ts DESC, row_id DESC' if newest_first
             else 'start_ts, row_id')
    sql = (f'SELECT {", ".join(_COLUMNS)} FROM spans{where} '
           f'ORDER BY {order} LIMIT ?')
    params.append(max(1, int(limit)))
    try:
        conn = _conn_ro()
        try:
            rows = conn.execute(sql, params).fetchall()
        finally:
            conn.close()
    except (sqlite3.Error, OSError):
        return []
    if newest_first:
        rows.reverse()
    return [_row_to_dict(r) for r in rows]


def tree(trace_id: str,
         entity_scope: Optional[str] = None) -> Dict[str, Any]:
    """The rooted span tree of one trace: every persisted span, nested
    by parentage. A span whose parent is missing (recorded by a process
    whose DB we cannot see, or simply not yet flushed) surfaces as a
    root rather than vanishing."""
    flat = query_spans(trace_id=trace_id, entity_scope=entity_scope)
    by_id: Dict[str, Dict[str, Any]] = {}
    for s in flat:
        node = dict(s)
        node['children'] = []
        by_id[node['span_id']] = node
    roots: List[Dict[str, Any]] = []
    for node in by_id.values():
        parent = by_id.get(node['parent_id'] or '')
        if parent is not None and parent is not node:
            parent['children'].append(node)
        else:
            roots.append(node)

    def sort_rec(nodes: List[Dict[str, Any]]) -> None:
        nodes.sort(key=lambda n: (n['start_ts'], n['span_id']))
        for n in nodes:
            sort_rec(n['children'])

    sort_rec(roots)
    return {'trace_id': trace_id, 'span_count': len(flat),
            'roots': roots}


def gc_spans(max_age_seconds: float = 7 * 24 * 3600,
             max_rows: int = 500_000) -> int:
    """Retention, same discipline as journal.gc_events: age window plus
    a row cap keyed on the Nth-NEWEST row id (never max-id arithmetic —
    AUTOINCREMENT ids go sparse after age deletes)."""
    flush(timeout=2.0)
    try:
        conn = _conn_ro()
        try:
            with sqlite_utils.immediate(conn):
                cur = conn.execute('DELETE FROM spans WHERE start_ts < ?',
                                   (time.time() - max_age_seconds,))
                deleted = cur.rowcount
                row = conn.execute(
                    'SELECT row_id FROM spans '
                    'ORDER BY row_id DESC LIMIT 1 OFFSET ?',
                    (max_rows,)).fetchone()
                if row is not None:
                    cur = conn.execute(
                        'DELETE FROM spans WHERE row_id <= ?', (row[0],))
                    deleted += cur.rowcount
        finally:
            conn.close()
        return max(0, deleted)
    except (sqlite3.Error, OSError):
        return 0


# ---------------------------------------------------------- chrome export

def chrome_trace(trace_id: Optional[str] = None,
                 timeline_path: Optional[str] = None,
                 limit: int = 100_000) -> Dict[str, Any]:
    """Spans as Chrome trace-event JSON ('X' complete events, μs),
    merged with the process-profiling events utils/timeline.py captured
    (``SKYTPU_TIMELINE_FILE_PATH``) so one perfetto load shows the
    request tree AND the decorated control-plane functions on a shared
    wall-clock axis. An unfiltered export over ``limit`` keeps the
    NEWEST spans (the requests being debugged), never the oldest."""
    events: List[Dict[str, Any]] = []
    spans_flat = (query_spans(trace_id=trace_id, limit=limit)
                  if trace_id
                  else query_spans(limit=limit, newest_first=True))
    for s in spans_flat:
        args: Dict[str, Any] = {'span_id': s['span_id']}
        if s.get('trace_id'):
            args['trace_id'] = s['trace_id']
        if s.get('parent_id'):
            args['parent_id'] = s['parent_id']
        if s.get('entity'):
            args['entity'] = s['entity']
        if isinstance(s.get('attrs'), dict):
            args.update({f'attr.{k}': v for k, v in s['attrs'].items()})
        events.append({
            'name': s['name'], 'ph': 'X',
            'ts': s['start_ts'] * 1e6,
            'dur': max(s['duration'], 0.0) * 1e6,
            'pid': str(s['pid']), 'tid': 'spans',
            'args': args,
        })
    tl_path = timeline_path or knobs.get_str('SKYTPU_TIMELINE_FILE_PATH')
    if tl_path and os.path.exists(os.path.expanduser(tl_path)):
        try:
            with open(os.path.expanduser(tl_path), 'r',
                      encoding='utf-8') as f:
                tl = json.load(f)
            for e in tl.get('traceEvents', []):
                if trace_id is not None and (
                        (e.get('args') or {}).get('trace_id') != trace_id):
                    continue
                events.append(e)
        except (OSError, ValueError):
            pass
    return {'traceEvents': events}


# ---------------------------------------------------------- rendering

def format_tree(result: Dict[str, Any]) -> str:
    """Human-readable indented tree with durations and % of parent —
    the `observe trace <id>` CLI surface."""
    lines = [f"trace {result['trace_id']}: "
             f"{result['span_count']} span(s)"]

    def walk(node: Dict[str, Any], depth: int,
             parent_dur: Optional[float]) -> None:
        dur_ms = node['duration'] * 1e3
        pct = ''
        if parent_dur and parent_dur > 0:
            pct = f' ({min(100.0, node["duration"] / parent_dur * 100):.0f}% of parent)'
        attrs = node.get('attrs')
        attr_str = ''
        if isinstance(attrs, dict) and attrs:
            inner = ', '.join(f'{k}={v}' for k, v in sorted(attrs.items()))
            attr_str = f'  [{inner}]'
        lines.append(f'{"  " * depth}{node["name"]}  '
                     f'{dur_ms:.1f}ms{pct}{attr_str}')
        for child in node['children']:
            walk(child, depth + 1, node['duration'])

    for root in result['roots']:
        walk(root, 1, None)
    return '\n'.join(lines)
