"""Controller-side fleet scraper: pull every replica's telemetry.

The component PRs 3–6 left missing: every replica exposes rich local
``/metrics`` (TTFT/TPOT histograms, queue depth, KV page gauges) and a
``/health`` saturation doc, but nothing ever AGGREGATED them — the LB
and autoscaler acted on LB-side QPS probes, and "what is fleet TTFT
p95 right now?" had no answer. The :class:`Scraper` runs in the serve
controller process, pulls every target each round, persists a curated
sample set into :mod:`~skypilot_tpu.observe.tsdb`, and keeps the last
good parse in memory for:

  * fleet aggregation — ``fleet_families()`` merges fresh shards
    (counters/gauges summed, histograms bucket-wise) for the LB's
    ``/-/fleet/metrics`` endpoint and the ``observe fleet`` CLI;
  * the saturation snapshot — ``saturation_snapshot()`` gives the LB
    (least-loaded tie-breaking) and the saturation autoscaler a
    ``ready_urls()``-style view of per-replica queue depth / in-flight
    / free KV pages, with freshness stamps so consumers can refuse
    stale signal;
  * the SLO engine — burn-rate windows evaluate over the persisted
    samples each round.

FAILURE CONTAINMENT is the design center: every target is scraped on
its own thread with its own wall-clock deadline, so a dead, wedged or
slow-loris replica can never delay a healthy target's scrape or wedge
the loop — it burns only its own timeout. A failed target journals a
``scrape_failed`` event, writes an ``up 0`` sample (the availability
SLO's raw material), and moves the staleness gauge; per-target detail
rides the journal/status endpoints because metric label sets must
stay declared and finite (the breaker-state precedent).

Failpoint: ``observe.scrape`` fires inside the per-target worker, so
chaos tests inject timeouts/errors without a real dead replica.
"""
from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import knobs

from skypilot_tpu.observe import journal
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.observe import promtext
from skypilot_tpu.observe import tsdb

logger = sky_logging.init_logger(__name__)

# Metric families persisted into tsdb each round (the curated set the
# SLO engine and fleet CLI read; storing the full exposition would
# multiply tsdb row volume ~10x for series nothing consumes).
STORED_FAMILIES = (
    'skytpu_engine_ttft_seconds',
    'skytpu_engine_tpot_seconds',
    # Per-class mirrors + goodput (observe/request_class.py): the raw
    # material for the goodput_<cls> SLO kinds and the loadgen
    # scorecard's fleet-attributed per-class quantiles. Bounded: the
    # cls label is the closed class registry.
    'skytpu_engine_class_ttft_seconds',
    'skytpu_engine_class_tpot_seconds',
    'skytpu_engine_goodput_total',
    'skytpu_engine_queue_depth',
    'skytpu_engine_in_flight',
    'skytpu_engine_kv_pages_free',
    'skytpu_engine_requests_total',
    'skytpu_engine_tokens_total',
    'skytpu_engine_prefix_requests_total',
)

# The synthetic per-target liveness series every round writes (1 on a
# successful scrape, 0 on failure) — the availability SLO's input.
UP_SERIES = 'skytpu_scrape_up'

_SCRAPE_OUTCOMES = ('ok', 'timeout', 'error')
_M_SCRAPES = metrics_lib.counter(
    'skytpu_scrape_total',
    'Per-target scrape attempts by outcome.',
    labels={'outcome': _SCRAPE_OUTCOMES})
_M_SCRAPE_SECONDS = metrics_lib.histogram(
    'skytpu_scrape_seconds',
    'Per-target scrape latency (metrics + health fetch + parse).')
_M_STALE = metrics_lib.gauge(
    'skytpu_scrape_stale_targets',
    'Targets whose last successful scrape is older than the staleness '
    'window. Per-target detail rides scrape_failed journal events and '
    'the /-/fleet/status endpoint (target names are unbounded; metric '
    'label sets must stay declared and finite).')
_M_TARGETS = metrics_lib.gauge(
    'skytpu_scrape_targets',
    'Targets configured for the current scrape round.')


class ScrapeTimeout(Exception):
    """A target exceeded its per-scrape wall-clock deadline."""


@dataclasses.dataclass(frozen=True)
class Target:
    entity: str                 # journal/tsdb identity: <svc>/<replica_id>
    url: str                    # base URL, e.g. http://127.0.0.1:8000


@dataclasses.dataclass
class Saturation:
    """One replica's engine-reported load, as of ``ts``."""
    entity: str
    url: str
    ts: float
    queue_depth: float = 0.0
    in_flight: float = 0.0
    kv_pages_free: Optional[float] = None
    # Pages parked in the replica's host-RAM spill tier (None when the
    # tier is off): paired with kv_pages_free it separates "device
    # pool full but sessions merely sleeping" from "genuinely out of
    # KV capacity" — only the latter should scale the fleet.
    kv_host_pages: Optional[float] = None

    def age(self, now: Optional[float] = None) -> float:
        return (time.time() if now is None else now) - self.ts


@dataclasses.dataclass
class _TargetState:
    target: Target
    last_attempt: float = 0.0
    last_success: float = 0.0
    last_error: str = ''
    families: Optional[Dict[str, promtext.Family]] = None
    saturation: Optional[Saturation] = None


@dataclasses.dataclass
class _ScrapeResult:
    """What a worker hands back to the round thread. Workers do ONLY
    network + parse — all sqlite (tsdb/journal) and scraper-state
    writes happen on the persistent scrape-loop thread, so per-round
    worker threads never open (and leak to GC) fresh thread-local
    sqlite connections, and a worker completing after the round's
    join deadline persists nothing stale."""
    ok: bool
    ts: float
    latency: float
    outcome: str = 'ok'                    # ok | timeout | error
    error: str = ''
    families: Optional[Dict[str, promtext.Family]] = None
    saturation: Optional[Saturation] = None


def _fetch(url: str, deadline: float) -> bytes:
    """GET with a WALL-CLOCK deadline, not just a socket timeout: a
    slow-loris upstream that trickles a byte per socket-timeout window
    keeps every recv "live" forever — so the body is read in chunks
    and the deadline checked between reads. Worst case one blocked
    recv adds one socket timeout past the deadline; the worker thread
    always terminates."""
    remaining = deadline - time.monotonic()
    if remaining <= 0:
        raise ScrapeTimeout(url)
    with urllib.request.urlopen(url, timeout=remaining) as resp:
        chunks: List[bytes] = []
        while True:
            if time.monotonic() > deadline:
                raise ScrapeTimeout(url)
            chunk = resp.read(65536)
            if not chunk:
                break
            chunks.append(chunk)
    return b''.join(chunks)


def _curated_rows(families: Dict[str, promtext.Family]
                  ) -> List[tsdb.SampleRow]:
    rows: List[tsdb.SampleRow] = []
    for fam_name in STORED_FAMILIES:
        fam = families.get(fam_name)
        if fam is None:
            continue
        for s in fam.samples:
            rows.append((s.name, promtext.labels_text(s.labels),
                         s.value))
    return rows


class Scraper:
    """Pulls targets; owns the last-good in-memory view. Thread-safe:
    ``set_targets`` may be called from the reconcile thread while a
    round runs on the scrape-loop thread."""

    def __init__(self,
                 metrics_path: str = '/metrics',
                 health_path: str = '/health',
                 timeout: Optional[float] = None,
                 staleness_seconds: Optional[float] = None):
        self.metrics_path = metrics_path
        self.health_path = health_path
        self.timeout = (knobs.get_float('SKYTPU_SCRAPE_TIMEOUT')
                        if timeout is None else timeout)
        self.staleness_seconds = (
            knobs.get_float('SKYTPU_SCRAPE_STALENESS')
            if staleness_seconds is None else staleness_seconds)
        self._lock = threading.Lock()
        self._states: Dict[str, _TargetState] = {}

    # ------------------------------------------------------------ targets
    def set_targets(self, targets: List[Target]) -> None:
        """Adopt the current replica set (called after each reconcile
        pass). State for departed targets is dropped — a scaled-down
        replica must not linger in snapshots or the staleness count."""
        with self._lock:
            fresh: Dict[str, _TargetState] = {}
            for t in targets:
                prev = self._states.get(t.entity)
                if prev is not None and prev.target.url == t.url:
                    fresh[t.entity] = prev
                else:
                    fresh[t.entity] = _TargetState(target=t)
            self._states = fresh
        _M_TARGETS.set(len(targets))

    def targets(self) -> List[Target]:
        with self._lock:
            return [s.target for s in self._states.values()]

    # ------------------------------------------------------------- round
    def scrape_round(self) -> Dict[str, bool]:
        """Scrape every target IN PARALLEL, one thread + one deadline
        each. Returns {entity: succeeded}. The round's wall time is
        bounded by the slowest single target's timeout, never the sum
        — a dead target cannot slow a healthy one (its thread is
        abandoned at the join deadline and self-terminates at its
        fetch deadline)."""
        targets = self.targets()
        if not targets:
            self._refresh_staleness()
            return {}
        results: Dict[str, _ScrapeResult] = {}
        results_lock = threading.Lock()

        def worker(target: Target) -> None:
            result = self._scrape_one(target)
            with results_lock:
                results[target.entity] = result

        threads = []
        for t in targets:
            th = threading.Thread(target=worker, args=(t,), daemon=True,
                                  name=f'scrape-{t.entity}')
            th.start()
            threads.append(th)
        # Join against one shared deadline: every worker self-bounds
        # at ~timeout (+ one socket-timeout of slack for a blocked
        # recv), so the round converges even if a worker never posts.
        deadline = time.monotonic() + self.timeout * 2 + 1.0
        for th in threads:
            th.join(max(0.0, deadline - time.monotonic()))
        with results_lock:
            posted = dict(results)
        # Persist on THIS (persistent) thread: one cached sqlite
        # connection for the loop's lifetime instead of one fresh
        # connection + DDL per worker per round. A worker still wedged
        # past the shared deadline counts as failed NOW (its late
        # result, if any, is discarded unread).
        out: Dict[str, bool] = {}
        for t in targets:
            result = posted.get(t.entity)
            if result is None:
                result = _ScrapeResult(
                    ok=False, ts=time.time(),
                    latency=self.timeout * 2, outcome='timeout',
                    error='ScrapeTimeout: worker exceeded the round '
                          'deadline')
            self._persist(t, result)
            out[t.entity] = result.ok
        self._refresh_staleness()
        return out

    def _scrape_one(self, target: Target) -> _ScrapeResult:
        """Worker half: network + parse ONLY (no sqlite, no scraper
        state — see _ScrapeResult)."""
        t0 = time.monotonic()
        deadline = t0 + self.timeout
        now = time.time()
        base = target.url.rstrip('/')
        try:
            if failpoints.ACTIVE:
                failpoints.fire('observe.scrape')
            text = _fetch(base + self.metrics_path, deadline).decode(
                'utf-8', errors='replace')
            families = promtext.parse(text)
            health: Dict[str, object] = {}
            try:
                health = json.loads(_fetch(base + self.health_path,
                                           deadline).decode())
            except (OSError, ValueError, ScrapeTimeout):
                # The saturation doc is an enrichment; a replica whose
                # /metrics answered is UP even if /health lagged (the
                # gauges below fall back to the metric families).
                health = {}
        except Exception as e:  # pylint: disable=broad-except
            if isinstance(e, (ScrapeTimeout, TimeoutError)) or (
                    isinstance(e, OSError) and
                    'timed out' in str(e).lower()):
                outcome = 'timeout'
            else:
                outcome = 'error'
            return _ScrapeResult(
                ok=False, ts=now, latency=time.monotonic() - t0,
                outcome=outcome,
                error=f'{type(e).__name__}: {e}'[:300])
        return _ScrapeResult(
            ok=True, ts=now, latency=time.monotonic() - t0,
            families=families,
            saturation=self._saturation_from(target, now, families,
                                             health))

    def _persist(self, target: Target, result: _ScrapeResult) -> None:
        """Round-thread half: tsdb/journal writes + state update."""
        _M_SCRAPES.inc(outcome=result.outcome)
        _M_SCRAPE_SECONDS.observe(result.latency)
        if not result.ok:
            tsdb.insert_samples(target.entity, [(UP_SERIES, '', 0.0)],
                                ts=result.ts)
            journal.record_event(
                'scrape_failed', entity=target.entity,
                reason=result.outcome,
                data={'url': target.url, 'error': result.error})
            with self._lock:
                state = self._states.get(target.entity)
                if state is not None:
                    state.last_attempt = result.ts
                    state.last_error = result.error
            return
        rows = _curated_rows(result.families)
        rows.append((UP_SERIES, '', 1.0))
        tsdb.insert_samples(target.entity, rows, ts=result.ts)
        with self._lock:
            state = self._states.get(target.entity)
            if state is not None:
                state.last_attempt = result.ts
                state.last_success = result.ts
                state.last_error = ''
                state.families = result.families
                state.saturation = result.saturation

    @staticmethod
    def _saturation_from(target: Target, now: float,
                         families: Dict[str, promtext.Family],
                         health: Dict[str, object]) -> Saturation:
        def gauge_value(name: str) -> Optional[float]:
            fam = families.get(name)
            if fam is None or not fam.samples:
                return None
            return fam.samples[0].value

        def pick(key: str, metric: str) -> Optional[float]:
            val = health.get(key)
            if isinstance(val, (int, float)):
                return float(val)
            return gauge_value(metric)

        host = health.get('kv_host')
        host_pages: Optional[float] = None
        if isinstance(host, dict) and \
                isinstance(host.get('pages'), (int, float)):
            host_pages = float(host['pages'])
        if host_pages is None:
            host_pages = gauge_value('skytpu_engine_kv_pages_spilled')

        return Saturation(
            entity=target.entity, url=target.url, ts=now,
            queue_depth=pick('queue_depth',
                             'skytpu_engine_queue_depth') or 0.0,
            in_flight=pick('in_flight',
                           'skytpu_engine_in_flight') or 0.0,
            kv_pages_free=pick('kv_pages_free',
                               'skytpu_engine_kv_pages_free'),
            kv_host_pages=host_pages)

    # --------------------------------------------------------- consumers
    def _refresh_staleness(self) -> None:
        now = time.time()
        with self._lock:
            stale = sum(
                1 for s in self._states.values()
                if now - s.last_success > self.staleness_seconds)
        _M_STALE.set(stale)

    def saturation_snapshot(self, max_age: Optional[float] = None
                            ) -> Dict[str, Saturation]:
        """url → freshest Saturation, FRESH entries only (older than
        ``max_age``, default the staleness window, are withheld —
        consumers fall back to their own signal rather than act on a
        dead replica's last word)."""
        horizon = self.staleness_seconds if max_age is None else max_age
        now = time.time()
        with self._lock:
            return {s.saturation.url: s.saturation
                    for s in self._states.values()
                    if s.saturation is not None and
                    s.saturation.age(now) <= horizon}

    def fleet_families(self) -> Dict[str, promtext.Family]:
        """Merged families over FRESH targets (counters/gauges summed,
        histograms bucket-wise) — the /-/fleet/metrics document."""
        now = time.time()
        with self._lock:
            shards = [s.families for s in self._states.values()
                      if s.families is not None and
                      now - s.last_success <= self.staleness_seconds]
        return promtext.merge_families(shards)

    def status(self) -> List[Dict[str, object]]:
        """Per-target JSON doc for /-/fleet/status and the CLI table."""
        now = time.time()
        out = []
        with self._lock:
            states = list(self._states.values())
        for s in sorted(states, key=lambda st: st.target.entity):
            sat = s.saturation
            doc: Dict[str, object] = {
                'entity': s.target.entity,
                'url': s.target.url,
                'last_success_age': (round(now - s.last_success, 3)
                                     if s.last_success else None),
                'stale': (now - s.last_success >
                          self.staleness_seconds),
                'error': s.last_error or None,
            }
            if sat is not None:
                doc.update({'queue_depth': sat.queue_depth,
                            'in_flight': sat.in_flight,
                            'kv_pages_free': sat.kv_pages_free})
            out.append(doc)
        return out


class ScrapeLoop:
    """The periodic driver: one daemon thread running
    ``scraper.scrape_round()`` every ``interval`` seconds, invoking
    ``on_round(scraper)`` after each round (the controller hooks SLO
    evaluation and saturation publication there). Round failures are
    contained per-target inside the scraper; an ``on_round`` exception
    is logged and the loop continues — fleet telemetry must never die
    of one bad evaluation."""

    def __init__(self, scraper: Scraper,
                 interval: Optional[float] = None,
                 on_round: Optional[Callable[[Scraper], None]] = None):
        self.scraper = scraper
        self.interval = (knobs.get_float('SKYTPU_SCRAPE_INTERVAL')
                         if interval is None else interval)
        self.on_round = on_round
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Serializes rounds: run_once() is also a public
        # force-a-round API (controller right after replicas turn
        # READY, the loadgen harness's settle()) and may be called
        # from another thread while the loop thread is mid-round —
        # on_round hooks (SLO evaluation mutates per-spec state
        # machines) are not written for concurrent entry. A round is
        # seconds of network + sqlite, so serialization uses a
        # condition-variable gate (held only for flag flips), never a
        # mutex held across the blocking work itself.
        self._round_cv = threading.Condition()
        self._round_active = False

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name='fleet-scrape-loop')
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def run_once(self) -> Dict[str, bool]:
        """One synchronous round + callback (tests; also lets a
        controller force a round right after replicas turn READY).
        Rounds are serialized: a forced round from another thread
        waits out the loop thread's in-flight round instead of
        racing its on_round hook."""
        with self._round_cv:
            while self._round_active:
                self._round_cv.wait()
            self._round_active = True
        try:
            results = self.scraper.scrape_round()
            if self.on_round is not None:
                try:
                    self.on_round(self.scraper)
                except Exception:  # pylint: disable=broad-except
                    logger.warning('scrape on_round hook failed:',
                                   exc_info=True)
            return results
        finally:
            with self._round_cv:
                self._round_active = False
                self._round_cv.notify_all()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception:  # pylint: disable=broad-except
                # The round itself contains per-target failures; this
                # guards the loop against everything else (e.g. a tsdb
                # schema error). Telemetry must not crash the
                # controller thread that hosts it.
                logger.warning('scrape round failed:', exc_info=True)
            self._stop.wait(self.interval)
