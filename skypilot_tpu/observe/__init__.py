"""skypilot_tpu.observe — the unified observability plane.

Five pieces, stdlib-only (plus ``utils``), importable from every
layer of the control plane:

  * :mod:`~skypilot_tpu.observe.metrics` — a thread-safe registry of
    Counter/Gauge/Histogram with declared, bounded label sets,
    rendered in Prometheus text exposition format (``/metrics`` on the
    API server and the serve load balancer);
  * :mod:`~skypilot_tpu.observe.journal` — a durable sqlite event
    journal every guarded status setter publishes transitions into,
    making docs/STATE_MACHINES.md observable at runtime (``/v1/events``
    + ``python -m skypilot_tpu.observe tail``);
  * :mod:`~skypilot_tpu.observe.trace` — contextvar/env-carried trace
    IDs minted per API request and threaded through controllers,
    recovery, backends and the slice driver's gang env, stamped onto
    journal events, timeline spans and usage events;
  * :mod:`~skypilot_tpu.observe.spans` — timed span trees keyed by
    those trace IDs: queue wait, optimizer plan, per-zone provision
    attempts, LB/engine hops — one request's latency decomposed at
    ``/v1/traces/<trace_id>`` (write-behind persistence into a
    ``spans`` table in the journal DB);
  * :mod:`~skypilot_tpu.observe.flight` — the engine hot loop's
    fixed-size lock-free event ring (``/debug/flight``; snapshotted
    into the journal on engine failures), from which per-request
    TTFT/TPOT derive without a single span or sqlite write per token.

The FLEET plane (PR 9) builds on those five:

  * :mod:`~skypilot_tpu.observe.promtext` — the one exposition
    parser/merger/quantile every metric-text consumer goes through;
  * :mod:`~skypilot_tpu.observe.tsdb` — the scraped-sample
    time-series table (same DB file, own retention);
  * :mod:`~skypilot_tpu.observe.scrape` — the controller-side scraper
    pulling every replica's ``/metrics`` + ``/health`` with
    per-target failure containment;
  * :mod:`~skypilot_tpu.observe.slo` — declarative SLOs evaluated as
    multi-window burn rates over the scraped samples;
  * :mod:`~skypilot_tpu.observe.costs` — catalog-priced replica
    metering joined against the scraped token/request counters
    ($/token, $/request, spot discount) with declarative CostBudget
    burn-rate alerts — the economic axis of the same plane.

See docs/OBSERVABILITY.md for the metric catalog, journal/span/sample
schema and the trace propagation diagram.
"""
from typing import Dict

from skypilot_tpu.observe import costs
from skypilot_tpu.observe import flight
from skypilot_tpu.observe import journal
from skypilot_tpu.observe import metrics
from skypilot_tpu.observe import promtext
from skypilot_tpu.observe import spans
from skypilot_tpu.observe import trace
from skypilot_tpu.observe import tsdb

__all__ = ['costs', 'flight', 'gc', 'journal', 'metrics', 'promtext',
           'spans', 'trace', 'tsdb']


def gc(max_age_seconds: float = 7 * 24 * 3600,
       max_rows: int = 500_000) -> Dict[str, int]:
    """Retention for ALL journal-DB tables (events + spans + scraped
    samples + cost accruals), one call — the API server's hourly GC loop and the serve
    controller's reconcile loop both run it, so every process that
    writes the journal also collects it (rows accrue in whichever
    process's DB the writer saw; GC only in the API server would leak
    the controller- and LB-written rows forever). Same Nth-newest-id
    row-cap discipline in every table; best-effort like every
    telemetry write."""
    return {'events': journal.gc_events(max_age_seconds=max_age_seconds,
                                        max_rows=max_rows),
            'spans': spans.gc_spans(max_age_seconds=max_age_seconds,
                                    max_rows=max_rows),
            'samples': tsdb.gc_samples(max_age_seconds=max_age_seconds,
                                       max_rows=max_rows),
            'costs': costs.gc_costs(max_age_seconds=max_age_seconds,
                                    max_rows=max_rows)}
