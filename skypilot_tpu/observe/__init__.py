"""skypilot_tpu.observe — the unified observability plane.

Three pieces, stdlib-only (plus ``utils``), importable from every
layer of the control plane:

  * :mod:`~skypilot_tpu.observe.metrics` — a thread-safe registry of
    Counter/Gauge/Histogram with declared, bounded label sets,
    rendered in Prometheus text exposition format (``/metrics`` on the
    API server and the serve load balancer);
  * :mod:`~skypilot_tpu.observe.journal` — a durable sqlite event
    journal every guarded status setter publishes transitions into,
    making docs/STATE_MACHINES.md observable at runtime (``/v1/events``
    + ``python -m skypilot_tpu.observe tail``);
  * :mod:`~skypilot_tpu.observe.trace` — contextvar/env-carried trace
    IDs minted per API request and threaded through controllers,
    recovery, backends and the slice driver's gang env, stamped onto
    journal events, timeline spans and usage events.

See docs/OBSERVABILITY.md for the metric catalog, journal schema and
the trace propagation diagram.
"""
from skypilot_tpu.observe import journal
from skypilot_tpu.observe import metrics
from skypilot_tpu.observe import trace

__all__ = ['journal', 'metrics', 'trace']
