"""Prometheus text-exposition parser, histogram merge, and quantile.

ONE definition of "how metric text is read" for the whole codebase.
Before this module, every consumer of a ``/metrics`` endpoint grew its
own ad-hoc line regexing (bench.py's private ``_histogram_quantile``
was the live example) — each with its own quiet assumptions about
label order and bucket layout. Now bench.py, the fleet CLI
(``python -m skypilot_tpu.observe fleet``), the controller scraper
(observe/scrape.py) and the SLO engine (observe/slo.py) all parse
through here, and the skylint ``metric-discipline`` checker flags any
new ad-hoc exposition regexing outside ``observe/``.

Three layers:

  * :func:`parse` — exposition text → ``{name: Family}`` (type, help,
    samples with parsed label sets). Tolerant of unknown families,
    strict about sample-line shape.
  * histogram structure — :func:`extract_histograms` groups one
    family's ``_bucket``/``_sum``/``_count`` samples into
    :class:`HistogramData` per label set, and :func:`merge_histograms`
    merges shards **bucket-wise** (cumulative Prometheus buckets merge
    by addition). Mismatched bucket layouts REFUSE loudly
    (:class:`BucketMismatchError`) — silently merging different
    layouts would fabricate quantiles.
  * :func:`histogram_quantile` — the dashboard estimate: linear
    interpolation inside the bucket the q-th sample lands in; the
    +Inf tail answers with the last finite bound; ``nan`` with no
    samples.

Fleet aggregation (:func:`merge_texts`): counters and gauges sum per
label set across shards, histograms merge bucket-wise — the
"federate-and-sum" shape ``/-/fleet/metrics`` exposes.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

# A parsed sample's label set: sorted (name, value) pairs — hashable,
# order-insensitive, so samples from shards that render labels in
# different orders still line up.
LabelKey = Tuple[Tuple[str, str], ...]


class BucketMismatchError(ValueError):
    """Histogram shards disagree on bucket layout: merging them
    bucket-wise would silently fabricate counts, so refuse loudly.
    The fix is at the source — histograms meant to merge fleet-wide
    must declare identical buckets (docs/OBSERVABILITY.md)."""


@dataclasses.dataclass
class Sample:
    name: str                      # full sample name incl. _bucket etc.
    labels: LabelKey
    value: float


@dataclasses.dataclass
class Family:
    name: str
    kind: str = 'untyped'          # counter | gauge | histogram | untyped
    help_text: str = ''
    samples: List[Sample] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class HistogramData:
    """One histogram series (one label set): cumulative buckets plus
    the _sum/_count scalars. ``buckets`` is sorted by bound; the +Inf
    bucket is ALWAYS present and equals ``count`` (renderers that obey
    the exposition contract guarantee it; :func:`extract_histograms`
    repairs a missing +Inf from _count)."""
    buckets: List[Tuple[float, float]]   # (le, cumulative count)
    sum: float = 0.0
    count: float = 0.0

    def layout(self) -> Tuple[float, ...]:
        return tuple(le for le, _ in self.buckets)


def _unescape(text: str) -> str:
    out, i = [], 0
    while i < len(text):
        ch = text[i]
        if ch == '\\' and i + 1 < len(text):
            nxt = text[i + 1]
            out.append({'n': '\n', '\\': '\\', '"': '"'}.get(nxt, nxt))
            i += 2
        else:
            out.append(ch)
            i += 1
    return ''.join(out)


def _parse_labels(text: str) -> LabelKey:
    """``a="x",b="y"`` → sorted pairs. Raises ValueError on shapes a
    conforming renderer never emits (the caller skips the line)."""
    pairs: List[Tuple[str, str]] = []
    i = 0
    while i < len(text):
        eq = text.index('=', i)
        name = text[i:eq].strip()
        if not name or text[eq + 1] != '"':
            raise ValueError(f'malformed label pair at {text[i:]!r}')
        j = eq + 2
        buf = []
        while j < len(text):
            ch = text[j]
            if ch == '\\' and j + 1 < len(text):
                buf.append(text[j:j + 2])
                j += 2
                continue
            if ch == '"':
                break
            buf.append(ch)
            j += 1
        else:
            raise ValueError('unterminated label value')
        pairs.append((name, _unescape(''.join(buf))))
        i = j + 1
        if i < len(text):
            if text[i] != ',':
                raise ValueError(f'expected "," at {text[i:]!r}')
            i += 1
    return tuple(sorted(pairs))


def _parse_value(text: str) -> float:
    if text == '+Inf':
        return math.inf
    if text == '-Inf':
        return -math.inf
    return float(text)


def base_name(sample_name: str) -> str:
    """``foo_bucket``/``foo_sum``/``foo_count`` → ``foo`` (histogram
    sample names fold into their family)."""
    for suffix in ('_bucket', '_sum', '_count'):
        if sample_name.endswith(suffix):
            return sample_name[:-len(suffix)]
    return sample_name


def parse(text: str) -> Dict[str, Family]:
    """Exposition text → families keyed by metric name. Sample lines
    that do not parse are SKIPPED (a scraper must survive a partially
    garbled shard), but ``# TYPE``/``# HELP`` inconsistencies within
    one document raise — that is a broken renderer, not line noise."""
    families: Dict[str, Family] = {}

    def fam(name: str) -> Family:
        f = families.get(name)
        if f is None:
            f = Family(name=name)
            families[name] = f
        return f

    histogram_bases = set()
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith('#'):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ('TYPE', 'HELP'):
                name = parts[2]
                if parts[1] == 'TYPE':
                    kind = parts[3].strip() if len(parts) > 3 else 'untyped'
                    f = fam(name)
                    if f.kind not in ('untyped', kind):
                        raise ValueError(
                            f'family {name!r} declared both {f.kind!r} '
                            f'and {kind!r} in one document')
                    f.kind = kind
                    if kind == 'histogram':
                        histogram_bases.add(name)
                else:
                    fam(name).help_text = _unescape(
                        parts[3] if len(parts) > 3 else '')
            continue
        # Sample line: name[{labels}] value [timestamp]
        try:
            if '{' in line:
                name_part, rest = line.split('{', 1)
                label_part, tail = rest.rsplit('}', 1)
                labels = _parse_labels(label_part)
            else:
                name_part, tail = line.split(None, 1)
                labels = ()
            name = name_part.strip()
            value = _parse_value(tail.split()[0])
        except (ValueError, IndexError):
            continue
        family_name = name
        folded = base_name(name)
        if folded in histogram_bases:
            family_name = folded
        fam(family_name).samples.append(Sample(name, labels, value))
    return families


# ------------------------------------------------------------ histograms

def _strip_le(labels: LabelKey) -> Tuple[LabelKey, Optional[float]]:
    le = None
    rest = []
    for k, v in labels:
        if k == 'le':
            le = _parse_value(v)
        else:
            rest.append((k, v))
    return tuple(rest), le


def extract_histograms(families: Mapping[str, Family],
                       family: str) -> Dict[LabelKey, HistogramData]:
    """One histogram family's samples → HistogramData per label set
    (the label set EXCLUDING ``le``). Missing +Inf buckets are
    repaired from ``_count`` (they are equal by the exposition
    contract)."""
    f = families.get(family)
    if f is None:
        return {}
    out: Dict[LabelKey, HistogramData] = {}

    def entry(key: LabelKey) -> HistogramData:
        h = out.get(key)
        if h is None:
            h = HistogramData(buckets=[])
            out[key] = h
        return h

    for s in f.samples:
        if s.name == f'{family}_bucket':
            key, le = _strip_le(s.labels)
            if le is None:
                continue
            entry(key).buckets.append((le, s.value))
        elif s.name == f'{family}_sum':
            entry(s.labels).sum = s.value
        elif s.name == f'{family}_count':
            entry(s.labels).count = s.value
    for h in out.values():
        h.buckets.sort(key=lambda b: b[0])
        if not h.buckets or h.buckets[-1][0] != math.inf:
            h.buckets.append((math.inf, h.count))
    return out


def merge_histograms(shards: Sequence[HistogramData]) -> HistogramData:
    """Bucket-wise merge: cumulative Prometheus buckets merge by
    ADDITION (each shard's ``le`` bucket counts samples <= le, so the
    union stream's count is the sum). Layouts must be identical —
    a mismatch raises :class:`BucketMismatchError` instead of
    interpolating counts that were never observed."""
    shards = [s for s in shards if s is not None]
    if not shards:
        return HistogramData(buckets=[(math.inf, 0.0)])
    layout = shards[0].layout()
    for s in shards[1:]:
        if s.layout() != layout:
            raise BucketMismatchError(
                f'cannot merge histograms with different bucket '
                f'layouts: {layout} vs {s.layout()} — fleet-merged '
                f'histograms must declare identical buckets')
    merged = HistogramData(
        buckets=[(le, sum(s.buckets[i][1] for s in shards))
                 for i, le in enumerate(layout)],
        sum=sum(s.sum for s in shards),
        count=sum(s.count for s in shards))
    return merged


def histogram_quantile(hist: Optional[HistogramData], q: float) -> float:
    """The Prometheus histogram_quantile estimate: linear
    interpolation inside the bucket the q-th sample lands in. The
    open-ended +Inf tail answers with the last finite bound (the
    honest lower bound a dashboard shows); no samples → ``nan``."""
    if hist is None or not hist.buckets:
        return float('nan')
    buckets = hist.buckets
    total = buckets[-1][1]
    if total <= 0:
        return float('nan')
    rank = q * total
    lo_bound = lo_count = 0.0
    for le, cum in buckets:
        if cum >= rank:
            if le == math.inf:
                return lo_bound
            span = cum - lo_count
            frac = ((rank - lo_count) / span) if span else 0.0
            return lo_bound + (le - lo_bound) * frac
        lo_bound, lo_count = le, cum
    return lo_bound


def quantile_from_text(text: str, family: str, q: float) -> float:
    """bench.py's original convenience shape: parse ``text``, merge
    every label set of ``family`` (they share a layout by declaration)
    and estimate the q-th quantile. ``nan`` when absent/empty."""
    hists = extract_histograms(parse(text), family)
    if not hists:
        return float('nan')
    return histogram_quantile(merge_histograms(list(hists.values())), q)


# --------------------------------------------------------- fleet merging

def merge_families(shards: Sequence[Mapping[str, Family]]
                   ) -> Dict[str, Family]:
    """Merge parsed shards into one fleet document: counters and
    gauges SUM per label set (fleet totals — a fleet queue depth is
    the sum of replica queue depths), histograms merge bucket-wise.
    A family typed differently across shards raises ValueError;
    mismatched histogram layouts raise BucketMismatchError."""
    out: Dict[str, Family] = {}
    # name -> label key -> value (scalar kinds)
    scalars: Dict[str, Dict[LabelKey, float]] = {}
    hist_shards: Dict[str, List[Dict[LabelKey, HistogramData]]] = {}
    for shard in shards:
        for name, f in shard.items():
            existing = out.get(name)
            if existing is None:
                out[name] = Family(name=name, kind=f.kind,
                                   help_text=f.help_text)
            else:
                if existing.kind == 'untyped':
                    existing.kind = f.kind
                elif f.kind not in ('untyped', existing.kind):
                    raise ValueError(
                        f'family {name!r} typed {existing.kind!r} on '
                        f'one shard and {f.kind!r} on another')
                if not existing.help_text:
                    existing.help_text = f.help_text
            if f.kind == 'histogram':
                hist_shards.setdefault(name, []).append(
                    extract_histograms(shard, name))
            else:
                acc = scalars.setdefault(name, {})
                for s in f.samples:
                    acc[s.labels] = acc.get(s.labels, 0.0) + s.value
    for name, acc in scalars.items():
        out[name].samples = [Sample(name, k, v)
                             for k, v in sorted(acc.items())]
    for name, per_shard in hist_shards.items():
        keys = sorted({k for shard in per_shard for k in shard})
        samples: List[Sample] = []
        for key in keys:
            merged = merge_histograms(
                [shard[key] for shard in per_shard if key in shard])
            for le, cum in merged.buckets:
                le_txt = '+Inf' if le == math.inf else _fmt_float(le)
                samples.append(Sample(
                    f'{name}_bucket', tuple(sorted(
                        key + (('le', le_txt),))), cum))
            samples.append(Sample(f'{name}_sum', key, merged.sum))
            samples.append(Sample(f'{name}_count', key, merged.count))
        out[name].samples = samples
    return out


def _fmt_float(value: float) -> str:
    if value == math.inf:
        return '+Inf'
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _escape_label(text: str) -> str:
    return (text.replace('\\', r'\\').replace('\n', r'\n')
            .replace('"', r'\"'))


def labels_text(labels: LabelKey) -> str:
    """Canonical (sorted, escaped) label rendering WITHOUT braces —
    the form tsdb stores, so a bucket series round-trips exactly."""
    return ','.join(f'{k}="{_escape_label(v)}"' for k, v in labels)


def render(families: Mapping[str, Family]) -> str:
    """Families → exposition text (the inverse of :func:`parse`),
    used by the fleet endpoint to re-expose merged shards."""
    lines: List[str] = []
    for name in sorted(families):
        f = families[name]
        if f.help_text:
            lines.append(f'# HELP {name} {_escape_label(f.help_text)}')
        if f.kind != 'untyped':
            lines.append(f'# TYPE {name} {f.kind}')
        for s in f.samples:
            if s.labels:
                inner = ','.join(f'{k}="{_escape_label(v)}"'
                                 for k, v in s.labels)
                label_txt = '{' + inner + '}'
            else:
                label_txt = ''
            lines.append(f'{s.name}{label_txt} {_fmt_float(s.value)}')
    return '\n'.join(lines) + ('\n' if lines else '')


def merge_texts(texts: Iterable[str]) -> str:
    """Exposition texts → one merged exposition text (the
    ``/-/fleet/metrics`` operation)."""
    return render(merge_families([parse(t) for t in texts]))
