"""Thread-safe metrics registry with declared, bounded label sets.

Reference analog: prometheus_client's Counter/Gauge/Histogram — rebuilt
stdlib-only (like ``analysis/``) so every control-plane process can
expose metrics without a dependency, and *stricter*: label sets are
declared up front as finite tuples and an undeclared label value is a
``ValueError`` at the call site. That is the cardinality discipline the
Google ads-infra paper treats as a precondition for fleet-wide
monitoring — a label fed from an f-string (user names, cluster names,
request ids) makes every scrape bigger than the last and eventually
OOMs the collector. The skylint ``metric-discipline`` checker enforces
the same contract statically.

Naming contract (also lint-enforced): ``skytpu_<subsystem>_<name>``,
snake_case, e.g. ``skytpu_lb_requests_total``.

Rendering follows the Prometheus text exposition format 0.0.4
(``# HELP`` / ``# TYPE`` lines, cumulative histogram buckets with a
``+Inf`` bucket equal to ``_count``).
"""
from __future__ import annotations

import math
import re
import threading
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

NAME_RE = re.compile(r'^skytpu_[a-z0-9]+(_[a-z0-9]+)+$')
_LABEL_RE = re.compile(r'^[a-z][a-z0-9_]*$')

# Latency buckets (seconds): sub-ms to minutes — control-plane
# operations span request-queue waits (ms) to provisioning (minutes).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0)

LabelSpec = Mapping[str, Sequence[str]]


def _fmt(value: float) -> str:
    if value == math.inf:
        return '+Inf'
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _escape(text: str) -> str:
    return (text.replace('\\', r'\\').replace('\n', r'\n')
            .replace('"', r'\"'))


class _Metric:
    kind = ''

    def __init__(self, name: str, help_text: str,
                 labels: Optional[LabelSpec] = None):
        if not NAME_RE.match(name):
            raise ValueError(
                f'metric name {name!r} must be skytpu_<subsystem>_<name> '
                f'snake_case (see docs/OBSERVABILITY.md)')
        self.name = name
        self.help_text = help_text
        self._label_names: Tuple[str, ...] = tuple((labels or {}).keys())
        self._label_values: Dict[str, frozenset] = {}
        for lname, values in (labels or {}).items():
            if not _LABEL_RE.match(lname):
                raise ValueError(f'label name {lname!r} is not snake_case')
            vals = frozenset(str(v) for v in values)
            if not vals:
                raise ValueError(f'label {lname!r} declares no values')
            self._label_values[lname] = vals
        self._lock = threading.Lock()

    def _labelspec(self) -> Dict[str, Tuple[str, ...]]:
        return {k: tuple(sorted(v)) for k, v in self._label_values.items()}

    def _key(self, labels: Mapping[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self._label_names):
            raise ValueError(
                f'{self.name}: got labels {sorted(labels)}, declared '
                f'{sorted(self._label_names)}')
        out = []
        for lname in self._label_names:
            value = str(labels[lname])
            if value not in self._label_values[lname]:
                raise ValueError(
                    f'{self.name}: undeclared value {value!r} for label '
                    f'{lname!r} (declared: '
                    f'{sorted(self._label_values[lname])}) — bounded '
                    f'label sets are the cardinality contract')
            out.append(value)
        return tuple(out)

    def _label_str(self, key: Tuple[str, ...],
                   extra: Optional[Tuple[Tuple[str, str], ...]] = None
                   ) -> str:
        pairs = list(zip(self._label_names, key)) + list(extra or ())
        if not pairs:
            return ''
        inner = ','.join(f'{k}="{_escape(v)}"' for k, v in pairs)
        return '{' + inner + '}'

    def _header(self) -> List[str]:
        return [f'# HELP {self.name} {_escape(self.help_text)}',
                f'# TYPE {self.name} {self.kind}']

    def render(self) -> List[str]:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing count."""
    kind = 'counter'

    def __init__(self, name: str, help_text: str,
                 labels: Optional[LabelSpec] = None):
        super().__init__(name, help_text, labels)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError('counters only go up')
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._values.items())
        for key, value in items:
            out.append(f'{self.name}{self._label_str(key)} {_fmt(value)}')
        return out

    def reset(self) -> None:
        with self._lock:
            self._values.clear()


class Gauge(_Metric):
    """A value that can go up and down."""
    kind = 'gauge'

    def __init__(self, name: str, help_text: str,
                 labels: Optional[LabelSpec] = None):
        super().__init__(name, help_text, labels)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted(self._values.items())
        for key, value in items:
            out.append(f'{self.name}{self._label_str(key)} {_fmt(value)}')
        return out

    def reset(self) -> None:
        with self._lock:
            self._values.clear()


class Histogram(_Metric):
    """Cumulative-bucket histogram (``_bucket``/``_sum``/``_count``)."""
    kind = 'histogram'

    def __init__(self, name: str, help_text: str,
                 labels: Optional[LabelSpec] = None,
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help_text, labels)
        bounds = tuple(sorted(buckets or DEFAULT_BUCKETS))
        if not bounds:
            raise ValueError('histogram needs at least one bucket bound')
        self.buckets = bounds
        # key -> (per-bucket counts, sum, count)
        self._data: Dict[Tuple[str, ...], List] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            entry = self._data.get(key)
            if entry is None:
                entry = [[0] * len(self.buckets), 0.0, 0]
                self._data[key] = entry
            counts, _, _ = entry
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    break
            entry[1] += value
            entry[2] += 1

    def render(self) -> List[str]:
        out = self._header()
        with self._lock:
            items = sorted((k, ([*v[0]], v[1], v[2]))
                           for k, v in self._data.items())
        for key, (counts, total, count) in items:
            cumulative = 0
            for bound, n in zip(self.buckets, counts):
                cumulative += n
                le = (('le', _fmt(bound)),)
                out.append(f'{self.name}_bucket'
                           f'{self._label_str(key, le)} {cumulative}')
            out.append(f'{self.name}_bucket'
                       f'{self._label_str(key, (("le", "+Inf"),))} '
                       f'{count}')
            out.append(f'{self.name}_sum{self._label_str(key)} '
                       f'{_fmt(total)}')
            out.append(f'{self.name}_count{self._label_str(key)} {count}')
        return out

    def reset(self) -> None:
        with self._lock:
            self._data.clear()


class Registry:
    """Process-wide metric registry.

    Declarations are idempotent get-or-create: a module may re-declare
    the same metric (same kind, help and label spec) and receive the
    existing instance — but a conflicting redeclaration raises, so two
    subsystems cannot silently share a name with different meanings.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help_text: str,
                       labels: Optional[LabelSpec], **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                want_labels = {k: tuple(sorted(str(x) for x in v))
                               for k, v in (labels or {}).items()}
                want_buckets = kwargs.get('buckets')
                bucket_conflict = (
                    isinstance(existing, Histogram) and
                    want_buckets is not None and
                    tuple(sorted(want_buckets)) != existing.buckets)
                if (type(existing) is not cls or
                        existing._labelspec() != want_labels or
                        bucket_conflict):
                    raise ValueError(
                        f'metric {name!r} already registered with a '
                        f'different kind, label spec or buckets')
                return existing
            metric = cls(name, help_text, labels, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help_text: str,
                labels: Optional[LabelSpec] = None) -> Counter:
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str,
              labels: Optional[LabelSpec] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str,
                  labels: Optional[LabelSpec] = None,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labels,
                                   buckets=buckets)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def render(self) -> str:
        """The whole registry in Prometheus text exposition format."""
        lines: List[str] = []
        for metric in self.metrics():
            lines.extend(metric.render())
        return '\n'.join(lines) + ('\n' if lines else '')

    def reset_for_tests(self) -> None:
        """Zero every metric's samples. Registrations are KEPT: modules
        hold references to their metric objects, so dropping the
        registration would silently disconnect them."""
        for metric in self.metrics():
            metric.reset()


# The default process-wide registry; the module-level factories below
# are the declaration surface instrumented code uses.
REGISTRY = Registry()


def counter(name: str, help_text: str,
            labels: Optional[LabelSpec] = None) -> Counter:
    return REGISTRY.counter(name, help_text, labels)


def gauge(name: str, help_text: str,
          labels: Optional[LabelSpec] = None) -> Gauge:
    return REGISTRY.gauge(name, help_text, labels)


def histogram(name: str, help_text: str,
              labels: Optional[LabelSpec] = None,
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return REGISTRY.histogram(name, help_text, labels, buckets=buckets)


def render() -> str:
    return REGISTRY.render()
