"""The closed request-class registry — the serving plane's declared,
bounded ``cls`` metric label.

Per-class measurement is the honest unit of serving evidence (PAPERS.md,
the Gemma-on-TPU serving comparison): an aggregate TTFT p95 over mixed
traffic answers nothing, because a batch job's 30 s first token is fine
and an interactive chat turn's is an outage. But a per-request class is
also exactly the kind of value that destroys a metrics plane when fed
raw: it arrives on an HTTP header (``X-Skytpu-Class``) any client can
set to anything, and an interpolated label makes every scrape bigger
than the last (the cardinality contract in docs/OBSERVABILITY.md).

So the class label is CLOSED here, once, for every consumer:

  * :data:`CLASSES` is the full declared value set — engines declare
    their per-class histograms over it, the SLO engine derives its
    per-class goodput kinds from it, the fleet CLI renders it;
  * :func:`normalize` is the ONE mapping from a raw client-supplied
    string into the set (unknown/absent → ``other``, never a new
    label value) — the LB clamps the header through it before
    forwarding, the engine clamps again before ``labels()`` (defense
    in depth: a replica addressed directly must stay bounded too).
    The skylint ``metric-discipline`` checker enforces statically that
    a raw ``X-Skytpu-Class`` read reaches no metric call without
    passing through it;
  * :data:`OBJECTIVES` carries each class's latency objective — the
    GOODPUT definition. A request counts toward goodput only if it
    completed within its class's objective (TTFT at/under the bound,
    and TPOT at/under the bound when the request decoded more than one
    token). Bounds are aligned with declared histogram bucket bounds
    so bucketed windowed evaluation (observe/slo.py) answers exactly.

Layering: this module lives in ``observe`` (rank 3) so both the serve
plane and the SLO engine import it downward; it imports nothing but the
stdlib.
"""
from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Tuple

# The declared finite label values. ``other`` is the clamp target for
# anything unknown and the default for unlabeled traffic — it MUST stay
# a member, or clamping would itself mint a new value.
CLASSES: Tuple[str, ...] = ('interactive', 'long_context', 'batch',
                            'other')
DEFAULT_CLASS = 'other'

# The header a client (or the loadgen harness) declares its class on.
# The LB clamps it through normalize() before forwarding — mirroring
# the X-Skytpu-Trace-Id header-hardening precedent (PR 5).
HEADER = 'X-Skytpu-Class'

# Per-class SLO kind names, derived once so observe/slo.py's KINDS and
# every scorecard column agree by construction.
GOODPUT_KINDS: Tuple[str, ...] = tuple('goodput_' + c for c in CLASSES)


@dataclasses.dataclass(frozen=True)
class ClassObjective:
    """One class's latency objective — the goodput cut. Both bounds
    are declared histogram bucket bounds (engine TTFT buckets include
    2.5/10/30 s, TPOT buckets include 0.25/0.5/1.0 s), so windowed
    bucket-delta evaluation needs no interpolation."""
    ttft_seconds: float
    tpot_seconds: float


OBJECTIVES: Mapping[str, ClassObjective] = {
    'interactive': ClassObjective(ttft_seconds=2.5, tpot_seconds=0.25),
    'long_context': ClassObjective(ttft_seconds=10.0, tpot_seconds=0.25),
    'batch': ClassObjective(ttft_seconds=30.0, tpot_seconds=1.0),
    'other': ClassObjective(ttft_seconds=10.0, tpot_seconds=0.5),
}
assert set(OBJECTIVES) == set(CLASSES)


def normalize(raw: Optional[str]) -> str:
    """Map a raw (client-supplied, untrusted) class string into the
    closed set: case/whitespace-insensitive exact match, anything else
    — including None/empty — clamps to ``other``. This is the ONE
    sanctioned path from an ``X-Skytpu-Class`` header value to a
    metric ``cls=`` label."""
    if not raw:
        return DEFAULT_CLASS
    value = raw.strip().lower()
    return value if value in CLASSES else DEFAULT_CLASS


def from_headers(headers) -> str:
    """The request's class from an HTTP header mapping (aiohttp
    CIMultiDict or plain dict), already clamped."""
    try:
        raw = headers.get(HEADER, '')
    except AttributeError:
        raw = ''
    return normalize(raw)


def is_good(cls: str, ttft_seconds: float,
            tpot_seconds: Optional[float]) -> bool:
    """The goodput predicate: did this request complete within its
    class's latency objective? ``tpot_seconds`` is None for
    single-token requests — TTFT alone judges those."""
    obj = OBJECTIVES.get(cls) or OBJECTIVES[DEFAULT_CLASS]
    if ttft_seconds > obj.ttft_seconds:
        return False
    return tpot_seconds is None or tpot_seconds <= obj.tpot_seconds
