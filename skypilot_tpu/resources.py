"""Resource spec: what hardware a task wants, validated and canonicalized.

Reference analog: sky/resources.py (`Resources:119`, `_set_accelerators:773`,
`get_cost:1514`, `less_demanding_than:1643`, `make_deploy_variables:1541`).

TPU-native differences: `accelerators: tpu-v5p-128` parses into a typed
`TpuSlice` (generation, chip count, ICI topology, host fan-out) instead of an
opaque string routed through GCP-specific fixups; `accelerator_args` gains
`topology` (ICI layout override) and `num_slices` (DCN multi-slice) in
addition to the reference's `runtime_version`.
"""
from __future__ import annotations

import textwrap
from typing import Any, Dict, List, Optional, Set, Union

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.catalog import tpu_catalog
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.tpu import topology
from skypilot_tpu.utils import registry

logger = sky_logging.init_logger(__name__)

DEFAULT_DISK_SIZE_GB = 100

_DISK_UNITS_GB = {'': 1, 'g': 1, 'gb': 1, 't': 1024, 'tb': 1024}


def parse_disk_size(value: Union[int, str]) -> int:
    """Parse `disk_size` with optional GB/TB suffix (reference analog:
    sky/utils/resources_utils.py:369 parse_memory_resource). `1024GB`
    appears verbatim in reference recipes (examples/training/torchtitan)."""
    if isinstance(value, int):
        return value
    s = str(value).strip().lower()
    num = s.rstrip('bgt')
    unit = s[len(num):]
    try:
        return int(float(num) * _DISK_UNITS_GB[unit])
    except (ValueError, KeyError):
        raise ValueError(
            f'resources.disk_size: expected an int or "<N>GB"/"<N>TB", '
            f'got {value!r}.') from None

# Single source of truth for valid YAML fields: the declarative schema
# (utils/schemas.py). Diverging hand-maintained lists caused real bugs.
from skypilot_tpu.utils import schemas as _schemas

_RESOURCES_FIELDS = frozenset(_schemas.RESOURCES_SCHEMA)


class Resources:
    """An (optionally partial) hardware requirement.

    A Resources is *launchable* when it names a cloud and a concrete TPU
    slice; the optimizer turns partial specs into launchable ones.
    """

    def __init__(
        self,
        cloud: Optional[Union[str, cloud_lib.Cloud]] = None,
        accelerators: Optional[str] = None,
        accelerator_args: Optional[Dict[str, Any]] = None,
        use_spot: Optional[bool] = None,
        spot_recovery: Optional[str] = None,
        region: Optional[str] = None,
        zone: Optional[str] = None,
        cpus: Optional[Union[int, str]] = None,
        memory: Optional[Union[int, str]] = None,
        disk_size: Optional[int] = None,
        disk_tier: Optional[str] = None,
        ports: Optional[Union[int, str, List[Union[int, str]]]] = None,
        image_id: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
        autostop: Optional[Union[int, bool, Dict[str, Any]]] = None,
        volumes: Optional[Dict[str, str]] = None,
        network_tier: Optional[str] = None,
        instance_type: Optional[str] = None,
    ):
        self._cloud: Optional[cloud_lib.Cloud] = None
        if cloud is not None:
            if isinstance(cloud, str):
                try:
                    cloud = registry.CLOUD_REGISTRY.from_str(cloud)
                except ValueError:
                    # Reference-supported providers parse opaquely and fail
                    # at optimize time with a swap hint (clouds/foreign.py);
                    # true typos still raise here.
                    from skypilot_tpu.clouds import foreign
                    if cloud.lower() in foreign.FOREIGN_CLOUD_NAMES:
                        cloud = foreign.ForeignCloud(cloud)
                    else:
                        raise
            self._cloud = cloud

        self._use_spot_specified = use_spot is not None
        self._use_spot = bool(use_spot) if use_spot is not None else False
        self._spot_recovery = spot_recovery

        self._region: Optional[str] = None
        self._zone: Optional[str] = None
        self._set_region_zone(region, zone)

        self._cpus = None if cpus is None else str(cpus)
        self._memory = None if memory is None else str(memory)
        self._disk_size = (parse_disk_size(disk_size)
                           if disk_size is not None else DEFAULT_DISK_SIZE_GB)
        self._disk_tier = disk_tier
        # Network performance tier (reference: sky/resources.py:155,
        # resources_utils.NetworkTier). On GCP TPU VMs 'best' maps to
        # gVNIC + compact placement at deploy time; on a single slice ICI
        # needs no enablement, so this mostly matters for multi-slice DCN.
        if network_tier is not None:
            tier = str(network_tier).lower()
            if tier not in ('standard', 'best'):
                raise ValueError(
                    f'network_tier must be standard|best, got {network_tier!r}')
            network_tier = tier
        self._network_tier = network_tier
        # Host VM shape override (reference: sky/resources.py instance_type).
        # TPU VMs fix the host shape per generation, so this matters only
        # for CPU-only tasks and foreign-cloud recipes; stored opaquely.
        self._instance_type = instance_type
        self._image_id = image_id
        self._labels = dict(labels) if labels else {}
        # {mount_path: volume_name} — persistent disks attached to every
        # host at provision (reference analog: sky/volumes/).
        self._volumes = dict(volumes) if volumes else {}
        self._set_ports(ports)
        self._set_autostop(autostop)

        self._accelerator_args: Dict[str, Any] = dict(accelerator_args or {})
        self._tpu: Optional[topology.TpuSlice] = None
        self._accelerators_str: Optional[str] = None
        self._set_accelerators(accelerators)

    # ------------------------------------------------------------------
    # Field setters / validation
    # ------------------------------------------------------------------
    def _set_accelerators(self, accelerators: Optional[str]) -> None:
        """Parse accelerator spec (analog: sky/resources.py:773)."""
        if accelerators is None:
            return
        if isinstance(accelerators, dict):
            # {name: count} style from YAML; TPU names embed the count.
            if len(accelerators) != 1:
                raise ValueError(
                    f'Expected a single accelerator entry, got {accelerators}')
            name, cnt = next(iter(accelerators.items()))
            if topology.is_tpu_accelerator(str(name)):
                if cnt not in (1, None):
                    raise ValueError(
                        f'TPU slices embed their size in the name (e.g. '
                        f'tpu-v5p-128); got count {cnt} for {name}.')
                accelerators = name
            else:
                # GPU-era '{A100: 8}' spec: keep as an opaque string.
                accelerators = name if cnt in (1, None) else f'{name}:{cnt}'
        accelerators = str(accelerators).strip()
        self._accelerators_str = accelerators
        if topology.is_tpu_accelerator(accelerators):
            topo_override = self._accelerator_args.get('topology')
            sl = topology.parse_tpu_accelerator(accelerators, topo_override)
            num_slices = int(self._accelerator_args.get('num_slices', 1))
            if num_slices > 1:
                sl = topology.TpuSlice(
                    sl.generation, sl.count, sl.num_chips, sl.topology,
                    sl.num_hosts, num_slices)
            self._tpu = sl
        # Non-TPU names (GPU-era YAMLs) parse but stay non-launchable; the
        # optimizer reports them infeasible with a TPU swap-in hint, so
        # reference recipes fail at optimize time with guidance, not at parse.

    def _set_region_zone(self, region: Optional[str],
                         zone: Optional[str]) -> None:
        if region is None and zone is None:
            return
        if self._cloud is not None:
            self._region, self._zone = self._cloud.validate_region_zone(
                region, zone)
        else:
            self._region, self._zone = tpu_catalog.validate_region_zone(
                region, zone)

    def _set_ports(self, ports) -> None:
        if ports is None:
            self._ports: List[str] = []
            return
        if not isinstance(ports, list):
            ports = [ports]
        self._ports = [str(p) for p in ports]

    def _set_autostop(self, autostop) -> None:
        # Canonical form: None or {'idle_minutes': int, 'down': bool}.
        if autostop is None or autostop is False:
            self._autostop: Optional[Dict[str, Any]] = None
        elif autostop is True:
            self._autostop = {'idle_minutes': 5, 'down': False}
        elif isinstance(autostop, int):
            self._autostop = {'idle_minutes': autostop, 'down': False}
        elif isinstance(autostop, dict):
            self._autostop = {
                'idle_minutes': int(autostop.get('idle_minutes', 5)),
                'down': bool(autostop.get('down', False)),
            }
        else:
            raise ValueError(f'Invalid autostop spec: {autostop!r}')

    # ------------------------------------------------------------------
    # Accessors
    # ------------------------------------------------------------------
    @property
    def cloud(self) -> Optional[cloud_lib.Cloud]:
        return self._cloud

    @property
    def tpu(self) -> Optional[topology.TpuSlice]:
        return self._tpu

    @property
    def volumes(self) -> Dict[str, str]:
        return dict(self._volumes)

    @property
    def accelerators(self) -> Optional[str]:
        return self._tpu.name if self._tpu is not None else self._accelerators_str

    @property
    def accelerator_args(self) -> Dict[str, Any]:
        return dict(self._accelerator_args)

    @property
    def use_spot(self) -> bool:
        return self._use_spot

    @property
    def use_spot_specified(self) -> bool:
        return self._use_spot_specified

    @property
    def spot_recovery(self) -> Optional[str]:
        return self._spot_recovery

    @property
    def region(self) -> Optional[str]:
        return self._region

    @property
    def zone(self) -> Optional[str]:
        return self._zone

    @property
    def cpus(self) -> Optional[str]:
        return self._cpus

    @property
    def memory(self) -> Optional[str]:
        return self._memory

    @property
    def disk_size(self) -> int:
        return self._disk_size

    @property
    def disk_tier(self) -> Optional[str]:
        return self._disk_tier

    @property
    def network_tier(self) -> Optional[str]:
        return self._network_tier

    @property
    def instance_type(self) -> Optional[str]:
        return self._instance_type

    @property
    def ports(self) -> List[str]:
        return list(self._ports)

    @property
    def image_id(self) -> Optional[str]:
        return self._image_id

    @property
    def labels(self) -> Dict[str, str]:
        return dict(self._labels)

    @property
    def autostop(self) -> Optional[Dict[str, Any]]:
        return dict(self._autostop) if self._autostop else None

    @property
    def num_hosts(self) -> int:
        """Worker VMs this resource spans (1 if no TPU yet)."""
        return self._tpu.total_hosts if self._tpu is not None else 1

    def is_launchable(self) -> bool:
        return self._cloud is not None and self._tpu is not None

    # ------------------------------------------------------------------
    # Copy / comparison
    # ------------------------------------------------------------------
    def copy(self, **override) -> 'Resources':
        cfg = dict(
            cloud=self._cloud,
            accelerators=self.accelerators,
            accelerator_args=self._accelerator_args or None,
            use_spot=self._use_spot if self._use_spot_specified else None,
            spot_recovery=self._spot_recovery,
            region=self._region,
            zone=self._zone,
            cpus=self._cpus,
            memory=self._memory,
            disk_size=self._disk_size,
            disk_tier=self._disk_tier,
            ports=self._ports or None,
            image_id=self._image_id,
            labels=self._labels or None,
            autostop=self._autostop,
            volumes=self._volumes or None,
            network_tier=self._network_tier,
            instance_type=self._instance_type,
        )
        cfg.update(override)
        return Resources(**cfg)

    def less_demanding_than(self, other: 'Resources') -> bool:
        """True if `other` (a cluster's resources) can serve this request.

        Reference analog: sky/resources.py:1643 — used by `exec` to check a
        task fits an existing cluster.
        """
        if self._cloud is not None and (other.cloud is None or
                                        not self._cloud.is_same_cloud(
                                            other.cloud)):
            return False
        if self._region is not None and self._region != other.region:
            return False
        if self._zone is not None and self._zone != other.zone:
            return False
        if self._use_spot_specified and self._use_spot != other.use_spot:
            return False
        if self._tpu is not None:
            if other.tpu is None:
                return False
            if (self._tpu.generation != other.tpu.generation or
                    self._tpu.total_chips > other.tpu.total_chips):
                return False
        return True

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Resources):
            return NotImplemented
        return self.to_yaml_config() == other.to_yaml_config()

    def __hash__(self) -> int:
        import json
        return hash(json.dumps(self.to_yaml_config(), sort_keys=True))

    # ------------------------------------------------------------------
    # Cost & deploy
    # ------------------------------------------------------------------
    def get_cost(self, seconds: float) -> float:
        """$ to run for `seconds` (analog: sky/resources.py:1514)."""
        if self._tpu is None:
            return 0.0
        if self._cloud is not None:
            hourly = self._cloud.hourly_cost(self)
        else:
            hourly = tpu_catalog.get_hourly_cost(
                self._tpu, use_spot=self._use_spot, region=self._region,
                zone=self._zone)
        return hourly * seconds / 3600.0

    def get_required_cloud_features(
            self) -> Set[cloud_lib.CloudImplementationFeatures]:
        feats: Set[cloud_lib.CloudImplementationFeatures] = set()
        if self._use_spot:
            feats.add(cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE)
        if self._tpu is not None and self._tpu.is_multi_host:
            feats.add(cloud_lib.CloudImplementationFeatures.MULTI_HOST)
        if self._tpu is not None and self._tpu.num_slices > 1:
            feats.add(cloud_lib.CloudImplementationFeatures.MULTI_SLICE)
        if self._ports:
            feats.add(cloud_lib.CloudImplementationFeatures.OPEN_PORTS)
        if self._autostop is not None:
            feats.add(cloud_lib.CloudImplementationFeatures.AUTOSTOP)
            if not self._autostop.get('down', False):
                feats.add(cloud_lib.CloudImplementationFeatures.STOP)
        return feats

    def make_deploy_variables(self, region: str, zones: Optional[List[str]],
                              cluster_name: str) -> Dict[str, Any]:
        """Analog: sky/resources.py:1541 → cloud.make_deploy_resources_variables."""
        assert self._cloud is not None, 'Resources must be launchable'
        return self._cloud.make_deploy_resources_variables(
            self, region, zones, cluster_name)

    # ------------------------------------------------------------------
    # YAML round trip
    # ------------------------------------------------------------------
    @classmethod
    def from_yaml_config(
            cls, config: Optional[Dict[str, Any]]
    ) -> Union['Resources', List['Resources'], Set['Resources']]:
        """Build from a task-YAML `resources:` section.

        Supports `any_of:` / `ordered:` candidate lists like the reference.
        """
        if config is None:
            return Resources()
        config = dict(config)
        unknown = set(config) - _RESOURCES_FIELDS
        if unknown:
            raise ValueError(
                f'Unknown resources fields: {sorted(unknown)}. '
                f'Valid: {sorted(_RESOURCES_FIELDS)}')
        config = cls._normalize_yaml_fields(config)
        return cls._from_normalized(config)

    @staticmethod
    def _normalize_yaml_fields(config: Dict[str, Any]) -> Dict[str, Any]:
        """Map the reference's newer spellings onto canonical fields.

        - `infra: cloud[/region[/zone]]` → cloud/region/zone (reference:
          sky/utils/infra_utils.py:38; `*` segments mean "any"; k8s
          contexts may themselves contain '/').
        - `gpus:` → `accelerators` (alias, sky/resources.py:43).
        """
        config = dict(config)
        infra = config.pop('infra', None)
        if infra is not None:
            raw = str(infra).strip().strip('/')
            head, _, rest = raw.partition('/')
            head = head.strip().lower()
            cloud = None if head in ('*', '') else head
            region = zone = None
            if cloud in ('k8s', 'kubernetes'):
                cloud = 'kubernetes'
                region = rest.strip() or None   # context name, may have '/'
            elif rest:
                region, _, zone = rest.partition('/')
                region = None if region.strip() in ('*', '') else region.strip()
                zone = None if zone.strip() in ('*', '') else zone.strip()
            for key, val in (('cloud', cloud), ('region', region),
                             ('zone', zone)):
                if val is not None:
                    if config.get(key) not in (None, val):
                        raise ValueError(
                            f'infra: {raw!r} conflicts with {key}: '
                            f'{config[key]!r}.')
                    config[key] = val
        gpus = config.pop('gpus', None)
        if gpus is not None:
            if config.get('accelerators') is not None:
                raise ValueError('Specify only one of gpus / accelerators.')
            config['accelerators'] = gpus
        return config

    @classmethod
    def _from_normalized(
            cls, config: Dict[str, Any]
    ) -> Union['Resources', List['Resources'], Set['Resources']]:
        any_of = config.pop('any_of', None)
        ordered = config.pop('ordered', None)
        if any_of is not None and ordered is not None:
            raise ValueError('Specify only one of any_of / ordered.')

        # Multi-candidate accelerators — `{H100:8, H200:8}` or a list — are
        # sugar for any_of (reference: sky/resources.py:2043-2060; YAML flow
        # mappings put the count inside the key with a None value).
        accels = config.get('accelerators')
        if isinstance(accels, dict) and len(accels) > 1:
            accels = [k if v is None else f'{k}:{v}' for k, v in
                      accels.items()]
        if isinstance(accels, (list, set)):
            if any_of is not None or ordered is not None:
                raise ValueError('Cannot combine a multi-candidate '
                                 'accelerators list with any_of/ordered.')
            config.pop('accelerators')
            any_of = [{'accelerators': str(a)} for a in accels]
        elif isinstance(accels, dict) and len(accels) == 1:
            # Normalize the 1-entry flow-mapping form '{H100:8}' (count in
            # the key) before _set_accelerators sees it.
            name, cnt = next(iter(accels.items()))
            if cnt is None and ':' in str(name):
                config['accelerators'] = str(name)

        def _one(override: Dict[str, Any]) -> 'Resources':
            merged = cls._normalize_yaml_fields({**config, **override})
            return cls(
                cloud=merged.get('cloud'),
                accelerators=merged.get('accelerators'),
                accelerator_args=merged.get('accelerator_args'),
                use_spot=merged.get('use_spot'),
                # job_recovery is the reference's newer name for the same
                # knob; accept both.
                spot_recovery=(merged.get('job_recovery') or
                               merged.get('spot_recovery')),
                volumes=merged.get('volumes'),
                region=merged.get('region'),
                zone=merged.get('zone'),
                cpus=merged.get('cpus'),
                memory=merged.get('memory'),
                disk_size=merged.get('disk_size'),
                disk_tier=merged.get('disk_tier'),
                ports=merged.get('ports'),
                image_id=merged.get('image_id'),
                labels=merged.get('labels'),
                autostop=merged.get('autostop'),
                network_tier=merged.get('network_tier'),
                instance_type=merged.get('instance_type'),
            )

        if any_of is not None:
            return {_one(o or {}) for o in any_of}
        if ordered is not None:
            return [_one(o or {}) for o in ordered]
        return _one({})

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}

        def add(key: str, value: Any) -> None:
            if value is not None and value != {} and value != []:
                cfg[key] = value

        add('cloud', None if self._cloud is None else repr(self._cloud).lower())
        add('accelerators', self.accelerators)
        add('accelerator_args', self._accelerator_args or None)
        if self._use_spot_specified:
            add('use_spot', self._use_spot)
        add('spot_recovery', self._spot_recovery)
        add('region', self._region)
        add('zone', self._zone)
        add('cpus', self._cpus)
        add('memory', self._memory)
        if self._disk_size != DEFAULT_DISK_SIZE_GB:
            add('disk_size', self._disk_size)
        add('disk_tier', self._disk_tier)
        add('network_tier', self._network_tier)
        add('instance_type', self._instance_type)
        add('ports', self._ports or None)
        add('image_id', self._image_id)
        add('labels', self._labels or None)
        add('autostop', self._autostop)
        add('volumes', self._volumes or None)
        return cfg

    def __repr__(self) -> str:
        parts = []
        if self._cloud is not None:
            parts.append(repr(self._cloud))
        if self._tpu is not None:
            parts.append(self._tpu.name)
            if self._use_spot:
                parts.append('[Spot]')
        if self._region:
            parts.append(self._region)
        if not parts:
            return '<Resources: empty>'
        return '<Resources: ' + ' '.join(parts) + '>'

    def format_brief(self) -> str:
        acc = self.accelerators or 'cpu'
        spot = '[spot]' if self._use_spot else ''
        cloud = repr(self._cloud).lower() if self._cloud else '?'
        return f'{cloud}:{acc}{spot}'
