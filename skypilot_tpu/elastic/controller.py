"""The closed-loop pool controller: one decision engine for every pool.

``PoolController`` turns one ElasticSpec into decisions; an
``ElasticController`` hosts many pools behind one loop (or behind a
caller-driven cadence — the serve reconcile loop and the scrape-round
callback both just call ``evaluate()``; a thread is only for pools
with no loop of their own).

The decision pipeline per round, uniform across pools:

    signal ── stale? ──> declared fallback (or hold) ──┐
       │                                               │
       └── fresh ──> reduce (ratio or band) ──> clamp ─┴─> raw target
                                                             │
    hysteresis: raw must HOLD for the up/downscale delay,     │
    a downscale needs `clean_rounds` confirming rounds        │
    (observe/slo.py's de-escalation idiom), and applied       │
    changes are `cooldown_seconds` apart ─────────────────────┘
                                                             │
    adopt ──> scale_up/scale_down hook ──> journal + metrics ─┘

Safety contract (PR-9): NO signal → hold; STALE signal → the
DECLARED fallback only (never a guess); every applied change and every
signal-source transition is journaled as an ``elastic_decision`` event
so a scale event is replayable from the journal alone. Decisions are
also published as ``skytpu_elastic_target{pool}`` (post-hysteresis
target) and ``skytpu_elastic_decisions_total{pool,action}`` (round
outcomes — `hold` counts rounds, so liveness is visible).

The hysteresis core is the serve autoscaler's (pending proposal +
delay), extracted here so serve/autoscalers.py, the disagg per-role
autoscalers, the data-worker pool and the rollout fleet all flap-damp
identically; serve's existing behavior is pinned by its tests and
preserved bit-for-bit (clean_rounds=1, cooldown=0 there).
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.analysis import state_machines
from skypilot_tpu.elastic import spec as spec_lib
from skypilot_tpu.observe import journal
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import vclock

logger = sky_logging.init_logger(__name__)

_TARGET_GAUGE = metrics_lib.gauge(
    'skytpu_elastic_target',
    'Post-hysteresis unit target per elastic pool (what the pool '
    'should converge to; the pool\'s own reconcile applies it).',
    labels={'pool': spec_lib.POOLS})
_DECISIONS_TOTAL = metrics_lib.counter(
    'skytpu_elastic_decisions_total',
    'Elastic controller round outcomes per pool. scale_up/scale_down '
    'count APPLIED target changes; hold counts evaluated rounds that '
    'changed nothing (liveness — a silent controller reads as zero).',
    labels={'pool': spec_lib.POOLS,
            'action': ('scale_up', 'scale_down', 'hold')})

# Signal source of one round's raw target — journaled so a replay can
# tell a signal-driven decision from a fallback-driven one.
_SOURCE_SIGNAL = 'signal'
_SOURCE_FALLBACK_STALE = 'fallback_stale'
_SOURCE_FALLBACK_NO_SIGNAL = 'fallback_no_signal'
_SOURCE_HOLD_STALE = 'hold_stale'
_SOURCE_HOLD_NO_SIGNAL = 'hold_no_signal'


class PoolController:
    """Decision engine for ONE pool. Pure in time: every entry point
    takes ``now``, so the contract unit-tests on a synthetic clock."""

    def __init__(self, spec: spec_lib.ElasticSpec):
        spec.validate()
        self.spec = spec
        initial = (spec.initial_units if spec.initial_units is not None
                   else spec.min_units)
        self.target = self._clamp(initial)
        # (proposed_target, since_when, confirming_rounds) while a
        # change is pending adoption; None otherwise.
        self.pending: Optional[Tuple[int, float, int]] = None
        self.last_change_ts: Optional[float] = None
        self.last_action = spec_lib.ElasticAction.HOLD
        # Journal source transitions, not every round: a 1s cadence
        # journaling 'hold' forever is DB bloat, but entering/leaving
        # a fallback is exactly what an operator replays.
        self._journaled_source = _SOURCE_SIGNAL

    # ------------------------------------------------------------ raw

    def _clamp(self, want: int) -> int:
        lo = self.spec.min_units
        hi = self.spec.max_units if self.spec.max_units is not None else max(
            lo, want)
        return max(lo, min(hi, want))

    def _reduce(self, value: float) -> int:
        s = self.spec
        if s.target_per_unit is not None:
            return self._clamp(math.ceil(value / s.target_per_unit))
        if s.band is not None:
            lo, hi = s.band
            up, down = self.target + s.step, self.target - s.step
            if s.invert:
                up, down = down, up
            if value > hi:
                return self._clamp(up)
            if value < lo:
                return self._clamp(down)
            return self.target
        # No target shape declared: the signal is informational only.
        return self.target

    def _fallback(self, now: float, reason: str) -> Tuple[int, str]:
        if self.spec.on_fallback is not None:
            self.spec.on_fallback(reason)
        if self.spec.fallback is not None:
            raw = self.spec.fallback(now)
            if raw is not None:
                return self._clamp(int(raw)), 'fallback_' + reason
        return self.target, 'hold_' + reason

    def compute_raw(self, now: float) -> Tuple[int, str]:
        """(raw target, signal source) for this instant — no decision
        state is advanced (safe to probe from tests/CLI)."""
        reading = self.spec.signal(now)
        if reading is None:
            return self._fallback(now, 'no_signal')
        if (self.spec.stale_after is not None and
                now - reading.ts > self.spec.stale_after):
            return self._fallback(now, 'stale')
        return self._reduce(reading.value), _SOURCE_SIGNAL

    # ------------------------------------------------------- decision

    def decide(self, now: float, raw: int,
               source: str = _SOURCE_SIGNAL) -> int:
        """Run one hysteresis round against a raw target and return
        the (possibly updated) adopted target."""
        action = spec_lib.ElasticAction.HOLD
        reason = 'steady'
        if raw == self.target:
            self.pending = None
        elif self.pending is None or self.pending[0] != raw:
            self.pending = (raw, now, 0)
            reason = 'pending'
        else:
            confirmed = self.pending[2] + 1
            self.pending = (self.pending[0], self.pending[1], confirmed)
            up = raw > self.target
            delay = (self.spec.upscale_delay_seconds if up
                     else self.spec.downscale_delay_seconds)
            held = now - self.pending[1]
            if held < delay:
                reason = 'pending'
            elif not up and confirmed < self.spec.clean_rounds:
                # slo.py's de-escalation idiom: growing is urgent,
                # shrinking waits for consecutive clean confirmation.
                reason = 'clean_rounds'
            elif (self.spec.cooldown_seconds > 0 and
                  self.last_change_ts is not None and
                  now - self.last_change_ts <
                  self.spec.cooldown_seconds):
                reason = 'cooldown'
            else:
                action = (spec_lib.ElasticAction.SCALE_UP if up
                          else spec_lib.ElasticAction.SCALE_DOWN)
                if self._adopt(now, raw, held, action):
                    reason = source
                else:
                    action = spec_lib.ElasticAction.HOLD
                    reason = 'refused_edge'
        self._publish(now, action, raw, reason, source)
        return self.target

    def _adopt(self, now: float, raw: int, held: float,
               action: spec_lib.ElasticAction) -> bool:
        if not state_machines.can_transition(
                state_machines.ELASTIC_ACTION_TRANSITIONS,
                self.last_action.name, action.name):
            # Fail closed like the guarded setters: an illegal edge
            # (scale-to-scale without a hold round) is a controller
            # bug; refusing it keeps the pool where it is.
            logger.error(
                f'elastic[{self.spec.pool}]: refusing illegal decision '
                f'edge {self.last_action.name} -> {action.name}')
            return False
        old = self.target
        logger.info(f'elastic[{self.spec.pool}]: {old} -> {raw} units '
                    f'(held {held:.0f}s).')
        self.target = raw
        self.pending = None
        self.last_change_ts = now
        data = {'pool': self.spec.pool, 'old': old, 'new': raw,
                'held_seconds': round(held, 3)}
        if self.spec.cost_delta is not None:
            # Projected dollar consequence of this decision (the cost
            # meter's projection — see ElasticSpec.cost_delta). A
            # failed projection annotates nothing; it never blocks the
            # decision itself.
            try:
                delta = self.spec.cost_delta(old, raw)
            except Exception:  # pylint: disable=broad-except
                logger.warning(
                    f'elastic[{self.spec.pool}]: cost projection '
                    f'failed:', exc_info=True)
                delta = None
            if delta is not None:
                data['usd_per_hour_delta'] = round(delta, 6)
        journal.record_event(
            'elastic_decision', entity=f'elastic/{self.spec.pool}',
            reason=action.value, data=data)
        hook = (self.spec.scale_up
                if action is spec_lib.ElasticAction.SCALE_UP
                else self.spec.scale_down)
        if hook is not None:
            try:
                hook(raw)
            except Exception:  # pylint: disable=broad-except
                # A hook failure must not kill the loop — the target
                # stands, the next reconcile retries convergence.
                logger.warning(
                    f'elastic[{self.spec.pool}]: scale hook failed:',
                    exc_info=True)
        return True

    def _publish(self, now: float, action: spec_lib.ElasticAction,
                 raw: int, reason: str, source: str) -> None:
        del now  # uniform signature; journal stamps its own clock.
        self.last_action = action
        _TARGET_GAUGE.set(float(self.target), pool=self.spec.pool)
        _DECISIONS_TOTAL.inc(pool=self.spec.pool, action=action.value)
        if source != self._journaled_source:
            # Entering/leaving a fallback or no-signal hold is the
            # safety contract in action — journal the edge once, keyed
            # by the SOURCE (the decide-level reason rides in data).
            journal.record_event(
                'elastic_decision',
                entity=f'elastic/{self.spec.pool}', reason=source,
                data={'pool': self.spec.pool, 'target': self.target,
                      'raw': raw, 'reason': reason, 'source': source,
                      'was': self._journaled_source})
            self._journaled_source = source

    def evaluate(self, now: Optional[float] = None) -> int:
        """One full round: reduce the signal, run hysteresis, publish."""
        now = vclock.now() if now is None else now
        raw, source = self.compute_raw(now)
        return self.decide(now, raw, source)


class ElasticController:
    """Hosts every registered pool behind ONE loop.

    ``run_once()`` is the caller-driven cadence (the loadgen harness's
    settle, a scrape-round callback, tests); ``start()`` spawns the
    periodic daemon thread for deployments where no existing loop owns
    the cadence. One round failure is contained per pool — fleet
    scaling must never die of one pool's bad reduction.
    """

    def __init__(self, interval: Optional[float] = None):
        self.interval = (knobs.get_float('SKYTPU_ELASTIC_INTERVAL')
                         if interval is None else interval)
        self._pools: Dict[str, PoolController] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, spec: spec_lib.ElasticSpec) -> PoolController:
        if spec.pool in self._pools:
            raise ValueError(
                f'elastic pool {spec.pool!r} is already registered')
        ctl = PoolController(spec)
        self._pools[spec.pool] = ctl
        return ctl

    def pool(self, name: str) -> PoolController:
        return self._pools[name]

    def pools(self) -> List[str]:
        return sorted(self._pools)

    def targets(self) -> Dict[str, int]:
        return {name: ctl.target
                for name, ctl in self._pools.items()}

    def run_once(self, now: Optional[float] = None) -> Dict[str, int]:
        now = vclock.now() if now is None else now
        out: Dict[str, int] = {}
        for name, ctl in sorted(self._pools.items()):
            try:
                out[name] = ctl.evaluate(now)
            except Exception:  # pylint: disable=broad-except
                logger.warning(
                    f'elastic[{name}]: evaluation round failed:',
                    exc_info=True)
                out[name] = ctl.target
        return out

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name='elastic-controller')
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            self.run_once()
            self._stop.wait(self.interval)
