"""ElasticSpec: the declarative contract a pool signs to be scaled.

Every independently scalable pool in the repo (serve monolith
replicas, disagg prefill, disagg decode, data-service workers, the
rollout fleet) registers ONE of these with the elastic controller
(controller.py) instead of hand-wiring its own scaling loop. The spec
declares:

  * a **signal** — a callable reducing the fleet telemetry plane
    (observe/scrape.py families, an autoscaler's QPS window, a
    dispatcher's result-buffer stats) to one fresh ``Reading``;
  * a **target** — either proportional (``target_per_unit``: raw
    target = ceil(value / target_per_unit), the serve QPS/queue-depth
    shape) or a **band** (hold while lo <= value <= hi, step the pool
    by ``step`` outside it — for signals like batch-wait share that
    do not map linearly onto a unit count);
  * **bounds** (min/max units), **hysteresis** (a proposed change must
    hold for the up/downscale delay), **flap resistance** (a
    scale-down additionally needs ``clean_rounds`` consecutive
    confirming rounds, the observe/slo.py de-escalation idiom) and a
    **cooldown** between applied changes;
  * the **safety contract** — no signal ever → hold; stale signal →
    the DECLARED ``fallback`` reducer (serve: the QPS window) or hold
    when none is declared. Never invent a target from missing data;
  * **hooks** — ``scale_up`` / ``scale_down`` callables the controller
    invokes with the adopted target (serve: reconcile picks the target
    up itself; data service: spawn/drain a worker; rollout: resize the
    fleet before minting leases the staleness window would drop).

The decision function stays pure — (signal, now) → target — so every
pool's scaling logic unit-tests with synthetic clocks, no clusters.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Optional, Tuple


class ElasticAction(enum.Enum):
    """One controller decision per evaluation round.

    Declared in analysis/state_machines.py (ELASTIC_ACTION_TRANSITIONS)
    so the enum-coverage lint forces new actions to be wired: between
    any two applied scale actions there is always at least one HOLD
    round (the pending/hysteresis arm), so SCALE_UP -> SCALE_DOWN is
    an illegal edge — thrash without an intervening hold is a bug.
    """
    SCALE_UP = 'scale_up'
    SCALE_DOWN = 'scale_down'
    HOLD = 'hold'


# Closed metric-label vocabulary: one name per scalable pool. Label
# sets must stay declared and finite (the breaker-state precedent), so
# a new pool means a new entry HERE, not an unbounded label.
POOLS: Tuple[str, ...] = (
    'serve',          # monolith replica fleet (QPS / engine queue depth)
    'prefill',        # disagg prefill pool (per-role queue depth)
    'decode',         # disagg decode pool (per-role queue depth)
    'data_workers',   # data-service CPU workers (batch-wait burn)
    'rollout',        # spot rollout fleet (result-buffer backpressure)
)


@dataclasses.dataclass(frozen=True)
class Reading:
    """One reduced signal observation: ``value`` as of ``ts``.

    ``ts`` is the observation time of the UNDERLYING data (a scrape
    round's success stamp, a saturation snapshot's freshness stamp),
    not the reduction time — staleness is judged against it.
    """
    value: float
    ts: float


@dataclasses.dataclass
class ElasticSpec:
    """Everything the controller needs to scale one pool. See module
    docstring for field semantics."""
    pool: str
    # now -> freshest Reading, or None when no signal was EVER
    # observed (an empty scrape, a never-started scraper).
    signal: Callable[[float], Optional[Reading]]
    # Exactly one target shape: proportional or band.
    target_per_unit: Optional[float] = None
    band: Optional[Tuple[float, float]] = None
    step: int = 1
    # High signal normally means GROW (queue building → add units);
    # invert for pools where high signal means the CONSUMER is behind
    # (rollout: a full result buffer → shrink the producer fleet).
    invert: bool = False
    min_units: int = 1
    max_units: Optional[int] = None
    initial_units: Optional[int] = None
    upscale_delay_seconds: float = 0.0
    downscale_delay_seconds: float = 0.0
    cooldown_seconds: float = 0.0
    clean_rounds: int = 1
    # A Reading older than this is STALE → fallback path. None = the
    # signal never goes stale (e.g. serve QPS, computed on demand).
    stale_after: Optional[float] = None
    # Declared stale/no-signal fallback reducer: now -> raw target
    # (None = hold). Serve declares its QPS window here.
    fallback: Optional[Callable[[float], Optional[int]]] = None
    # Observability bridge for pool-local fallback accounting (serve
    # keeps its skytpu_serve_autoscaler_fallback_total contract alive
    # through this) — called with 'stale' or 'no_signal'.
    on_fallback: Optional[Callable[[str], None]] = None
    scale_up: Optional[Callable[[int], None]] = None
    scale_down: Optional[Callable[[int], None]] = None
    # Cost projection bridge (observe/costs.py CostMeter.projector):
    # (old_units, new_units) -> projected $/hour delta, or None when
    # nothing is priced yet. The controller stamps the result onto
    # every elastic_decision journal event so each scale decision
    # carries its dollar consequence; the price math itself stays in
    # the cost meter.
    cost_delta: Optional[Callable[[int, int], Optional[float]]] = None

    def validate(self) -> None:
        if self.pool not in POOLS:
            raise ValueError(
                f'unknown elastic pool {self.pool!r}: the metric label '
                f'set is closed — declare it in elastic/spec.py POOLS '
                f'(known: {", ".join(POOLS)})')
        if self.target_per_unit is not None and self.band is not None:
            raise ValueError(
                f'pool {self.pool!r} declares BOTH target_per_unit and '
                f'band — pick one target shape')
        if self.band is not None and self.band[0] > self.band[1]:
            raise ValueError(
                f'pool {self.pool!r} band low {self.band[0]} > high '
                f'{self.band[1]}')
        if self.min_units < 0:
            raise ValueError(
                f'pool {self.pool!r} min_units {self.min_units} < 0')
        if (self.max_units is not None and
                self.max_units < self.min_units):
            raise ValueError(
                f'pool {self.pool!r} max_units {self.max_units} < '
                f'min_units {self.min_units}')
        if self.step < 1:
            raise ValueError(
                f'pool {self.pool!r} band step {self.step} < 1')
        if self.clean_rounds < 1:
            raise ValueError(
                f'pool {self.pool!r} clean_rounds {self.clean_rounds} '
                f'< 1 (the confirming round itself counts)')
