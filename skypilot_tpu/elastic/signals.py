"""Signal reducers: fleet telemetry (PR-9 scrape plane) → Readings.

An ElasticSpec's ``signal`` is just a callable; these helpers build
the common ones from a ``Scraper`` so pools declare "metric name +
reducer" instead of re-implementing exposition plumbing. Freshness is
taken from the scraper's own per-target success stamps, so a dead
scrape plane surfaces as a STALE/absent Reading — which the controller
turns into the declared fallback or a hold, never a guess.
"""
from __future__ import annotations

from typing import Callable, Optional

from skypilot_tpu.elastic import spec as spec_lib
from skypilot_tpu.observe import promtext
from skypilot_tpu.observe import scrape as scrape_lib

SignalFn = Callable[[float], Optional[spec_lib.Reading]]


def _fresh_ts(scraper: 'scrape_lib.Scraper', now: float
              ) -> Optional[float]:
    """Timestamp of the freshest NON-stale target, or None when the
    whole plane is stale/empty (→ no signal)."""
    ages = [doc['last_success_age'] for doc in scraper.status()
            if doc.get('last_success_age') is not None and
            not doc.get('stale')]
    if not ages:
        return None
    return now - min(ages)


def scraped_sum(scraper: 'scrape_lib.Scraper', family: str) -> SignalFn:
    """Sum of one counter/gauge family over the fresh fleet (merged by
    ``fleet_families()`` — counters/gauges sum across replicas)."""

    def signal(now: float) -> Optional[spec_lib.Reading]:
        ts = _fresh_ts(scraper, now)
        if ts is None:
            return None
        fam = scraper.fleet_families().get(family)
        if fam is None:
            return None
        value = float(sum(s.value for s in fam.samples))
        return spec_lib.Reading(value=value, ts=ts)

    return signal


def scraped_burn(scraper: 'scrape_lib.Scraper', family: str) -> SignalFn:
    """Burn rate of a histogram's ``_sum`` (or a counter) over the
    fresh fleet: d(total)/dt between evaluations. For
    ``skytpu_train_batch_wait_seconds`` this is seconds blocked per
    wall-clock second — the batch-wait share driving the data-worker
    pool. The first evaluation (no baseline yet) reports no signal, so
    the controller HOLDS instead of reacting to an all-time total."""
    state = {'total': None, 'ts': None}

    def signal(now: float) -> Optional[spec_lib.Reading]:
        ts = _fresh_ts(scraper, now)
        if ts is None:
            return None
        fam = scraper.fleet_families().get(family)
        total = _hist_sum(fam)
        if total is None:
            total = (float(sum(s.value for s in fam.samples))
                     if fam is not None else None)
        if total is None:
            return None
        prev_total, prev_ts = state['total'], state['ts']
        state['total'], state['ts'] = total, ts
        if prev_total is None or ts <= prev_ts:
            return None
        burn = max(0.0, total - prev_total) / (ts - prev_ts)
        return spec_lib.Reading(value=burn, ts=ts)

    return signal


def _hist_sum(fam: Optional[promtext.Family]) -> Optional[float]:
    if fam is None:
        return None
    total = None
    for sample in fam.samples:
        if sample.name.endswith('_sum'):
            total = (total or 0.0) + sample.value
    return total


def callback(fn: Callable[[], Optional[float]]) -> SignalFn:
    """Wrap an always-fresh in-process probe (a dispatcher's own
    result-buffer stats, an autoscaler's QPS window) — the Reading is
    stamped with the evaluation instant, so it never goes stale; the
    probe returning None means no signal."""

    def signal(now: float) -> Optional[spec_lib.Reading]:
        value = fn()
        if value is None:
            return None
        return spec_lib.Reading(value=float(value), ts=now)

    return signal
