"""skypilot_tpu.elastic — one closed-loop controller for every pool.

Declarative elastic scaling (docs/ELASTIC.md). The repo runs five
independently scalable pools — serve monolith replicas, disagg
prefill, disagg decode, data-service CPU workers, the spot rollout
fleet — and before this package each closed (or failed to close) its
own loop. Now a pool registers ONE :class:`~spec.ElasticSpec` and the
controller does the rest:

  * :mod:`spec`       — the declarative contract: signal, target shape
    (proportional ``target_per_unit`` or a hold band), min/max bounds,
    up/downscale delays, clean-rounds flap resistance, cooldown,
    declared stale fallback, scale hooks; plus the ``ElasticAction``
    decision enum (transitions declared in analysis/state_machines.py)
    and the CLOSED ``POOLS`` metric-label vocabulary;
  * :mod:`controller` — the decision engine (``PoolController``) and
    the multi-pool host loop (``ElasticController``): one hysteresis
    core for every pool, every decision journaled
    (``elastic_decision``) and published
    (``skytpu_elastic_target{pool}``,
    ``skytpu_elastic_decisions_total{pool,action}``), and the PR-9
    safety contract enforced uniformly — no signal → hold, stale
    signal → the DECLARED fallback, never a guess;
  * :mod:`signals`    — reducers from the fleet telemetry plane
    (observe/scrape.py): fleet sums, histogram shares (batch-wait
    burn), and in-process probe wrappers.

serve/autoscalers.py, the per-role disagg autoscalers, the
data-service worker pool (data_service/elastic.py) and the rollout
fleet (train/rollout/elastic.py) all scale through this package.
"""
