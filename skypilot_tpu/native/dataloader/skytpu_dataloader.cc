// Native tokenized-corpus batch extractor.
//
// Reference context: the reference (SkyPilot) has no native data path — it
// delegates input pipelines to HF datasets inside recipes (SURVEY §2.11).
// This framework owns the trainer, and on TPU the host input pipeline must
// keep a >400 GB/s chip fed from one VM; the Python/numpy fancy-index path
// tops out well below a memcpy. This library does the hot part natively:
//
//   - mmap the pre-tokenized corpus (uint16/uint32 .bin) once, O_RDONLY
//   - batch_at_step: gather B rows of S+1 tokens with dtype widening to
//     int32, parallelized across rows with a thread team
//   - prefetch: madvise(WILLNEED) the next step's pages so the gather
//     never faults on cold file pages
//
// Semantics are EXACTLY skypilot_tpu/data/loader.py::batch_at_step —
// batch k is a pure function of (corpus, k) — so checkpoint/resume gets
// the same token stream from either implementation (asserted in
// tests/unit_tests/test_native.py).
//
// C ABI (ctypes-consumed; no pybind11 in this image):
//   dl_open(path, elem_size) -> handle | NULL
//   dl_num_tokens(h) -> int64
//   dl_batch_at_step(h, step, batch, seq, out_int32) -> 0 | errno
//   dl_prefetch(h, step, batch, seq) -> 0
//   dl_close(h)

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct TokenFile {
  void* base = nullptr;
  int64_t bytes = 0;
  int elem_size = 2;  // 2 = uint16, 4 = uint32/int32
  int64_t n_tokens() const { return bytes / elem_size; }
};

// Row-start rule shared with the Python indexer: rows stride through the
// corpus with wraparound, consecutive steps read consecutive windows.
inline int64_t row_start(int64_t usable, int64_t step, int64_t seq,
                         int64_t batch, int64_t row) {
  // (row * usable / batch + step * seq) % usable, in int64 (usable and
  // step*seq both fit: corpora are < 2^47 tokens).
  int64_t s = (row * usable) / batch + step * seq;
  s %= usable;
  return s < 0 ? s + usable : s;
}

void copy_rows(const TokenFile* tf, int64_t step, int64_t batch, int64_t seq,
               int32_t* out, int64_t row_begin, int64_t row_end) {
  const int64_t need = seq + 1;
  const int64_t usable = tf->n_tokens() - need;
  for (int64_t i = row_begin; i < row_end; ++i) {
    const int64_t s = row_start(usable, step, seq, batch, i);
    int32_t* dst = out + i * need;
    if (tf->elem_size == 2) {
      const uint16_t* src = static_cast<const uint16_t*>(tf->base) + s;
      for (int64_t j = 0; j < need; ++j) dst[j] = src[j];
    } else {
      const int32_t* src = static_cast<const int32_t*>(tf->base) + s;
      std::memcpy(dst, src, need * sizeof(int32_t));
    }
  }
}

}  // namespace

extern "C" {

void* dl_open(const char* path, int elem_size) {
  if (elem_size != 2 && elem_size != 4) return nullptr;
  int fd = ::open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < elem_size) {
    ::close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  ::close(fd);  // mapping keeps its own reference
  if (base == MAP_FAILED) return nullptr;
  // Rows gather from scattered offsets: random beats readahead here.
  madvise(base, st.st_size, MADV_RANDOM);
  auto* tf = new TokenFile();
  tf->base = base;
  tf->bytes = st.st_size;
  tf->elem_size = elem_size;
  return tf;
}

int64_t dl_num_tokens(void* h) {
  return h ? static_cast<TokenFile*>(h)->n_tokens() : 0;
}

int dl_batch_at_step(void* h, int64_t step, int64_t batch, int64_t seq,
                     int32_t* out) {
  auto* tf = static_cast<TokenFile*>(h);
  if (tf == nullptr || batch <= 0 || seq <= 0) return EINVAL;
  const int64_t need = seq + 1;
  // Same minimum as the Python indexer (loader.py raises when
  // n < need + 1): usable = n - need must be >= 1.
  if (tf->n_tokens() < need + 1) return ERANGE;
  // Thread team sized to the work: one thread per ~1 MiB of output, capped
  // at hardware concurrency. Small batches stay single-threaded (spawn
  // cost dominates).
  const int64_t total_bytes = batch * need * 4;
  int n_threads = static_cast<int>(total_bytes / (1 << 20));
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads > hw) n_threads = hw;
  if (n_threads <= 1 || batch == 1) {
    copy_rows(tf, step, batch, seq, out, 0, batch);
    return 0;
  }
  if (n_threads > batch) n_threads = static_cast<int>(batch);
  std::vector<std::thread> team;
  team.reserve(n_threads);
  const int64_t per = (batch + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    const int64_t lo = t * per;
    const int64_t hi = std::min<int64_t>(lo + per, batch);
    if (lo >= hi) break;
    team.emplace_back(copy_rows, tf, step, batch, seq, out, lo, hi);
  }
  for (auto& th : team) th.join();
  return 0;
}

int32_t dl_max_token(void* h) {
  // Full-corpus max, threaded — backs the trainer's vocab-bounds check
  // without materializing the corpus in Python.
  auto* tf = static_cast<TokenFile*>(h);
  if (tf == nullptr || tf->n_tokens() == 0) return -1;
  const int64_t n = tf->n_tokens();
  int n_threads = static_cast<int>(std::thread::hardware_concurrency());
  if (n_threads < 1) n_threads = 1;
  if (n > 0 && n < (1 << 20)) n_threads = 1;
  std::vector<int32_t> maxima(n_threads, 0);
  auto scan = [tf, n, n_threads](int t, int32_t* out) {
    const int64_t per = (n + n_threads - 1) / n_threads;
    const int64_t lo = t * per;
    const int64_t hi = std::min<int64_t>(lo + per, n);
    int32_t m = 0;
    if (tf->elem_size == 2) {
      const uint16_t* p = static_cast<const uint16_t*>(tf->base);
      for (int64_t i = lo; i < hi; ++i) m = std::max<int32_t>(m, p[i]);
    } else {
      const int32_t* p = static_cast<const int32_t*>(tf->base);
      for (int64_t i = lo; i < hi; ++i) m = std::max(m, p[i]);
    }
    *out = m;
  };
  if (n_threads == 1) {
    scan(0, &maxima[0]);
  } else {
    std::vector<std::thread> team;
    team.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) team.emplace_back(scan, t, &maxima[t]);
    for (auto& th : team) th.join();
  }
  return *std::max_element(maxima.begin(), maxima.end());
}

int dl_prefetch(void* h, int64_t step, int64_t batch, int64_t seq) {
  auto* tf = static_cast<TokenFile*>(h);
  if (tf == nullptr) return EINVAL;
  const int64_t need = seq + 1;
  const int64_t usable = tf->n_tokens() - need;
  if (usable <= 0) return ERANGE;
  const long page = sysconf(_SC_PAGESIZE);
  char* base = static_cast<char*>(tf->base);
  for (int64_t i = 0; i < batch; ++i) {
    const int64_t s = row_start(usable, step, seq, batch, i);
    char* lo = base + s * tf->elem_size;
    char* aligned = reinterpret_cast<char*>(
        reinterpret_cast<uintptr_t>(lo) & ~(page - 1));
    size_t len = (lo - aligned) + need * tf->elem_size;
    madvise(aligned, len, MADV_WILLNEED);
  }
  return 0;
}

void dl_close(void* h) {
  auto* tf = static_cast<TokenFile*>(h);
  if (tf == nullptr) return;
  munmap(tf->base, tf->bytes);
  delete tf;
}

}  // extern "C"
