"""On-demand builder for the native (C++) components.

The reference ships its only native piece (the Go fuse-proxy) as a
prebuilt container image; this repo compiles from source on first use —
the toolchain (g++) is part of the TPU VM runtime image — and caches the
artifacts next to the sources in `native/bin/`. Every entry point degrades
gracefully: callers get None when no compiler is available and fall back
to pure-Python paths (loader) or report the feature unsupported
(fuse-proxy on k8s).
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_BIN_DIR = os.path.join(_NATIVE_DIR, 'bin')

_COMMON_FLAGS = ['-O2', '-std=c++17', '-pthread', '-Wall']

# target name -> (sources, extra flags)
TARGETS: Dict[str, Tuple[List[str], List[str]]] = {
    'skytpu_dataloader.so': (['dataloader/skytpu_dataloader.cc'],
                             ['-shared', '-fPIC']),
    'fusermount-shim': (['fuse_proxy/fusermount_shim.cc'], []),
    'fuse-proxy-server': (['fuse_proxy/fuse_proxy_server.cc'], []),
}

_HEADERS = ['fuse_proxy/proxy_proto.h']


def _out_of_date(out: str, sources: List[str]) -> bool:
    if not os.path.exists(out):
        return True
    out_mtime = os.path.getmtime(out)
    deps = sources + _HEADERS
    return any(
        os.path.exists(os.path.join(_NATIVE_DIR, s)) and
        os.path.getmtime(os.path.join(_NATIVE_DIR, s)) > out_mtime
        for s in deps)


def build_target(name: str) -> Optional[str]:
    """Compile (if stale) and return the artifact path, or None."""
    if name not in TARGETS:
        raise ValueError(f'Unknown native target {name!r}; '
                         f'valid: {sorted(TARGETS)}')
    sources, extra = TARGETS[name]
    out = os.path.join(_BIN_DIR, name)
    if not _out_of_date(out, sources):
        return out
    gxx = shutil.which('g++') or shutil.which('c++')
    if gxx is None:
        logger.debug(f'No C++ compiler; native target {name} unavailable.')
        return None
    os.makedirs(_BIN_DIR, exist_ok=True)
    srcs = [os.path.join(_NATIVE_DIR, s) for s in sources]
    cmd = [gxx, *_COMMON_FLAGS, *extra, '-I', _NATIVE_DIR, *srcs, '-o', out]
    proc = subprocess.run(cmd, capture_output=True, text=True, check=False)
    if proc.returncode != 0:
        logger.warning(f'Native build of {name} failed:\n{proc.stderr}')
        return None
    logger.info(f'Built native target {name}.')
    return out
