// fusermount shim: the binary installed AS `fusermount3` in unprivileged
// pods. libfuse execs it with _FUSE_COMMFD pointing at a socketpair and
// expects the opened /dev/fuse fd back over it. The shim does no mounting
// itself — it forwards (cwd, argv tail) to the privileged proxy server and
// relays the fd the server sends back to libfuse, so unmodified gcsfuse
// binaries work in pods without CAP_SYS_ADMIN.
//
// Reference analog: addons/fuse-proxy fusermount-shim (Go); see
// proxy_proto.h for the contract.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>
#include <vector>

#include "proxy_proto.h"

namespace {

int connect_proxy() {
  int sock = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (sock < 0) return -1;
  struct sockaddr_un addr = {};
  addr.sun_family = AF_UNIX;
  const char* path = fuseproxy::socket_path();
  if (std::strlen(path) >= sizeof(addr.sun_path)) {
    close(sock);
    return -1;
  }
  std::strcpy(addr.sun_path, path);
  if (connect(sock, reinterpret_cast<struct sockaddr*>(&addr),
              sizeof(addr)) != 0) {
    close(sock);
    return -1;
  }
  return sock;
}

// libfuse's fd-passing convention: one message, one data byte, the fd in
// SCM_RIGHTS.
bool send_fd_to_commfd(int commfd, int fd) {
  char byte = '\0';
  struct iovec iov = {&byte, 1};
  struct msghdr msg = {};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  alignas(struct cmsghdr) char cbuf[CMSG_SPACE(sizeof(int))] = {};
  msg.msg_control = cbuf;
  msg.msg_controllen = sizeof(cbuf);
  struct cmsghdr* cm = CMSG_FIRSTHDR(&msg);
  cm->cmsg_level = SOL_SOCKET;
  cm->cmsg_type = SCM_RIGHTS;
  cm->cmsg_len = CMSG_LEN(sizeof(int));
  std::memcpy(CMSG_DATA(cm), &fd, sizeof(int));
  ssize_t w;
  do {
    w = sendmsg(commfd, &msg, 0);
  } while (w < 0 && errno == EINTR);
  return w == 1;
}

}  // namespace

int main(int argc, char** argv) {
  char cwd[4096];
  if (getcwd(cwd, sizeof(cwd)) == nullptr) {
    perror("fusermount-shim: getcwd");
    return 1;
  }
  std::vector<std::string> req;
  req.emplace_back(cwd);
  for (int i = 1; i < argc; ++i) req.emplace_back(argv[i]);

  int sock = connect_proxy();
  if (sock < 0) {
    fprintf(stderr, "fusermount-shim: cannot reach proxy at %s: %s\n",
            fuseproxy::socket_path(), strerror(errno));
    return 1;
  }
  if (!fuseproxy::send_request(sock, req)) {
    fprintf(stderr, "fusermount-shim: request send failed\n");
    close(sock);
    return 1;
  }
  uint32_t exit_code = 1;
  int fuse_fd = -1;
  std::string err_text;
  if (!fuseproxy::recv_response(sock, &exit_code, &fuse_fd, &err_text)) {
    fprintf(stderr, "fusermount-shim: bad response from proxy\n");
    close(sock);
    return 1;
  }
  close(sock);
  if (!err_text.empty()) fputs(err_text.c_str(), stderr);

  if (fuse_fd >= 0) {
    const char* commfd_env = getenv("_FUSE_COMMFD");
    if (commfd_env == nullptr) {
      fprintf(stderr,
              "fusermount-shim: got a fuse fd but _FUSE_COMMFD unset\n");
      close(fuse_fd);
      return 1;
    }
    int commfd = atoi(commfd_env);
    if (!send_fd_to_commfd(commfd, fuse_fd)) {
      fprintf(stderr, "fusermount-shim: fd relay to _FUSE_COMMFD failed\n");
      close(fuse_fd);
      return 1;
    }
    close(fuse_fd);
  }
  return static_cast<int>(exit_code);
}
