// Wire protocol shared by the fusermount shim and the proxy server.
//
// Reference analog: addons/fuse-proxy (Go, 712 LoC) — a rootless-FUSE
// helper for k8s: unprivileged pods can't mount, so a shim binary that
// LOOKS like fusermount3 forwards the call over a unix socket to a
// privileged DaemonSet server, which performs the real mount and passes
// the opened /dev/fuse fd back via SCM_RIGHTS. This is the C++ build of
// the same contract (the reference's README documents the behavior; the
// implementation here is original).
//
// Framing (all integers little-endian u32):
//   request:  MAGIC, nstrings, nstrings x { len, bytes }
//             strings[0] = client cwd (mountpoint paths are cwd-relative)
//             strings[1..] = fusermount argv tail
//   response: MAGIC, exit_code, has_fd, stderr_len, stderr bytes
//             when has_fd == 1 the /dev/fuse fd rides the SAME sendmsg as
//             the header via SCM_RIGHTS (one message, no ordering races).
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>
#include <vector>

namespace fuseproxy {

constexpr uint32_t kMagic = 0x53544655;  // "UFTS"
constexpr uint32_t kMaxStrings = 64;
constexpr uint32_t kMaxStringLen = 64 * 1024;

inline const char* socket_path() {
  const char* p = getenv("SKYTPU_FUSE_PROXY_SOCKET");
  return p && *p ? p : "/run/skytpu-fuse-proxy/proxy.sock";
}

inline bool write_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

inline bool read_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

inline bool send_request(int fd, const std::vector<std::string>& strings) {
  uint32_t header[2] = {kMagic, static_cast<uint32_t>(strings.size())};
  if (!write_all(fd, header, sizeof(header))) return false;
  for (const auto& s : strings) {
    uint32_t len = static_cast<uint32_t>(s.size());
    if (!write_all(fd, &len, 4) || !write_all(fd, s.data(), len))
      return false;
  }
  return true;
}

inline bool recv_request(int fd, std::vector<std::string>* strings) {
  uint32_t header[2];
  if (!read_all(fd, header, sizeof(header)) || header[0] != kMagic ||
      header[1] > kMaxStrings)
    return false;
  strings->clear();
  for (uint32_t i = 0; i < header[1]; ++i) {
    uint32_t len;
    if (!read_all(fd, &len, 4) || len > kMaxStringLen) return false;
    std::string s(len, '\0');
    if (len > 0 && !read_all(fd, &s[0], len)) return false;
    strings->push_back(std::move(s));
  }
  return true;
}

// Response header + optional fd in ONE sendmsg (SCM_RIGHTS must accompany
// data bytes; coupling it to the header removes any ordering question).
inline bool send_response(int sock, uint32_t exit_code, int fuse_fd,
                          const std::string& err_text) {
  uint32_t header[4] = {kMagic, exit_code,
                        fuse_fd >= 0 ? 1u : 0u,
                        static_cast<uint32_t>(err_text.size())};
  struct iovec iov = {header, sizeof(header)};
  struct msghdr msg = {};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  alignas(struct cmsghdr) char cbuf[CMSG_SPACE(sizeof(int))] = {};
  if (fuse_fd >= 0) {
    msg.msg_control = cbuf;
    msg.msg_controllen = sizeof(cbuf);
    struct cmsghdr* cm = CMSG_FIRSTHDR(&msg);
    cm->cmsg_level = SOL_SOCKET;
    cm->cmsg_type = SCM_RIGHTS;
    cm->cmsg_len = CMSG_LEN(sizeof(int));
    std::memcpy(CMSG_DATA(cm), &fuse_fd, sizeof(int));
  }
  ssize_t w;
  do {
    w = sendmsg(sock, &msg, 0);
  } while (w < 0 && errno == EINTR);
  if (w != sizeof(header)) return false;
  return err_text.empty() ||
         write_all(sock, err_text.data(), err_text.size());
}

inline bool recv_response(int sock, uint32_t* exit_code, int* fuse_fd,
                          std::string* err_text) {
  uint32_t header[4];
  struct iovec iov = {header, sizeof(header)};
  struct msghdr msg = {};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  alignas(struct cmsghdr) char cbuf[CMSG_SPACE(sizeof(int))] = {};
  msg.msg_control = cbuf;
  msg.msg_controllen = sizeof(cbuf);
  ssize_t r;
  do {
    r = recvmsg(sock, &msg, MSG_CMSG_CLOEXEC);
  } while (r < 0 && errno == EINTR);
  if (r != sizeof(header) || header[0] != kMagic) return false;
  *exit_code = header[1];
  *fuse_fd = -1;
  if (header[2] == 1) {
    for (struct cmsghdr* cm = CMSG_FIRSTHDR(&msg); cm != nullptr;
         cm = CMSG_NXTHDR(&msg, cm)) {
      if (cm->cmsg_level == SOL_SOCKET && cm->cmsg_type == SCM_RIGHTS &&
          cm->cmsg_len >= CMSG_LEN(sizeof(int))) {
        std::memcpy(fuse_fd, CMSG_DATA(cm), sizeof(int));
      }
    }
    if (*fuse_fd < 0) return false;  // promised an fd but none arrived
  }
  uint32_t err_len = header[3];
  if (err_len > kMaxStringLen) return false;
  err_text->assign(err_len, '\0');
  return err_len == 0 || read_all(sock, &(*err_text)[0], err_len);
}

}  // namespace fuseproxy
