// Privileged fuse-proxy server: performs real fusermount calls on behalf
// of unprivileged shim clients and passes the /dev/fuse fd back.
//
// Deployment: one instance per node (k8s DaemonSet with CAP_SYS_ADMIN, or
// a root process on a TPU VM), listening on a unix socket that pod/job
// containers bind-mount. Each connection is served by a forked child, so a
// wedged fusermount never blocks the accept loop.
//
// Flags / env:
//   --socket PATH   (or SKYTPU_FUSE_PROXY_SOCKET)  listen path
//   --fusermount P  (or SKYTPU_FUSE_PROXY_FUSERMOUNT) real binary,
//                   default "fusermount3" — tests point this at a fake
//   --once          serve a single connection then exit (tests)
//
// Reference analog: addons/fuse-proxy server (Go); protocol in
// proxy_proto.h.

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <poll.h>
#include <signal.h>
#include <string>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

#include "proxy_proto.h"

namespace {

const char* g_fusermount = "fusermount3";

// libfuse convention: one data byte with the fd attached. Non-blocking —
// by the time this runs the fusermount child has exited, so the fd (if
// any) is already queued in the socketpair buffer.
int recv_fd_nonblock(int commfd) {
  char byte;
  struct iovec iov = {&byte, 1};
  struct msghdr msg = {};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  alignas(struct cmsghdr) char cbuf[CMSG_SPACE(sizeof(int))] = {};
  msg.msg_control = cbuf;
  msg.msg_controllen = sizeof(cbuf);
  ssize_t r = recvmsg(commfd, &msg, MSG_DONTWAIT | MSG_CMSG_CLOEXEC);
  if (r < 0) return -1;
  for (struct cmsghdr* cm = CMSG_FIRSTHDR(&msg); cm != nullptr;
       cm = CMSG_NXTHDR(&msg, cm)) {
    if (cm->cmsg_level == SOL_SOCKET && cm->cmsg_type == SCM_RIGHTS &&
        cm->cmsg_len >= CMSG_LEN(sizeof(int))) {
      int fd;
      std::memcpy(&fd, CMSG_DATA(cm), sizeof(int));
      return fd;
    }
  }
  return -1;
}

// Run the real fusermount with the client's argv in the client's cwd.
// Returns its exit code; *fuse_fd gets the passed fd (or -1); *err_text
// gets captured stderr.
int run_fusermount(const std::vector<std::string>& req, int* fuse_fd,
                   std::string* err_text) {
  *fuse_fd = -1;
  const std::string& cwd = req[0];
  int commfd[2];
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, commfd) != 0) return 127;
  int errpipe[2];
  if (pipe(errpipe) != 0) {
    close(commfd[0]);
    close(commfd[1]);
    return 127;
  }
  pid_t pid = fork();
  if (pid < 0) return 127;
  if (pid == 0) {
    signal(SIGCHLD, SIG_DFL);
    close(commfd[0]);
    close(errpipe[0]);
    dup2(errpipe[1], 2);
    close(errpipe[1]);
    if (chdir(cwd.c_str()) != 0) {
      fprintf(stderr, "fuse-proxy: chdir(%s): %s\n", cwd.c_str(),
              strerror(errno));
      _exit(126);
    }
    char commfd_str[16];
    snprintf(commfd_str, sizeof(commfd_str), "%d", commfd[1]);
    setenv("_FUSE_COMMFD", commfd_str, 1);
    // The commfd must survive exec: clear CLOEXEC.
    std::vector<char*> argv;
    argv.push_back(const_cast<char*>(g_fusermount));
    for (size_t i = 1; i < req.size(); ++i)
      argv.push_back(const_cast<char*>(req[i].c_str()));
    argv.push_back(nullptr);
    execvp(g_fusermount, argv.data());
    fprintf(stderr, "fuse-proxy: exec %s: %s\n", g_fusermount,
            strerror(errno));
    _exit(127);
  }
  close(commfd[1]);
  close(errpipe[1]);
  // Drain stderr until the child closes it (exit), then reap.
  char buf[4096];
  ssize_t r;
  while ((r = read(errpipe[0], buf, sizeof(buf))) > 0)
    err_text->append(buf, static_cast<size_t>(r));
  close(errpipe[0]);
  int wstatus = 0;
  while (waitpid(pid, &wstatus, 0) < 0 && errno == EINTR) {
  }
  *fuse_fd = recv_fd_nonblock(commfd[0]);
  close(commfd[0]);
  if (WIFEXITED(wstatus)) return WEXITSTATUS(wstatus);
  return 128 + (WIFSIGNALED(wstatus) ? WTERMSIG(wstatus) : 0);
}

// Defense in depth on top of socket permissions: only root, the proxy's
// own uid, or uids listed in SKYTPU_FUSE_PROXY_ALLOW_UIDS (comma list)
// may drive a root fusermount.
bool uid_allowed(uid_t uid) {
  if (uid == 0 || uid == geteuid()) return true;
  const char* env = getenv("SKYTPU_FUSE_PROXY_ALLOW_UIDS");
  if (!env) return false;
  std::string s(env);
  size_t pos = 0;
  while (pos < s.size()) {
    size_t comma = s.find(',', pos);
    if (comma == std::string::npos) comma = s.size();
    std::string tok = s.substr(pos, comma - pos);
    // strtoul, not stoul: malformed tokens must read as "not allowed",
    // never throw (an uncaught exception would kill the handler child).
    if (!tok.empty()) {
      char* end = nullptr;
      unsigned long val = strtoul(tok.c_str(), &end, 10);
      if (end && *end == '\0' && val == uid) return true;
    }
    pos = comma + 1;
  }
  return false;
}

// Allowlist the client-controlled argv: running as root, fusermount skips
// its setuid safety checks, so arbitrary flags must not pass through.
// Allowed: -u/-z/-q/--, one "-o <opts>" (allow_other/allow_root gated
// behind SKYTPU_FUSE_PROXY_ALLOW_OTHER), and bare mountpoint operands.
bool argv_allowed(const std::vector<std::string>& req, std::string* why) {
  bool other_ok = getenv("SKYTPU_FUSE_PROXY_ALLOW_OTHER") != nullptr;
  for (size_t i = 1; i < req.size(); ++i) {
    const std::string& a = req[i];
    if (a == "-u" || a == "-z" || a == "-q" || a == "--") continue;
    if (a == "-o") {
      if (i + 1 >= req.size()) {
        *why = "fuse-proxy: -o without a value\n";
        return false;
      }
      const std::string& o = req[++i];
      if (!other_ok && (o.find("allow_other") != std::string::npos ||
                        o.find("allow_root") != std::string::npos)) {
        *why = "fuse-proxy: allow_other/allow_root denied (set "
               "SKYTPU_FUSE_PROXY_ALLOW_OTHER=1 on the proxy to "
               "permit)\n";
        return false;
      }
      continue;
    }
    if (!a.empty() && a[0] == '-') {
      *why = "fuse-proxy: flag not allowed: " + a + "\n";
      return false;
    }
  }
  return true;
}

void serve_one(int conn) {
  struct ucred cred = {};
  socklen_t clen = sizeof(cred);
  if (getsockopt(conn, SOL_SOCKET, SO_PEERCRED, &cred, &clen) == 0 &&
      !uid_allowed(cred.uid)) {
    fuseproxy::send_response(conn, 1, -1,
                             "fuse-proxy: peer uid not allowed\n");
    return;
  }
  std::vector<std::string> req;
  if (!fuseproxy::recv_request(conn, &req) || req.empty()) {
    fuseproxy::send_response(conn, 1, -1, "fuse-proxy: bad request\n");
    return;
  }
  std::string why;
  if (!argv_allowed(req, &why)) {
    fuseproxy::send_response(conn, 1, -1, why);
    return;
  }
  int fuse_fd = -1;
  std::string err_text;
  int code = run_fusermount(req, &fuse_fd, &err_text);
  fuseproxy::send_response(conn, static_cast<uint32_t>(code), fuse_fd,
                           err_text);
  if (fuse_fd >= 0) close(fuse_fd);
}

}  // namespace

int main(int argc, char** argv) {
  const char* sock_path = fuseproxy::socket_path();
  bool once = false;
  const char* env_fm = getenv("SKYTPU_FUSE_PROXY_FUSERMOUNT");
  if (env_fm && *env_fm) g_fusermount = env_fm;
  for (int i = 1; i < argc; ++i) {
    if (!strcmp(argv[i], "--socket") && i + 1 < argc) sock_path = argv[++i];
    else if (!strcmp(argv[i], "--fusermount") && i + 1 < argc)
      g_fusermount = argv[++i];
    else if (!strcmp(argv[i], "--once")) once = true;
    else {
      fprintf(stderr, "usage: %s [--socket PATH] [--fusermount BIN] "
                      "[--once]\n", argv[0]);
      return 2;
    }
  }

  int lsock = socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (lsock < 0) {
    perror("fuse-proxy: socket");
    return 1;
  }
  struct sockaddr_un addr = {};
  addr.sun_family = AF_UNIX;
  if (strlen(sock_path) >= sizeof(addr.sun_path)) {
    fprintf(stderr, "fuse-proxy: socket path too long\n");
    return 1;
  }
  strcpy(addr.sun_path, sock_path);
  unlink(sock_path);
  if (bind(lsock, reinterpret_cast<struct sockaddr*>(&addr),
           sizeof(addr)) != 0 ||
      listen(lsock, 16) != 0) {
    fprintf(stderr, "fuse-proxy: bind/listen %s: %s\n", sock_path,
            strerror(errno));
    return 1;
  }
  // 0660: only the proxy's user/group reach the socket (put trusted job
  // uids in the group, or list them in SKYTPU_FUSE_PROXY_ALLOW_UIDS —
  // SO_PEERCRED is checked per connection as a second layer). The
  // reference relies on the mount namespace alone; a root fusermount
  // deserves tighter defaults.
  chmod(sock_path, 0660);
  fprintf(stderr, "fuse-proxy: listening on %s (fusermount=%s)\n",
          sock_path, g_fusermount);

  for (;;) {
    int conn = accept(lsock, nullptr, nullptr);
    if (conn < 0) {
      if (errno == EINTR) continue;
      perror("fuse-proxy: accept");
      return 1;
    }
    if (once) {
      serve_one(conn);
      close(conn);
      return 0;
    }
    pid_t pid = fork();
    if (pid == 0) {
      close(lsock);
      serve_one(conn);
      close(conn);
      _exit(0);
    }
    close(conn);
    // Opportunistic reap of finished connection children.
    while (waitpid(-1, nullptr, WNOHANG) > 0) {
    }
  }
}
