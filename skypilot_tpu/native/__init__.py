"""Native (C++) components: fast data-loader core and the fuse-proxy.

See `native/build.py` for the build contract and the per-component .cc
files for design docs. Python consumers: `data/native_loader.py`
(dataloader) and `data/mounting_utils.py` (fuse-proxy shim on k8s).
"""
from skypilot_tpu.native.build import build_target  # noqa: F401
