"""Layered immutable config.

Reference analog: sky/skypilot_config.py — server config → user
~/.skytpu/config.yaml → project ./.skytpu.yaml → per-task `config:` overrides,
merged once at import and exposed via `get_nested`. A contextvar overlay
supports per-request overrides inside the async API server
(reference: sky/utils/context.py).
"""
from __future__ import annotations

import contextlib
import contextvars
import copy
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger(__name__)

USER_CONFIG_PATH = '~/.skytpu/config.yaml'
PROJECT_CONFIG_NAME = '.skytpu.yaml'
ENV_VAR_CONFIG_PATH = 'SKYTPU_CONFIG'

_global_config: Optional[Dict[str, Any]] = None
_load_lock = threading.Lock()
_override_var: contextvars.ContextVar[Optional[Dict[str, Any]]] = (
    contextvars.ContextVar('skytpu_config_override', default=None))


def _merge_dicts(base: Dict[str, Any], override: Dict[str, Any]
                 ) -> Dict[str, Any]:
    """Recursive dict merge; override wins; lists replace wholesale."""
    out = dict(base)
    for k, v in override.items():
        if (k in out and isinstance(out[k], dict) and isinstance(v, dict)):
            out[k] = _merge_dicts(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out


def _load_layers() -> Dict[str, Any]:
    layers: List[str] = []
    env_path = knobs.get_str(ENV_VAR_CONFIG_PATH)
    if env_path:
        layers.append(os.path.expanduser(env_path))
    else:
        layers.append(os.path.expanduser(USER_CONFIG_PATH))
        layers.append(os.path.join(os.getcwd(), PROJECT_CONFIG_NAME))
    merged: Dict[str, Any] = {}
    for path in layers:
        if os.path.exists(path):
            try:
                merged = _merge_dicts(merged, common_utils.read_yaml(path))
            except Exception as e:  # pylint: disable=broad-except
                logger.warning(f'Failed to load config {path}: {e}')
    return merged


def _config() -> Dict[str, Any]:
    global _global_config
    if _global_config is None:
        with _load_lock:
            if _global_config is None:
                _global_config = _load_layers()
    override = _override_var.get()
    if override:
        return _merge_dicts(_global_config, override)
    return _global_config


def reload_config() -> None:
    global _global_config
    with _load_lock:
        _global_config = None


def get_nested(keys: Iterable[str], default_value: Any = None) -> Any:
    """config.get_nested(('provision', 'max_retries'), 3)

    Reference analog: sky/skypilot_config.py:311.
    """
    cur: Any = _config()
    for k in keys:
        if not isinstance(cur, dict) or k not in cur:
            return default_value
        cur = cur[k]
    return cur


def to_dict() -> Dict[str, Any]:
    return copy.deepcopy(_config())


@contextlib.contextmanager
def override(config_dict: Optional[Dict[str, Any]]):
    """Per-request config overlay (analog: sky/utils/context.py usage)."""
    if not config_dict:
        yield
        return
    current = _override_var.get() or {}
    token = _override_var.set(_merge_dicts(current, config_dict))
    try:
        yield
    finally:
        _override_var.reset(token)


def get_effective_region_config(cloud: str, region: Optional[str],
                                keys: Tuple[str, ...],
                                default_value: Any = None) -> Any:
    """Cloud/region-scoped lookup (analog: skypilot_config.py:339):

    {cloud}.{key} overridden by {cloud}.regions.{region}.{key}.
    """
    base = get_nested((cloud,) + keys, default_value)
    if region is None:
        return base
    region_val = get_nested((cloud, 'regions', region) + keys, None)
    return base if region_val is None else region_val
