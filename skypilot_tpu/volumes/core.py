"""Volume CRUD: persistent disks attachable to TPU-VM clusters.

Reference analog: sky/volumes/ (`sky volume apply/ls/delete`, 772 LoC).
GCP persistent disks via the compute REST API (same thin-client pattern as
provision/gcp/tpu_api.py); volume records live in the control-plane DB so
`skytpu volumes ls` works offline. Tasks attach volumes with

    volumes:
      /mnt/data: my-volume

which lands in the TPU node body's dataDisks at provision time
(provision/gcp/instance._node_body).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import requests

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import sky_logging
from skypilot_tpu.adaptors import gcp as gcp_adaptor

logger = sky_logging.init_logger(__name__)

_COMPUTE_ROOT = 'https://compute.googleapis.com/compute/v1'
_TIMEOUT = 60


def _headers() -> Dict[str, str]:
    return {'Authorization': f'Bearer {gcp_adaptor.get_access_token()}',
            'Content-Type': 'application/json'}


def _request(method: str, url: str,
             json_body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    resp = requests.request(method, url, headers=_headers(), json=json_body,
                            timeout=_TIMEOUT)
    if resp.status_code == 404:
        raise exceptions.ClusterDoesNotExist(f'{url} -> 404')
    if resp.status_code >= 400:
        raise exceptions.StorageError(
            f'{method} {url} -> {resp.status_code}: {resp.text}')
    return resp.json() if resp.text else {}


def _wait_zone_op(project: str, zone: str, op_name: str,
                  timeout: float = 300) -> None:
    url = f'{_COMPUTE_ROOT}/projects/{project}/zones/{zone}/operations/{op_name}'
    deadline = time.time() + timeout
    while time.time() < deadline:
        op = _request('GET', url)
        if op.get('status') == 'DONE':
            if op.get('error'):
                raise exceptions.StorageError(str(op['error']))
            return
        time.sleep(2)
    raise exceptions.StorageError(f'operation {op_name} timed out')


def apply(name: str, size_gb: int, zone: str,
          disk_type: str = 'pd-balanced',
          project: Optional[str] = None) -> Dict[str, Any]:
    """Create (or adopt, if it already exists) a persistent disk."""
    project = project or gcp_adaptor.get_project_id()
    url = f'{_COMPUTE_ROOT}/projects/{project}/zones/{zone}/disks'
    try:
        existing = _request('GET', f'{url}/{name}')
        # Adopt the disk AS IT IS — recording the requested size/type for
        # a pre-existing disk would lie to `volumes ls`.
        size_gb = int(existing.get('sizeGb', size_gb))
        disk_type = existing.get('type', disk_type).rsplit('/', 1)[-1]
        logger.info(f'Volume {name!r} already exists in {zone} '
                    f'({size_gb} GiB {disk_type}); adopting.')
    except exceptions.ClusterDoesNotExist:
        body = {
            'name': name,
            'sizeGb': str(size_gb),
            'type': f'projects/{project}/zones/{zone}/diskTypes/{disk_type}',
            'labels': {'skytpu-volume': name},
        }
        op = _request('POST', url, json_body=body)
        _wait_zone_op(project, zone, op['name'])
        logger.info(f'Volume {name!r} ({size_gb} GiB {disk_type}) created '
                    f'in {zone}.')
    handle = {'project': project, 'zone': zone, 'size_gb': size_gb,
              'disk_type': disk_type}
    global_state.add_or_update_volume(name, handle, 'READY')
    return {'name': name, **handle}


def ls() -> List[Dict[str, Any]]:
    return global_state.get_volumes()


def delete(name: str) -> None:
    record = global_state.get_volume(name)
    if record is None:
        raise exceptions.StorageError(f'Volume {name!r} not found.')
    handle = record['handle'] or {}
    project, zone = handle.get('project'), handle.get('zone')
    if project and zone:
        url = (f'{_COMPUTE_ROOT}/projects/{project}/zones/{zone}/'
               f'disks/{name}')
        try:
            op = _request('DELETE', url)
            _wait_zone_op(project, zone, op['name'])
        except exceptions.ClusterDoesNotExist:
            pass   # already gone on the cloud side
    global_state.remove_volume(name)
    logger.info(f'Volume {name!r} deleted.')


def attachment_plan(provider_config: Dict[str, Any], warn: bool = True
                    ) -> 'tuple[List[str], List[str], bool]':
    """Single source of truth for volume attachment: (volume names in
    attach order, mount paths in the same order, read_only).

    Both the attach side (dataDisks, provision/gcp/instance) and the mount
    side (device index ↔ mount path, provisioner) derive from THIS — they
    must agree exactly or devices map to the wrong paths.
    """
    volumes_map = provider_config.get('volumes_map') or {}
    mounts = sorted(volumes_map)
    names = [volumes_map[m] for m in mounts]
    read_only = (int(provider_config.get('num_hosts', 1)) > 1 or
                 int(provider_config.get('num_slices', 1)) > 1)
    if names and read_only and warn:
        logger.warning(
            'Multi-host slices attach volumes READ_ONLY (GCP rejects '
            'multi-attach READ_WRITE on plain persistent disks): '
            f'{names} will be mounted read-only. Jobs writing to them '
            'will get EROFS — write checkpoints to storage mounts '
            '(gs:// MOUNT/MOUNT_CACHED) instead.')
    return names, mounts, read_only


def data_disks_for(volume_names: List[str],
                   read_only: bool = False) -> List[Dict[str, Any]]:
    """dataDisks entries for a TPU node body.

    `read_only=True` for multi-host slices / multislice clusters: a
    non-multi-writer PD can only attach READ_WRITE to a single host, so
    multi-host attachments must be READ_ONLY or GCP rejects the create.
    """
    disks = []
    for name in volume_names:
        record = global_state.get_volume(name)
        if record is None:
            raise exceptions.StorageError(
                f'Volume {name!r} not found; create it with '
                f'`skytpu volumes apply`.')
        handle = record['handle'] or {}
        disks.append({
            'sourceDisk': (f'projects/{handle["project"]}/zones/'
                           f'{handle["zone"]}/disks/{name}'),
            'mode': 'READ_ONLY' if read_only else 'READ_WRITE',
        })
    return disks
