"""Network volumes (reference analog: sky/volumes/)."""
from skypilot_tpu.volumes.core import apply
from skypilot_tpu.volumes.core import delete
from skypilot_tpu.volumes.core import ls

__all__ = ['apply', 'ls', 'delete']
