"""Seeded, fully-materialized request schedules — the replay contract.

Everything random in a load run derives from ONE seed through one
``random.Random`` stream, and the whole schedule is materialized before
a single request is sent. That ordering is what makes a run
bit-replayable: client concurrency, network jitter and replica churn
can change *when* requests complete, but never *what* was offered —
``schedule_hash`` (sha256 over the canonical JSON of every request
spec) is identical for the same (profile, seed) on any machine, any
``--workers`` setting, any day. The scorecard records the hash; a
regression bisect replays the exact traffic by seed alone.

Workload shape (the million-user serving pattern scaled by profile):

  * N TENANTS x M SESSIONS, both Zipf-popular: a few tenants dominate
    traffic and, within each, a few sessions are hot — the skew that
    makes session routing matter (a uniform workload would never
    expose a hot-spot amplifier).
  * PREFIX REUSE: every session owns a seeded prefix token block; each
    of its requests is ``prefix ++ fresh suffix`` — the chat pattern
    (system prompt + growing history) that prefix KV caches and
    consistent-hash affinity exist for.
  * CLASSES: each request draws a declared class
    (observe/request_class.py) from the profile's mix; classes differ
    in prompt/suffix/new-token lengths, so the mixed short/long
    admission behavior is part of the offered load.
  * ARRIVALS: a diurnal sinusoid compressed into the run's duration,
    plus a multiplicative SPIKE window — sampled by rejection against
    the intensity envelope (deterministic: the accept/reject draws
    come from the same seeded stream). Each request is labeled with
    its phase (offpeak/peak/spike) so the scorecard reports offered
    truth per class per phase.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import random
from typing import Dict, List, Optional, Tuple

from skypilot_tpu.observe import request_class

# Prompt token ids are drawn from this range — comfortably inside every
# debug/test model's vocab (the fleet e2e suite uses ids < 32).
_TOKEN_LOW, _TOKEN_HIGH = 1, 63


@dataclasses.dataclass(frozen=True)
class ClassShape:
    """One request class's size parameters (token counts)."""
    prefix_len: int          # session-shared prompt head
    suffix_len: int          # fresh per-request tail
    max_new_tokens: int
    weight: float            # share of the class mix


@dataclasses.dataclass(frozen=True)
class Profile:
    """A named workload. ``duration_s`` is the schedule's span; the
    runner replays arrival offsets against its own start time."""
    name: str
    tenants: int
    sessions_per_tenant: int
    requests: int
    duration_s: float
    classes: Dict[str, ClassShape]
    zipf_a: float = 1.1              # tenant/session popularity skew
    diurnal_amplitude: float = 0.6   # peak-to-trough intensity swing
    spike_start_frac: float = 0.5    # spike window, as run fractions
    spike_len_frac: float = 0.2
    spike_factor: float = 3.0
    stream_fraction: float = 0.5     # share of requests using SSE
    # Class-shaped spikes (the disaggregation scenario): when set,
    # requests scheduled INSIDE the spike window draw their class with
    # ``spike_class`` boosted to ``spike_class_weight`` of the mix
    # (other classes share the remainder proportionally) — a burst of
    # long prompts OVER steady interactive traffic, not instead of it.
    # Empty string = the plain uniform-mix spike every earlier profile
    # uses (their schedule hashes must replay unchanged).
    spike_class: str = ''
    spike_class_weight: float = 0.0

    def max_prompt_len(self) -> int:
        return max(c.prefix_len + c.suffix_len
                   for c in self.classes.values())

    def max_new(self) -> int:
        return max(c.max_new_tokens for c in self.classes.values())


PROFILES: Dict[str, Profile] = {
    # CPU-runnable in seconds — the bench tripwire and the checked-in
    # scorecard's profile. Prefix lengths clear the engine's 64-token
    # prefix-snapshot minimum so session affinity shows up as prefix
    # HITS, not just stable routing.
    'smoke': Profile(
        name='smoke', tenants=3, sessions_per_tenant=4, requests=36,
        duration_s=6.0,
        classes={
            'interactive': ClassShape(prefix_len=64, suffix_len=4,
                                      max_new_tokens=6, weight=0.6),
            'long_context': ClassShape(prefix_len=96, suffix_len=16,
                                       max_new_tokens=4, weight=0.25),
            'batch': ClassShape(prefix_len=64, suffix_len=8,
                                max_new_tokens=8, weight=0.15),
        }),
    # A few minutes on CPU, a shakeout on real hardware.
    'small': Profile(
        name='small', tenants=8, sessions_per_tenant=8, requests=160,
        duration_s=40.0,
        classes={
            'interactive': ClassShape(prefix_len=16, suffix_len=8,
                                      max_new_tokens=8, weight=0.6),
            'long_context': ClassShape(prefix_len=48, suffix_len=16,
                                       max_new_tokens=8, weight=0.25),
            'batch': ClassShape(prefix_len=16, suffix_len=16,
                                max_new_tokens=16, weight=0.15),
        }),
    # The disaggregation proof profile (docs/serving.md): steady
    # interactive chat turns (short prompts — below the LB's
    # two-stage threshold, so they live on the decode pool) with a
    # mid-run SPIKE of long-prompt traffic (3x intensity, 85%
    # long_context inside the window). The long prompts bucket to
    # 2048 — several prefill chunks each. On a monolithic pool every
    # replica decodes interactive traffic, so the burst's prefills
    # crawl one interleaved chunk per scheduling round (chunked
    # prefill caps the interactive-TPOT damage but cannot mint
    # prefill capacity): the burst class's TTFT blows up and its
    # goodput breaches. Behind a disaggregated 1+2 stack the
    # dedicated prefill pool drains the same spike flat out while
    # interactive TPOT holds within the calm run's band (the
    # checked-in LOADGEN_PREFILL_BURST*.json scorecards, pinned by
    # TestPrefillBurstArtifacts).
    'prefill_burst': Profile(
        name='prefill_burst', tenants=4, sessions_per_tenant=4,
        requests=60, duration_s=12.0,
        classes={
            'interactive': ClassShape(prefix_len=32, suffix_len=8,
                                      max_new_tokens=10, weight=0.8),
            'long_context': ClassShape(prefix_len=1500, suffix_len=32,
                                       max_new_tokens=2, weight=0.2),
        },
        diurnal_amplitude=0.2, spike_start_frac=0.4,
        spike_len_frac=0.25, spike_factor=3.0,
        spike_class='long_context', spike_class_weight=0.85,
        stream_fraction=0.4),
    # The million-user SHAPE (tenant/session cardinality and skew) at
    # a request count a TPU fleet sustains for ~half an hour; scale
    # `requests` up from the CLI for longer soaks.
    'soak': Profile(
        name='soak', tenants=1000, sessions_per_tenant=50,
        requests=20000, duration_s=1800.0,
        classes={
            'interactive': ClassShape(prefix_len=128, suffix_len=64,
                                      max_new_tokens=64, weight=0.7),
            'long_context': ClassShape(prefix_len=1024, suffix_len=128,
                                       max_new_tokens=32, weight=0.2),
            'batch': ClassShape(prefix_len=128, suffix_len=256,
                                max_new_tokens=128, weight=0.1),
        }),
}

# The burst profile's no-burst control: identical classes/skew/rates
# with the spike window removed — the scorecard pair the acceptance
# band compares ("interactive TPOT under the burst within tolerance of
# its no-burst run").
PROFILES['prefill_calm'] = dataclasses.replace(
    PROFILES['prefill_burst'], name='prefill_calm',
    spike_len_frac=0.0, spike_factor=1.0, spike_class='',
    spike_class_weight=0.0)

# The elastic-controller proof profile (docs/ELASTIC.md): calm → a
# sustained 2x-QPS ramp window → calm, with the diurnal swing removed
# so the ONLY intensity change is the ramp itself. Same class mix as
# smoke; the window is long enough (40% of the run) for a controller
# to ride out its hysteresis and react inside it, and the arrivals are
# the same seeded draw as every profile — scale decisions replay
# against a schedule-hash-stable offered load. Defined as a
# dataclasses.replace variant (the prefill_calm precedent) so existing
# profiles' schedule hashes cannot drift.
PROFILES['ramp'] = dataclasses.replace(
    PROFILES['smoke'], name='ramp', requests=48, duration_s=8.0,
    diurnal_amplitude=0.0, spike_start_frac=0.3, spike_len_frac=0.4,
    spike_factor=2.0)

# The KV-memory-hierarchy proof profile (docs/ENGINE.md "KV memory
# hierarchy"): many long-context sessions against a deliberately
# entry-starved device prefix cache, Zipf-skewed re-activation so
# sessions go idle and RETURN. Without the host spill tier every
# eviction is a full re-prefill and the replica's resident-session
# peak is capped at the device store size; with
# SKYTPU_ENGINE_KV_HOST_MB + SKYTPU_ENGINE_KV_IDLE_SPILL_S the same
# schedule parks idle sessions in host RAM and wakes them on return —
# the concurrent_sessions_peak column the KV-hierarchy bench compares
# (int8+spill vs none+no-spill, TPOT held in band). A NEW entry, not a
# replace-variant: existing profiles' schedule hashes must not drift.
PROFILES['churn'] = Profile(
    name='churn', tenants=4, sessions_per_tenant=6, requests=72,
    duration_s=12.0,
    classes={
        'interactive': ClassShape(prefix_len=64, suffix_len=4,
                                  max_new_tokens=4, weight=0.45),
        'long_context': ClassShape(prefix_len=256, suffix_len=16,
                                   max_new_tokens=4, weight=0.55),
    },
    diurnal_amplitude=0.3, spike_len_frac=0.0, spike_factor=1.0,
    stream_fraction=0.25)


@dataclasses.dataclass(frozen=True)
class RequestSpec:
    """One scheduled request — everything the client needs to send it
    and the scorecard needs to attribute it. ``t`` is the offset from
    run start in seconds; ``phase`` labels the arrival-intensity
    regime it was scheduled under."""
    index: int
    t: float
    tenant: str
    session: str
    cls: str
    phase: str
    tokens: Tuple[int, ...]
    max_new_tokens: int
    stream: bool

    def to_doc(self) -> Dict[str, object]:
        doc = dataclasses.asdict(self)
        doc['tokens'] = list(self.tokens)
        return doc


def _zipf_weights(n: int, a: float) -> List[float]:
    return [1.0 / (k + 1) ** a for k in range(n)]


def _intensity(profile: Profile, t: float) -> float:
    """Relative arrival intensity at offset ``t``: diurnal sinusoid
    (trough at the start, peak mid-run) times the spike factor inside
    the spike window."""
    frac = t / profile.duration_s
    lam = 1.0 + profile.diurnal_amplitude * math.sin(
        2.0 * math.pi * frac - math.pi / 2.0)
    if (profile.spike_start_frac <= frac <
            profile.spike_start_frac + profile.spike_len_frac):
        lam *= profile.spike_factor
    return lam


def _phase(profile: Profile, t: float) -> str:
    frac = t / profile.duration_s
    if (profile.spike_start_frac <= frac <
            profile.spike_start_frac + profile.spike_len_frac):
        return 'spike'
    return 'peak' if _intensity(profile, t) >= 1.0 else 'offpeak'


def build_schedule(profile: Profile, seed: int) -> List[RequestSpec]:
    """The full request schedule for (profile, seed) — pure function,
    no wall clock, no I/O. Sorted by arrival offset; ``index`` is the
    arrival order (ties broken by draw order, itself deterministic)."""
    unknown = set(profile.classes) - set(request_class.CLASSES)
    if unknown:
        raise ValueError(
            f'profile {profile.name!r} declares classes outside the '
            f'closed registry: {sorted(unknown)} (declared: '
            f'{request_class.CLASSES})')
    rng = random.Random(seed)
    tenants = [f'tenant-{i:04d}' for i in range(profile.tenants)]
    tenant_w = _zipf_weights(profile.tenants, profile.zipf_a)
    session_w = _zipf_weights(profile.sessions_per_tenant,
                              profile.zipf_a)

    # Session prefix blocks: derived LAZILY from a per-(seed, session,
    # class) child stream, so only sessions actually drawn pay for
    # their prefixes — under Zipf skew most of a large profile's
    # tenant x session space is never touched (the 'soak' profile's
    # full space is ~64M tokens; its 20k requests hit a tiny
    # fraction). Child-seeding keeps the determinism contract: a
    # session's prefix depends on nothing but (seed, session, cls),
    # never on draw order.
    prefix_cache: Dict[Tuple[str, str], Tuple[int, ...]] = {}

    def session_prefix(session: str, cls: str) -> Tuple[int, ...]:
        key = (session, cls)
        prefix = prefix_cache.get(key)
        if prefix is None:
            child = random.Random(f'{seed}/{session}/{cls}')
            prefix = tuple(
                child.randint(_TOKEN_LOW, _TOKEN_HIGH)
                for _ in range(profile.classes[cls].prefix_len))
            prefix_cache[key] = prefix
        return prefix

    class_names = sorted(profile.classes)
    class_weights = [profile.classes[c].weight for c in class_names]
    spike_weights = None
    if profile.spike_class:
        if profile.spike_class not in profile.classes:
            raise ValueError(
                f'profile {profile.name!r} spike_class '
                f'{profile.spike_class!r} is not one of its classes')
        if not 0.0 < profile.spike_class_weight < 1.0:
            raise ValueError('spike_class_weight must be in (0, 1)')
        rest = sum(w for c, w in zip(class_names, class_weights)
                   if c != profile.spike_class)
        if rest <= 0:
            raise ValueError(
                f'profile {profile.name!r}: spike_class '
                f'{profile.spike_class!r} needs at least one OTHER '
                f'positive-weight class to spike against')
        spike_weights = [
            profile.spike_class_weight if c == profile.spike_class
            else w / rest * (1.0 - profile.spike_class_weight)
            for c, w in zip(class_names, class_weights)]
    lam_max = max(_intensity(profile, x * profile.duration_s / 1000.0)
                  for x in range(1000)) * 1.001

    drawn = []
    for _ in range(profile.requests):
        # Arrival: rejection-sample against the intensity envelope.
        while True:
            t = rng.random() * profile.duration_s
            if rng.random() * lam_max <= _intensity(profile, t):
                break
        tenant = rng.choices(tenants, weights=tenant_w)[0]
        s_idx = rng.choices(range(profile.sessions_per_tenant),
                            weights=session_w)[0]
        session = f'{tenant}/s{s_idx:03d}'
        in_spike = spike_weights is not None and _phase(
            profile, t) == 'spike'
        cls = rng.choices(class_names,
                          weights=(spike_weights if in_spike
                                   else class_weights))[0]
        shape = profile.classes[cls]
        suffix = tuple(rng.randint(_TOKEN_LOW, _TOKEN_HIGH)
                       for _ in range(shape.suffix_len))
        stream = rng.random() < profile.stream_fraction
        drawn.append((t, tenant, session, cls, suffix, stream))

    drawn.sort(key=lambda d: d[0])
    out: List[RequestSpec] = []
    for index, (t, tenant, session, cls, suffix, stream) in \
            enumerate(drawn):
        shape = profile.classes[cls]
        prefix = session_prefix(session, cls)
        out.append(RequestSpec(
            index=index, t=round(t, 6), tenant=tenant, session=session,
            cls=cls, phase=_phase(profile, t), tokens=prefix + suffix,
            max_new_tokens=shape.max_new_tokens, stream=stream))
    return out


def schedule_hash(schedule: List[RequestSpec]) -> str:
    """sha256 over the canonical JSON of every spec — the replay
    contract the scorecard records and the bench tripwire asserts."""
    blob = json.dumps([spec.to_doc() for spec in schedule],
                      sort_keys=True, separators=(',', ':'))
    return hashlib.sha256(blob.encode()).hexdigest()


def offered_truth(schedule: List[RequestSpec]
                  ) -> Dict[str, Dict[str, Dict[str, float]]]:
    """The offered-load side of the scorecard: per class and per
    (class, phase), how many requests / prompt tokens / requested new
    tokens the schedule contains. Ground truth by construction — it
    describes the schedule, not the run."""
    by_class: Dict[str, Dict[str, float]] = {}
    by_phase: Dict[str, Dict[str, float]] = {}
    for spec in schedule:
        for key, acc in ((spec.cls, by_class),
                         (f'{spec.cls}/{spec.phase}', by_phase)):
            row = acc.setdefault(key, {'requests': 0,
                                       'prompt_tokens': 0,
                                       'new_tokens_requested': 0,
                                       'sessions': 0})
            row['requests'] += 1
            row['prompt_tokens'] += len(spec.tokens)
            row['new_tokens_requested'] += spec.max_new_tokens
    sessions_by_class: Dict[str, set] = {}
    for spec in schedule:
        sessions_by_class.setdefault(spec.cls, set()).add(spec.session)
    for cls, sessions in sessions_by_class.items():
        by_class[cls]['sessions'] = len(sessions)
    for row in by_phase.values():
        row.pop('sessions', None)
    return {'by_class': by_class, 'by_class_phase': by_phase}


def resolve_profile(name: str,
                    requests: Optional[int] = None,
                    duration_s: Optional[float] = None) -> Profile:
    """A named profile, optionally rescaled (request count / duration
    overrides change the schedule — and therefore the hash — exactly
    as a different profile would)."""
    base = PROFILES.get(name)
    if base is None:
        raise ValueError(
            f'unknown profile {name!r}; available: '
            f'{sorted(PROFILES)}')
    if requests is None and duration_s is None:
        return base
    return dataclasses.replace(
        base,
        requests=base.requests if requests is None else requests,
        duration_s=(base.duration_s if duration_s is None
                    else duration_s))
