"""Async traffic clients: replay a materialized schedule against a
live LB (or a bare engine replica).

The runner is deliberately dumb: the schedule IS the experiment; the
client's only jobs are (a) send each request at its scheduled offset,
as the declared class and session, over the declared transport
(plain /generate POST or SSE /v1/completions streaming), and (b) keep
honest books about what actually happened client-side (completions,
errors, and a client-view latency it clearly labels as secondary —
the scorecard's headline latency columns come from the fleet plane,
never from these stopwatches).

Concurrency: ``workers`` bounds in-flight requests with a semaphore.
It shapes DELIVERY only — the offered schedule (and its hash) is fixed
before the first send, which is exactly the determinism contract the
tests pin (same seed => identical schedule at --workers 1 and 4).
"""
from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Dict, List, Optional

import aiohttp

from skypilot_tpu import sky_logging
from skypilot_tpu.observe import request_class
from skypilot_tpu.loadgen import schedule as schedule_lib

logger = sky_logging.init_logger(__name__)


@dataclasses.dataclass
class RequestResult:
    index: int
    cls: str
    phase: str
    session: str
    ok: bool
    status: int = 0
    error: str = ''
    tokens_out: int = 0
    # Client-view timings — SECONDARY evidence (queueing in the client,
    # the proxy hop and SSE parsing all ride on them); the scorecard's
    # latency columns come from /-/fleet/metrics.
    latency_s: float = 0.0
    client_ttft_s: Optional[float] = None


@dataclasses.dataclass
class RunResult:
    started_at: float
    wall_s: float
    results: List[RequestResult]

    def completed(self) -> int:
        return sum(1 for r in self.results if r.ok)

    def errors(self) -> int:
        return sum(1 for r in self.results if not r.ok)

    def client_view(self) -> Dict[str, Dict[str, float]]:
        """Per-class client-side summary (marked secondary in the
        scorecard)."""
        out: Dict[str, Dict[str, float]] = {}
        by_cls: Dict[str, List[RequestResult]] = {}
        for r in self.results:
            by_cls.setdefault(r.cls, []).append(r)
        for cls, rows in sorted(by_cls.items()):
            ok = [r for r in rows if r.ok]
            row: Dict[str, float] = {
                'sent': len(rows), 'completed': len(ok),
                'errors': len(rows) - len(ok),
            }
            ttfts = sorted(r.client_ttft_s for r in ok
                           if r.client_ttft_s is not None)
            if ttfts:
                row['client_ttft_ms_p50'] = round(
                    ttfts[len(ttfts) // 2] * 1e3, 2)
            lats = sorted(r.latency_s for r in ok)
            if lats:
                row['client_latency_ms_p50'] = round(
                    lats[len(lats) // 2] * 1e3, 2)
            out[cls] = row
        return out


def _headers(spec: schedule_lib.RequestSpec) -> Dict[str, str]:
    return {request_class.HEADER: spec.cls,
            'X-Skytpu-Session': spec.session}


async def _send_generate(session, base_url: str,
                         spec: schedule_lib.RequestSpec
                         ) -> RequestResult:
    t0 = time.monotonic()
    try:
        async with session.post(
                f'{base_url}/generate',
                json={'tokens': list(spec.tokens),
                      'max_new_tokens': spec.max_new_tokens},
                headers=_headers(spec)) as resp:
            body = await resp.json(content_type=None)
            ok = resp.status == 200
            return RequestResult(
                index=spec.index, cls=spec.cls, phase=spec.phase,
                session=spec.session, ok=ok, status=resp.status,
                error='' if ok else str(body)[:200],
                tokens_out=(len(body.get('tokens', [])) if ok else 0),
                latency_s=time.monotonic() - t0)
    except (aiohttp.ClientError, OSError, asyncio.TimeoutError,
            ValueError) as e:
        return RequestResult(
            index=spec.index, cls=spec.cls, phase=spec.phase,
            session=spec.session, ok=False,
            error=f'{type(e).__name__}: {e}'[:200],
            latency_s=time.monotonic() - t0)


async def _send_stream(session, base_url: str,
                       spec: schedule_lib.RequestSpec) -> RequestResult:
    """SSE streaming client (/v1/completions stream=true, token-id
    prompt): counts data events, stamps client TTFT at the first
    content-bearing chunk."""
    t0 = time.monotonic()
    ttft = None
    chunks = 0
    try:
        async with session.post(
                f'{base_url}/v1/completions',
                json={'prompt': list(spec.tokens),
                      'max_tokens': spec.max_new_tokens,
                      'stream': True},
                headers=_headers(spec)) as resp:
            if resp.status != 200:
                body = await resp.text()
                return RequestResult(
                    index=spec.index, cls=spec.cls, phase=spec.phase,
                    session=spec.session, ok=False, status=resp.status,
                    error=body[:200], latency_s=time.monotonic() - t0)
            async for raw in resp.content:
                line = raw.decode('utf-8', errors='replace').strip()
                if not line.startswith('data:'):
                    continue
                payload = line[len('data:'):].strip()
                if payload == '[DONE]':
                    break
                chunks += 1
                if ttft is None:
                    ttft = time.monotonic() - t0
        return RequestResult(
            index=spec.index, cls=spec.cls, phase=spec.phase,
            session=spec.session, ok=True, status=200,
            tokens_out=chunks, latency_s=time.monotonic() - t0,
            client_ttft_s=ttft)
    except (aiohttp.ClientError, OSError, asyncio.TimeoutError,
            ValueError) as e:
        return RequestResult(
            index=spec.index, cls=spec.cls, phase=spec.phase,
            session=spec.session, ok=False,
            error=f'{type(e).__name__}: {e}'[:200],
            latency_s=time.monotonic() - t0)


async def run_schedule(base_url: str,
                       schedule: List[schedule_lib.RequestSpec],
                       workers: int = 4,
                       time_scale: float = 1.0,
                       request_timeout: float = 120.0) -> RunResult:
    """Replay ``schedule`` against ``base_url``. Each request fires at
    its scheduled offset (scaled by ``time_scale`` — <1 compresses a
    long profile into a short wall-clock run); ``workers`` bounds
    in-flight requests. Every spec yields exactly one RequestResult,
    success or not — the books must balance against the schedule."""
    base = base_url.rstrip('/')
    sem = asyncio.Semaphore(max(1, workers))
    started_at = time.time()
    t0 = time.monotonic()
    timeout = aiohttp.ClientTimeout(total=None, connect=30.0,
                                    sock_read=request_timeout)
    async with aiohttp.ClientSession(timeout=timeout) as session:

        async def one(spec: schedule_lib.RequestSpec) -> RequestResult:
            delay = spec.t * time_scale - (time.monotonic() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            async with sem:
                if spec.stream:
                    return await _send_stream(session, base, spec)
                return await _send_generate(session, base, spec)

        results = await asyncio.gather(*(one(s) for s in schedule))
    return RunResult(started_at=started_at,
                     wall_s=time.monotonic() - t0,
                     results=list(results))


async def wait_ready(base_url: str, path: str = '/-/lb/health',
                     timeout_s: float = 600.0) -> None:
    """Poll a health endpoint until 200 or deadline (engine warmup on
    CPU takes tens of seconds — compiling the debug model's buckets)."""
    base = base_url.rstrip('/')
    deadline = time.monotonic() + timeout_s
    async with aiohttp.ClientSession() as session:
        while True:
            try:
                async with session.get(base + path) as resp:
                    if resp.status == 200:
                        return
            except (OSError, aiohttp.ClientError):
                pass
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f'{base}{path} never became ready '
                    f'({timeout_s:.0f}s)')
            await asyncio.sleep(1.0)
