"""Scorecard assembly: offered-load truth + fleet-plane measurement.

One JSON artifact per run, merging three evidence planes that must
never be conflated:

  * ``offered`` — what the schedule SENT (per class, per phase).
    Ground truth by construction.
  * ``fleet`` — what the FLEET PLANE measured: per-class TTFT/TPOT
    quantiles, goodput good/slow counts and prefix-cache hit rate
    parsed (via observe/promtext — the one exposition parser) from
    ``/-/fleet/metrics``, plus the per-class burn/state columns the
    LB's ``/-/fleet/status`` reports from its SLO engine. This is the
    headline evidence: none of it comes from client stopwatches.
  * ``client`` — the runner's own books (completions, errors, its
    secondary latency view). Kept for reconciliation: fleet-side
    request counts should match what the client believes it sent.

The scorecard also records the ``schedule_hash`` — the replay
contract — and the ``routing`` drill results (session→replica
stability across an LB restart, load-bound compliance) when the
harness ran one.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.observe import promtext
from skypilot_tpu.observe import request_class
from skypilot_tpu.loadgen import client as client_lib
from skypilot_tpu.loadgen import schedule as schedule_lib

SCHEMA_VERSION = 1

_CLASS_FAMILIES = (('skytpu_engine_class_ttft_seconds', 'ttft'),
                   ('skytpu_engine_class_tpot_seconds', 'tpot'))
_QUANTILES = ((0.50, 'p50'), (0.95, 'p95'))


def _counter_by_labels(fams, family: str) -> Dict[tuple, float]:
    fam = fams.get(family)
    if fam is None:
        return {}
    return {s.labels: s.value for s in fam.samples}


def fleet_section(metrics_text: str) -> Dict[str, Any]:
    """The fleet-measured half of the scorecard from one
    ``/-/fleet/metrics`` document. Tolerant throughout: a class with
    no samples yet yields a row of what IS known (goodput counts seed
    at zero on every engine), never a KeyError."""
    fams = promtext.parse(metrics_text)
    goodput = _counter_by_labels(fams, 'skytpu_engine_goodput_total')
    class_hists = {short: promtext.extract_histograms(fams, family)
                   for family, short in _CLASS_FAMILIES}
    by_class: Dict[str, Dict[str, Any]] = {}
    for cls in request_class.CLASSES:
        row: Dict[str, Any] = {}
        good = goodput.get((('cls', cls), ('outcome', 'good')), 0.0)
        slow = goodput.get((('cls', cls), ('outcome', 'slow')), 0.0)
        row['good'] = good
        row['slow'] = slow
        total = good + slow
        row['goodput'] = round(good / total, 4) if total else None
        for _, short in _CLASS_FAMILIES:
            hist = class_hists[short].get((('cls', cls),))
            if hist is None:
                continue
            for q, suffix in _QUANTILES:
                v = promtext.histogram_quantile(hist, q)
                if v == v:
                    # One spelling everywhere ('<fam>_p95_ms'): the
                    # status table, the fleet CLI and this section
                    # must join on the same keys.
                    row[f'{short}_{suffix}_ms'] = round(v * 1e3, 2)
        by_class[cls] = row
    aggregate: Dict[str, Any] = {}
    for family, short in (('skytpu_engine_ttft_seconds', 'ttft'),
                          ('skytpu_engine_tpot_seconds', 'tpot')):
        for q, suffix in _QUANTILES:
            v = promtext.quantile_from_text(metrics_text, family, q)
            if v == v:
                aggregate[f'{short}_{suffix}_ms'] = round(v * 1e3, 2)
    requests_fam = fams.get('skytpu_engine_requests_total')
    if requests_fam is not None:
        aggregate['requests_total'] = sum(
            s.value for s in requests_fam.samples)
    kv_peak = fams.get('skytpu_engine_kv_sessions_peak')
    if kv_peak is not None and kv_peak.samples:
        # Summed across replicas by the fleet merge: each replica's
        # high-water mark of sessions resident in its KV hierarchy
        # (device prefix store + host spill tier). The KV-hierarchy
        # bench compares this column across int8+spill vs
        # none+no-spill runs of the churn profile.
        aggregate['concurrent_sessions_peak'] = sum(
            s.value for s in kv_peak.samples)
    prefix = _counter_by_labels(fams,
                                'skytpu_engine_prefix_requests_total')
    hits = prefix.get((('outcome', 'hit'),), 0.0)
    misses = prefix.get((('outcome', 'miss'),), 0.0)
    prefix_row: Dict[str, Any] = {'hits': hits, 'misses': misses}
    lookups = hits + misses
    prefix_row['hit_rate'] = (round(hits / lookups, 4)
                              if lookups else None)
    return {'by_class': by_class, 'aggregate': aggregate,
            'prefix': prefix_row}


def prefix_counts(metrics_text: str) -> tuple:
    """(hits, misses) of the fleet's prefix-cache lookups — the churn
    scenario diffs these across an LB restart."""
    fams = promtext.parse(metrics_text)
    prefix = _counter_by_labels(fams,
                                'skytpu_engine_prefix_requests_total')
    return (prefix.get((('outcome', 'hit'),), 0.0),
            prefix.get((('outcome', 'miss'),), 0.0))


def build_scorecard(
        *, profile: schedule_lib.Profile, seed: int,
        schedule: List[schedule_lib.RequestSpec],
        run: Optional[client_lib.RunResult],
        fleet_metrics_text: str = '',
        fleet_status: Optional[Dict[str, Any]] = None,
        slo_events: Optional[List[Dict[str, Any]]] = None,
        scale_events: Optional[List[Dict[str, Any]]] = None,
        routing: Optional[Dict[str, Any]] = None,
        stack: Optional[Dict[str, Any]] = None,
        cost: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Merge one run's evidence planes into the scorecard doc."""
    doc: Dict[str, Any] = {
        'schema_version': SCHEMA_VERSION,
        'generated_unix': round(time.time(), 3),
        'profile': profile.name,
        'seed': seed,
        'requests': len(schedule),
        'duration_s': profile.duration_s,
        'schedule_hash': schedule_lib.schedule_hash(schedule),
        'offered': schedule_lib.offered_truth(schedule),
    }
    if stack:
        doc['stack'] = stack
    if run is not None:
        doc['client'] = {
            'note': ('client-side view, SECONDARY evidence — the '
                     'headline latency columns are fleet-attributed '
                     '(fleet.by_class)'),
            'completed': run.completed(),
            'errors': run.errors(),
            'wall_s': round(run.wall_s, 3),
            'by_class': run.client_view(),
        }
    if fleet_metrics_text:
        doc['fleet'] = fleet_section(fleet_metrics_text)
    if fleet_status is not None:
        doc['slo'] = {
            'states': fleet_status.get('slo') or {},
            'classes': fleet_status.get('classes') or {},
        }
    if slo_events is not None:
        doc['slo_events'] = slo_events
    if scale_events is not None:
        # The elastic controller's journaled reactions to this run's
        # offered load (elastic_decision events): with the schedule
        # hash pinning the arrivals, a scale event is replayable —
        # same seed, same profile, same signal, same decision.
        doc['scale_events'] = scale_events
    if routing is not None:
        doc['routing'] = routing
    if cost is not None:
        # The economic plane (observe/costs.py CostMeter.summary):
        # per-pool metered dollars, the cost_per_token_usd join and
        # spot_discount (on-demand reference over metered spend) —
        # every number priced through the one cost code path, none
        # computed here.
        doc['cost'] = cost
    return doc


def write_scorecard(doc: Dict[str, Any], path: str) -> None:
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write('\n')


def diff_scorecards(current: Dict[str, Any], last_good: Dict[str, Any],
                    quantile_tolerance: float = 3.0
                    ) -> Dict[str, Any]:
    """The bench tripwire's comparison: replay must be EXACT (same
    seed+profile => same schedule hash, byte for byte), quality must
    not collapse (per-class goodput may not drop more than the
    tolerance band; per-class p95s may not exceed last-good by more
    than ``quantile_tolerance``x — CPU boxes are noisy, an order of
    magnitude is not noise)."""
    out: Dict[str, Any] = {'replay_ok': None, 'regressions': []}
    if (current.get('profile') == last_good.get('profile') and
            current.get('seed') == last_good.get('seed')):
        out['replay_ok'] = (current.get('schedule_hash') ==
                            last_good.get('schedule_hash'))
        if not out['replay_ok']:
            out['regressions'].append(
                'schedule_hash changed for the same (profile, seed) — '
                'the replay contract is broken')
    cur_cls = (current.get('fleet') or {}).get('by_class') or {}
    old_cls = (last_good.get('fleet') or {}).get('by_class') or {}
    for cls, old_row in old_cls.items():
        cur_row = cur_cls.get(cls) or {}
        old_gp, cur_gp = old_row.get('goodput'), cur_row.get('goodput')
        if old_gp is not None and cur_gp is not None and \
                cur_gp < old_gp - 0.25:
            out['regressions'].append(
                f'{cls}: goodput {cur_gp} vs last-good {old_gp}')
        # Quantiles are only evidence at quantile-worthy sample
        # counts: at n < 20 the p95 IS the max of a handful of
        # CPU-noise samples (observed 10x swings run to run on an
        # otherwise identical tree) — the goodput band above is the
        # small-n tripwire.
        finished = min(
            cur_row.get('good', 0.0) + cur_row.get('slow', 0.0),
            old_row.get('good', 0.0) + old_row.get('slow', 0.0))
        if finished < 20:
            continue
        for key in ('ttft_p95_ms', 'tpot_p95_ms'):
            old_v, cur_v = old_row.get(key), cur_row.get(key)
            if old_v and cur_v and cur_v > old_v * quantile_tolerance:
                out['regressions'].append(
                    f'{cls}: {key} {cur_v} vs last-good {old_v} '
                    f'(>{quantile_tolerance}x)')
    out['ok'] = (out['replay_ok'] is not False and
                 not out['regressions'])
    return out
