"""The live local stack and the routing drill — how a scorecard gets
made without a TPU fleet.

``LocalStack`` spawns N real ``skypilot_tpu.serve.engine`` replicas
(CPU debug model) behind an in-process LoadBalancer wired EXACTLY as
the service controller wires it — Scraper + SLOEngine + ScrapeLoop +
``attach_fleet`` — so the scorecard's fleet columns exercise the same
scrape → tsdb → burn-rate path production runs. Nothing here is a
mock; the only concession to CPU is the model size.

``routing_drill`` is the deterministic consistent-hash proof: it
replays Zipf-popular session traffic against the REAL
PrefixAffinityPolicy object, restarts it (a fresh policy instance —
exactly the state an LB restart discards), and measures
session→replica stability, the bounded-load guarantee, and churn
remap fractions. Policy-level on purpose: the properties under test
are routing invariants, and measuring them through subprocess restarts
would only add noise to the same arithmetic.
"""
from __future__ import annotations

import asyncio
import collections
import heapq
import math
import os
import random
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger(__name__)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class LocalStack:
    """N live CPU engine replicas + in-process LB/scraper/SLO plane.

    Use as an async context manager::

        async with LocalStack(profile, replicas=2, run_dir=d) as stack:
            result = await client.run_schedule(stack.lb_url, schedule)
            text = await stack.fleet_metrics()
    """

    def __init__(self, profile, replicas: int = 2,
                 run_dir: str = '.',
                 model: str = 'llama-debug',
                 policy: str = 'prefix_affinity',
                 scrape_interval: float = 1.0,
                 warmup_timeout: float = 600.0,
                 disagg: Optional[tuple] = None):
        self.profile = profile
        self.replicas = replicas
        self.run_dir = run_dir
        self.model = model
        self.policy = policy
        self.scrape_interval = scrape_interval
        self.warmup_timeout = warmup_timeout
        # Disaggregated stack: (n_prefill, n_decode) real engine
        # replicas wired through the LB's two-stage PoolRouter exactly
        # as the service controller wires it (set_pool_replicas +
        # role-tagged scrape targets + per-stage SLO kinds). None =
        # monolithic `replicas`-wide stack.
        self.disagg = disagg
        self.lb_port = _free_port()
        self.lb_url = f'http://127.0.0.1:{self.lb_port}'
        self.started_unix: float = 0.0
        self._procs: List[subprocess.Popen] = []
        self._urls: List[str] = []
        self._pool_urls: Dict[str, List[str]] = {}
        self._runner = None
        self._scrape_loop = None
        self._slo_engine = None
        self._scraper = None
        self._lb = None
        self._elastic = None
        self._cost_meter = None

    # ------------------------------------------------------------ wiring
    def _engine_cmd(self, port: int,
                    handoff_port: Optional[int] = None) -> List[str]:
        max_len = (_next_pow2(self.profile.max_prompt_len()) +
                   self.profile.max_new() + 16)
        buckets = sorted({
            _next_pow2(c.prefix_len + c.suffix_len)
            for c in self.profile.classes.values()})
        cmd = [sys.executable, '-m', 'skypilot_tpu.serve.engine',
               '--model', self.model, '--max-len', str(max_len),
               '--warm-buckets', ','.join(str(b) for b in buckets),
               '--host', '127.0.0.1', '--port', str(port)]
        if handoff_port is not None:
            cmd += ['--handoff-port', str(handoff_port)]
        return cmd

    async def __aenter__(self) -> 'LocalStack':
        # A failure inside enter (engine never warms, port races)
        # must not leak the engine subprocesses — __aexit__ never
        # runs when __aenter__ raises, and leaked replicas poison
        # every later run on the box.
        try:
            return await self._enter()
        except BaseException:
            await self.__aexit__()
            raise

    async def _enter(self) -> 'LocalStack':
        from aiohttp import web

        from skypilot_tpu.observe import costs as costs_lib
        from skypilot_tpu.observe import scrape
        from skypilot_tpu.observe import slo as slo_lib
        from skypilot_tpu.observe import request_class
        from skypilot_tpu.serve import load_balancer as lb_lib

        if self.disagg:
            n_prefill, n_decode = self.disagg
            roles = (['prefill'] * n_prefill) + (['decode'] * n_decode)
        else:
            roles = [None] * self.replicas
        ports = [_free_port() for _ in roles]
        pool_urls: Dict[str, List[str]] = {'prefill': [], 'decode': []}
        for i, (role, port) in enumerate(zip(roles, ports)):
            env = {**os.environ, 'JAX_PLATFORMS': 'cpu',
                   # Enough prefix-cache entries that eviction noise
                   # doesn't mask the routing signal the churn
                   # scenario measures.
                   # Deliberately larger than the registry default
                   # (4): an explicit, validated bench setting — an
                   # operator-set value still wins.
                   'SKYTPU_ENGINE_PREFIX_CACHE': knobs.raw(
                       'SKYTPU_ENGINE_PREFIX_CACHE', default='16'),
                   'SKYTPU_OBSERVE_DB': os.path.join(
                       self.run_dir, f'replica-{i}.db')}
            handoff_port = None
            if role is not None:
                from skypilot_tpu.serve.disagg import handoff
                env['SKYTPU_ENGINE_ROLE'] = role
                # Handoffs must never pay an XLA compile mid-run.
                env['SKYTPU_ENGINE_WARM_DISAGG'] = '1'
                # Decode replicas listen at the fixed-offset handoff
                # port the LB derives from their URL; prefill
                # replicas need no receiver.
                handoff_port = (port + handoff.HANDOFF_PORT_OFFSET
                                if role == 'decode' else 0)
                pool_urls[role].append(f'http://127.0.0.1:{port}')
            self._procs.append(subprocess.Popen(
                self._engine_cmd(port, handoff_port=handoff_port),
                stdout=sys.stderr, stderr=sys.stderr, env=env))
        urls = [f'http://127.0.0.1:{p}' for p in ports]
        self._urls = urls
        self._pool_urls = pool_urls

        # Warm up every replica before the LB fronts it.
        from skypilot_tpu.loadgen import client as client_lib
        await asyncio.gather(*(
            client_lib.wait_ready(u, path='/health',
                                  timeout_s=self.warmup_timeout)
            for u in urls))

        self._scraper = scrape.Scraper(timeout=3.0,
                                       staleness_seconds=10.0)
        # Short SLO windows sized to a seconds-long run, goodput kinds
        # included — the scorecard's burn columns come from here.
        specs = [slo_lib.SLOSpec(kind='availability', objective=0.9,
                                 fast_window=10.0, slow_window=30.0,
                                 fast_burn=1.5, slow_burn=1.0)]
        specs += [slo_lib.SLOSpec(kind=kind, objective=0.9,
                                  fast_window=10.0, slow_window=30.0,
                                  fast_burn=2.0, slow_burn=1.0)
                  for kind in request_class.GOODPUT_KINDS]
        if self.disagg:
            # Per-stage kinds over role-tagged targets — same wiring
            # as a disagg service controller.
            specs += [
                slo_lib.SLOSpec(kind='prefill_queue', objective=0.9,
                                threshold_seconds=2.5,
                                fast_window=10.0, slow_window=30.0,
                                fast_burn=2.0, slow_burn=1.0),
                slo_lib.SLOSpec(kind='decode_ttft', objective=0.9,
                                threshold_seconds=1.0,
                                fast_window=10.0, slow_window=30.0,
                                fast_burn=2.0, slow_burn=1.0),
            ]
        self._slo_engine = slo_lib.SLOEngine(specs, entity='loadgen')
        # Cost meter wired exactly as the service controller wires it:
        # every scrape target is a metered replica (pool from the role
        # segment), priced once from the catalog at the knob-selected
        # price class, accrued per scrape round. Short join window — a
        # loadgen run is seconds long.
        self._cost_meter = costs_lib.CostMeter(entity='loadgen',
                                               join_window=600.0)
        self._lb = lb_lib.LoadBalancer(self.policy,
                                       service_name='loadgen')
        self._lb.attach_fleet(self._scraper, self._slo_engine,
                              self._cost_meter)
        if self.disagg:
            # Single-stage traffic (short prompts, control paths)
            # rides the decode pool; eligible long-prompt traffic
            # routes two-stage through the PoolRouter.
            self._lb.set_ready_replicas(pool_urls['decode'])
            self._lb.set_pool_replicas(pool_urls['prefill'],
                                       pool_urls['decode'])
            targets = []
            for role in ('prefill', 'decode'):
                targets += [
                    scrape.Target(f'loadgen/{role}/{i}', u)
                    for i, u in enumerate(pool_urls[role])]
            self._scraper.set_targets(targets)
            for t in targets:
                self._cost_meter.register(t.entity,
                                          t.entity.split('/')[1])
        else:
            self._lb.set_ready_replicas(urls)
            targets = [scrape.Target(f'loadgen/{i}', u)
                       for i, u in enumerate(urls)]
            self._scraper.set_targets(targets)
            for t in targets:
                self._cost_meter.register(t.entity, 'serve')

        lb = self._lb

        # SHADOW elastic controller (docs/ELASTIC.md): the stack's
        # replica set is fixed, but a PoolController per pool watches
        # the same scraped queue-depth signal a live deployment would
        # scale on and journals every decision — the scorecard's
        # scale_events column, replayable against the schedule hash.
        # No hooks: targets are published, replicas never move.
        from skypilot_tpu.elastic import controller as elastic_ctl
        from skypilot_tpu.elastic import signals as elastic_signals
        from skypilot_tpu.elastic import spec as elastic_spec
        self._elastic = elastic_ctl.ElasticController(interval=1.0)

        def _queue_probe(members):
            def probe():
                snap = self._scraper.saturation_snapshot()
                depths = [sat.queue_depth for u, sat in snap.items()
                          if u in members]
                if not depths:
                    return None
                return float(sum(depths))
            return elastic_signals.callback(probe)

        shadow_pools = ([('prefill', pool_urls['prefill']),
                         ('decode', pool_urls['decode'])]
                        if self.disagg else [('serve', urls)])
        for pool_name, members in shadow_pools:
            self._elastic.register(elastic_spec.ElasticSpec(
                pool=pool_name, signal=_queue_probe(set(members)),
                target_per_unit=4.0, min_units=1,
                max_units=2 * max(1, len(members)),
                initial_units=len(members),
                # Every shadow decision carries its projected $/hour
                # delta — the cost meter prices it, the journal event
                # records it.
                cost_delta=self._cost_meter.projector(pool_name)))

        def on_round(s):
            snap = s.saturation_snapshot()
            lb.set_replica_saturation(
                {u: sat.queue_depth for u, sat in snap.items()})
            self._slo_engine.evaluate()
            self._cost_meter.accrue()
            self._cost_meter.evaluate()
            self._elastic.run_once()

        self._scrape_loop = scrape.ScrapeLoop(
            self._scraper, interval=self.scrape_interval,
            on_round=on_round)
        self._runner = web.AppRunner(self._lb.build_app())
        await self._runner.setup()
        await web.TCPSite(self._runner, '127.0.0.1',
                          self.lb_port).start()
        self._scrape_loop.start()
        self.started_unix = time.time()
        return self

    async def __aexit__(self, *exc) -> None:
        if self._scrape_loop is not None:
            self._scrape_loop.stop()
        if self._runner is not None:
            await self._runner.cleanup()
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

    def reset_routing(self) -> None:
        """Simulate an LB restart's routing-state loss: swap in a
        FRESH policy instance — in-flight counts gone, hash ring
        rebuilt from nothing but the replica set, exactly what a
        restarted LB process starts from. The churn scenario measures
        whether prefix hit rate survives this."""
        from skypilot_tpu.utils import registry
        fresh = registry.LB_POLICY_REGISTRY.type_from_str(
            self.policy)()
        fresh.set_ready_replicas(self._pool_urls['decode']
                                 if self.disagg else self._urls)
        self._lb.policy = fresh
        if self.disagg:
            from skypilot_tpu.serve import load_balancing_policies
            router = load_balancing_policies.PoolRouter()
            router.set_pools(self._pool_urls['prefill'],
                             self._pool_urls['decode'])
            self._lb._pools = router  # pylint: disable=protected-access

    # ------------------------------------------------------- evidence
    def settle(self) -> None:
        """One final synchronous scrape round + SLO evaluation so the
        scorecard reads counters that include the run's tail."""
        if self._scrape_loop is not None:
            self._scrape_loop.run_once()

    async def fleet_metrics(self) -> str:
        import aiohttp
        async with aiohttp.ClientSession() as session:
            async with session.get(
                    f'{self.lb_url}/-/fleet/metrics') as resp:
                return await resp.text()

    async def fleet_status(self) -> Dict[str, Any]:
        import aiohttp
        async with aiohttp.ClientSession() as session:
            async with session.get(
                    f'{self.lb_url}/-/fleet/status') as resp:
                return await resp.json()

    def slo_events(self) -> List[Dict[str, Any]]:
        """This run's slo_* journal events — the evidence the
        scorecard's burn columns must agree with."""
        from skypilot_tpu.observe import journal
        events = journal.query(since=self.started_unix - 1.0,
                               entity_scope='loadgen')
        return [e for e in events
                if str(e.get('kind', '')).startswith('slo_')]

    def scale_events(self) -> List[Dict[str, Any]]:
        """This run's ``elastic_decision`` journal events — the
        scorecard's scale-events column: every controller reaction to
        the offered ramp, replayable against the schedule hash."""
        from skypilot_tpu.observe import journal
        return journal.query(kind='elastic_decision',
                             since=self.started_unix - 1.0)

    def cost_summary(self) -> Dict[str, Any]:
        """The cost meter's windowed summary over the whole run — the
        scorecard's cost section ($/token, spot discount, per-pool
        dollars), priced entirely through observe/costs.py."""
        window = time.time() - self.started_unix + 5.0
        return self._cost_meter.summary(window=window)


# ------------------------------------------------------------- routing

def routing_drill(seed: int, replicas: int = 3, sessions: int = 300,
                  requests: int = 3000, zipf_a: float = 1.1,
                  hold: int = 16,
                  churn_schedule: Optional[List[int]] = None
                  ) -> Dict[str, Any]:
    """The consistent-hash proof, against the real policy objects.

    Drives ``requests`` Zipf-popular session picks through a
    PrefixAffinityPolicy while requests stay in flight for ``hold``
    steps, then:

      * RESTART: builds a FRESH policy (what an LB restart leaves —
        no in-flight state survives) over the same replica set and
        checks each session's post-restart home against the replica
        that served MOST of its loaded-run traffic. The stability
        fraction is the headline number (>= 0.9 is the contract —
        only bounded-load spill traffic may move).
      * LOAD BOUND: at every loaded pick, verifies the chosen
        replica's in-flight count stayed within the policy's
        capacity ceil(c * (total+1) / n).
      * CHURN: removes each replica in ``churn_schedule`` (default:
        the last) and checks that only sessions homed on the removed
        replica remap.
    """
    from skypilot_tpu.serve import load_balancing_policies as lb_pol

    rng = random.Random(seed ^ 0x5E551084)
    urls = [f'http://replica-{i}' for i in range(replicas)]
    session_ids = [f'drill/s{i:04d}' for i in range(sessions)]
    weights = [1.0 / (k + 1) ** zipf_a for k in range(sessions)]

    policy = lb_pol.PrefixAffinityPolicy()
    policy.set_ready_replicas(urls)
    in_flight: List[tuple] = []          # heap on completion step
    observed: Dict[str, collections.Counter] = {
        s: collections.Counter() for s in session_ids}
    bound_violations = 0
    max_load_ratio = 0.0
    for step in range(requests):
        while in_flight and in_flight[0][0] <= step:
            policy.request_finished(heapq.heappop(in_flight)[1])
        session = rng.choices(session_ids, weights=weights)[0]
        # Capacity BEFORE the pick — the bound select() must honor.
        with policy._lock:  # pylint: disable=protected-access
            total = sum(policy._in_flight.get(u, 0) for u in urls)
            capacity = math.ceil(policy.LOAD_BOUND * (total + 1) /
                                 len(urls))
        url = policy.select(session)
        load = policy._in_flight.get(url, 0)  # pylint: disable=protected-access
        if load + 1 > capacity:
            bound_violations += 1
        if total:
            max_load_ratio = max(
                max_load_ratio,
                (load + 1) / ((total + 1) / len(urls)))
        policy.request_started(url)
        heapq.heappush(in_flight,
                       (step + 1 + rng.randrange(hold), url))
        observed[session][url] += 1

    active = {s: c for s, c in observed.items() if c}
    homes = {s: c.most_common(1)[0][0] for s, c in active.items()}

    # RESTART: a fresh policy carries zero in-flight state — exactly
    # what survives an LB restart (nothing but the replica set).
    restarted = lb_pol.PrefixAffinityPolicy()
    restarted.set_ready_replicas(urls)
    stable = sum(1 for s, home in homes.items()
                 if restarted.select(s) == home)
    stability = stable / len(homes) if homes else 1.0

    # CHURN: drop a replica; only its sessions may remap.
    gone = urls[(churn_schedule or [replicas - 1])[0]]
    churned = lb_pol.PrefixAffinityPolicy()
    churned.set_ready_replicas([u for u in urls if u != gone])
    kept = [s for s, home in homes.items() if home != gone]
    kept_stable = sum(1 for s in kept if churned.select(s) == homes[s])
    return {
        'replicas': replicas,
        'sessions': len(homes),
        'requests': requests,
        'zipf_a': zipf_a,
        'restart_stability': round(stability, 4),
        'load_bound': lb_pol.PrefixAffinityPolicy.LOAD_BOUND,
        'bound_violations': bound_violations,
        'max_load_vs_mean': round(max_load_ratio, 3),
        'churn_removed': gone,
        'churn_unrelated_kept': (round(kept_stable / len(kept), 4)
                                 if kept else 1.0),
    }
