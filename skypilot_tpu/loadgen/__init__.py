"""Replayable multi-tenant traffic harness with fleet-attributed
per-class SLO scorecards.

ROADMAP item 3 said it plainly: scale claims were asserted, not
measured — every latency number so far was a client-side guess. This
package closes that gap with two disciplines borrowed from the
serving-infrastructure literature (PAPERS.md — the Google ads serving
paper's continuous class-attributed load measurement; the Gemma-on-TPU
comparison's per-class TTFT/TPOT reporting):

  1. REPLAYABLE OFFERED LOAD. One seed derives everything — tenants,
     sessions, Zipfian session popularity, prompt/prefix content,
     request classes, diurnal + spike arrival times — into a fully
     materialized request schedule BEFORE a single byte is sent
     (:mod:`~skypilot_tpu.loadgen.schedule`). The schedule's sha256
     is the replay contract: same seed -> byte-identical schedule,
     regardless of client concurrency, machine, or how the run went.

  2. FLEET-ATTRIBUTED SCORING. The harness never grades itself with
     client stopwatches. Each request carries a declared class
     (``X-Skytpu-Class``, clamped through the closed registry) and a
     session id (``X-Skytpu-Session``, the consistent-hash routing
     key); the scorecard's per-class TTFT/TPOT quantiles, goodput and
     SLO burn columns come from the PR-9 fleet plane —
     ``/-/fleet/metrics`` + ``/-/fleet/status`` — merged with the
     harness's own offered-load truth (what it sent, per class, per
     phase) in :mod:`~skypilot_tpu.loadgen.report`.

Entry point::

    python -m skypilot_tpu.loadgen --seed 7 --profile smoke \
        --local-stack 2 --report scorecard.json

``--local-stack N`` spawns N CPU engine replicas behind an in-process
LoadBalancer wired exactly as the service controller wires it
(:mod:`~skypilot_tpu.loadgen.harness`); ``--base-url`` points at any
live LB instead. The checked-in artifact (LOADGEN_LAST_GOOD.json) and
``SKYTPU_BENCH_METRIC=loadgen`` (bench.py) make the harness the
CPU-proxy regression tripwire for every future serving PR.
"""
from skypilot_tpu.loadgen.schedule import (PROFILES, Profile,
                                           RequestSpec, build_schedule,
                                           schedule_hash)

__all__ = ['PROFILES', 'Profile', 'RequestSpec', 'build_schedule',
           'schedule_hash']
