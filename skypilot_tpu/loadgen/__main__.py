"""Traffic-harness CLI: ``python -m skypilot_tpu.loadgen``.

Typical runs::

  # Bit-replayable schedule only (no network) — print the hash:
  python -m skypilot_tpu.loadgen --seed 7 --profile smoke --dry-run

  # Full scorecard against a self-spawned 2-replica CPU stack:
  python -m skypilot_tpu.loadgen --seed 7 --profile smoke \
      --local-stack 2 --report scorecard.json

  # Against a live serve LB:
  python -m skypilot_tpu.loadgen --seed 7 --profile small \
      --base-url http://127.0.0.1:8080 --report scorecard.json

Exit codes: 0 ok, 1 run failed, 2 usage error.
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
from typing import Any, Dict, Optional

from skypilot_tpu.loadgen import schedule as schedule_lib
from skypilot_tpu.utils import knobs


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog='python -m skypilot_tpu.loadgen',
        description='Seeded, replayable multi-tenant traffic harness '
                    'with fleet-attributed per-class SLO scorecards.')
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--profile', default='smoke',
                        help=f'one of {sorted(schedule_lib.PROFILES)}')
    parser.add_argument('--requests', type=int, default=None,
                        help='override the profile request count '
                             '(changes the schedule hash)')
    parser.add_argument('--duration', type=float, default=None,
                        help='override the profile duration seconds')
    parser.add_argument('--workers', type=int, default=4,
                        help='max in-flight client requests')
    parser.add_argument('--time-scale', type=float, default=1.0,
                        help='multiply arrival offsets (<1 compresses '
                             'the run)')
    parser.add_argument('--base-url', default=None,
                        help='drive a live serve LB at this URL')
    parser.add_argument('--local-stack', type=int, default=0,
                        metavar='N',
                        help='spawn N local CPU engine replicas '
                             'behind an in-process LB and drive those')
    parser.add_argument('--disagg', default=None, metavar='P+D',
                        help="disaggregate the local stack into P "
                             "prefill + D decode replicas (e.g. "
                             "'1+2'); implies --local-stack P+D and "
                             'two-stage KV-handoff routing')
    parser.add_argument('--model', default='llama-debug',
                        help='model for --local-stack replicas')
    parser.add_argument('--policy', default='prefix_affinity',
                        help='LB policy for --local-stack')
    parser.add_argument('--run-dir', default=None,
                        help='scratch dir for --local-stack observe '
                             'DBs (default: a fresh temp dir)')
    parser.add_argument('--report', default=None,
                        help='write the scorecard JSON here')
    parser.add_argument('--dry-run', action='store_true',
                        help='build + hash the schedule, no traffic')
    parser.add_argument('--no-routing-drill', action='store_true',
                        help='skip the consistent-hash routing drill')
    parser.add_argument('--no-churn', action='store_true',
                        help='skip the mid-run LB-restart churn '
                             'scenario (--local-stack only)')
    return parser


async def _run_local(args, profile, schedule) -> Dict[str, Any]:
    import dataclasses

    from skypilot_tpu.loadgen import client as client_lib
    from skypilot_tpu.loadgen import harness as harness_lib
    from skypilot_tpu.loadgen import report as report_lib

    churn_on = not args.no_churn and len(schedule) >= 4
    async with harness_lib.LocalStack(
            profile, replicas=args.local_stack, run_dir=args.run_dir,
            model=args.model, policy=args.policy,
            disagg=args.disagg_pools) as stack:
        await client_lib.wait_ready(stack.lb_url)
        churn: Dict[str, Any] = {}
        if churn_on:
            # Replica-churn schedule: run the first half, RESTART the
            # LB's routing state (fresh policy — what a real restart
            # discards), run the second half, and diff the fleet's
            # prefix-hit counters across the cut. A restart-stable
            # ring keeps sessions on the replicas that hold their
            # prefix snapshots, so the phase-2 hit rate must not
            # collapse.
            half = len(schedule) // 2
            first, second = schedule[:half], schedule[half:]
            rebase = second[0].t
            second = [dataclasses.replace(s, t=round(s.t - rebase, 6))
                      for s in second]
            run1 = await client_lib.run_schedule(
                stack.lb_url, first, workers=args.workers,
                time_scale=args.time_scale)
            stack.settle()
            h1, m1 = report_lib.prefix_counts(
                await stack.fleet_metrics())
            stack.reset_routing()
            run2 = await client_lib.run_schedule(
                stack.lb_url, second, workers=args.workers,
                time_scale=args.time_scale)
            run = client_lib.RunResult(
                started_at=run1.started_at,
                wall_s=run1.wall_s + run2.wall_s,
                results=run1.results + run2.results)
            stack.settle()
            h2, m2 = report_lib.prefix_counts(
                await stack.fleet_metrics())

            def rate(h, m):
                return round(h / (h + m), 4) if h + m else None

            churn = {
                'requests_before_restart': len(first),
                'requests_after_restart': len(second),
                'phase1': {'hits': h1, 'misses': m1,
                           'hit_rate': rate(h1, m1)},
                'phase2': {'hits': h2 - h1, 'misses': m2 - m1,
                           'hit_rate': rate(h2 - h1, m2 - m1)},
            }
        else:
            run = await client_lib.run_schedule(
                stack.lb_url, schedule, workers=args.workers,
                time_scale=args.time_scale)
            # One settling scrape round so the final requests'
            # publishes are in the merged view the scorecard reads.
            stack.settle()
        return {
            'run': run,
            'churn': churn,
            'fleet_metrics_text': await stack.fleet_metrics(),
            'fleet_status': await stack.fleet_status(),
            'slo_events': stack.slo_events(),
            'scale_events': stack.scale_events(),
            'cost': stack.cost_summary(),
            'stack': {'mode': 'local', 'replicas': args.local_stack,
                      'model': args.model, 'policy': args.policy,
                      'disagg': args.disagg},
        }


async def _run_remote(args, schedule) -> Dict[str, Any]:
    import aiohttp

    from skypilot_tpu.loadgen import client as client_lib

    base = args.base_url.rstrip('/')
    run = await client_lib.run_schedule(
        base, schedule, workers=args.workers,
        time_scale=args.time_scale)
    out: Dict[str, Any] = {
        'run': run,
        'fleet_metrics_text': '',
        'fleet_status': None,
        'stack': {'mode': 'remote', 'base_url': base},
    }
    async with aiohttp.ClientSession() as session:
        try:
            async with session.get(base + '/-/fleet/metrics') as resp:
                if resp.status == 200:
                    out['fleet_metrics_text'] = await resp.text()
            async with session.get(base + '/-/fleet/status') as resp:
                if resp.status == 200:
                    out['fleet_status'] = await resp.json()
        except (OSError, aiohttp.ClientError) as e:
            print(f'loadgen: fleet endpoints unavailable ({e}); '
                  f'scorecard will carry offered/client planes only',
                  file=sys.stderr)
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    args.disagg_pools = None
    if args.disagg:
        try:
            p, _, d = args.disagg.partition('+')
            args.disagg_pools = (int(p), int(d))
            if min(args.disagg_pools) < 1:
                raise ValueError
        except ValueError:
            print(f"loadgen: --disagg wants 'P+D' with P,D >= 1, got "
                  f'{args.disagg!r}', file=sys.stderr)
            return 2
        if args.base_url:
            print('loadgen: --disagg needs a local stack',
                  file=sys.stderr)
            return 2
        args.local_stack = sum(args.disagg_pools)
    if args.base_url and args.local_stack:
        print('loadgen: --base-url and --local-stack are exclusive',
              file=sys.stderr)
        return 2
    try:
        profile = schedule_lib.resolve_profile(
            args.profile, requests=args.requests,
            duration_s=args.duration)
        schedule = schedule_lib.build_schedule(profile, args.seed)
    except ValueError as e:
        print(f'loadgen: {e}', file=sys.stderr)
        return 2
    sched_hash = schedule_lib.schedule_hash(schedule)
    if args.dry_run:
        print(json.dumps({
            'profile': profile.name, 'seed': args.seed,
            'requests': len(schedule), 'schedule_hash': sched_hash,
            'offered': schedule_lib.offered_truth(schedule),
        }, indent=1, sort_keys=True))
        return 0

    if not args.base_url and not args.local_stack:
        print('loadgen: need --base-url, --local-stack N or --dry-run',
              file=sys.stderr)
        return 2

    routing: Optional[Dict[str, Any]] = None
    if not args.no_routing_drill:
        from skypilot_tpu.loadgen import harness as harness_lib
        routing = harness_lib.routing_drill(args.seed)

    if args.local_stack:
        if args.run_dir is None:
            args.run_dir = tempfile.mkdtemp(prefix='skytpu-loadgen-')
        # The harness process's own journal/tsdb live in the run dir
        # unless the operator already pinned a DB.
        if not knobs.is_set('SKYTPU_OBSERVE_DB'):
            knobs.export('SKYTPU_OBSERVE_DB',
                         os.path.join(args.run_dir, 'observe.db'))
        evidence = asyncio.run(_run_local(args, profile, schedule))
    else:
        evidence = asyncio.run(_run_remote(args, schedule))

    churn = evidence.get('churn')
    if churn:
        routing = dict(routing or {})
        routing['live_churn'] = churn

    from skypilot_tpu.loadgen import report as report_lib
    doc = report_lib.build_scorecard(
        profile=profile, seed=args.seed, schedule=schedule,
        run=evidence['run'],
        fleet_metrics_text=evidence.get('fleet_metrics_text', ''),
        fleet_status=evidence.get('fleet_status'),
        slo_events=evidence.get('slo_events'),
        scale_events=evidence.get('scale_events'),
        routing=routing, stack=evidence.get('stack'),
        cost=evidence.get('cost'))
    if args.report:
        report_lib.write_scorecard(doc, args.report)
        print(f'loadgen: wrote scorecard to {args.report}',
              file=sys.stderr)
    run = evidence['run']
    summary = {
        'schedule_hash': sched_hash,
        'completed': run.completed(),
        'errors': run.errors(),
    }
    fleet = doc.get('fleet') or {}
    for cls, row in sorted((fleet.get('by_class') or {}).items()):
        if row.get('goodput') is not None:
            summary[f'{cls}_goodput'] = row['goodput']
    print(json.dumps(summary, sort_keys=True))
    return 0 if run.errors() == 0 else 1


if __name__ == '__main__':
    sys.exit(main())
