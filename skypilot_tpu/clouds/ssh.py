"""BYO-machine pools: existing SSH-reachable hosts as a cloud.

Reference analog: sky/ssh_node_pools/ (pools from
~/.sky/ssh_node_pools.yaml). Pools here are TPU-first: a pool declares the
slice its hosts form (reserved TPU-VMs managed outside any cloud console,
lab machines, ...), and 'provisioning' is allocation from the pool:

~/.skytpu/ssh_node_pools.yaml:
    my-v4-pool:
      user: ubuntu
      identity_file: ~/.ssh/id_ed25519
      accelerator: tpu-v4-16        # optional: slice the hosts form
      hosts: [10.0.0.1, 10.0.0.2]

Each pool is a zone of the single 'ssh' region; allocation state lives in
~/.skytpu/ssh_pool_state.json so concurrent clusters can't double-book a
host.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

logger = sky_logging.init_logger(__name__)

SSH_REGION = 'ssh'
POOLS_PATH = '~/.skytpu/ssh_node_pools.yaml'


def load_pools() -> Dict[str, Dict[str, Any]]:
    import yaml
    path = os.path.expanduser(POOLS_PATH)
    if not os.path.exists(path):
        return {}
    with open(path, 'r', encoding='utf-8') as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ValueError(f'{POOLS_PATH} must map pool names to configs.')
    return data


@registry.CLOUD_REGISTRY.register(name='ssh')
class Ssh(cloud_lib.Cloud):
    """Pools of pre-existing SSH hosts behind the Cloud interface."""

    _REPR = 'SSH'

    @classmethod
    def unsupported_features(
            cls, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        return {
            cloud_lib.CloudImplementationFeatures.STOP:
                'BYO machines are not stopped; down releases them to the '
                'pool.',
            cloud_lib.CloudImplementationFeatures.AUTOSTOP:
                'autostop would stop machines this framework does not own.',
            cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
                'firewalling BYO machines is out of scope.',
        }

    # ------------------------------------------------------------------
    def _matching_pools(self, resources: 'resources_lib.Resources'
                        ) -> List[str]:
        from skypilot_tpu.provision.ssh import instance as ssh_instance
        sl = resources.tpu
        # Load both files once; the optimizer calls this several times per
        # launch attempt.
        pools = load_pools()
        alloc_state = ssh_instance.load_allocations()
        out = []
        for name, pool in pools.items():
            acc = pool.get('accelerator')
            if sl is not None:
                if acc is None:
                    continue
                from skypilot_tpu.tpu import topology
                try:
                    pool_sl = topology.parse_tpu_accelerator(str(acc))
                except Exception as e:  # pylint: disable=broad-except
                    # A malformed accelerator string silently hides the
                    # whole pool from matching — say which and why.
                    logger.debug(f'ssh pool {name!r}: unparseable '
                                 f'accelerator {acc!r} ({e}); skipping.')
                    continue
                if (pool_sl.generation != sl.generation or
                        pool_sl.num_chips != sl.num_chips):
                    continue
                needed = sl.total_hosts
            else:
                needed = 1
            free = ssh_instance.free_hosts(name, pool_cfg=pool,
                                           state=alloc_state)
            if len(free) >= needed:
                out.append(name)
        return out

    def regions_with_offering(self, resources: 'resources_lib.Resources'
                              ) -> List[cloud_lib.Region]:
        if resources.region not in (None, SSH_REGION):
            return []
        pools = self._matching_pools(resources)
        if resources.zone is not None:
            pools = [p for p in pools if p == resources.zone]
        if not pools:
            return []
        return [cloud_lib.Region(
            SSH_REGION, tuple(cloud_lib.Zone(p) for p in pools))]

    def zones_provision_loop(
            self, *, region: str, resources: 'resources_lib.Resources'
    ) -> Iterator[List[cloud_lib.Zone]]:
        del region
        for pool in self._matching_pools(resources):
            if resources.zone is not None and pool != resources.zone:
                continue
            yield [cloud_lib.Zone(pool)]

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Tuple[List['resources_lib.Resources'], List[str]]:
        if resources.region not in (None, SSH_REGION):
            return [], []
        pools = self._matching_pools(resources)
        if not pools:
            want = resources.tpu.name if resources.tpu else 'cpu'
            return [], [f'ssh: no pool with free capacity for {want} '
                        f'(pools: {sorted(load_pools()) or "none"})']
        return [resources.copy(cloud=self, region=SSH_REGION)], []

    def hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        del resources
        return 0.0   # sunk cost, like kubernetes

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', region: str,
            zones: Optional[List[str]], cluster_name: str) -> Dict[str, Any]:
        sl = resources.tpu
        return {
            'cloud': 'ssh',
            'pools': zones or list(load_pools()),
            # Per-slice host count: the provision layer multiplies by
            # num_slices itself (same contract as local.py).
            'num_hosts': sl.num_hosts if sl else 1,
            'num_slices': sl.num_slices if sl else 1,
            'chips_per_host': sl.chips_per_host if sl else 1,
            'cluster_name': cluster_name,
        }

    def validate_region_zone(self, region: Optional[str],
                             zone: Optional[str]
                             ) -> Tuple[Optional[str], Optional[str]]:
        if region is not None and region != SSH_REGION:
            raise ValueError(f"ssh cloud's region is {SSH_REGION!r}, got "
                             f'{region!r}.')
        if zone is not None and zone not in load_pools():
            raise ValueError(f'Unknown ssh pool {zone!r}; pools: '
                             f'{sorted(load_pools())}')
        return region, zone

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        try:
            pools = load_pools()
        except ValueError as e:
            return False, str(e)
        if not pools:
            return False, f'No pools configured in {POOLS_PATH}.'
        return True, None
