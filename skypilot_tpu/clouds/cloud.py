"""Abstract Cloud: capability probing, feasibility, deploy variables.

Reference analog: sky/clouds/cloud.py — `Cloud:140` with
`regions_with_offering:188`, `make_deploy_resources_variables:311`,
`get_feasible_launchable_resources:428`, `check_credentials:497`, and the
capability enum `CloudImplementationFeatures:33`.
"""
from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


class CloudImplementationFeatures(enum.Enum):
    """Capabilities a cloud may or may not implement.

    Reference analog: sky/clouds/cloud.py:33. The execution layer checks the
    requested features against `unsupported_features`; unsupported ones fail
    fast with a clear message instead of mid-provision.
    """
    MULTI_HOST = 'multi_host'
    MULTI_SLICE = 'multi_slice'          # DCN-connected slices (MEGASCALE)
    SPOT_INSTANCE = 'spot_instance'
    STOP = 'stop'
    AUTOSTOP = 'autostop'
    OPEN_PORTS = 'open_ports'
    STORAGE_MOUNTING = 'storage_mounting'
    QUEUED_RESOURCES = 'queued_resources'  # GCP queued-resources / DWS


@dataclasses.dataclass(frozen=True)
class Zone:
    name: str


@dataclasses.dataclass(frozen=True)
class Region:
    name: str
    zones: Tuple[Zone, ...] = ()


class Cloud:
    """Base class. Subclasses register via @registry.CLOUD_REGISTRY.register."""

    _REPR = 'Cloud'

    # ------------------------------------------------------------------
    # Identity / capability
    # ------------------------------------------------------------------
    @classmethod
    def canonical_name(cls) -> str:
        return cls.__name__.lower()

    def __repr__(self) -> str:
        return self._REPR

    def is_same_cloud(self, other: 'Cloud') -> bool:
        return isinstance(other, type(self))

    @classmethod
    def unsupported_features(
            cls, resources: 'resources_lib.Resources'
    ) -> Dict[CloudImplementationFeatures, str]:
        """Feature -> reason string for everything this cloud cannot do."""
        raise NotImplementedError

    @classmethod
    def check_features_are_supported(
            cls, resources: 'resources_lib.Resources',
            requested: Set[CloudImplementationFeatures]) -> None:
        unsupported = cls.unsupported_features(resources)
        bad = {f: unsupported[f] for f in requested if f in unsupported}
        if bad:
            table = '; '.join(f'{f.value}: {reason}'
                              for f, reason in bad.items())
            raise NotImplementedError(
                f'{cls.__name__} does not support the requested features — '
                f'{table}')

    # ------------------------------------------------------------------
    # Offerings / feasibility
    # ------------------------------------------------------------------
    def regions_with_offering(self, resources: 'resources_lib.Resources'
                              ) -> List[Region]:
        """Regions (with zones) that can host `resources`, cheapest first.

        Reference analog: sky/clouds/cloud.py:188.
        """
        raise NotImplementedError

    def zones_provision_loop(
            self, *, region: str,
            resources: 'resources_lib.Resources') -> Iterator[List[Zone]]:
        """Yield zone batches to try within a region during failover."""
        raise NotImplementedError

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Tuple[List['resources_lib.Resources'], List[str]]:
        """(feasible concrete candidates, fuzzy near-miss names).

        Reference analog: sky/clouds/cloud.py:428.
        """
        raise NotImplementedError

    def validate_region_zone(
            self, region: typing.Optional[str], zone: typing.Optional[str]
    ) -> Tuple[typing.Optional[str], typing.Optional[str]]:
        """Validate/canonicalize a (region, zone) pair for this cloud."""
        from skypilot_tpu.catalog import tpu_catalog
        return tpu_catalog.validate_region_zone(region, zone)

    # ------------------------------------------------------------------
    # Pricing
    # ------------------------------------------------------------------
    def instance_cost(self, resources: 'resources_lib.Resources',
                      seconds: float) -> float:
        hours = seconds / 3600.0
        return self.hourly_cost(resources) * hours

    def hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        raise NotImplementedError

    def get_egress_cost(self, num_gigabytes: float) -> float:
        """Egress $ for moving data out of this cloud."""
        return 0.0

    # ------------------------------------------------------------------
    # Deployment
    # ------------------------------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', region: str,
            zones: Optional[List[str]],
            cluster_name: str) -> Dict[str, Any]:
        """Cloud-specific variables consumed by the provisioner.

        Reference analog: sky/clouds/cloud.py:311 +
        sky/clouds/gcp.py:509-545 (tpu_vm/tpu_type/tpu_node_name vars).
        """
        raise NotImplementedError

    # ------------------------------------------------------------------
    # Credentials
    # ------------------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        """(ok, reason-if-not). Reference analog: cloud.py:497."""
        raise NotImplementedError

    def get_credential_file_mounts(self) -> Dict[str, str]:
        """Local credential files to sync onto clusters (dst -> src)."""
        return {}


def cloud_in_iterable(cloud: Cloud, clouds: typing.Iterable[Cloud]) -> bool:
    return any(cloud.is_same_cloud(c) for c in clouds)


def get_cloud(name: str) -> Cloud:
    cloud = registry.CLOUD_REGISTRY.from_str(name)
    assert cloud is not None
    return cloud
