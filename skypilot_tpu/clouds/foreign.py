"""Clouds the reference supports but this TPU-first framework does not run.

Reference analog: sky/clouds/ registers ~20 provider classes (aws.py,
azure.py, oci.py, ...). Deliberate scope decision (SURVEY §2.2 row
"other 16+ clouds": no): those providers have no TPUs, so instead of
porting dead provisioners we parse their names into an opaque
`ForeignCloud`. Reference recipes that pin `cloud: aws` therefore load
cleanly and fail at *optimize* time with a swap-to-TPU hint — the same
treatment GPU accelerator strings get (resources.py `_set_accelerators`) —
rather than exploding at parse time with "unknown cloud".
"""
from __future__ import annotations

import typing
from typing import Dict, List, Optional, Tuple

from skypilot_tpu.clouds import cloud as cloud_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

# Provider names accepted by the reference (sky/clouds/__init__.py plus
# registry aliases). Anything else is still a hard parse error — typos in
# `cloud:` must not silently become "infeasible".
FOREIGN_CLOUD_NAMES = frozenset({
    'aws', 'azure', 'oci', 'ibm', 'lambda', 'lambdacloud', 'scp',
    'runpod', 'vast', 'vsphere', 'cudo', 'paperspace', 'do',
    'digitalocean', 'fluidstack', 'nebius', 'hyperbolic', 'seeweb',
    'coreweave', 'shadeform',
})


class ForeignCloud(cloud_lib.Cloud):
    """A recognized-but-unsupported provider: parses, never feasible."""

    def __init__(self, name: str):
        self._name = name.lower()
        self._REPR = self._name.upper() if len(self._name) <= 3 \
            else self._name.capitalize()

    @classmethod
    def canonical_name(cls) -> str:
        return 'foreign'

    def is_same_cloud(self, other: 'cloud_lib.Cloud') -> bool:
        return isinstance(other, ForeignCloud) and other._name == self._name

    @classmethod
    def unsupported_features(
            cls, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        return {f: 'provider outside the TPU-first scope'
                for f in cloud_lib.CloudImplementationFeatures}

    def validate_region_zone(
            self, region: Optional[str],
            zone: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
        # Opaque passthrough: we cannot validate another provider's names.
        return region, zone

    def regions_with_offering(
            self, resources: 'resources_lib.Resources'
    ) -> List[cloud_lib.Region]:
        return []

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Tuple[List['resources_lib.Resources'], List[str]]:
        return [], [
            f'cloud {self._name!r} is outside this framework\'s TPU-first '
            f'scope — swap to `cloud: gcp` (or kubernetes) with a '
            f'`tpu-v5p-8`-style accelerator'
        ]

    def __deepcopy__(self, memo):
        return ForeignCloud(self._name)
