"""Per-cloud policy: feasibility, deploy variables, credentials.

Reference analog: sky/clouds/ (abstract Cloud at sky/clouds/cloud.py:140).
"""
from skypilot_tpu.clouds.cloud import (  # noqa: F401
    Cloud,
    CloudImplementationFeatures,
    Region,
    Zone,
)
from skypilot_tpu.clouds.gcp import GCP  # noqa: F401
from skypilot_tpu.clouds.kubernetes import Kubernetes  # noqa: F401
from skypilot_tpu.clouds.local import Local  # noqa: F401
from skypilot_tpu.clouds.ssh import Ssh  # noqa: F401
