"""Local "cloud": fabricated TPU slices backed by local processes.

This is the in-process fake cloud the test strategy requires (SURVEY.md §4
takeaway: "add a fake TPU provisioner ... as the equivalent of
`enable_all_clouds`"). Every slice "host" is a directory under
~/.skytpu/local_cloud/<cluster>/host<i> plus commands executed locally, so
the full launch→setup→gang-exec→logs→down path runs hermetically in CI with
zero cloud credentials. JAX jobs run on whatever local backend exists
(CPU with xla_force_host_platform_device_count, or the one real chip).

It intentionally implements the same Cloud/provision interfaces as GCP so
the backend cannot special-case it.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.tpu import topology
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

LOCAL_CLOUD_ROOT = os.path.expanduser('~/.skytpu/local_cloud')

# The fake capacity the local cloud advertises, mirroring the catalog's shape:
# every generation is available in one fake region with two zones (two zones
# so failover paths are exercisable by fault injection).
LOCAL_REGION = 'local'
LOCAL_ZONES = ('local-a', 'local-b')
# Cap fabricated slices so tests don't spawn hundreds of processes.
MAX_LOCAL_CHIPS = 64

# Fault injection hook: map zone name -> exception to raise at provision time
# (set by tests / chaos tooling via skypilot_tpu.provision.local.instance).
PROVISION_FAULTS: Dict[str, Any] = {}


@registry.CLOUD_REGISTRY.register
class Local(cloud_lib.Cloud):
    """Fabricated TPU slices on localhost (hermetic end-to-end testing)."""

    _REPR = 'Local'

    @classmethod
    def unsupported_features(
            cls, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        # Storage mounting IS supported: local-dir sources realize as
        # copies/symlinks/write-back caches under each fabricated host
        # (data/storage.py mount_command_for), making the MOUNT_CACHED
        # flush-barrier contract hermetically testable.
        return {}

    def regions_with_offering(self, resources: 'resources_lib.Resources'
                              ) -> List[cloud_lib.Region]:
        sl = resources.tpu
        if sl is None or sl.total_chips > MAX_LOCAL_CHIPS:
            return []
        if resources.region is not None and resources.region != LOCAL_REGION:
            return []
        zones = tuple(
            cloud_lib.Zone(z) for z in LOCAL_ZONES
            if resources.zone is None or resources.zone == z)
        return [cloud_lib.Region(LOCAL_REGION, zones)] if zones else []

    def zones_provision_loop(
            self, *, region: str, resources: 'resources_lib.Resources'
    ) -> Iterator[List[cloud_lib.Zone]]:
        del region
        for z in LOCAL_ZONES:
            if resources.zone is not None and z != resources.zone:
                continue
            yield [cloud_lib.Zone(z)]

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Tuple[List['resources_lib.Resources'], List[str]]:
        sl = resources.tpu
        if sl is None:
            return [], []
        if sl.total_chips > MAX_LOCAL_CHIPS:
            return [], [f'local supports ≤{MAX_LOCAL_CHIPS} chips']
        if resources.region is not None and resources.region != LOCAL_REGION:
            return [], []
        return [resources.copy(cloud=self, region=LOCAL_REGION)], []

    def hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        # Nominal nonzero pricing so the optimizer can rank local below
        # real clouds only when real clouds are enabled.
        sl = resources.tpu
        assert sl is not None
        per_chip = 0.01 if not resources.use_spot else 0.005
        return per_chip * sl.total_chips

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', region: str,
            zones: Optional[List[str]], cluster_name: str) -> Dict[str, Any]:
        sl = resources.tpu
        assert sl is not None
        return {
            'cloud': 'local',
            'region': region,
            'zones': zones or list(LOCAL_ZONES),
            'tpu_generation': sl.generation,
            'accelerator_type': sl.gcp_accelerator_type,
            'topology': sl.topology_str,
            'num_hosts': sl.num_hosts,
            'num_slices': sl.num_slices,
            'use_spot': resources.use_spot,
            'cluster_name': cluster_name,
            'root_dir': LOCAL_CLOUD_ROOT,
        }

    def validate_region_zone(self, region: Optional[str],
                             zone: Optional[str]
                             ) -> Tuple[Optional[str], Optional[str]]:
        if zone is not None:
            if zone not in LOCAL_ZONES:
                raise ValueError(
                    f'Zone {zone!r} unknown to local cloud; '
                    f'zones: {LOCAL_ZONES}')
            return LOCAL_REGION, zone
        if region is not None and region != LOCAL_REGION:
            raise ValueError(f'Local cloud has a single region '
                             f'{LOCAL_REGION!r}, got {region!r}.')
        return region, zone

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        return True, None
