"""GCP as a TPU cloud: feasibility, deploy vars, credentials.

Reference analog: sky/clouds/gcp.py — but where the reference buries TPU
handling in special cases of a GPU-centric cloud (`gcp.py:509-545` deploy
vars, `:717-741` TPU-VM pseudo-instance-type, `:1095-1101` spot-TPU cleanup
flag), here TPU slices are the primary schedulable resource and the deploy
variables speak slice language (accelerator_type, topology, hosts,
runtime_version, queued-resource usage).
"""
from __future__ import annotations

import os
import subprocess
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.catalog import tpu_catalog
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.tpu import topology
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

_CREDENTIAL_HINT = (
    'Run `gcloud auth application-default login`, or set '
    'GOOGLE_APPLICATION_CREDENTIALS to a service-account key.')

# Generations GCP exposes via the queued-resources API (required for v5p+
# and recommended for all multi-host slices).
_QUEUED_RESOURCE_GENERATIONS = frozenset({'v5e', 'v5p', 'v6e'})


@registry.CLOUD_REGISTRY.register
class GCP(cloud_lib.Cloud):
    """Google Cloud TPU slices (tpu.googleapis.com v2 API)."""

    _REPR = 'GCP'

    @classmethod
    def unsupported_features(
            cls, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        unsupported: Dict[cloud_lib.CloudImplementationFeatures, str] = {}
        sl = resources.tpu
        if sl is not None and not sl.gen.supports_stop:
            unsupported[cloud_lib.CloudImplementationFeatures.STOP] = (
                f'TPU {sl.generation} VMs cannot be stopped; only '
                f'terminated. Use `down` instead of `stop`.')
            unsupported[cloud_lib.CloudImplementationFeatures.AUTOSTOP] = (
                f'autostop requires stop support, unavailable on '
                f'{sl.generation}.')
        return unsupported

    # ------------------------------------------------------------------
    # Offerings
    # ------------------------------------------------------------------
    def regions_with_offering(self, resources: 'resources_lib.Resources'
                              ) -> List[cloud_lib.Region]:
        sl = resources.tpu
        assert sl is not None
        if resources.region is not None:
            region_names = [resources.region]
        else:
            region_names = tpu_catalog.get_regions(sl)
        regions = []
        for rname in region_names:
            zones = tpu_catalog.get_zones(sl, rname)
            if resources.zone is not None:
                zones = [z for z in zones if z == resources.zone]
            if zones:
                regions.append(
                    cloud_lib.Region(
                        rname, tuple(cloud_lib.Zone(z) for z in zones)))
        return regions

    def zones_provision_loop(
            self, *, region: str, resources: 'resources_lib.Resources'
    ) -> Iterator[List[cloud_lib.Zone]]:
        # TPU slices are zonal: try one zone at a time.
        sl = resources.tpu
        assert sl is not None
        for z in tpu_catalog.get_zones(sl, region):
            if resources.zone is not None and z != resources.zone:
                continue
            yield [cloud_lib.Zone(z)]

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Tuple[List['resources_lib.Resources'], List[str]]:
        if resources.accelerators is None:
            # CPU-only task: not a TPU slice; GCP TPU cloud offers nothing.
            return [], []
        sl = resources.tpu
        if sl is None:
            # GPU-era accelerator name: infeasible, suggest TPU swap-ins.
            fuzzy = [s.name for s in topology.legal_slices('v5e')[:4]]
            fuzzy += [s.name for s in topology.legal_slices('v5p')[:2]]
            return [], fuzzy
        if not tpu_catalog.accelerator_in_region_or_zone(
                sl, resources.region, resources.zone):
            return [], [f'{sl.name} in other regions']
        launchable = resources.copy(cloud=self)
        return [launchable], []

    # ------------------------------------------------------------------
    # Pricing
    # ------------------------------------------------------------------
    def hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        sl = resources.tpu
        assert sl is not None
        return tpu_catalog.get_hourly_cost(sl, use_spot=resources.use_spot,
                                           region=resources.region,
                                           zone=resources.zone)

    def get_egress_cost(self, num_gigabytes: float) -> float:
        # Approximate tiered GCP internet egress (analog: sky/clouds/gcp.py).
        if num_gigabytes <= 0:
            return 0.0
        if num_gigabytes <= 1024:
            return 0.12 * num_gigabytes
        if num_gigabytes <= 10240:
            return 0.11 * num_gigabytes
        return 0.08 * num_gigabytes

    # ------------------------------------------------------------------
    # Deploy variables
    # ------------------------------------------------------------------
    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', region: str,
            zones: Optional[List[str]], cluster_name: str) -> Dict[str, Any]:
        sl = resources.tpu
        assert sl is not None, 'GCP deploy requires a concrete TPU slice'
        args = resources.accelerator_args
        runtime_version = args.get('runtime_version',
                                   sl.gen.default_runtime_version)
        use_queued = bool(
            args.get('use_queued_resources',
                     sl.generation in _QUEUED_RESOURCE_GENERATIONS))
        return {
            'cloud': 'gcp',
            'region': region,
            'zones': zones or [],
            'tpu_generation': sl.generation,
            'accelerator_type': sl.gcp_accelerator_type,
            'topology': sl.topology_str,
            'num_hosts': sl.num_hosts,
            'num_slices': sl.num_slices,
            'runtime_version': runtime_version,
            'use_spot': resources.use_spot,
            'use_queued_resources': use_queued,
            'reserved': bool(args.get('reserved', False)),
            'disk_size_gb': resources.disk_size,
            'labels': resources.labels,
            'volumes_map': resources.volumes,
            'ports': resources.ports,
            'cluster_name': cluster_name,
            'project_id': os.environ.get('GOOGLE_CLOUD_PROJECT', ''),
            'network': args.get('network', 'default'),
        }

    # ------------------------------------------------------------------
    # Credentials
    # ------------------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        adc = os.environ.get('GOOGLE_APPLICATION_CREDENTIALS')
        if adc and os.path.exists(os.path.expanduser(adc)):
            return True, None
        default_adc = os.path.expanduser(
            '~/.config/gcloud/application_default_credentials.json')
        if os.path.exists(default_adc):
            return True, None
        try:
            proc = subprocess.run(
                ['gcloud', 'auth', 'list', '--format=value(account)'],
                capture_output=True, text=True, timeout=10, check=False)
            if proc.returncode == 0 and proc.stdout.strip():
                return True, None
        except (FileNotFoundError, subprocess.TimeoutExpired):
            pass
        return False, f'No GCP credentials found. {_CREDENTIAL_HINT}'

    def get_credential_file_mounts(self) -> Dict[str, str]:
        out = {}
        default_adc = os.path.expanduser(
            '~/.config/gcloud/application_default_credentials.json')
        if os.path.exists(default_adc):
            out['~/.config/gcloud/application_default_credentials.json'] = (
                default_adc)
        return out
