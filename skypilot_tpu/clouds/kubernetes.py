"""Kubernetes as a cloud: TPU slices as pod gangs on GKE node pools.

Reference analog: sky/clouds/kubernetes.py (:1264) + GKE TPU detection
(sky/clouds/utils/gcp_utils.py:43, provision/kubernetes/utils.py: label
keys `cloud.google.com/gke-tpu-accelerator` / `gke-tpu-topology`, resource
key `google.com/tpu`). Redesigned slice-first: one TPU slice = one gang of
pods (one pod per TPU host) pinned to a matching GKE TPU node pool; the
gang env (TPU_WORKER_ID / hostnames) comes from the same slice runtime as
TPU VMs, so jobs cannot tell the difference.

Feasibility is live, not catalog-based (reference kubernetes_catalog.py
pattern): `kubectl get nodes` label introspection decides which slice
shapes this cluster can host.
"""
from __future__ import annotations

import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

logger = sky_logging.init_logger(__name__)

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

# generation -> GKE accelerator label value (cloud.google.com/gke-tpu-accelerator)
GKE_TPU_ACCELERATOR = {
    'v4': 'tpu-v4-podslice',
    'v5e': 'tpu-v5-lite-podslice',
    'v5p': 'tpu-v5p-slice',
    'v6e': 'tpu-v6e-slice',
}
GENERATION_OF_GKE_ACCELERATOR = {v: k for k, v in GKE_TPU_ACCELERATOR.items()}

TPU_LABEL_KEY = 'cloud.google.com/gke-tpu-accelerator'
TPU_TOPOLOGY_LABEL_KEY = 'cloud.google.com/gke-tpu-topology'
TPU_RESOURCE_KEY = 'google.com/tpu'

KUBERNETES_REGION = 'kubernetes'


@registry.CLOUD_REGISTRY.register(aliases=['k8s'])
class Kubernetes(cloud_lib.Cloud):
    """GKE TPU node pools behind the standard Cloud interface."""

    _REPR = 'Kubernetes'

    @classmethod
    def unsupported_features(
            cls, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        return {
            cloud_lib.CloudImplementationFeatures.STOP:
                'pods are deleted, not stopped; re-launch to resume.',
            cloud_lib.CloudImplementationFeatures.AUTOSTOP:
                'use autodown (delete) — pods cannot stop.',
            cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
                'external exposure needs a Service/Ingress; not wired yet.',
        }

    # ------------------------------------------------------------------
    # Live cluster introspection (the "catalog")
    # ------------------------------------------------------------------
    @classmethod
    def _configured_context(cls) -> Optional[str]:
        from skypilot_tpu import config as config_lib
        return config_lib.get_nested(('kubernetes', 'context'), None)

    @classmethod
    def _tpu_node_pools(cls) -> List[Dict[str, Any]]:
        """[{generation, topology, chips_per_node, count}] from node labels.

        Uses the CONFIGURED context — feasibility must look at the same
        cluster provisioning will target, not whatever the kubeconfig's
        current context happens to be."""
        from skypilot_tpu.provision.kubernetes import instance as k8s_instance
        return k8s_instance.list_tpu_node_pools(cls._configured_context())

    def _fits(self, sl, pools: List[Dict[str, Any]]) -> bool:
        for pool in pools:
            if (pool['generation'] == sl.generation and
                    pool['topology'] == sl.topology_str and
                    pool['count'] >= sl.num_hosts * sl.num_slices):
                return True
        return False

    def regions_with_offering(self, resources: 'resources_lib.Resources'
                              ) -> List[cloud_lib.Region]:
        sl = resources.tpu
        if sl is None:
            return []
        if resources.region not in (None, KUBERNETES_REGION):
            return []
        try:
            pools = self._tpu_node_pools()
        except Exception as e:  # pylint: disable=broad-except
            # No kubectl / unreachable cluster just means "no offering
            # here", but silently so makes `skytpu check` undebuggable.
            logger.debug(f'kubernetes node-pool introspection failed: '
                         f'{e}')
            return []
        if not self._fits(sl, pools):
            return []
        return [cloud_lib.Region(KUBERNETES_REGION,
                                 (cloud_lib.Zone(KUBERNETES_REGION),))]

    def zones_provision_loop(
            self, *, region: str, resources: 'resources_lib.Resources'
    ) -> Iterator[List[cloud_lib.Zone]]:
        del region, resources
        yield [cloud_lib.Zone(KUBERNETES_REGION)]

    def get_feasible_launchable_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Tuple[List['resources_lib.Resources'], List[str]]:
        sl = resources.tpu
        if sl is None:
            return [], []
        if resources.region not in (None, KUBERNETES_REGION):
            return [], []
        try:
            pools = self._tpu_node_pools()
        except Exception as e:  # pylint: disable=broad-except
            return [], [f'kubernetes: {e}']
        if not self._fits(sl, pools):
            have = {f"{p['generation']}:{p['topology']}x{p['count']}"
                    for p in pools}
            return [], [f'kubernetes: no TPU node pool fits '
                        f'{sl.name} (have: {sorted(have) or "none"})']
        return [resources.copy(cloud=self, region=KUBERNETES_REGION)], []

    def hourly_cost(self, resources: 'resources_lib.Resources') -> float:
        # In-cluster capacity is sunk cost; report 0 so the optimizer
        # prefers an existing cluster over provisioning cloud slices
        # (reference models k8s as free for the same reason).
        del resources
        return 0.0

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', region: str,
            zones: Optional[List[str]], cluster_name: str) -> Dict[str, Any]:
        sl = resources.tpu
        assert sl is not None
        from skypilot_tpu import config as config_lib
        return {
            'cloud': 'kubernetes',
            'namespace': config_lib.get_nested(
                ('kubernetes', 'namespace'), 'default'),
            'context': config_lib.get_nested(
                ('kubernetes', 'context'), None),
            'image': config_lib.get_nested(
                ('kubernetes', 'image'),
                'python:3.11-slim'),
            'tpu_generation': sl.generation,
            'gke_accelerator': GKE_TPU_ACCELERATOR[sl.generation],
            'topology': sl.topology_str,
            'num_hosts': sl.num_hosts,
            'num_slices': sl.num_slices,
            'chips_per_host': sl.chips_per_host,
            'cluster_name': cluster_name,
        }

    def validate_region_zone(self, region: Optional[str],
                             zone: Optional[str]
                             ) -> Tuple[Optional[str], Optional[str]]:
        for val in (region, zone):
            if val is not None and val != KUBERNETES_REGION:
                raise ValueError(
                    f'Kubernetes has a single pseudo-region '
                    f'{KUBERNETES_REGION!r}; got {val!r}.')
        return region, zone

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.kubernetes import instance as k8s_instance
        return k8s_instance.check_credentials()
