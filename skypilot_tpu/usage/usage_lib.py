"""Usage telemetry: local JSONL event log, optional remote shipping.

Reference analog: sky/usage/usage_lib.py (events → Grafana Loki, heartbeat
via a skylet event). Redesigned local-first: every tracked entrypoint
appends one JSON line to ~/.skytpu/usage/events.jsonl (rotated by size);
if SKYTPU_USAGE_ENDPOINT is set, events are also POSTed best-effort.
Disable entirely with SKYTPU_DISABLE_USAGE=1.

Privacy: events carry operation name, duration, outcome, resource *shape*
(generation/chips/spot) and a stable anonymous user hash — never task
commands, env values, or paths.
"""
from __future__ import annotations

import contextlib
import functools
import json
import os
import time
from typing import Any, Dict, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils

logger = sky_logging.init_logger(__name__)

_MAX_LOG_BYTES = 8 * 1024 * 1024


def _enabled() -> bool:
    return os.environ.get('SKYTPU_DISABLE_USAGE', '0') != '1'


def _log_path() -> str:
    d = os.path.expanduser('~/.skytpu/usage')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, 'events.jsonl')


def _rotate(path: str) -> None:
    try:
        if os.path.getsize(path) > _MAX_LOG_BYTES:
            os.replace(path, path + '.1')
    except OSError:
        pass


def resource_shape(task) -> Optional[Dict[str, Any]]:
    """The privacy-safe slice of a task's resources."""
    try:
        res = task.resources_list()[0]
        if res.tpu is None:
            return None
        return {
            'generation': res.tpu.generation,
            'chips': res.tpu.total_chips,
            'num_slices': res.tpu.num_slices,
            'spot': res.use_spot,
        }
    except Exception:  # pylint: disable=broad-except
        return None


def record_event(operation: str, *, duration_s: Optional[float] = None,
                 outcome: str = 'ok', error_type: Optional[str] = None,
                 resources: Optional[Dict[str, Any]] = None) -> None:
    if not _enabled():
        return
    event = {
        'ts': time.time(),
        'op': operation,
        'outcome': outcome,
        'user': common_utils.get_user_hash(),
    }
    if duration_s is not None:
        event['duration_s'] = round(duration_s, 3)
    if error_type:
        event['error'] = error_type
    if resources:
        event['resources'] = resources
    try:
        path = _log_path()
        _rotate(path)
        with open(path, 'a', encoding='utf-8') as f:
            f.write(json.dumps(event) + '\n')
    except OSError:
        pass
    endpoint = os.environ.get('SKYTPU_USAGE_ENDPOINT')
    if endpoint:
        with contextlib.suppress(Exception):
            import requests
            requests.post(endpoint, json=event, timeout=2)


def tracked(operation: str):
    """Decorator: time + outcome-class the wrapped entrypoint."""

    def deco(fn):

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _enabled():
                return fn(*args, **kwargs)
            t0 = time.time()
            resources = None
            if args:
                resources = resource_shape(args[0])
            try:
                out = fn(*args, **kwargs)
            except BaseException as e:
                record_event(operation, duration_s=time.time() - t0,
                             outcome='error', error_type=type(e).__name__,
                             resources=resources)
                raise
            record_event(operation, duration_s=time.time() - t0,
                         resources=resources)
            return out

        return wrapper

    return deco
