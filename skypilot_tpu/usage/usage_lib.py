"""Usage telemetry: local JSONL event log, optional remote shipping.

Reference analog: sky/usage/usage_lib.py (events → Grafana Loki, heartbeat
via a skylet event). Redesigned local-first: every tracked entrypoint
appends one JSON line to ~/.skytpu/usage/events.jsonl (rotated by size);
if SKYTPU_USAGE_ENDPOINT is set, events are also POSTed best-effort.
Disable entirely with SKYTPU_DISABLE_USAGE=1.

Privacy: events carry operation name, duration, outcome, resource *shape*
(generation/chips/spot) and a stable anonymous user hash — never task
commands, env values, or paths.
"""
from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Any, Dict, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import jsonl_utils
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger(__name__)

_MAX_LOG_BYTES = jsonl_utils.DEFAULT_MAX_BYTES


def _enabled() -> bool:
    return not knobs.get_bool('SKYTPU_DISABLE_USAGE')


def _log_path() -> str:
    # Pure: jsonl_utils.append_jsonl creates the directory itself (and
    # swallows I/O errors), so no makedirs — and no exception — here.
    return os.path.join(os.path.expanduser('~/.skytpu/usage'),
                        'events.jsonl')


def resource_shape(task) -> Optional[Dict[str, Any]]:
    """The privacy-safe slice of a task's resources."""
    try:
        res = task.resources_list()[0]
        if res.tpu is None:
            return None
        return {
            'generation': res.tpu.generation,
            'chips': res.tpu.total_chips,
            'num_slices': res.tpu.num_slices,
            'spot': res.use_spot,
        }
    except Exception:  # pylint: disable=broad-except
        return None


def record_event(operation: str, *, duration_s: Optional[float] = None,
                 outcome: str = 'ok', error_type: Optional[str] = None,
                 resources: Optional[Dict[str, Any]] = None) -> None:
    if not _enabled():
        return
    event = {
        'ts': time.time(),
        'op': operation,
        'outcome': outcome,
        'user': common_utils.get_user_hash(),
    }
    # The trace id is a random correlation token, not an identity —
    # privacy-compatible, and it lets a usage event be joined against
    # the observe journal / timeline of the same request. Lazy import:
    # usage and observe are layer peers, so the bridge is runtime-only.
    from skypilot_tpu.observe import trace as trace_lib
    trace_id = trace_lib.get()
    if trace_id:
        event['trace_id'] = trace_id
    if duration_s is not None:
        event['duration_s'] = round(duration_s, 3)
    if error_type:
        event['error'] = error_type
    if resources:
        event['resources'] = resources
    # Shared rotating writer (utils/jsonl_utils) — the same one the
    # observe journal's JSONL export appends through. It never raises
    # (a failed local write returns False), so a read-only HOME can
    # neither fail the tracked operation nor skip the remote POST
    # below — constrained environments are exactly where the endpoint
    # matters.
    jsonl_utils.append_jsonl(_log_path(), event, _MAX_LOG_BYTES)
    endpoint = knobs.get_str('SKYTPU_USAGE_ENDPOINT')
    if endpoint:
        with contextlib.suppress(Exception):
            import requests
            requests.post(endpoint, json=event, timeout=2)


def tracked(operation: str):
    """Decorator: time + outcome-class the wrapped entrypoint."""

    def deco(fn):

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _enabled():
                return fn(*args, **kwargs)
            t0 = time.time()
            resources = None
            if args:
                resources = resource_shape(args[0])
            try:
                out = fn(*args, **kwargs)
            except BaseException as e:
                record_event(operation, duration_s=time.time() - t0,
                             outcome='error', error_type=type(e).__name__,
                             resources=resources)
                raise
            record_event(operation, duration_s=time.time() - t0,
                         resources=resources)
            return out

        return wrapper

    return deco
