"""Usage telemetry (reference analog: sky/usage/)."""
from skypilot_tpu.usage.usage_lib import record_event
from skypilot_tpu.usage.usage_lib import tracked

__all__ = ['record_event', 'tracked']
