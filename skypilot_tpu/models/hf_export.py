"""HF checkpoint export: native param pytrees → safetensors directory.

The inverse of hf_import.params_from_hf — finetuned weights (e.g. a
LoRA merge, train/lora.py) are written back as a standard HF checkpoint
so they serve through the existing --hf-dir path (engine + real
tokenizer) and interoperate with the wider HF ecosystem, the same
round-trip the reference's finetuning recipes produce (torchtune in
llm/llama-3_1-finetuning/lora.yaml writes HF-format output dirs).

Only the dense Llama/Qwen2 families round-trip (the ones hf_import
reads); anything else fails loudly. Layout inversion mirrors import:
un-stack the leading [L] axis and transpose projections back to torch's
[out, in].
"""
from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any, Dict, Optional

import numpy as np

from skypilot_tpu import sky_logging
from skypilot_tpu.models import llama

logger = sky_logging.init_logger(__name__)

# Sidecar files copied verbatim from the source checkpoint when present:
# tokenizer + generation config make the exported dir directly servable.
_SIDECARS = ('config.json', 'generation_config.json', 'tokenizer.json',
             'tokenizer_config.json', 'special_tokens_map.json')


def _to_numpy(x) -> np.ndarray:
    """Device array → numpy (bf16 arrives as an ml_dtypes array, which
    safetensors.numpy round-trips — the artifact keeps its dtype)."""
    import jax
    return np.asarray(jax.device_get(x))


def hf_tensors_from_params(params: llama.Params, cfg: llama.LlamaConfig
                           ) -> Dict[str, np.ndarray]:
    """Flat HF-named tensor dict (torch layouts) from a native tree."""
    lay = params['layers']
    out: Dict[str, np.ndarray] = {
        'model.embed_tokens.weight': _to_numpy(params['embed']),
        'model.norm.weight': _to_numpy(params['final_norm']),
    }

    def unstack(name: str, arr, transpose: bool):
        a = _to_numpy(arr)
        for i in range(cfg.n_layers):
            t = a[i]
            out[f'model.layers.{i}.{name}'] = (
                np.ascontiguousarray(t.T) if transpose else t)

    unstack('input_layernorm.weight', lay['attn_norm'], False)
    unstack('self_attn.q_proj.weight', lay['wq'], True)
    unstack('self_attn.k_proj.weight', lay['wk'], True)
    unstack('self_attn.v_proj.weight', lay['wv'], True)
    unstack('self_attn.o_proj.weight', lay['wo'], True)
    unstack('post_attention_layernorm.weight', lay['mlp_norm'], False)
    unstack('mlp.gate_proj.weight', lay['w_gate'], True)
    unstack('mlp.up_proj.weight', lay['w_up'], True)
    unstack('mlp.down_proj.weight', lay['w_down'], True)
    if cfg.qkv_bias:
        unstack('self_attn.q_proj.bias', lay['bq'], False)
        unstack('self_attn.k_proj.bias', lay['bk'], False)
        unstack('self_attn.v_proj.bias', lay['bv'], False)
    if not cfg.tie_embeddings:
        out['lm_head.weight'] = np.ascontiguousarray(
            _to_numpy(params['lm_head']).T)
    return out


def save_hf_checkpoint(params: llama.Params, cfg: llama.LlamaConfig,
                       out_dir: str,
                       source_dir: Optional[str] = None) -> str:
    """Write `out_dir` as an HF checkpoint directory.

    `source_dir`: the original HF checkpoint — its config.json and
    tokenizer sidecars are copied so the export serves immediately via
    --hf-dir. Without it a minimal config.json is synthesized from the
    native config (tokenizer must then be supplied separately).
    """
    if type(cfg) is not llama.LlamaConfig:
        raise ValueError(
            f'HF export supports the dense Llama/Qwen2 family only, got '
            f'{type(cfg).__name__} (the families hf_import reads).')
    from safetensors.numpy import save_file
    out_dir = os.path.abspath(os.path.expanduser(out_dir))
    os.makedirs(out_dir, exist_ok=True)
    tensors = hf_tensors_from_params(params, cfg)
    tmp = os.path.join(out_dir, '.model.safetensors.tmp')
    save_file(tensors, tmp)
    os.replace(tmp, os.path.join(out_dir, 'model.safetensors'))

    copied = set()
    if source_dir:
        source_dir = os.path.abspath(os.path.expanduser(source_dir))
        for name in _SIDECARS:
            src = os.path.join(source_dir, name)
            if os.path.exists(src):
                shutil.copy2(src, os.path.join(out_dir, name))
                copied.add(name)
    if 'config.json' not in copied:
        with open(os.path.join(out_dir, 'config.json'), 'w',
                  encoding='utf-8') as f:
            json.dump(_minimal_hf_config(cfg), f, indent=1)
    n = sum(int(np.prod(t.shape)) for t in tensors.values())
    logger.info(f'Exported HF checkpoint to {out_dir}: '
                f'{n / 1e9:.2f}B params, {len(tensors)} tensors.')
    return out_dir


def _minimal_hf_config(cfg: llama.LlamaConfig) -> Dict[str, Any]:
    arch = 'Qwen2ForCausalLM' if cfg.qkv_bias else 'LlamaForCausalLM'
    out: Dict[str, Any] = {
        'architectures': [arch],
        'vocab_size': cfg.vocab_size,
        'hidden_size': cfg.dim,
        'num_hidden_layers': cfg.n_layers,
        'num_attention_heads': cfg.n_heads,
        'num_key_value_heads': cfg.n_kv_heads,
        'intermediate_size': cfg.ffn_dim,
        'rope_theta': cfg.rope_theta,
        'rms_norm_eps': cfg.rms_eps,
        'max_position_embeddings': cfg.max_seq_len,
        'tie_word_embeddings': cfg.tie_embeddings,
        'head_dim': cfg.hd,
    }
    if cfg.rope_scaling:
        # rope_scaling is a frozen RopeScaling dataclass after
        # LlamaConfig.__post_init__ (raw dicts are converted there).
        rs = (dataclasses.asdict(cfg.rope_scaling)
              if dataclasses.is_dataclass(cfg.rope_scaling)
              else dict(cfg.rope_scaling))
        rope_type = rs.get('rope_type', 'llama3')
        if rope_type == 'llama3':
            out['rope_scaling'] = {
                'rope_type': 'llama3',
                'factor': rs['factor'],
                'low_freq_factor': rs.get('low_freq_factor', 1.0),
                'high_freq_factor': rs.get('high_freq_factor', 4.0),
                'original_max_position_embeddings':
                    rs.get('original_max_position', 8192),
            }
        elif rope_type == 'yarn':
            # beta/attention_factor MUST round-trip: transformers'
            # defaults differ per model, and a config loading cleanly
            # with wrong betas computes different RoPE frequencies —
            # silently wrong logits.
            out['rope_scaling'] = {
                'rope_type': 'yarn',
                'factor': rs['factor'],
                'beta_fast': rs.get('beta_fast', 32.0),
                'beta_slow': rs.get('beta_slow', 1.0),
                'original_max_position_embeddings':
                    rs.get('original_max_position', 8192),
            }
            if rs.get('attention_factor') is not None:
                out['rope_scaling']['attention_factor'] = \
                    rs['attention_factor']
        else:
            # A mislabeled config.json loads cleanly elsewhere and
            # generates garbage; refuse instead.
            raise NotImplementedError(
                f'HF export for rope_type {rope_type!r} is not wired; '
                f"supported: 'llama3', 'yarn'.")
    return out
