"""Llama-family decoder, TPU-first.

Design (vs the reference's torch-xla recipe, examples/tpu/v6e/
train-llama3-8b.yaml + docs/source/reference/tpu.rst:100-118):
  - pure JAX pytree params; layers stacked on a leading 'layers' axis and
    iterated with `lax.scan` → one traced layer, fast compiles, XLA-friendly.
  - bf16 compute / fp32 params & softmax / fp32 RoPE; einsums hit the MXU.
  - sharding via logical axis names resolved through parallel.Rules —
    the same model runs pure-DP, FSDP, TP, sequence-parallel or any mix.
  - `jax.checkpoint` rematerialisation policies to trade FLOPs for HBM.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from skypilot_tpu.ops.attention import attention as _attention
from skypilot_tpu.ops import norms, rotary
from skypilot_tpu.parallel import sharding as sharding_lib

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Rope scaling; frozen so configs stay hashable (decode jits with
    the config as a static argument). rope_type 'llama3' uses the NTK
    low/high_freq_factor fields; 'yarn' (gpt-oss long context) uses
    beta_fast/beta_slow + the 0.1·ln(factor)+1 concentration factor
    (override via attention_factor). ops/rotary.py implements both."""
    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192
    rope_type: str = 'llama3'
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    attention_factor: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: Optional[int] = None          # default dim // n_heads
    ffn_dim: int = 14336
    rope_theta: float = 500000.0
    rope_scaling: Optional[RopeScaling] = None  # accepts a dict in __init__
    rms_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16               # activation/compute dtype
    param_dtype: Any = jnp.float32          # master param dtype
    remat: str = 'full'                     # 'none' | 'dots' | 'full'
    attention_impl: str = 'auto'            # ops.attention impl
    # Ring-attention causal shard layout: 'seq' (contiguous) or 'zigzag'
    # (balanced causal work; tokens/labels/positions must be zigzag-permuted
    # — train_lib does this when it sees this flag).
    ring_layout: str = 'seq'
    scan_layers: bool = True
    pipeline_stages: int = 1                # >1: GPipe over the 'stage' axis
    num_microbatches: int = 1               # PP microbatches (divides batch)
    # Qwen2-family attention: biases on the q/k/v projections only.
    qkv_bias: bool = False
    # Gemma-family knobs: norms scale by (1+w) with zero-init w, the MLP
    # uses tanh-gelu gating, embeddings scale by sqrt(dim), and final
    # logits are tanh-softcapped.
    norm_plus_one: bool = False
    mlp_activation: str = 'silu'            # 'silu' | 'gelu'
    embed_scale: bool = False
    final_logit_softcap: Optional[float] = None
    # Gemma-2 additions: attention-logit softcap, post-sublayer norms
    # (attn/FFN outputs normed before the residual add), and sliding-
    # window attention on a repeating layer pattern: every
    # `sliding_window_pattern`-th layer is GLOBAL, the rest local
    # (pattern 2 = Gemma-2's alternation; 6 = Gemma-3's 5 local : 1
    # global).
    attn_logit_softcap: Optional[float] = None
    post_norms: bool = False
    sliding_window: Optional[int] = None
    sliding_window_pattern: int = 2
    # Gemma-3 additions: learned RMS-norm on q/k heads before RoPE, and a
    # separate (smaller) rope base for the local sliding-window layers.
    qk_norm: bool = False
    local_rope_theta: Optional[float] = None
    # gpt-oss additions: learned per-head attention-sink logits (a
    # phantom key absorbing softmax mass, ops/attention.py), and the
    # clamped SwiGLU variant (inputs clipped at ±limit, gate activated
    # with sigmoid(1.702·x), +1 on the linear term).
    attn_sinks: bool = False
    swiglu_limit: Optional[float] = None

    def act(self, x):
        if self.mlp_activation == 'gelu':
            return jax.nn.gelu(x)           # tanh approximation (Gemma)
        return jax.nn.silu(x)

    def glu(self, gate, up):
        """The gated-MLP inner product (shared by the dense MLP and the
        MoE experts)."""
        if self.swiglu_limit is not None:
            limit = self.swiglu_limit
            gate = jnp.minimum(gate, limit)
            up = jnp.clip(up, -limit, limit)
            return gate * jax.nn.sigmoid(1.702 * gate) * (up + 1)
        return self.act(gate) * up

    def __post_init__(self):
        if isinstance(self.rope_scaling, dict):
            object.__setattr__(self, 'rope_scaling',
                               RopeScaling(**self.rope_scaling))

    @property
    def hd(self) -> int:
        return self.head_dim if self.head_dim is not None else (
            self.dim // self.n_heads)

    @property
    def num_params(self) -> int:
        a = 4 if self.n_kv_heads == self.n_heads else 2 + 2 * (
            self.n_kv_heads / self.n_heads)
        attn = int(a * self.dim * self.n_heads * self.hd)
        if self.qkv_bias:
            attn += (self.n_heads + 2 * self.n_kv_heads) * self.hd
        mlp = 3 * self.dim * self.ffn_dim
        per_layer = attn + mlp + 2 * self.dim
        embed = self.vocab_size * self.dim * (1 if self.tie_embeddings else 2)
        return self.n_layers * per_layer + embed + self.dim


PRESETS: Dict[str, LlamaConfig] = {
    # Debug/test config: tiny, CPU-friendly, all axes divisible by 2.
    'llama-debug': LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                               n_kv_heads=2, ffn_dim=128, max_seq_len=128,
                               rope_theta=10000.0, remat='none'),
    # ~1.1B flagship-mini for single-chip benchmarking.
    'llama-1b': LlamaConfig(vocab_size=32768, dim=2048, n_layers=16,
                            n_heads=16, n_kv_heads=8, ffn_dim=7168,
                            max_seq_len=4096, tie_embeddings=True),
    'llama3-8b': LlamaConfig(vocab_size=128256, dim=4096, n_layers=32,
                             n_heads=32, n_kv_heads=8, ffn_dim=14336,
                             max_seq_len=8192,
                             rope_scaling=dict(factor=8.0, low_freq_factor=1.0,
                                               high_freq_factor=4.0,
                                               original_max_position=8192)),
    'llama3-70b': LlamaConfig(vocab_size=128256, dim=8192, n_layers=80,
                              n_heads=64, n_kv_heads=8, ffn_dim=28672,
                              max_seq_len=8192),
    'llama2-7b': LlamaConfig(vocab_size=32000, dim=4096, n_layers=32,
                             n_heads=32, n_kv_heads=32, ffn_dim=11008,
                             rope_theta=10000.0, max_seq_len=4096),
    # Llama-3.2 small models (reference: llm/llama-3_2/ recipes): tied
    # embeddings, same 3.1-style NTK rope scaling (factor 32).
    'llama3.2-1b': LlamaConfig(vocab_size=128256, dim=2048, n_layers=16,
                               n_heads=32, n_kv_heads=8, ffn_dim=8192,
                               max_seq_len=8192, tie_embeddings=True,
                               rope_scaling=dict(factor=32.0,
                                                 low_freq_factor=1.0,
                                                 high_freq_factor=4.0,
                                                 original_max_position=8192)),
    'llama3.2-3b': LlamaConfig(vocab_size=128256, dim=3072, n_layers=28,
                               n_heads=24, n_kv_heads=8, ffn_dim=8192,
                               max_seq_len=8192, tie_embeddings=True,
                               rope_scaling=dict(factor=32.0,
                                                 low_freq_factor=1.0,
                                                 high_freq_factor=4.0,
                                                 original_max_position=8192)),
    # CodeLlama-7b (reference: llm/codellama/): llama2 geometry with the
    # 16k-context rope base and a 32016-token vocab (infill specials).
    'codellama-7b': LlamaConfig(vocab_size=32016, dim=4096, n_layers=32,
                                n_heads=32, n_kv_heads=32, ffn_dim=11008,
                                rope_theta=1e6, max_seq_len=16384),
    # Qwen2/2.5 family (reference serves these via vLLM recipes,
    # llm/qwen/): same decoder as Llama plus q/k/v projection biases.
    'qwen2-7b': LlamaConfig(vocab_size=152064, dim=3584, n_layers=28,
                            n_heads=28, n_kv_heads=4, ffn_dim=18944,
                            rope_theta=1e6, rms_eps=1e-6,
                            max_seq_len=32768, qkv_bias=True),
    'qwen2-72b': LlamaConfig(vocab_size=152064, dim=8192, n_layers=80,
                             n_heads=64, n_kv_heads=8, ffn_dim=29568,
                             rope_theta=1e6, rms_eps=1e-6,
                             max_seq_len=32768, qkv_bias=True),
    # Qwen2.5 small sizes (reference serves these via vLLM/ollama
    # recipes): same decoder family, tied embeddings on the small ones.
    'qwen2.5-1.5b': LlamaConfig(vocab_size=151936, dim=1536, n_layers=28,
                                n_heads=12, n_kv_heads=2, ffn_dim=8960,
                                rope_theta=1e6, rms_eps=1e-6,
                                max_seq_len=32768, qkv_bias=True,
                                tie_embeddings=True),
    # Gemma family (reference: llm/gemma/, llm/gemma3/ recipes): (1+w)
    # norms, tanh-gelu MLP gating, sqrt(dim)-scaled embeddings, tied
    # head; gemma2 additionally softcaps the final logits.
    'gemma-7b': LlamaConfig(vocab_size=256000, dim=3072, n_layers=28,
                            n_heads=16, n_kv_heads=16, head_dim=256,
                            ffn_dim=24576, rope_theta=10000.0,
                            rms_eps=1e-6, max_seq_len=8192,
                            tie_embeddings=True, norm_plus_one=True,
                            mlp_activation='gelu', embed_scale=True),
    'gemma2-9b': LlamaConfig(vocab_size=256000, dim=3584, n_layers=42,
                             n_heads=16, n_kv_heads=8, head_dim=256,
                             ffn_dim=14336, rope_theta=10000.0,
                             rms_eps=1e-6, max_seq_len=8192,
                             tie_embeddings=True, norm_plus_one=True,
                             mlp_activation='gelu', embed_scale=True,
                             final_logit_softcap=30.0,
                             attn_logit_softcap=50.0, post_norms=True,
                             sliding_window=4096),
    # Gemma-3 (reference: llm/gemma3/ recipes): drops the softcaps in
    # favor of learned QK-norm; 5 local : 1 global layer pattern with a
    # 1024 window and a SEPARATE small rope base for local layers.
    # (The reference model linearly rescales global rope for >32k
    # context; that stretch is not modeled here.)
    'gemma3-12b': LlamaConfig(vocab_size=262208, dim=3840, n_layers=48,
                              n_heads=16, n_kv_heads=8, head_dim=256,
                              ffn_dim=15360, rope_theta=1e6,
                              rms_eps=1e-6, max_seq_len=32768,
                              tie_embeddings=True, norm_plus_one=True,
                              mlp_activation='gelu', embed_scale=True,
                              post_norms=True, qk_norm=True,
                              sliding_window=1024,
                              sliding_window_pattern=6,
                              local_rope_theta=10000.0),
}


# ---------------------------------------------------------------------------
# Params: init + partition specs
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    """Initialise (unsharded) params; use under jit with out_shardings to
    materialise directly sharded on a mesh."""
    hd = cfg.hd
    k = iter(jax.random.split(rng, 16))
    init = jax.nn.initializers.normal(stddev=0.02, dtype=cfg.param_dtype)
    trunc = jax.nn.initializers.variance_scaling(
        1.0, 'fan_in', 'truncated_normal', dtype=cfg.param_dtype)
    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim
    # (1+w)-style norms carry their identity in the "+1": w inits to 0.
    norm_init = jnp.zeros if cfg.norm_plus_one else jnp.ones
    params: Params = {
        'embed': init(next(k), (cfg.vocab_size, D)),
        'layers': {
            'attn_norm': norm_init((L, D), cfg.param_dtype),
            'wq': trunc(next(k), (L, D, cfg.n_heads * hd)),
            'wk': trunc(next(k), (L, D, cfg.n_kv_heads * hd)),
            'wv': trunc(next(k), (L, D, cfg.n_kv_heads * hd)),
            'wo': trunc(next(k), (L, cfg.n_heads * hd, D)),
            'mlp_norm': norm_init((L, D), cfg.param_dtype),
            'w_gate': trunc(next(k), (L, D, F)),
            'w_up': trunc(next(k), (L, D, F)),
            'w_down': trunc(next(k), (L, F, D)),
        },
        'final_norm': norm_init((D,), cfg.param_dtype),
    }
    if cfg.qkv_bias:
        params['layers']['bq'] = jnp.zeros((L, cfg.n_heads * hd),
                                           cfg.param_dtype)
        params['layers']['bk'] = jnp.zeros((L, cfg.n_kv_heads * hd),
                                           cfg.param_dtype)
        params['layers']['bv'] = jnp.zeros((L, cfg.n_kv_heads * hd),
                                           cfg.param_dtype)
    if cfg.post_norms:
        params['layers']['post_attn_norm'] = norm_init((L, D),
                                                       cfg.param_dtype)
        params['layers']['post_mlp_norm'] = norm_init((L, D),
                                                      cfg.param_dtype)
    if cfg.qk_norm:
        params['layers']['q_norm'] = norm_init((L, hd), cfg.param_dtype)
        params['layers']['k_norm'] = norm_init((L, hd), cfg.param_dtype)
    if cfg.attn_sinks:
        # Zero-init: exp(0)=1 joins each softmax denominator from step
        # one (the "sink token" is present immediately, then learned).
        params['layers']['sink'] = jnp.zeros((L, cfg.n_heads),
                                             cfg.param_dtype)
    if not cfg.tie_embeddings:
        params['lm_head'] = init(next(k), (D, cfg.vocab_size))
    return params


def param_specs(cfg: LlamaConfig,
                rules: Optional[sharding_lib.Rules] = None) -> Params:
    """Pytree of PartitionSpec mirroring init_params' structure."""
    r = rules or sharding_lib.Rules()
    if cfg.pipeline_stages > 1:
        r = r.override(layers='stage')
    s = r.spec
    specs: Params = {
        'embed': s('vocab', 'embed'),
        'layers': {
            'attn_norm': s('layers', 'norm'),
            'wq': s('layers', 'embed', 'heads'),
            'wk': s('layers', 'embed', 'kv_heads'),
            'wv': s('layers', 'embed', 'kv_heads'),
            'wo': s('layers', 'heads', 'embed'),
            'mlp_norm': s('layers', 'norm'),
            'w_gate': s('layers', 'embed', 'mlp'),
            'w_up': s('layers', 'embed', 'mlp'),
            'w_down': s('layers', 'mlp', 'embed'),
        },
        'final_norm': s('norm'),
    }
    if cfg.qkv_bias:
        specs['layers']['bq'] = s('layers', 'heads')
        specs['layers']['bk'] = s('layers', 'kv_heads')
        specs['layers']['bv'] = s('layers', 'kv_heads')
    if cfg.post_norms:
        specs['layers']['post_attn_norm'] = s('layers', 'norm')
        specs['layers']['post_mlp_norm'] = s('layers', 'norm')
    if cfg.qk_norm:
        specs['layers']['q_norm'] = s('layers', 'norm')
        specs['layers']['k_norm'] = s('layers', 'norm')
    if cfg.attn_sinks:
        specs['layers']['sink'] = s('layers', 'heads')
    if not cfg.tie_embeddings:
        specs['lm_head'] = s('embed', 'vocab')
    return specs


def validate_divisibility(cfg: LlamaConfig, mesh_shape: Dict[str, int]):
    """Raise if the model dims don't divide the mesh axes they shard over."""
    tp = mesh_shape.get('tensor', 1)
    fsdp = mesh_shape.get('fsdp', 1)
    checks = [
        ('n_heads', cfg.n_heads, tp), ('n_kv_heads', cfg.n_kv_heads, tp),
        ('ffn_dim', cfg.ffn_dim, tp), ('vocab_size', cfg.vocab_size, tp),
        ('dim', cfg.dim, fsdp),
    ]
    for name, val, ax in checks:
        if ax > 1 and val % ax != 0:
            raise ValueError(f'{name}={val} not divisible by mesh axis '
                             f'size {ax}')


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _pipelined_layers(x, layers, layer_fn, cfg: LlamaConfig, sin, cos):
    """GPipe the layer stack over the 'stage' mesh axis (parallel.pipeline).

    layer_fn(x, lp, sin, cos) -> x. With ring attention the region is
    FLATTENED: manual over both 'stage' and 'sequence', activations and
    RoPE tables enter sequence-sharded, and attention_block calls the
    in-region ring directly. Shardy rejects opening a new manual region
    inside a parent that binds other axes, so nesting the sequence
    shard_map under the stage one (round-2 design) cannot lower; one
    merged manual region + the ring's custom_vjp backward is the shape
    that composes (VERDICT r2 item 3)."""
    from jax.sharding import PartitionSpec as P
    from skypilot_tpu.parallel import pipeline as pipeline_lib
    b, s_len, d = x.shape
    m = cfg.num_microbatches
    if b % m != 0:
        raise ValueError(f'batch {b} not divisible by num_microbatches {m}')
    if cfg.n_layers % cfg.pipeline_stages != 0:
        raise ValueError(f'n_layers {cfg.n_layers} not divisible by '
                         f'pipeline_stages {cfg.pipeline_stages}')
    # f32 at the shard_map boundary off-TPU only: the replicated input's
    # cotangent is a psum over 'stage', and XLA CPU crashes promoting bf16
    # all-reduces. On TPU keep bf16 (half the boundary/psum traffic).
    from skypilot_tpu.ops.attention import _on_tpu
    boundary_dtype = x.dtype if _on_tpu() else jnp.float32

    xm = x.reshape(m, b // m, s_len, d).astype(boundary_dtype)
    ring = cfg.attention_impl == 'ring'
    axes = {'stage', 'sequence'} if ring else {'stage'}
    x_spec = P(None, None, 'sequence') if ring else P()
    rope_spec = P('sequence') if ring else P()

    def sm_fn(layers_local, xm_local, sin_l, cos_l):
        def fn(xx, lp):
            return layer_fn(xx, lp, sin_l, cos_l)
        out = pipeline_lib.pipeline_apply(fn, layers_local,
                                          xm_local.astype(x.dtype))
        return out.astype(boundary_dtype)

    out = jax.shard_map(sm_fn,
                        in_specs=(P('stage'), x_spec, rope_spec, rope_spec),
                        out_specs=x_spec, axis_names=axes,
                        check_vma=False)(layers, xm, sin, cos)
    return out.reshape(b, s_len, d).astype(x.dtype)


def window_active(layer_idx, cfg: LlamaConfig):
    """Traced bool: does this layer attend within the sliding window?
    Every `sliding_window_pattern`-th layer is GLOBAL, the rest local
    (pattern 2 = Gemma-2 alternation, 6 = Gemma-3's 5:1)."""
    p = cfg.sliding_window_pattern
    return (layer_idx % p) != (p - 1)


def select_rope(sin, cos, layer_idx, cfg: LlamaConfig):
    """Pick this layer's RoPE tables. With `local_rope_theta` set the
    tables ALWAYS arrive stacked on a leading [2] dim (rope_tables is
    the single constructor: 0 = global theta, 1 = local theta for
    sliding-window layers); selection is a traced where so all layers
    share one scan body."""
    if cfg.local_rope_theta is not None:
        if layer_idx is None:
            raise ValueError(
                'local_rope_theta needs per-layer ids at every call site '
                '(scan xs) to select the rope table.')
        is_local = window_active(layer_idx, cfg)
        return (jnp.where(is_local, sin[1], sin[0]),
                jnp.where(is_local, cos[1], cos[0]))
    return sin, cos


def rope_tables(cfg: LlamaConfig, positions) -> Tuple[jnp.ndarray,
                                                      jnp.ndarray]:
    """(sin, cos) for RoPE; stacked [2, ...] when the config uses a
    separate local rope base (Gemma-3)."""
    sin, cos = rotary.rope_frequencies(cfg.hd, positions, cfg.rope_theta,
                                       cfg.rope_scaling)
    if cfg.local_rope_theta is not None:
        sin_l, cos_l = rotary.rope_frequencies(cfg.hd, positions,
                                               cfg.local_rope_theta, None)
        return jnp.stack([sin, sin_l]), jnp.stack([cos, cos_l])
    return sin, cos


def attention_block(x: jnp.ndarray, lp: Params, cfg: LlamaConfig,
                    rules: sharding_lib.Rules, sin: jnp.ndarray,
                    cos: jnp.ndarray, q_offset,
                    norm_key: str = 'attn_norm',
                    layer_idx=None) -> jnp.ndarray:
    """Pre-norm attention sublayer (shared by the dense and MoE models):
    rms_norm → qkv → rope → attention (xla/flash/ring) → wo. Returns the
    residual branch (caller adds it to x)."""
    b, s_len, _ = x.shape
    hd = cfg.hd
    con = functools.partial(sharding_lib.constrain, rules=rules)

    h = norms.rms_norm(x, lp[norm_key], cfg.rms_eps,
                       scale_plus_one=cfg.norm_plus_one)
    q = jnp.einsum('bsd,dh->bsh', h, lp['wq'].astype(cfg.dtype))
    kk = jnp.einsum('bsd,dh->bsh', h, lp['wk'].astype(cfg.dtype))
    vv = jnp.einsum('bsd,dh->bsh', h, lp['wv'].astype(cfg.dtype))
    if cfg.qkv_bias:
        q = q + lp['bq'].astype(cfg.dtype)
        kk = kk + lp['bk'].astype(cfg.dtype)
        vv = vv + lp['bv'].astype(cfg.dtype)
    q = q.reshape(b, s_len, cfg.n_heads, hd)
    kk = kk.reshape(b, s_len, cfg.n_kv_heads, hd)
    vv = vv.reshape(b, s_len, cfg.n_kv_heads, hd)
    q = con(q, 'batch', 'seq', 'act_heads', 'head_dim')
    if cfg.qk_norm:
        # Gemma-3: learned RMS-norm over head_dim before RoPE.
        q = norms.rms_norm(q, lp['q_norm'], cfg.rms_eps,
                           scale_plus_one=cfg.norm_plus_one)
        kk = norms.rms_norm(kk, lp['k_norm'], cfg.rms_eps,
                            scale_plus_one=cfg.norm_plus_one)
    sin, cos = select_rope(sin, cos, layer_idx, cfg)
    q = rotary.apply_rope(q, sin, cos)
    kk = rotary.apply_rope(kk, sin, cos)
    if cfg.attention_impl == 'ring':
        if cfg.sliding_window is not None:
            raise NotImplementedError(
                'sliding_window (Gemma-2 local layers) with ring attention '
                'is not supported: windowed shards would need neighbor-'
                "bounded rings. Use attention_impl='auto'/'xla'.")
        if cfg.attn_logit_softcap is not None:
            raise NotImplementedError(
                'attn_logit_softcap with ring attention is not supported '
                "(the ring kernel does not cap logits); use 'auto'/'xla'.")
        if cfg.local_rope_theta is not None:
            raise NotImplementedError(
                'local_rope_theta (dual rope bases) with ring attention '
                "is not supported; use 'auto'/'xla'.")
        if cfg.attn_sinks:
            raise NotImplementedError(
                'attn_sinks (gpt-oss) with ring attention is not '
                'supported: the sink logit must join exactly one '
                "shard's softmax denominator. Use 'auto'/'xla'.")
        from skypilot_tpu.ops import ring_attention as ring_lib
        from skypilot_tpu.ops.attention import _on_tpu
        ring_kw = dict(causal=True,
                       layout=getattr(cfg, 'ring_layout', 'seq'),
                       interpret=not _on_tpu())
        if cfg.pipeline_stages > 1:
            # Inside the flattened stage+sequence manual region
            # (_pipelined_layers): 'sequence' is already bound — run the
            # in-region ring directly.
            out = ring_lib.ring_attention(q, kk, vv, **ring_kw)
        else:
            # GSPMD level: manual only over 'sequence'; batch/tensor axes
            # stay with the partitioner.
            out = ring_lib.ring_attention_sharded(q, kk, vv, **ring_kw)
    else:
        window = cfg.sliding_window
        w_active = None
        if window is not None and layer_idx is not None:
            # Traced flag so local and global layers share one scan
            # body / compiled program (window_active: every
            # sliding_window_pattern-th layer is global).
            w_active = window_active(layer_idx, cfg)
        out = _attention(q, kk, vv, impl=cfg.attention_impl,
                         causal=True, q_offset=q_offset,
                         kv_offset=q_offset,
                         logit_softcap=cfg.attn_logit_softcap,
                         window=window, window_active=w_active,
                         sinks=(lp['sink'].astype(jnp.float32)
                                if cfg.attn_sinks else None))
    out = out.reshape(b, s_len, cfg.n_heads * hd)
    attn_out = jnp.einsum('bsh,hd->bsd', out, lp['wo'].astype(cfg.dtype))
    if cfg.post_norms:
        attn_out = norms.rms_norm(attn_out, lp['post_attn_norm'],
                                  cfg.rms_eps,
                                  scale_plus_one=cfg.norm_plus_one)
    return con(attn_out, 'batch', 'seq', 'act_embed')


def _layer(x: jnp.ndarray, lp: Params, cfg: LlamaConfig,
           rules: sharding_lib.Rules, sin: jnp.ndarray, cos: jnp.ndarray,
           q_offset, layer_idx=None) -> jnp.ndarray:
    con = functools.partial(sharding_lib.constrain, rules=rules)
    x = x + attention_block(x, lp, cfg, rules, sin, cos, q_offset,
                            layer_idx=layer_idx)

    h = norms.rms_norm(x, lp['mlp_norm'], cfg.rms_eps,
                       scale_plus_one=cfg.norm_plus_one)
    gate = jnp.einsum('bsd,df->bsf', h, lp['w_gate'].astype(cfg.dtype))
    up = jnp.einsum('bsd,df->bsf', h, lp['w_up'].astype(cfg.dtype))
    inner = cfg.glu(gate, up)
    inner = con(inner, 'batch', 'seq', 'mlp')
    down = jnp.einsum('bsf,fd->bsd', inner, lp['w_down'].astype(cfg.dtype))
    if cfg.post_norms:
        down = norms.rms_norm(down, lp['post_mlp_norm'], cfg.rms_eps,
                              scale_plus_one=cfg.norm_plus_one)
    return x + con(down, 'batch', 'seq', 'act_embed')


_REMAT_POLICIES = {
    'none': None,
    'dots': 'dots_with_no_batch_dims_saveable',
    'full': 'nothing_saveable',
}


def forward(params: Params,
            tokens: jnp.ndarray,
            cfg: LlamaConfig,
            rules: Optional[sharding_lib.Rules] = None,
            positions: Optional[jnp.ndarray] = None,
            q_offset: int | jnp.ndarray = 0,
            return_hidden: bool = False) -> jnp.ndarray:
    """tokens [B, S] int32 → logits [B, S, vocab] (fp32).

    `positions`/`q_offset` allow context-parallel callers to pass shard-local
    global positions. `return_hidden=True` returns the final-norm hidden
    states [B, S, D] fp32 instead of logits (embedding extraction — the
    reference's flagship batch-inference workload computes text embeddings
    with an LLM, llm/batch_inference/README.md).
    """
    rules = rules or sharding_lib.Rules()
    con = functools.partial(sharding_lib.constrain, rules=rules)
    b, s_len = tokens.shape
    tokens = con(tokens, 'batch', 'seq')

    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.dim ** 0.5, cfg.dtype)
    x = con(x, 'batch', 'seq', 'act_embed')

    if positions is None:
        if (cfg.attention_impl == 'ring' and
                getattr(cfg, 'ring_layout', 'seq') == 'zigzag'):
            raise ValueError(
                "ring_layout='zigzag' needs zigzag-permuted tokens and "
                "explicit `positions` (ops.ring_attention.zigzag_positions)"
                " — contiguous tokens would be causally masked as if they "
                "were zigzag chunks. train_lib's train/eval steps do the "
                "permutation automatically.")
        positions = jnp.arange(s_len) + q_offset
    sin, cos = rope_tables(cfg, positions)

    # Inside the flattened stage+sequence pipeline region, 'sequence' is a
    # manual axis — drop it from the layer-internal sharding constraints.
    layer_rules = (rules.override(seq=None)
                   if cfg.pipeline_stages > 1 and cfg.attention_impl == 'ring'
                   else rules)

    def layer_fn(xx, lp_idx, sin_l, cos_l):
        lp, idx = lp_idx
        return _layer(xx, lp, cfg, layer_rules, sin_l, cos_l, q_offset,
                      layer_idx=idx)

    policy_name = _REMAT_POLICIES[cfg.remat]
    if policy_name is not None:
        policy = getattr(jax.checkpoint_policies, policy_name)
        layer_fn = jax.checkpoint(layer_fn, policy=policy)

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    if cfg.pipeline_stages > 1:
        x = _pipelined_layers(x, (params['layers'], layer_ids), layer_fn,
                              cfg, sin, cos)
    elif cfg.scan_layers:
        def body(carry, lp_idx):
            return layer_fn(carry, lp_idx, sin, cos), None
        x, _ = jax.lax.scan(body, x, (params['layers'], layer_ids))
    else:
        for i in range(cfg.n_layers):
            lp = jax.tree.map(lambda p: p[i], params['layers'])
            x = layer_fn(x, (lp, jnp.int32(i)), sin, cos)

    x = norms.rms_norm(x, params['final_norm'], cfg.rms_eps,
                       scale_plus_one=cfg.norm_plus_one)
    if return_hidden:
        return con(x.astype(jnp.float32), 'batch', 'seq', 'act_embed')
    head = (params['embed'].T if cfg.tie_embeddings else params['lm_head'])
    logits = jnp.einsum('bsd,dv->bsv', x, head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    if cfg.final_logit_softcap:
        cap = cfg.final_logit_softcap
        logits = cap * jnp.tanh(logits / cap)
    return con(logits, 'batch', 'seq', 'vocab')
