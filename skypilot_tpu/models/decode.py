"""KV-cache incremental decoding for the Llama family (prefill + step).

The reference serves TPUs through external engines (JetStream/vLLM recipes,
/root/reference/examples/tpu/v6e/README.md:119-127); this framework owns the
model code, so the serve plane gets a native engine. TPU-first choices:

  - **Static shapes everywhere**: the cache is [L, B, T, KH, hd] with T
    fixed at init; a decode step attends over all T with the causal mask
    derived from `q_offset=length` — no dynamic slicing, so XLA compiles
    one step kernel and reuses it for every token.
  - **Layer scan**: the per-layer cache update rides the same `lax.scan`
    as training, so decode compiles in seconds even for 80-layer models.
  - **Generation is one jit**: prefill + `lax.scan` over steps, greedy or
    temperature sampling inside the scan (no host round-trip per token).

Cache layout note: KH (kv-heads) shards over 'tensor' like training, batch
over ('data','fsdp'); decode on a sharded mesh reuses the training rules.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama
from skypilot_tpu.ops import norms, rotary
from skypilot_tpu.ops.attention import attention as _attention
from skypilot_tpu.parallel import sharding as sharding_lib


def bucket_size(n: int, floor: int = 16) -> int:
    """Round up to a power of two — the shared prompt-bucketing contract
    (bounded XLA compile count) used by the serving engine and offline
    batch inference alike."""
    b = floor
    while b < n:
        b *= 2
    return b


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: jnp.ndarray        # [L, B, T, KH, hd]
    v: jnp.ndarray        # [L, B, T, KH, hd]
    length: jnp.ndarray   # [B] int32: valid prefix length PER ROW —
    #                       ragged batches (mixed prompt lengths) share
    #                       one cache; pad slots are causally masked and
    #                       overwritten before they are ever attended.


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QuantizedWeight:
    """Weight-only int8 with a per-output-channel scale.

    Decode reads every weight every token (HBM-bound): int8 halves the
    bytes vs bf16. The dequant (`int8 * scale`) fuses into the consuming
    matmul's operand load under XLA, so no bf16 copy is ever
    materialized in HBM."""
    q: jnp.ndarray       # int8, original shape
    scale: jnp.ndarray   # compute dtype, broadcastable over q


def _quantize_int8(w: jnp.ndarray) -> QuantizedWeight:
    """Symmetric per-output-channel (last-dim) int8 quantization.

    Quantizes from the weights AS GIVEN (callers pass the fp32 masters,
    not a bf16-rounded copy) and keeps the scale in fp32 — one rounding
    step (int8) instead of three (bf16 weight, int8, bf16 scale)."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2,
                     keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127,
                 127).astype(jnp.int8)
    return QuantizedWeight(q=q, scale=scale)


def _d(w, dtype):
    """Dense view of a (possibly quantized) weight in the compute dtype."""
    if isinstance(w, QuantizedWeight):
        # Dequant in fp32 (the scale's dtype), then one cast.
        return (w.q.astype(jnp.float32) * w.scale).astype(dtype)
    return w.astype(dtype)


# Layer matrices worth quantizing: ≥2-D projections (the per-layer
# stacks are 3-D: [L, in, out]). Norm scales/biases stay exact. The MoE
# decode path is not quant-aware — cast_params_for_decode rejects it
# loudly rather than serving silently-wrong weights. MLA's projections
# (incl. the absorbed w_uk/w_uv) read through _d and quantize fine.
_QUANT_KEYS = frozenset(
    ['wq', 'wk', 'wv', 'wo', 'w_gate', 'w_up', 'w_down',
     'w_dkv', 'w_kr', 'w_uk', 'w_uv', 'ws_gate', 'ws_up', 'ws_down'])


def cast_params_for_decode(params, cfg: llama.LlamaConfig,
                           quantize: Optional[str] = None):
    """Cast weights to the compute dtype once, for serving.

    Decode is HBM-bandwidth bound — every token reads every weight — so
    serving from fp32 master params wastes 2x bandwidth (and bf16 wastes
    2x vs `quantize='int8'`, which keeps a per-channel scale and
    dequantizes inside the matmul). Training keeps the fp32 masters; a
    serve engine calls this once at load."""
    if quantize not in (None, 'int8'):
        raise ValueError(f"quantize must be None or 'int8', got "
                         f'{quantize!r}')
    if quantize != 'int8':
        return jax.tree.map(lambda p: p.astype(cfg.dtype), params)
    from skypilot_tpu.models import moe as moe_lib
    if isinstance(cfg, moe_lib.MoEConfig):
        raise NotImplementedError(
            'int8 decode is implemented for the dense Llama and MLA '
            'families (MoE expert dispatch is not quant-aware yet).')
    # NOTE: quantized params do not mirror llama.param_specs' tree any
    # more (QuantizedWeight subtrees) — mesh placement handles them by
    # giving the int8 tensor the fp weight's spec and the per-channel
    # scale the same spec with broadcast (size-1) dims unsharded
    # (serve/engine._setup_mesh), so int8 composes with --mesh.
    out = {}
    for key, sub in params.items():
        if key != 'layers':
            out[key] = jax.tree.map(lambda p: p.astype(cfg.dtype), sub)
            continue
        layers = {}
        for k, w in sub.items():
            # ndim <= 3: per-layer [L, in, out] projection stacks. 4-D
            # routed-expert stacks (DeepSeek-MoE [L,E,in,out]) stay dense
            # — moe_ffn reads them directly, not through _d.
            if k in _QUANT_KEYS and 2 <= w.ndim <= 3:
                # Quantize from the RAW (fp32 master) weights, not a
                # bf16-rounded copy.
                layers[k] = _quantize_int8(w)
            else:
                layers[k] = w.astype(cfg.dtype)
        out[key] = layers
    return out


def init_cache(cfg: llama.LlamaConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.hd)
    return KVCache(k=jnp.zeros(shape, cfg.dtype),
                   v=jnp.zeros(shape, cfg.dtype),
                   length=jnp.zeros((batch,), jnp.int32))


def cache_pspecs(cfg: llama.LlamaConfig) -> KVCache:
    """PartitionSpecs mirroring init_cache's tree (the serving engine
    places the cache with these under --mesh). k/v [L, B, T, KH, hd]:
    batch over data/fsdp, kv-heads over tensor — the training rule
    table's layout, so decode's attention contractions stay local per
    TP shard."""
    del cfg
    from jax.sharding import PartitionSpec as P
    kv = P(None, ('data', 'fsdp'), None, 'tensor', None)
    return KVCache(k=kv, v=kv, length=P(('data', 'fsdp')))


def init_page_pool(cfg: llama.LlamaConfig, n_pages: int, page_size: int,
                   batch: int, max_pages: int, quant: str = 'none'):
    """Block-paged K/V pool for the serving engine (models/paging.py):
    [L, n_pages, page_size, KH, hd] pools, a zeroed [batch, max_pages]
    int32 page table (0 = trash page), and per-row lengths. Page COUNT
    is data, not shape — one pool serves every request mix.
    ``quant='int8'`` (SKYTPU_ENGINE_KV_QUANT) pools int8 codes plus
    [L, n_pages, page_size, KH] float32 scale sidecars — ~2x the pages
    in the same HBM footprint at bf16."""
    from skypilot_tpu.models import paging
    shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.hd)
    pool_dtype = jnp.int8 if quant == 'int8' else cfg.dtype

    def scale():
        # Distinct buffers: the step jits donate the cache tree, and
        # two leaves aliasing one buffer would double-donate.
        return (jnp.zeros(shape[:-1], jnp.float32)
                if quant == 'int8' else None)

    return paging.PagedKV(
        k=jnp.zeros(shape, pool_dtype), v=jnp.zeros(shape, pool_dtype),
        table=jnp.zeros((batch, max_pages), jnp.int32),
        length=jnp.zeros((batch,), jnp.int32),
        k_scale=scale(), v_scale=scale())


def paged_pspecs(cfg: llama.LlamaConfig, quant: str = 'none'):
    """PartitionSpecs mirroring init_page_pool's tree: the PAGE axis
    shards over data/fsdp (pages are interchangeable, so the pool
    spreads like the contiguous cache's batch axis did), kv-heads over
    tensor; tables/lengths replicate (tiny, host-updated). The scale
    sidecars mirror the pools minus the last axis."""
    del cfg
    from jax.sharding import PartitionSpec as P
    from skypilot_tpu.models import paging
    kv = P(None, ('data', 'fsdp'), None, 'tensor', None)
    scale = (P(None, ('data', 'fsdp'), None, 'tensor')
             if quant == 'int8' else None)
    return paging.PagedKV(k=kv, v=kv, table=P(), length=P(),
                          k_scale=scale, v_scale=scale)


def _qkv(x: jnp.ndarray, lp, cfg: llama.LlamaConfig, sin, cos):
    """Shared with training math: norm → q/k/v projections → (qk-norm) →
    rope. sin/cos must already be per-layer (llama.select_rope)."""
    b, s, _ = x.shape
    hd = cfg.hd
    h = norms.rms_norm(x, lp['attn_norm'], cfg.rms_eps,
                       scale_plus_one=cfg.norm_plus_one)
    q = jnp.einsum('bsd,dh->bsh', h, _d(lp['wq'], cfg.dtype))
    k = jnp.einsum('bsd,dh->bsh', h, _d(lp['wk'], cfg.dtype))
    v = jnp.einsum('bsd,dh->bsh', h, _d(lp['wv'], cfg.dtype))
    if cfg.qkv_bias:
        q = q + lp['bq'].astype(cfg.dtype)
        k = k + lp['bk'].astype(cfg.dtype)
        v = v + lp['bv'].astype(cfg.dtype)
    q = q.reshape(b, s, cfg.n_heads, hd)
    k = k.reshape(b, s, cfg.n_kv_heads, hd)
    v = v.reshape(b, s, cfg.n_kv_heads, hd)
    if cfg.qk_norm:
        q = norms.rms_norm(q, lp['q_norm'], cfg.rms_eps,
                           scale_plus_one=cfg.norm_plus_one)
        k = norms.rms_norm(k, lp['k_norm'], cfg.rms_eps,
                           scale_plus_one=cfg.norm_plus_one)
    q = rotary.apply_rope(q, sin, cos)
    k = rotary.apply_rope(k, sin, cos)
    return q, k, v


def _wo_project(out, lp, cfg: llama.LlamaConfig) -> jnp.ndarray:
    """Attention output projection (+ Gemma-2 post-attention norm)."""
    y = jnp.einsum('bsh,hd->bsd', out, _d(lp['wo'], cfg.dtype))
    if cfg.post_norms:
        y = norms.rms_norm(y, lp['post_attn_norm'], cfg.rms_eps,
                           scale_plus_one=cfg.norm_plus_one)
    return y


def _ffn(x: jnp.ndarray, lp, cfg: llama.LlamaConfig) -> jnp.ndarray:
    """Post-attention FFN block: dense SwiGLU, or routed experts for MoE
    configs. The MoE path reuses training's grouped static-capacity
    dispatch (models/moe.py) — at decode (S=1) every group holds one
    token, top-k choices land on distinct experts, and the min-8 capacity
    means no token is ever dropped, so decode matches the training
    forward exactly (asserted in tests/unit_tests/test_decode.py)."""
    from skypilot_tpu.models import moe as moe_lib
    if isinstance(cfg, moe_lib.MoEConfig):
        h = norms.rms_norm(x, lp['moe_norm'], cfg.rms_eps)
        y, _ = moe_lib.moe_ffn(h, lp, cfg, sharding_lib.Rules())
        return y
    h = norms.rms_norm(x, lp['mlp_norm'], cfg.rms_eps,
                       scale_plus_one=cfg.norm_plus_one)
    gate = jnp.einsum('bsd,df->bsf', h, _d(lp['w_gate'], cfg.dtype))
    up = jnp.einsum('bsd,df->bsf', h, _d(lp['w_up'], cfg.dtype))
    down = jnp.einsum('bsf,fd->bsd', cfg.glu(gate, up),
                      _d(lp['w_down'], cfg.dtype))
    if cfg.post_norms:
        down = norms.rms_norm(down, lp['post_mlp_norm'], cfg.rms_eps,
                              scale_plus_one=cfg.norm_plus_one)
    return down


def _unembed(x: jnp.ndarray, params, cfg: llama.LlamaConfig) -> jnp.ndarray:
    x = norms.rms_norm(x, params['final_norm'], cfg.rms_eps,
                       scale_plus_one=cfg.norm_plus_one)
    head = (params['embed'].T if cfg.tie_embeddings else params['lm_head'])
    logits = jnp.einsum('bsd,dv->bsv', x, head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    if cfg.final_logit_softcap:
        cap = cfg.final_logit_softcap
        logits = cap * jnp.tanh(logits / cap)
    return logits


def prefill(params, tokens: jnp.ndarray, cfg: llama.LlamaConfig,
            max_len: int, rules: Optional[sharding_lib.Rules] = None,
            lengths: Optional[jnp.ndarray] = None
            ) -> Tuple[jnp.ndarray, KVCache]:
    """Process the prompt in one pass. tokens [B, S] → (per-row
    last-content-position logits [B, vocab], filled cache).

    `lengths` [B] enables RAGGED batches: rows are right-padded to S,
    content occupies [0, lengths[b]). Causality already keeps content
    positions from attending the later pad positions, pad K/V beyond a
    row's length is masked during decode (per-row q_offset) and each
    decode step overwrites its own slot before attending it — so no
    padding mask is needed anywhere. MoE caveat: pad tokens still route
    (and can consume expert capacity within their row's groups) during
    a ragged prefill; with the default min-8 capacity this only matters
    when capacity binds — use uniform-length batches when bit-exact MoE
    prefill equivalence is required."""
    rules = rules or sharding_lib.Rules()
    b, s = tokens.shape
    if s > max_len:
        raise ValueError(f'prompt length {s} exceeds cache max_len {max_len}')
    lengths = (jnp.full((b,), s, jnp.int32) if lengths is None
               else jnp.asarray(lengths, jnp.int32))
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.dim ** 0.5, cfg.dtype)
    positions = jnp.arange(s)
    sin, cos = llama.rope_tables(cfg, positions)

    # Ring attention is a training-time context-parallel impl; decode
    # prompts fit on-chip, so route it to the standard path.
    impl = 'auto' if cfg.attention_impl == 'ring' else cfg.attention_impl

    def body(carry, xs):
        lp, layer_idx = xs
        sin_l, cos_l = llama.select_rope(sin, cos, layer_idx, cfg)
        q, k, v = _qkv(carry, lp, cfg, sin_l, cos_l)
        w_active = (llama.window_active(layer_idx, cfg)
                    if cfg.sliding_window else None)
        out = _attention(q, k, v, impl=impl, causal=True,
                         logit_softcap=cfg.attn_logit_softcap,
                         window=cfg.sliding_window, window_active=w_active,
                         sinks=(lp['sink'].astype(jnp.float32)
                                if cfg.attn_sinks else None))
        out = out.reshape(b, s, cfg.n_heads * cfg.hd)
        carry = carry + _wo_project(out, lp, cfg)
        carry = carry + _ffn(carry, lp, cfg)
        return carry, (k, v)

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    x, (ks, vs) = jax.lax.scan(body, x, (params['layers'], layer_ids))
    pad = [(0, 0), (0, 0), (0, max_len - s), (0, 0), (0, 0)]
    cache = KVCache(k=jnp.pad(ks, pad), v=jnp.pad(vs, pad),
                    length=lengths)
    x_last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)
    logits = _unembed(x_last, params, cfg)
    return logits[:, 0], cache


def prefill_extend(params, tokens: jnp.ndarray, cfg: llama.LlamaConfig,
                   max_len: int, prefix_k: jnp.ndarray,
                   prefix_v: jnp.ndarray,
                   lengths: Optional[jnp.ndarray] = None
                   ) -> Tuple[jnp.ndarray, KVCache]:
    """Prefill a SUFFIX over an already-computed prefix KV
    (prefix caching: a shared system prompt / chat history pays its
    prefill once; later requests only prefill their new tokens).

    tokens [B, S2] (suffix, right-padded; `lengths` [B] = real suffix
    lengths), prefix_k/v [L, B, P, KH, hd] with every row holding a
    FULL P-token prefix. Returns per-row last-content logits and a
    cache whose rows are [prefix + suffix] with length P + lengths.

    The suffix queries run at positions P..P+S2 (rope + causal offsets)
    attending over [prefix_kv ++ suffix_kv] — exactly the math full
    prefill would produce (asserted bit-for-bit in tests). P and the S2
    bucket are static → one compile per (P, S2-bucket) pair; callers
    keep P to powers of two to bound the program count.

    MoE configs route the FFN through the expert path (decode._ffn) —
    exact equivalence with full prefill additionally requires expert
    capacity not to bind (drops depend on how many tokens share a
    dispatch group; a P+S2 split groups differently than one pass) —
    the same batch-composition nondeterminism capacity-bound MoE
    serving always has.
    """
    b, s2 = tokens.shape
    p = prefix_k.shape[2]
    if p + s2 > max_len:
        raise ValueError(f'prefix ({p}) + suffix ({s2}) exceeds '
                         f'max_len ({max_len})')
    lengths = (jnp.full((b,), s2, jnp.int32) if lengths is None
               else jnp.asarray(lengths, jnp.int32))
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.dim ** 0.5, cfg.dtype)
    positions = jnp.arange(s2) + p
    sin, cos = llama.rope_tables(cfg, positions)
    impl = 'auto' if cfg.attention_impl == 'ring' else cfg.attention_impl

    def body(carry, xs):
        lp, layer_idx, pk, pv = xs
        sin_l, cos_l = llama.select_rope(sin, cos, layer_idx, cfg)
        q, k, v = _qkv(carry, lp, cfg, sin_l, cos_l)
        k_all = jnp.concatenate([pk.astype(k.dtype), k], axis=1)
        v_all = jnp.concatenate([pv.astype(v.dtype), v], axis=1)
        w_active = (llama.window_active(layer_idx, cfg)
                    if cfg.sliding_window else None)
        out = _attention(q, k_all, v_all, impl=impl, causal=True,
                         q_offset=p, kv_offset=0,
                         logit_softcap=cfg.attn_logit_softcap,
                         window=cfg.sliding_window,
                         window_active=w_active,
                         sinks=(lp['sink'].astype(jnp.float32)
                                if cfg.attn_sinks else None))
        out = out.reshape(b, s2, cfg.n_heads * cfg.hd)
        carry = carry + _wo_project(out, lp, cfg)
        carry = carry + _ffn(carry, lp, cfg)
        return carry, (k, v)

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    x, (ks, vs) = jax.lax.scan(
        body, x, (params['layers'], layer_ids, prefix_k, prefix_v))
    full_k = jnp.concatenate([prefix_k.astype(ks.dtype), ks], axis=2)
    full_v = jnp.concatenate([prefix_v.astype(vs.dtype), vs], axis=2)
    pad = [(0, 0), (0, 0), (0, max_len - p - s2), (0, 0), (0, 0)]
    cache = KVCache(k=jnp.pad(full_k, pad), v=jnp.pad(full_v, pad),
                    length=p + lengths)
    x_last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)
    logits = _unembed(x_last, params, cfg)
    return logits[:, 0], cache


def decode_step(params, token: jnp.ndarray, cache: KVCache,
                cfg: llama.LlamaConfig,
                rules: Optional[sharding_lib.Rules] = None,
                active: Optional[jnp.ndarray] = None
                ) -> Tuple[jnp.ndarray, KVCache]:
    """One incremental step. token [B] int32 → (logits [B, vocab], cache).

    `active` [B] bool (continuous batching): rows where it is False do not
    advance their cache length — their compute still runs (static shapes)
    but writes land on the row's frozen `length` slot, which the next
    admission overwrites, and the caller discards their logits.

    Implemented as the K=1 case of `verify_step` (the K-wide step below)
    plus the length advance — ONE copy of the per-layer cache-scatter /
    attention body serves single-step decode, speculative verification,
    and anything else that needs multi-token steps.
    """
    del rules
    logits, cache = verify_step(params, token[:, None], cache, cfg)
    advance = 1 if active is None else active.astype(jnp.int32)
    return logits[:, 0], KVCache(k=cache.k, v=cache.v,
                                 length=cache.length + advance)

def verify_step(params, tokens: jnp.ndarray, cache: KVCache,
                cfg: llama.LlamaConfig
                ) -> Tuple[jnp.ndarray, KVCache]:
    """Process K tokens per row at each row's own offset in ONE call —
    the target-model half of speculative decoding (and a K-token
    decode_step in general).

    tokens [B, K] → logits [B, K, vocab]; K/V for all K positions are
    written at rows' [length, length+K) slots, but `length` is NOT
    advanced — the caller commits however many tokens verification
    accepts (stale K/V beyond the committed length is causally masked
    and overwritten later, so rollback is free — the same property
    ragged decode already relies on).
    """
    b, kk = tokens.shape
    length = cache.length
    rows = jnp.arange(b)
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.dim ** 0.5, cfg.dtype)
    positions = length[:, None] + jnp.arange(kk)          # [B, K]
    sin, cos = llama.rope_tables(cfg, positions)

    def body(carry, xs):
        x_c, k_cache, v_cache = carry
        lp, layer_idx = xs
        sin_l, cos_l = llama.select_rope(sin, cos, layer_idx, cfg)
        q, k_new, v_new = _qkv(x_c, lp, cfg, sin_l, cos_l)
        k_l = jax.lax.dynamic_index_in_dim(k_cache, layer_idx, axis=0,
                                           keepdims=False)
        v_l = jax.lax.dynamic_index_in_dim(v_cache, layer_idx, axis=0,
                                           keepdims=False)
        k_l = k_l.at[rows[:, None], positions].set(k_new)
        v_l = v_l.at[rows[:, None], positions].set(v_new)
        k_cache = jax.lax.dynamic_update_index_in_dim(k_cache, k_l,
                                                      layer_idx, axis=0)
        v_cache = jax.lax.dynamic_update_index_in_dim(v_cache, v_l,
                                                      layer_idx, axis=0)
        w_active = (llama.window_active(layer_idx, cfg)
                    if cfg.sliding_window else None)
        out = _attention(q, k_l, v_l, impl='xla', causal=True,
                         q_offset=length, kv_offset=0,
                         logit_softcap=cfg.attn_logit_softcap,
                         window=cfg.sliding_window, window_active=w_active,
                         sinks=(lp['sink'].astype(jnp.float32)
                                if cfg.attn_sinks else None))
        out = out.reshape(b, kk, cfg.n_heads * cfg.hd)
        x_c = x_c + _wo_project(out, lp, cfg)
        x_c = x_c + _ffn(x_c, lp, cfg)
        return (x_c, k_cache, v_cache), None

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    (x, ks, vs), _ = jax.lax.scan(
        body, (x, cache.k, cache.v), (params['layers'], layer_ids))
    logits = _unembed(x, params, cfg)
    return logits, KVCache(k=ks, v=vs, length=length)


def paged_verify_step(params, tokens: jnp.ndarray, pcache,
                      cfg: llama.LlamaConfig, *, max_len: int,
                      active: Optional[jnp.ndarray] = None,
                      attn: str = 'fused'):
    """`verify_step` over the block-paged pool, IN PLACE: K/V for the
    K fed positions are written straight into each row's pages
    (inactive rows route to the trash page) and attention indexes the
    pages per layer inside the scan body (ops/paged_attention.py) — no
    contiguous [L, B, max_len, ...] view is materialized and nothing
    scatters back afterwards. Bit-identical to
    gather_view → verify_step → scatter_steps by construction: the
    per-layer page gather reads the same values the materialized view
    held, the new K/V overlay lands at the same positions, and the
    attention reduction is the unchanged XLA path (property-tested in
    tests/unit_tests/test_paging.py). `length` does NOT advance — the
    same commit contract as verify_step.

    Int8 pools (k_scale/v_scale sidecars set) thread the scales
    through the scan carry and the dequant fuses into the per-layer
    page gather (ops/paged_attention.py) — allclose to the fp path,
    gated by the pinned quality eval."""
    from skypilot_tpu.models import paging
    from skypilot_tpu.ops import paged_attention as pa
    quant = paging.quantized(pcache)
    b, kk = tokens.shape
    length = pcache.length
    positions = length[:, None] + jnp.arange(kk)          # [B, K]
    pid, off = paging._write_indices(pcache, positions, active)
    table = pcache.table
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.dim ** 0.5, cfg.dtype)
    sin, cos = llama.rope_tables(cfg, positions)

    def body(carry, xs):
        x_c, kp_all, vp_all, ks_all, vs_all = carry
        lp, layer_idx = xs
        sin_l, cos_l = llama.select_rope(sin, cos, layer_idx, cfg)
        q, k_new, v_new = _qkv(x_c, lp, cfg, sin_l, cos_l)

        def sel(a):
            return jax.lax.dynamic_index_in_dim(a, layer_idx, axis=0,
                                                keepdims=False)

        kp, vp = sel(kp_all), sel(vp_all)
        ks = sel(ks_all) if quant else None
        vs = sel(vs_all) if quant else None
        w_active = (llama.window_active(layer_idx, cfg)
                    if cfg.sliding_window else None)
        res = pa.paged_attention_step(
            q, kp, vp, table, length, k_new, v_new, pid, off,
            max_len=max_len, impl=attn,
            logit_softcap=cfg.attn_logit_softcap,
            window=cfg.sliding_window, window_active=w_active,
            sinks=(lp['sink'].astype(jnp.float32)
                   if cfg.attn_sinks else None),
            k_scale=ks, v_scale=vs)

        def put(a, new):
            return jax.lax.dynamic_update_index_in_dim(a, new,
                                                       layer_idx,
                                                       axis=0)

        if quant:
            out, kp, vp, ks, vs = res
            ks_all, vs_all = put(ks_all, ks), put(vs_all, vs)
        else:
            out, kp, vp = res
        kp_all, vp_all = put(kp_all, kp), put(vp_all, vp)
        out = out.reshape(b, kk, cfg.n_heads * cfg.hd)
        x_c = x_c + _wo_project(out, lp, cfg)
        x_c = x_c + _ffn(x_c, lp, cfg)
        return (x_c, kp_all, vp_all, ks_all, vs_all), None

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    # None rides the carry as an empty pytree on the fp path — the
    # quant branches above are static Python, so one program per mode.
    (x, kps, vps, kss, vss), _ = jax.lax.scan(
        body, (x, pcache.k, pcache.v, pcache.k_scale, pcache.v_scale),
        (params['layers'], layer_ids))
    logits = _unembed(x, params, cfg)
    return logits, dataclasses.replace(pcache, k=kps, v=vps,
                                       k_scale=kss, v_scale=vss)


def paged_decode_step(params, token: jnp.ndarray, pcache,
                      cfg: llama.LlamaConfig, *, max_len: int,
                      active: Optional[jnp.ndarray] = None,
                      attn: str = 'fused'):
    """One in-place paged decode step — the K=1 case of
    :func:`paged_verify_step` plus the per-row length advance (the
    same relationship decode_step has to verify_step)."""
    logits, pcache = paged_verify_step(params, token[:, None], pcache,
                                       cfg, max_len=max_len,
                                       active=active, attn=attn)
    advance = 1 if active is None else active.astype(jnp.int32)
    return logits[:, 0], dataclasses.replace(
        pcache, length=pcache.length + advance)


def paged_prefill_extend(params, tokens: jnp.ndarray, pcache,
                         cfg: llama.LlamaConfig, *, slot, p: int,
                         lengths, attn: str = 'fused'):
    """`prefill_extend` for ONE paged row, in place: the [1, S2] suffix
    attends [prefix ++ suffix] with the prefix gathered per layer from
    the (possibly shared) pages row ``slot``'s table covers, and the
    suffix K/V writes land straight in the row's own pages — the
    chunked-prefill / prefix-hit program with no gather_prefix
    materialization across layers and no scatter_suffix afterwards.
    Bit-identical to the gather formulation for the same reason
    paged_verify_step is. length[slot] = p + lengths. Int8 pools
    dequantize the gathered prefix per layer and quantize the suffix
    writes — the same codes every later gather reads."""
    del attn  # extend has no pallas kernel yet; the fused path serves.
    from skypilot_tpu.models import paging
    from skypilot_tpu.ops import paged_attention as pa
    quant = paging.quantized(pcache)
    b, s2 = tokens.shape
    psz = paging.page_size_of(pcache)
    pre_pos = jnp.arange(p)
    pre_pid = pcache.table[slot, pre_pos // psz]           # [p]
    pre_off = pre_pos % psz
    suf_pos = p + jnp.arange(s2)
    suf_pid = pcache.table[slot, suf_pos // psz]           # [s2]
    suf_off = suf_pos % psz
    lengths = jnp.asarray(lengths, jnp.int32).reshape((b,))
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.dim ** 0.5, cfg.dtype)
    positions = jnp.arange(s2) + p
    sin, cos = llama.rope_tables(cfg, positions)
    impl = 'auto' if cfg.attention_impl == 'ring' else cfg.attention_impl

    def body(carry, xs):
        x_c, kp_all, vp_all, ks_all, vs_all = carry
        lp, layer_idx = xs
        sin_l, cos_l = llama.select_rope(sin, cos, layer_idx, cfg)
        q, k, v = _qkv(x_c, lp, cfg, sin_l, cos_l)

        def sel(a):
            return jax.lax.dynamic_index_in_dim(a, layer_idx, axis=0,
                                                keepdims=False)

        kp, vp = sel(kp_all), sel(vp_all)
        if quant:
            ks, vs = sel(ks_all), sel(vs_all)
            kq, ks_new = pa.quantize_values(k)
            vq, vs_new = pa.quantize_values(v)
            # The suffix attends its own DEQUANTIZED values — exactly
            # what later decode gathers of these positions will read.
            k = pa.dequantize_values(kq, ks_new, k.dtype)
            v = pa.dequantize_values(vq, vs_new, v.dtype)
            pk = pa.dequantize_values(kp[pre_pid, pre_off][None],
                                      ks[pre_pid, pre_off][None],
                                      k.dtype)
            pv = pa.dequantize_values(vp[pre_pid, pre_off][None],
                                      vs[pre_pid, pre_off][None],
                                      v.dtype)
        else:
            pk = kp[pre_pid, pre_off][None]                # [1, p, ...]
            pv = vp[pre_pid, pre_off][None]
        k_all = jnp.concatenate([pk.astype(k.dtype), k], axis=1)
        v_all = jnp.concatenate([pv.astype(v.dtype), v], axis=1)
        w_active = (llama.window_active(layer_idx, cfg)
                    if cfg.sliding_window else None)
        out = _attention(q, k_all, v_all, impl=impl, causal=True,
                         q_offset=p, kv_offset=0,
                         logit_softcap=cfg.attn_logit_softcap,
                         window=cfg.sliding_window,
                         window_active=w_active,
                         sinks=(lp['sink'].astype(jnp.float32)
                                if cfg.attn_sinks else None))

        def put(a, new):
            return jax.lax.dynamic_update_index_in_dim(a, new,
                                                       layer_idx,
                                                       axis=0)

        if quant:
            kp_all = put(kp_all, kp.at[suf_pid, suf_off].set(kq[0]))
            vp_all = put(vp_all, vp.at[suf_pid, suf_off].set(vq[0]))
            ks_all = put(ks_all,
                         ks.at[suf_pid, suf_off].set(ks_new[0]))
            vs_all = put(vs_all,
                         vs.at[suf_pid, suf_off].set(vs_new[0]))
        else:
            kp_all = put(kp_all, kp.at[suf_pid, suf_off].set(k[0]))
            vp_all = put(vp_all, vp.at[suf_pid, suf_off].set(v[0]))
        out = out.reshape(b, s2, cfg.n_heads * cfg.hd)
        x_c = x_c + _wo_project(out, lp, cfg)
        x_c = x_c + _ffn(x_c, lp, cfg)
        return (x_c, kp_all, vp_all, ks_all, vs_all), None

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    (x, kps, vps, kss, vss), _ = jax.lax.scan(
        body, (x, pcache.k, pcache.v, pcache.k_scale, pcache.v_scale),
        (params['layers'], layer_ids))
    x_last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)
    logits = _unembed(x_last, params, cfg)
    length = pcache.length.at[slot].set(p + lengths[0])
    return logits[:, 0], dataclasses.replace(pcache, k=kps, v=vps,
                                             k_scale=kss, v_scale=vss,
                                             length=length)


# Persistent compile caches for the speculative loop (cfg static:
# model configs are frozen/hashable dataclasses).
_verify_step_jit = jax.jit(verify_step, static_argnames=('cfg',))
_decode_step_jit = jax.jit(decode_step, static_argnames=('cfg',))


def generate_speculative(params, cfg: llama.LlamaConfig,
                         draft_params, draft_cfg: llama.LlamaConfig,
                         prompt: jnp.ndarray, max_new_tokens: int, *,
                         k: int = 4, max_len: Optional[int] = None,
                         eos_id: Optional[int] = None,
                         prompt_lengths: Optional[jnp.ndarray] = None,
                         return_stats: bool = False):
    """Greedy speculative decoding: a cheap draft proposes k tokens,
    the target verifies them in ONE K-wide call (verify_step), and the
    longest agreeing prefix commits — plus the target's own next token,
    so every round commits ≥ 1 token and the OUTPUT IS EXACTLY the
    target model's greedy generation regardless of the draft (the
    speculative-decoding guarantee; pin-tested against generate()).

    Reference analog: vLLM/JetStream speculative decoding on TPU
    serving. TPU-first: all shapes static (rounds are k draft steps +
    one K-wide verify; per-row acceptance just moves the cache
    `length`, rollback costs nothing); batch rows progress at their own
    rates under per-row offsets.

    Requires vocab-compatible models (draft.vocab_size >=
    target.vocab_size) and greedy (temperature-0) semantics.
    """
    b, s = prompt.shape
    if draft_cfg.vocab_size < cfg.vocab_size:
        raise ValueError(
            f'draft vocab {draft_cfg.vocab_size} < target vocab '
            f'{cfg.vocab_size}: draft proposals could be unscorable')
    if max_len is None:
        max_len = min(cfg.max_seq_len, s + max_new_tokens + 2 * k)
    # The verify lookahead needs up to 2k slots past s + max_new (k of
    # in-flight writes + up to k of final-round overshoot). Near the
    # context limit, shrink k — and when even k=1 doesn't fit, fall
    # back to plain generate (identical output contract, just slower).
    budget = max_len - s - max_new_tokens
    if budget < 2 * k:
        k = budget // 2
        if k < 1:
            out = generate(params, prompt, cfg, max_new_tokens,
                           max_len=max_len, eos_id=eos_id,
                           prompt_lengths=prompt_lengths)
            if return_stats:
                return out, {'rounds': max_new_tokens, 'fallback': True}
            return out
    import numpy as np
    if max_new_tokens <= 0:
        out = jnp.zeros((b, 0), jnp.int32)
        return (out, {'rounds': 0}) if return_stats else out

    t_logits, t_cache = prefill(params, prompt, cfg, max_len,
                                lengths=prompt_lengths)
    d_logits, d_cache = prefill(draft_params, prompt, draft_cfg, max_len,
                                lengths=prompt_lengths)
    del d_logits
    last = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)   # [B]

    # Module-level jits with the config static: the compile caches
    # persist across calls (the per-call jit(partial(...)) alternative
    # would retrace every invocation).
    verify_t = functools.partial(_verify_step_jit, cfg=cfg)
    step_d = functools.partial(_decode_step_jit, cfg=draft_cfg)
    out = np.zeros((b, max_new_tokens), np.int32)
    count = np.ones((b,), np.int64)     # committed tokens per row
    done = np.zeros((b,), bool)
    last_h = np.asarray(jax.device_get(last))
    out[:, 0] = last_h
    if eos_id is not None:
        done |= (last_h == eos_id)
        count[done] = max_new_tokens
        for r in np.flatnonzero(done):
            out[r, :] = eos_id

    # Invariant at the top of each round: both caches hold KV for every
    # committed token EXCEPT `last` (the newest), and both `length`s
    # advance by exactly the number of tokens a round commits.
    rounds = 0
    while count.min() < max_new_tokens:
        rounds += 1
        t_len0 = t_cache.length
        d_len0 = d_cache.length
        # 1) Draft proposes d1..dk following `last` (writing its own KV
        # for [last, d1..d_{k-1}] as a side effect).
        proposals = []
        d_tok = last
        for _ in range(k):
            dl, d_cache = step_d(draft_params, d_tok, d_cache)
            d_tok = jnp.argmax(
                dl[:, :cfg.vocab_size], axis=-1).astype(jnp.int32)
            proposals.append(d_tok)
        drafted = jnp.stack(proposals, axis=1)               # [B, k]

        # 2) Target scores fed = [last, d1..d_{k-1}] in ONE K-wide call;
        # greedy[:, i] is the target's token following fed[:, :i+1], so
        # d_j is accepted iff d_j == greedy[:, j-1] for every j' <= j.
        fed = jnp.concatenate([last[:, None], drafted[:, :-1]], axis=1)
        v_logits, t_cache = verify_t(params, fed, t_cache)
        greedy = np.asarray(jax.device_get(
            jnp.argmax(v_logits, axis=-1)))                  # [B, k]
        drafted_h = np.asarray(jax.device_get(drafted))

        # 3) Per-row commit: the agreed run d1..da, plus the target's
        # correction greedy[a] when a < k (so every round commits >= 1).
        # When a == k the new `last` is d_k (scored equal to greedy[k-1]
        # but its KV is not written yet — exactly the invariant).
        n_commit = np.zeros((b,), np.int32)
        new_last = last_h.copy()
        for r in range(b):
            if done[r] or count[r] >= max_new_tokens:
                continue
            a = 0
            while a < k and drafted_h[r, a] == greedy[r, a]:
                a += 1
            if a < k:
                row = list(drafted_h[r, :a]) + [int(greedy[r, a])]
            else:
                row = list(drafted_h[r, :k])
            n_commit[r] = len(row)
            new_last[r] = row[-1]
            space = max_new_tokens - int(count[r])
            take = row[:space]
            out[r, count[r]:count[r] + len(take)] = take
            count[r] = min(count[r] + len(row), max_new_tokens)
            if eos_id is not None and eos_id in take:
                p = int(count[r]) - len(take) + take.index(eos_id)
                out[r, p:] = eos_id
                count[r] = max_new_tokens
                done[r] = True
        last_h = new_last
        last = jnp.asarray(last_h)

        # 4) Both cache lengths advance by the committed count (rows
        # that committed nothing roll the draft's k-step advance back).
        adv = jnp.asarray(n_commit, jnp.int32)
        t_cache = KVCache(k=t_cache.k, v=t_cache.v,
                          length=t_len0 + adv)
        d_cache = KVCache(k=d_cache.k, v=d_cache.v,
                          length=d_len0 + adv)
    if return_stats:
        return jnp.asarray(out), {'rounds': rounds}
    return jnp.asarray(out)


def _select_token(logits: jnp.ndarray, temperature: float,
                  rng: Optional[jax.Array],
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None) -> jnp.ndarray:
    """Greedy (temperature<=0) or filtered sampling. top_k keeps the k
    highest logits; top_p keeps the smallest nucleus whose probability
    mass reaches p (the highest-probability token always survives). All
    static-shaped: filters are masks, never gathers, so one compiled
    step serves every request."""
    if temperature <= 0.0 or rng is None:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    neg_inf = jnp.finfo(logits.dtype).min
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k, None]
        logits = jnp.where(logits < kth, neg_inf, logits)
    if top_p is not None and 0.0 < top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep ranks whose PRECEDING mass is < p (rank 0 always kept);
        # the cutoff logit is the smallest kept sorted logit.
        keep = (cum - probs) < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, neg_inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def top_k_logprobs(logits: jnp.ndarray, k: int
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k alternative logprobs of the UNPENALIZED model distribution
    (OpenAI ``logprobs=N`` / ``top_logprobs``): [..., V] logits →
    (values [..., k] fp32, ids [..., k] i32). Family-agnostic (plain
    logits math), shared by the serving engine's step/admit/verify
    programs for both the KVCache and MLA latent families — and only
    COMPILED into the variants whose requests asked for it (the
    engine's ``want_tops`` static flag)."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
    v, i = jax.lax.top_k(logits, k)
    return (v - lse).astype(jnp.float32), i.astype(jnp.int32)


def chosen_logprob(logits: jnp.ndarray, tokens: jnp.ndarray
                   ) -> jnp.ndarray:
    """log P(token) under the UNMODIFIED model distribution
    (temperature/top-k/top-p shape sampling, not the reported
    probability — OpenAI `logprobs` semantics). logits [B, V],
    tokens [B] → [B] fp32."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, tokens[:, None], axis=-1)[:, 0]
    return gold - logz


def select_token_per_row(logits: jnp.ndarray, temperature: jnp.ndarray,
                         top_k: jnp.ndarray, top_p: jnp.ndarray,
                         rng: jax.Array,
                         counts: Optional[jnp.ndarray] = None,
                         presence: Optional[jnp.ndarray] = None,
                         frequency: Optional[jnp.ndarray] = None
                         ) -> jnp.ndarray:
    """Vectorized PER-ROW sampling for the continuous batcher: rows with
    different sampling params share one compiled step.

    logits [B,V]; temperature [B] f32 (<=0 → greedy); top_k [B] int32
    (<=0 → off, values clamped to vocab — an oversized client top_k can
    not fail the batch); top_p [B] f32 (outside (0,1) → off). Same mask
    construction as `_select_token`, lifted to per-row thresholds.

    `counts` [B,V] int32 (+ per-row `presence`/`frequency` [B] f32):
    OpenAI repetition penalties — logits lose presence·1[count>0] +
    frequency·count BEFORE temperature/filtering, so they bite in
    greedy mode too. Counts cover GENERATED tokens (vLLM semantics).
    """
    b, v = logits.shape
    del b
    logits = logits.astype(jnp.float32)
    if counts is not None:
        pen = (presence[:, None] * (counts > 0).astype(jnp.float32) +
               frequency[:, None] * counts.astype(jnp.float32))
        logits = logits - pen
    greedy = temperature <= 0.0
    scaled = logits / jnp.where(greedy, 1.0, temperature)[:, None]
    neg_inf = jnp.finfo(jnp.float32).min
    # top-k: per-row threshold at the k-th highest logit.
    asc = jnp.sort(scaled, axis=-1)                     # ascending [B, V]
    k = jnp.clip(top_k, 1, v)
    kth = jnp.take_along_axis(asc, (v - k)[:, None], axis=-1)
    use_k = (top_k > 0)[:, None]
    scaled = jnp.where(use_k & (scaled < kth), neg_inf, scaled)
    # top-p nucleus on the (possibly top-k-filtered) logits.
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    use_p = (top_p > 0.0) & (top_p < 1.0)
    p_eff = jnp.where(use_p, top_p, 1.0)[:, None]
    keep = (cum - probs) < p_eff                        # rank 0 always kept
    cutoff = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    scaled = jnp.where(use_p[:, None] & (scaled < cutoff), neg_inf, scaled)
    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1).astype(jnp.int32),
                     sampled)


@functools.partial(jax.jit,
                   static_argnames=('cfg', 'max_new_tokens', 'max_len',
                                    'temperature', 'eos_id', 'top_k',
                                    'top_p'))
def generate(params, prompt: jnp.ndarray, cfg: llama.LlamaConfig,
             max_new_tokens: int, *, max_len: Optional[int] = None,
             temperature: float = 0.0, eos_id: Optional[int] = None,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             prompt_lengths: Optional[jnp.ndarray] = None,
             rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Greedy/temperature/top-k/top-p generation, fully jitted.

    prompt [B, S] → generated tokens [B, max_new_tokens] (positions after an
    eos are filled with eos). `prompt_lengths` [B] serves RAGGED batches:
    rows right-padded to S generate from their own content length (the
    dynamic batcher in serve/engine.py relies on this to group
    mixed-length requests under one compiled program).
    """
    b, s = prompt.shape
    if max_len is None:
        max_len = min(cfg.max_seq_len, s + max_new_tokens)
    if s + max_new_tokens > max_len:
        raise ValueError(
            f'prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds '
            f'max_len ({max_len})')
    logits, cache = prefill(params, prompt, cfg, max_len,
                            lengths=prompt_lengths)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    first = _select_token(logits, temperature, rng, top_k, top_p)
    done0 = (jnp.full((b,), False) if eos_id is None else first == eos_id)

    def body(carry, step_rng):
        tok, cache, done = carry
        logits, cache = decode_step(params, tok, cache, cfg)
        nxt = _select_token(logits, temperature, step_rng, top_k, top_p)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            done = jnp.logical_or(done, nxt == eos_id)
        return (nxt, cache, done), nxt

    step_rngs = jax.random.split(rng, max(max_new_tokens - 1, 1))
    (_, _, _), rest = jax.lax.scan(body, (first, cache, done0),
                                   step_rngs[:max_new_tokens - 1])
    return jnp.concatenate([first[:, None], rest.T], axis=1)
