"""DeepSeek-family decoder: Multi-head Latent Attention (MLA), TPU-first.

Reference context: the reference launches DeepSeek models through vLLM/
SGLang recipes (llm/deepseek-r1/, llm/kimi-k2/ — SURVEY §2.11); here the
architecture is native. MLA replaces GQA's shared K/V heads with a
low-rank KV bottleneck:

  c_kv   = x · W_dkv                      [B,S,r]       (latent, r≈512)
  k_rope = rope(x · W_kr)                 [B,S,dr]      (ONE shared rope key)
  k_nope = c_kv · W_uk  (per head)        [B,S,H,dn]
  v      = c_kv · W_uv  (per head)        [B,S,H,dv]
  q      = x · W_q → split (q_nope [dn] | q_rope [dr], rope'd per head)
  score  = q_nope·k_nope + q_rope·k_rope  (shared-rope term broadcast)

TPU-first decode: the cache holds ONLY (c_kv, k_rope) — r+dr floats per
token instead of 2·H·hd (≈18x smaller than MHA at DeepSeek-V2 shapes), so
the HBM-bound decode step reads a fraction of the K/V traffic. Scores are
computed by ABSORPTION — q_nope is pulled through W_uk once per step
(q̃ = q_nope·W_ukᵀ, score = q̃·c_kv) and the value side re-expands
probs·c_kv through W_uv — so the per-token work is einsums over the
latent, never a materialized [B,T,H,dn] K tensor.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama as llama_lib
from skypilot_tpu.ops import norms, rotary
from skypilot_tpu.models.decode import _d, _select_token
from skypilot_tpu.parallel import sharding as sharding_lib

Params = Dict[str, Any]

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class MLAConfig(llama_lib.LlamaConfig):
    """DeepSeek-V2-style dims. n_kv_heads is ignored (no KV heads at all —
    the latent replaces them)."""
    kv_lora_rank: int = 512          # r: latent width
    qk_nope_head_dim: int = 128      # dn: non-rope q/k per head
    qk_rope_head_dim: int = 64       # dr: shared rope key width
    v_head_dim: int = 128            # dv: value width per head

    @property
    def num_params(self) -> int:
        D, H = self.dim, self.n_heads
        r, dn, dr, dv = (self.kv_lora_rank, self.qk_nope_head_dim,
                         self.qk_rope_head_dim, self.v_head_dim)
        attn = (D * H * (dn + dr)        # W_q
                + D * r + D * dr         # W_dkv, W_kr
                + r * H * dn             # W_uk
                + r * H * dv             # W_uv
                + H * dv * D)            # W_o
        mlp = 3 * self.dim * self.ffn_dim
        per_layer = attn + mlp + 2 * self.dim
        embed = self.vocab_size * self.dim * (1 if self.tie_embeddings
                                              else 2)
        return self.n_layers * per_layer + embed + self.dim


@dataclasses.dataclass(frozen=True)
class DeepSeekMoEConfig(MLAConfig):
    """The REAL DeepSeek-V2/V3/R1 architecture: MLA attention + a
    mixture-of-experts FFN with always-on SHARED experts beside the
    routed ones (reference recipes: llm/deepseek-r1/, llm/kimi-k2/ —
    served there via vLLM/SGLang; native here). `ffn_dim` is the
    PER-EXPERT width; shared experts add `n_shared_experts · ffn_dim`
    of dense SwiGLU on every token."""
    n_experts: int = 8
    top_k: int = 2
    n_shared_experts: int = 1
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.02
    router_group_size: int = 2048

    @property
    def num_params(self) -> int:
        D, H, F, E = self.dim, self.n_heads, self.ffn_dim, self.n_experts
        r, dn, dr, dv = (self.kv_lora_rank, self.qk_nope_head_dim,
                         self.qk_rope_head_dim, self.v_head_dim)
        attn = (D * H * (dn + dr) + D * r + D * dr + r * H * dn +
                r * H * dv + H * dv * D)
        ffn = (E * 3 * D * F                      # routed experts
               + self.n_shared_experts * 3 * D * F  # shared experts
               + D * E)                           # router
        per_layer = attn + ffn + 2 * D
        embed = self.vocab_size * D * (1 if self.tie_embeddings else 2)
        return self.n_layers * per_layer + embed + D


PRESETS: Dict[str, MLAConfig] = {
    'mla-debug': MLAConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                           n_kv_heads=4, ffn_dim=128, max_seq_len=128,
                           rope_theta=10000.0, remat='none',
                           kv_lora_rank=32, qk_nope_head_dim=16,
                           qk_rope_head_dim=8, v_head_dim=16),
    # DeepSeek-V2-Lite class (~16B total with MoE in the real model; this
    # dense variant keeps the attention geometry).
    'deepseek-v2-lite': MLAConfig(vocab_size=102400, dim=2048, n_layers=27,
                                  n_heads=16, n_kv_heads=16, ffn_dim=10944,
                                  rope_theta=10000.0, max_seq_len=32768,
                                  kv_lora_rank=512, qk_nope_head_dim=128,
                                  qk_rope_head_dim=64, v_head_dim=128),
    'deepseek-moe-debug': DeepSeekMoEConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=4,
        ffn_dim=64, max_seq_len=128, rope_theta=10000.0, remat='none',
        kv_lora_rank=32, qk_nope_head_dim=16, qk_rope_head_dim=8,
        v_head_dim=16, n_experts=4, top_k=2, n_shared_experts=1,
        # Ample capacity: no routed token is ever dropped, so decode
        # matches the training forward bit-for-bit in tests.
        capacity_factor=4.0),
    # DeepSeek-V2 geometry (236B total / 21B active in the real model):
    # MLA (r=512) + 160 routed experts (1536-wide, top-6) + 2 shared.
    'deepseek-v2': DeepSeekMoEConfig(
        vocab_size=102400, dim=5120, n_layers=60, n_heads=128,
        n_kv_heads=128, ffn_dim=1536, max_seq_len=32768,
        rope_theta=10000.0, kv_lora_rank=512, qk_nope_head_dim=128,
        qk_rope_head_dim=64, v_head_dim=128, n_experts=160, top_k=6,
        n_shared_experts=2),
    # Kimi-K2 geometry (reference recipe llm/kimi-k2/ serves it via
    # vLLM/SGLang): the DeepSeek-V3 architecture at 1T total / 32B
    # active — MLA (r=512) + 384 routed experts (2048-wide, top-8) + 1
    # shared, 64 heads, 61 layers.
    'kimi-k2': DeepSeekMoEConfig(
        vocab_size=163840, dim=7168, n_layers=61, n_heads=64,
        n_kv_heads=64, ffn_dim=2048, max_seq_len=131072,
        rope_theta=50000.0, kv_lora_rank=512, qk_nope_head_dim=128,
        qk_rope_head_dim=64, v_head_dim=128, n_experts=384, top_k=8,
        n_shared_experts=1),
}


def init_params(rng: jax.Array, cfg: MLAConfig) -> Params:
    k = iter(jax.random.split(rng, 24))
    init = jax.nn.initializers.normal(stddev=0.02, dtype=cfg.param_dtype)
    trunc = jax.nn.initializers.variance_scaling(
        1.0, 'fan_in', 'truncated_normal', dtype=cfg.param_dtype)
    L, D, F, H = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.n_heads
    r, dn, dr, dv = (cfg.kv_lora_rank, cfg.qk_nope_head_dim,
                     cfg.qk_rope_head_dim, cfg.v_head_dim)
    params: Params = {
        'embed': init(next(k), (cfg.vocab_size, D)),
        'layers': {
            'attn_norm': jnp.ones((L, D), cfg.param_dtype),
            'wq': trunc(next(k), (L, D, H * (dn + dr))),
            'w_dkv': trunc(next(k), (L, D, r)),
            'w_kr': trunc(next(k), (L, D, dr)),
            'kv_norm': jnp.ones((L, r), cfg.param_dtype),
            'w_uk': trunc(next(k), (L, r, H * dn)),
            'w_uv': trunc(next(k), (L, r, H * dv)),
            'wo': trunc(next(k), (L, H * dv, D)),
            'mlp_norm': jnp.ones((L, D), cfg.param_dtype),
            'w_gate': trunc(next(k), (L, D, F)),
            'w_up': trunc(next(k), (L, D, F)),
            'w_down': trunc(next(k), (L, F, D)),
        },
        'final_norm': jnp.ones((D,), cfg.param_dtype),
    }
    if isinstance(cfg, DeepSeekMoEConfig):
        E = cfg.n_experts
        layers = params['layers']
        for key in ('mlp_norm', 'w_gate', 'w_up', 'w_down'):
            del layers[key]
        layers['moe_norm'] = jnp.ones((L, D), cfg.param_dtype)
        layers['router'] = init(next(k), (L, D, E))
        layers['w_gate'] = trunc(next(k), (L, E, D, F))
        layers['w_up'] = trunc(next(k), (L, E, D, F))
        layers['w_down'] = trunc(next(k), (L, E, F, D))
        if cfg.n_shared_experts:
            Fs = F * cfg.n_shared_experts
            layers['ws_gate'] = trunc(next(k), (L, D, Fs))
            layers['ws_up'] = trunc(next(k), (L, D, Fs))
            layers['ws_down'] = trunc(next(k), (L, Fs, D))
    if not cfg.tie_embeddings:
        params['lm_head'] = init(next(k), (D, cfg.vocab_size))
    return params


def param_specs(cfg: MLAConfig,
                rules: Optional[sharding_lib.Rules] = None) -> Params:
    r = rules or sharding_lib.Rules()
    if cfg.pipeline_stages > 1:
        r = r.override(layers='stage')
    s = r.spec
    specs: Params = {
        'embed': s('vocab', 'embed'),
        'layers': {
            'attn_norm': s('layers', 'norm'),
            'wq': s('layers', 'embed', 'heads'),
            # The latent is small and shared by every head: replicate it
            # over 'tensor' (sharding r would all-gather every step).
            'w_dkv': s('layers', 'embed', 'norm'),
            'w_kr': s('layers', 'embed', 'norm'),
            'kv_norm': s('layers', 'norm'),
            'w_uk': s('layers', 'norm', 'heads'),
            'w_uv': s('layers', 'norm', 'heads'),
            'wo': s('layers', 'heads', 'embed'),
            'mlp_norm': s('layers', 'norm'),
            'w_gate': s('layers', 'embed', 'mlp'),
            'w_up': s('layers', 'embed', 'mlp'),
            'w_down': s('layers', 'mlp', 'embed'),
        },
        'final_norm': s('norm'),
    }
    if isinstance(cfg, DeepSeekMoEConfig):
        layers = specs['layers']
        for key in ('mlp_norm', 'w_gate', 'w_up', 'w_down'):
            del layers[key]
        layers['moe_norm'] = s('layers', 'norm')
        layers['router'] = s('layers', 'embed', 'norm')
        layers['w_gate'] = s('layers', 'expert', 'embed', 'mlp')
        layers['w_up'] = s('layers', 'expert', 'embed', 'mlp')
        layers['w_down'] = s('layers', 'expert', 'mlp', 'embed')
        if cfg.n_shared_experts:
            layers['ws_gate'] = s('layers', 'embed', 'mlp')
            layers['ws_up'] = s('layers', 'embed', 'mlp')
            layers['ws_down'] = s('layers', 'mlp', 'embed')
    if not cfg.tie_embeddings:
        specs['lm_head'] = s('embed', 'vocab')
    return specs


def validate_divisibility(cfg: MLAConfig, mesh_shape: Dict[str, int]):
    tp = mesh_shape.get('tensor', 1)
    if tp > 1 and cfg.n_heads % tp != 0:
        raise ValueError(f'n_heads={cfg.n_heads} not divisible by tensor '
                         f'axis {tp}')
    ep = mesh_shape.get('expert', 1)
    if isinstance(cfg, DeepSeekMoEConfig) and ep > 1 and \
            cfg.n_experts % ep != 0:
        raise ValueError(f'n_experts={cfg.n_experts} not divisible by '
                         f'expert axis {ep}')


# ---------------------------------------------------------------------------
# Attention core (shared by train forward and decode)
# ---------------------------------------------------------------------------

def _latents(x, lp, cfg: MLAConfig, rope_sin, rope_cos):
    """x [B,S,D] → (q_nope [B,S,H,dn], q_rope [B,S,H,dr],
    c_kv [B,S,r], k_rope [B,S,dr]); norms + rope applied."""
    b, s, _ = x.shape
    H, dn, dr = cfg.n_heads, cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    h = norms.rms_norm(x, lp['attn_norm'], cfg.rms_eps)
    q = jnp.einsum('bsd,dh->bsh', h, _d(lp['wq'], cfg.dtype))
    q = q.reshape(b, s, H, dn + dr)
    q_nope, q_rope = q[..., :dn], q[..., dn:]
    q_rope = rotary.apply_rope(q_rope, rope_sin, rope_cos)
    c_kv = jnp.einsum('bsd,dr->bsr', h, _d(lp['w_dkv'], cfg.dtype))
    c_kv = norms.rms_norm(c_kv, lp['kv_norm'], cfg.rms_eps)
    k_rope = jnp.einsum('bsd,dr->bsr', h, _d(lp['w_kr'], cfg.dtype))
    # One shared rope key: apply rope with a singleton heads axis.
    k_rope = rotary.apply_rope(k_rope[:, :, None, :], rope_sin,
                               rope_cos)[:, :, 0, :]
    return q_nope, q_rope, c_kv, k_rope


def _attend_latent(q_nope, q_rope, c_kv, k_rope, lp, cfg: MLAConfig,
                   q_offset):
    """Absorbed-matmul MLA attention over the latent cache.

    q_* [B,S,H,*], c_kv [B,T,r], k_rope [B,T,dr] → out [B,S,H*dv].
    Scores never materialize per-head keys: q̃ = q_nope·W_ukᵀ lives in
    latent space, and values re-expand through W_uv after the probs·c_kv
    contraction."""
    b, s, H, dn = q_nope.shape
    t = c_kv.shape[1]
    r, dv = cfg.kv_lora_rank, cfg.v_head_dim
    scale = (dn + cfg.qk_rope_head_dim) ** -0.5
    w_uk = _d(lp['w_uk'], cfg.dtype).reshape(r, H, dn)
    # Absorption: q̃ [B,S,H,r]
    q_lat = jnp.einsum('bshd,rhd->bshr', q_nope, w_uk)
    scores = (jnp.einsum('bshr,btr->bhst', q_lat, c_kv) +
              jnp.einsum('bshr,btr->bhst', q_rope, k_rope)
              ).astype(jnp.float32) * scale
    q_off = jnp.asarray(q_offset)
    q_pos = (jnp.arange(s)[None, :] + (q_off[:, None] if q_off.ndim == 1
                                       else q_off))
    if q_pos.ndim == 1:
        q_pos = q_pos[None, :]
    kv_pos = jnp.arange(t)
    mask = q_pos[:, None, :, None] >= kv_pos[None, None, None, :]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
    # Value side: contract probs with the latent, THEN expand per head.
    ctx = jnp.einsum('bhst,btr->bshr', probs, c_kv)        # [B,S,H,r]
    w_uv = _d(lp['w_uv'], cfg.dtype).reshape(r, H, dv)
    out = jnp.einsum('bshr,rhv->bshv', ctx, w_uv)
    return out.reshape(b, s, H * dv)


def _mlp(x, lp, cfg: MLAConfig):
    h = norms.rms_norm(x, lp['mlp_norm'], cfg.rms_eps)
    gate = jnp.einsum('bsd,df->bsf', h, _d(lp['w_gate'], cfg.dtype))
    up = jnp.einsum('bsd,df->bsf', h, _d(lp['w_up'], cfg.dtype))
    return jnp.einsum('bsf,fd->bsd', cfg.act(gate) * up,
                      _d(lp['w_down'], cfg.dtype))


def _ffn(x, lp, cfg: MLAConfig, rules=None):
    """(residual_branch, router_aux). DeepSeek-MoE configs route through
    shared + routed experts; dense MLA uses the SwiGLU _mlp."""
    if not isinstance(cfg, DeepSeekMoEConfig):
        return _mlp(x, lp, cfg), jnp.zeros((), jnp.float32)
    from skypilot_tpu.models import moe as moe_lib
    rules = rules or sharding_lib.Rules()
    h = norms.rms_norm(x, lp['moe_norm'], cfg.rms_eps)
    y, aux = moe_lib.moe_ffn(h, lp, cfg, rules)
    if cfg.n_shared_experts:
        # Shared experts: dense SwiGLU every token takes, beside the
        # routed ones (DeepSeek-V2 §MoE; absent from Mixtral-style MoE).
        gate = jnp.einsum('bsd,df->bsf', h, _d(lp['ws_gate'], cfg.dtype))
        up = jnp.einsum('bsd,df->bsf', h, _d(lp['ws_up'], cfg.dtype))
        y = y + jnp.einsum('bsf,fd->bsd', cfg.act(gate) * up,
                           _d(lp['ws_down'], cfg.dtype))
    return y, aux


def _layer(carry, lp, cfg: MLAConfig, sin, cos, q_offset, rules=None):
    x, aux_sum = carry
    q_nope, q_rope, c_kv, k_rope = _latents(x, lp, cfg, sin, cos)
    out = _attend_latent(q_nope, q_rope, c_kv, k_rope, lp, cfg, q_offset)
    x = x + jnp.einsum('bsh,hd->bsd', out, _d(lp['wo'], cfg.dtype))
    y, aux = _ffn(x, lp, cfg, rules)
    return (x + y, aux_sum + aux)


# train_lib probes this: forward(return_aux=True) yields the router
# load-balance aux (0 for dense-MLA configs).
HAS_AUX = True


def forward(params: Params, tokens: jnp.ndarray, cfg: MLAConfig,
            rules: Optional[sharding_lib.Rules] = None,
            positions: Optional[jnp.ndarray] = None,
            q_offset: int | jnp.ndarray = 0,
            return_aux: bool = False):
    """tokens [B,S] → logits [B,S,V] fp32 (+ router aux if asked)."""
    if cfg.pipeline_stages > 1:
        raise NotImplementedError(
            'pipeline_stages>1 is not implemented for MLA models '
            '(dense Llama/MoE have the GPipe path); shard with '
            'tensor/expert/data axes instead.')
    if cfg.attention_impl == 'ring':
        raise NotImplementedError(
            'ring attention is not implemented for MLA (the latent-space '
            'scores need a latent-aware ring); MLA contexts are cheap — '
            'the r+dr cache usually makes sequence sharding unnecessary.')
    rules = rules or sharding_lib.Rules()
    con = functools.partial(sharding_lib.constrain, rules=rules)
    b, s = tokens.shape
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    x = con(x, 'batch', 'seq', 'act_embed')
    if positions is None:
        positions = jnp.arange(s) + q_offset
    sin, cos = rotary.rope_frequencies(cfg.qk_rope_head_dim, positions,
                                       cfg.rope_theta, cfg.rope_scaling)
    layer_fn = functools.partial(_layer, cfg=cfg, sin=sin, cos=cos,
                                 q_offset=q_offset, rules=rules)
    policy_name = llama_lib._REMAT_POLICIES[cfg.remat]
    if policy_name is not None:
        policy = getattr(jax.checkpoint_policies, policy_name)
        layer_fn = jax.checkpoint(layer_fn, policy=policy)
    aux0 = jnp.zeros((), jnp.float32)
    if cfg.scan_layers:
        def body(carry, lp):
            return layer_fn(carry, lp), None
        (x, aux), _ = jax.lax.scan(body, (x, aux0), params['layers'])
    else:
        carry = (x, aux0)
        for i in range(cfg.n_layers):
            lp = jax.tree.map(lambda p: p[i], params['layers'])
            carry = layer_fn(carry, lp)
        x, aux = carry
    x = norms.rms_norm(x, params['final_norm'], cfg.rms_eps)
    head = (params['embed'].T if cfg.tie_embeddings else params['lm_head'])
    logits = jnp.einsum('bsd,dv->bsv', x, head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    logits = con(logits, 'batch', 'seq', 'vocab')
    if return_aux:
        weight = getattr(cfg, 'router_aux_weight', 0.0)
        return logits, weight * aux / cfg.n_layers
    return logits


# ---------------------------------------------------------------------------
# Latent-cache decode
# ---------------------------------------------------------------------------

@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LatentCache:
    """r + dr floats per token per layer — the MLA payoff: ≈18x smaller
    than an MHA K/V cache at DeepSeek-V2 shapes, so the HBM-bound decode
    step reads a fraction of the cache traffic."""
    c_kv: jnp.ndarray      # [L, B, T, r]
    k_rope: jnp.ndarray    # [L, B, T, dr]
    length: jnp.ndarray    # [B]


def init_cache(cfg: MLAConfig, batch: int, max_len: int) -> LatentCache:
    return LatentCache(
        c_kv=jnp.zeros((cfg.n_layers, batch, max_len, cfg.kv_lora_rank),
                       cfg.dtype),
        k_rope=jnp.zeros((cfg.n_layers, batch, max_len,
                          cfg.qk_rope_head_dim), cfg.dtype),
        length=jnp.zeros((batch,), jnp.int32))


def cache_pspecs(cfg: MLAConfig) -> LatentCache:
    """PartitionSpecs mirroring init_cache's tree (serving engine mesh
    placement). c_kv/k_rope [L, B, T, r]: batch over data/fsdp; the
    latent dim REPLICATES over tensor like w_dkv/w_kr (param_specs) —
    every TP shard scores its own heads against the full shared latent,
    so decode needs no latent all-gather."""
    del cfg
    from jax.sharding import PartitionSpec as P
    lat = P(None, ('data', 'fsdp'), None, None)
    return LatentCache(c_kv=lat, k_rope=lat, length=P(('data', 'fsdp')))


def init_page_pool(cfg: MLAConfig, n_pages: int, page_size: int,
                   batch: int, max_pages: int, quant: str = 'none'):
    """Block-paged latent pool (models/paging.py): the MLA family's
    r+dr floats per token, pooled as [L, n_pages, page_size, r] /
    [L, n_pages, page_size, dr] pages — same page-table contract as
    the dense PagedKV, ~18x less HBM per page at DeepSeek shapes.
    ``quant='int8'`` (SKYTPU_ENGINE_KV_QUANT) pools int8 codes plus
    [L, n_pages, page_size] float32 per-token scale sidecars."""
    from skypilot_tpu.models import paging
    dt = jnp.int8 if quant == 'int8' else cfg.dtype

    def scale():
        # Distinct buffers — the step jits donate the cache tree.
        return (jnp.zeros((cfg.n_layers, n_pages, page_size),
                          jnp.float32) if quant == 'int8' else None)

    return paging.PagedLatent(
        c_kv=jnp.zeros((cfg.n_layers, n_pages, page_size,
                        cfg.kv_lora_rank), dt),
        k_rope=jnp.zeros((cfg.n_layers, n_pages, page_size,
                          cfg.qk_rope_head_dim), dt),
        table=jnp.zeros((batch, max_pages), jnp.int32),
        length=jnp.zeros((batch,), jnp.int32),
        c_scale=scale(), r_scale=scale())


def paged_pspecs(cfg: MLAConfig, quant: str = 'none'):
    """PartitionSpecs mirroring init_page_pool: page axis over
    data/fsdp, the latent dim replicated over tensor (like
    cache_pspecs); tables/lengths replicate; scale sidecars mirror
    the pools minus the last axis."""
    del cfg
    from jax.sharding import PartitionSpec as P
    from skypilot_tpu.models import paging
    lat = P(None, ('data', 'fsdp'), None, None)
    scale = P(None, ('data', 'fsdp'), None) if quant == 'int8' else None
    return paging.PagedLatent(c_kv=lat, k_rope=lat, table=P(),
                              length=P(), c_scale=scale,
                              r_scale=scale)


def prefill(params, tokens: jnp.ndarray, cfg: MLAConfig, max_len: int,
            lengths: Optional[jnp.ndarray] = None
            ) -> Tuple[jnp.ndarray, LatentCache]:
    b, s = tokens.shape
    lengths = (jnp.full((b,), s, jnp.int32) if lengths is None
               else jnp.asarray(lengths, jnp.int32))
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    sin, cos = rotary.rope_frequencies(cfg.qk_rope_head_dim,
                                       jnp.arange(s), cfg.rope_theta,
                                       cfg.rope_scaling)

    def body(carry, lp):
        q_nope, q_rope, c_kv, k_rope = _latents(carry, lp, cfg, sin, cos)
        out = _attend_latent(q_nope, q_rope, c_kv, k_rope, lp, cfg, 0)
        carry = carry + jnp.einsum('bsh,hd->bsd', out,
                                   _d(lp['wo'], cfg.dtype))
        carry = carry + _ffn(carry, lp, cfg)[0]
        return carry, (c_kv, k_rope)

    x, (cs, krs) = jax.lax.scan(body, x, params['layers'])
    pad3 = [(0, 0), (0, 0), (0, max_len - s), (0, 0)]
    cache = LatentCache(c_kv=jnp.pad(cs, pad3), k_rope=jnp.pad(krs, pad3),
                        length=lengths)
    x_last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)
    x_last = norms.rms_norm(x_last, params['final_norm'], cfg.rms_eps)
    head = (params['embed'].T if cfg.tie_embeddings else params['lm_head'])
    logits = jnp.einsum('bsd,dv->bsv', x_last, head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits[:, 0], cache


def prefill_extend(params, tokens: jnp.ndarray, cfg: MLAConfig,
                   max_len: int, prefix_c: jnp.ndarray,
                   prefix_kr: jnp.ndarray,
                   lengths: Optional[jnp.ndarray] = None
                   ) -> Tuple[jnp.ndarray, LatentCache]:
    """Prefill a SUFFIX over a stored latent prefix (prefix caching for
    the MLA/DeepSeek family — same contract as decode.prefill_extend,
    but the snapshot is (c_kv, k_rope) latents instead of K/V heads, so
    a cached chat history costs r+dr floats per token).

    tokens [B, S2] (suffix, right-padded; `lengths` [B] real suffix
    lengths), prefix_c [L, B, P, r], prefix_kr [L, B, P, dr] — every
    row holds a FULL P-token prefix. Returns per-row last-content
    logits and a LatentCache of [prefix ++ suffix] rows with length
    P + lengths. Suffix queries run at positions P.. (rope + causal
    offsets) attending [prefix ++ suffix] latents — exactly what full
    prefill computes (asserted bit-for-bit in test_prefix_cache)."""
    b, s2 = tokens.shape
    p = prefix_c.shape[2]
    if p + s2 > max_len:
        raise ValueError(f'prefix ({p}) + suffix ({s2}) exceeds '
                         f'max_len ({max_len})')
    lengths = (jnp.full((b,), s2, jnp.int32) if lengths is None
               else jnp.asarray(lengths, jnp.int32))
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    sin, cos = rotary.rope_frequencies(cfg.qk_rope_head_dim,
                                       jnp.arange(s2) + p,
                                       cfg.rope_theta, cfg.rope_scaling)

    def body(carry, xs):
        lp, pc, pkr = xs
        q_nope, q_rope, c_new, kr_new = _latents(carry, lp, cfg, sin, cos)
        c_all = jnp.concatenate([pc.astype(c_new.dtype), c_new], axis=1)
        kr_all = jnp.concatenate([pkr.astype(kr_new.dtype), kr_new],
                                 axis=1)
        out = _attend_latent(q_nope, q_rope, c_all, kr_all, lp, cfg,
                             q_offset=p)
        carry = carry + jnp.einsum('bsh,hd->bsd', out,
                                   _d(lp['wo'], cfg.dtype))
        carry = carry + _ffn(carry, lp, cfg)[0]
        return carry, (c_new, kr_new)

    x, (cs, krs) = jax.lax.scan(body, x,
                                (params['layers'], prefix_c, prefix_kr))
    pad3 = [(0, 0), (0, 0), (0, max_len - p - s2), (0, 0)]
    cache = LatentCache(
        c_kv=jnp.pad(jnp.concatenate([prefix_c, cs], axis=2), pad3),
        k_rope=jnp.pad(jnp.concatenate([prefix_kr, krs], axis=2), pad3),
        length=p + lengths)
    x_last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)
    x_last = norms.rms_norm(x_last, params['final_norm'], cfg.rms_eps)
    head = (params['embed'].T if cfg.tie_embeddings else params['lm_head'])
    logits = jnp.einsum('bsd,dv->bsv', x_last, head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits[:, 0], cache


def verify_step(params, tokens: jnp.ndarray, cache: LatentCache,
                cfg: MLAConfig) -> Tuple[jnp.ndarray, LatentCache]:
    """Process K tokens per row at each row's own offset in ONE call —
    the MLA half of speculative decoding (mirrors decode.verify_step's
    contract, over the latent cache).

    tokens [B, K] → logits [B, K, vocab]; latents for all K positions
    are written at rows' [length, length+K) slots, but `length` is NOT
    advanced — the caller commits however many tokens verification
    accepts (stale latents beyond the committed length are causally
    masked and overwritten later, the same property ragged decode
    relies on). decode_step below is its K=1 case — ONE copy of the
    per-layer latent-scatter/attend body serves both."""
    b, kk = tokens.shape
    length = cache.length
    rows = jnp.arange(b)
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    positions = length[:, None] + jnp.arange(kk)          # [B, K]
    sin, cos = rotary.rope_frequencies(cfg.qk_rope_head_dim, positions,
                                       cfg.rope_theta, cfg.rope_scaling)

    def body(carry, xs):
        x_c, c_all, kr_all = carry
        lp, layer_idx = xs
        q_nope, q_rope, c_new, kr_new = _latents(x_c, lp, cfg, sin, cos)
        c_l = jax.lax.dynamic_index_in_dim(c_all, layer_idx, 0, False)
        kr_l = jax.lax.dynamic_index_in_dim(kr_all, layer_idx, 0, False)
        c_l = c_l.at[rows[:, None], positions].set(c_new)
        kr_l = kr_l.at[rows[:, None], positions].set(kr_new)
        c_all = jax.lax.dynamic_update_index_in_dim(c_all, c_l, layer_idx,
                                                    0)
        kr_all = jax.lax.dynamic_update_index_in_dim(kr_all, kr_l,
                                                     layer_idx, 0)
        out = _attend_latent(q_nope, q_rope, c_l, kr_l, lp, cfg,
                             q_offset=length)
        x_c = x_c + jnp.einsum('bsh,hd->bsd', out,
                               _d(lp['wo'], cfg.dtype))
        x_c = x_c + _ffn(x_c, lp, cfg)[0]
        return (x_c, c_all, kr_all), None

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    (x, cs, krs), _ = jax.lax.scan(
        body, (x, cache.c_kv, cache.k_rope), (params['layers'], layer_ids))
    x = norms.rms_norm(x, params['final_norm'], cfg.rms_eps)
    head = (params['embed'].T if cfg.tie_embeddings else params['lm_head'])
    logits = jnp.einsum('bsd,dv->bsv', x, head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits, LatentCache(c_kv=cs, k_rope=krs, length=length)


def paged_verify_step(params, tokens: jnp.ndarray, pcache,
                      cfg: MLAConfig, *, max_len: int,
                      active: Optional[jnp.ndarray] = None,
                      attn: str = 'fused'):
    """`verify_step` over the block-paged LATENT pool, in place: the K
    fed positions' (c_kv, k_rope) write straight into each row's pages
    (inactive rows to the trash page) and the absorbed-matmul
    attention indexes pages per layer inside the scan — no contiguous
    latent view, no scatter-back. Bit-identical to
    gather_view → verify_step → scatter_steps for the same reason the
    dense path is (decode.paged_verify_step); the attention itself is
    the unchanged `_attend_latent` reduction. `attn='pallas'` routes
    here too: the Pallas kernel covers the dense K/V family only, and
    the latent family's absorbed attention serves through this fused
    lax formulation (documented in docs/ENGINE.md). Int8 pools
    (c_scale/r_scale sidecars set) dequantize inside the per-layer
    gather and quantize the written latents — the overlay attends the
    DEQUANTIZED values, exactly what future gathers read."""
    del attn
    from skypilot_tpu.models import paging
    from skypilot_tpu.ops import paged_attention as pa
    quant = paging.quantized(pcache)
    b, kk = tokens.shape
    length = pcache.length
    rows = jnp.arange(b)
    positions = length[:, None] + jnp.arange(kk)          # [B, K]
    pid, off = paging._write_indices(pcache, positions, active)
    table = pcache.table
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    sin, cos = rotary.rope_frequencies(cfg.qk_rope_head_dim, positions,
                                       cfg.rope_theta, cfg.rope_scaling)

    def body(carry, xs):
        x_c, cp_all, krp_all, cs_all, rs_all = carry
        lp, layer_idx = xs
        q_nope, q_rope, c_new, kr_new = _latents(x_c, lp, cfg, sin, cos)

        def sel(a):
            return jax.lax.dynamic_index_in_dim(a, layer_idx, 0, False)

        def put(a, new):
            return jax.lax.dynamic_update_index_in_dim(a, new,
                                                       layer_idx, 0)

        cp, krp = sel(cp_all), sel(krp_all)
        if quant:
            cs, rs = sel(cs_all), sel(rs_all)
            cq, cs_new = pa.quantize_values(c_new)
            krq, rs_new = pa.quantize_values(kr_new)
            c_new = pa.dequantize_values(cq, cs_new, c_new.dtype)
            kr_new = pa.dequantize_values(krq, rs_new, kr_new.dtype)
            c_l = pa.dequantize_values(
                pa.gather_pages(cp, table, max_len),
                pa.gather_pages(cs, table, max_len), c_new.dtype)
            kr_l = pa.dequantize_values(
                pa.gather_pages(krp, table, max_len),
                pa.gather_pages(rs, table, max_len), kr_new.dtype)
        else:
            c_l = pa.gather_pages(cp, table, max_len)
            kr_l = pa.gather_pages(krp, table, max_len)
        c_l = c_l.at[rows[:, None], positions].set(c_new)
        kr_l = kr_l.at[rows[:, None], positions].set(kr_new)
        out = _attend_latent(q_nope, q_rope, c_l, kr_l, lp, cfg,
                             q_offset=length)
        if quant:
            cp_all = put(cp_all, pa.write_pages(cp, cq, pid, off))
            krp_all = put(krp_all, pa.write_pages(krp, krq, pid, off))
            cs_all = put(cs_all, pa.write_pages(cs, cs_new, pid, off))
            rs_all = put(rs_all, pa.write_pages(rs, rs_new, pid, off))
        else:
            cp_all = put(cp_all, pa.write_pages(cp, c_new, pid, off))
            krp_all = put(krp_all,
                          pa.write_pages(krp, kr_new, pid, off))
        x_c = x_c + jnp.einsum('bsh,hd->bsd', out,
                               _d(lp['wo'], cfg.dtype))
        x_c = x_c + _ffn(x_c, lp, cfg)[0]
        return (x_c, cp_all, krp_all, cs_all, rs_all), None

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    (x, cps, krps, css, rss), _ = jax.lax.scan(
        body, (x, pcache.c_kv, pcache.k_rope, pcache.c_scale,
               pcache.r_scale),
        (params['layers'], layer_ids))
    x = norms.rms_norm(x, params['final_norm'], cfg.rms_eps)
    head = (params['embed'].T if cfg.tie_embeddings else params['lm_head'])
    logits = jnp.einsum('bsd,dv->bsv', x, head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    return logits, dataclasses.replace(pcache, c_kv=cps, k_rope=krps,
                                       c_scale=css, r_scale=rss)


def paged_decode_step(params, token: jnp.ndarray, pcache,
                      cfg: MLAConfig, *, max_len: int,
                      active: Optional[jnp.ndarray] = None,
                      attn: str = 'fused'):
    """K=1 case of :func:`paged_verify_step` + the length advance."""
    logits, pcache = paged_verify_step(params, token[:, None], pcache,
                                       cfg, max_len=max_len,
                                       active=active, attn=attn)
    advance = 1 if active is None else active.astype(jnp.int32)
    return logits[:, 0], dataclasses.replace(
        pcache, length=pcache.length + advance)


def paged_prefill_extend(params, tokens: jnp.ndarray, pcache,
                         cfg: MLAConfig, *, slot, p: int, lengths,
                         attn: str = 'fused'):
    """`prefill_extend` for one paged latent row, in place — the MLA
    half of decode.paged_prefill_extend: the suffix attends
    [prefix ++ suffix] latents with the prefix gathered per layer from
    the row's (possibly shared) pages, and the suffix latents land
    straight in the row's own pages. length[slot] = p + lengths.
    Int8 pools dequantize the gathered prefix latents and quantize
    the suffix writes (decode.paged_prefill_extend's discipline)."""
    del attn
    from skypilot_tpu.models import paging
    from skypilot_tpu.ops import paged_attention as pa
    quant = paging.quantized(pcache)
    b, s2 = tokens.shape
    psz = paging.page_size_of(pcache)
    pre_pos = jnp.arange(p)
    pre_pid = pcache.table[slot, pre_pos // psz]           # [p]
    pre_off = pre_pos % psz
    suf_pos = p + jnp.arange(s2)
    suf_pid = pcache.table[slot, suf_pos // psz]           # [s2]
    suf_off = suf_pos % psz
    lengths = jnp.asarray(lengths, jnp.int32).reshape((b,))
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    sin, cos = rotary.rope_frequencies(cfg.qk_rope_head_dim,
                                       jnp.arange(s2) + p,
                                       cfg.rope_theta, cfg.rope_scaling)

    def body(carry, xs):
        x_c, cp_all, krp_all, cs_all, rs_all = carry
        lp, layer_idx = xs
        q_nope, q_rope, c_new, kr_new = _latents(x_c, lp, cfg, sin, cos)

        def sel(a):
            return jax.lax.dynamic_index_in_dim(a, layer_idx, 0, False)

        def put(a, new):
            return jax.lax.dynamic_update_index_in_dim(a, new,
                                                       layer_idx, 0)

        cp, krp = sel(cp_all), sel(krp_all)
        if quant:
            cs, rs = sel(cs_all), sel(rs_all)
            cq, cs_new = pa.quantize_values(c_new)
            krq, rs_new = pa.quantize_values(kr_new)
            # The suffix attends its own DEQUANTIZED latents — exactly
            # what later decode gathers of these positions will read.
            c_new = pa.dequantize_values(cq, cs_new, c_new.dtype)
            kr_new = pa.dequantize_values(krq, rs_new, kr_new.dtype)
            pc = pa.dequantize_values(cp[pre_pid, pre_off][None],
                                      cs[pre_pid, pre_off][None],
                                      c_new.dtype)
            pkr = pa.dequantize_values(krp[pre_pid, pre_off][None],
                                       rs[pre_pid, pre_off][None],
                                       kr_new.dtype)
        else:
            pc = cp[pre_pid, pre_off][None]                # [1, p, r]
            pkr = krp[pre_pid, pre_off][None]              # [1, p, dr]
        c_all = jnp.concatenate([pc.astype(c_new.dtype), c_new], axis=1)
        kr_all = jnp.concatenate([pkr.astype(kr_new.dtype), kr_new],
                                 axis=1)
        out = _attend_latent(q_nope, q_rope, c_all, kr_all, lp, cfg,
                             q_offset=p)
        if quant:
            cp_all = put(cp_all, cp.at[suf_pid, suf_off].set(cq[0]))
            krp_all = put(krp_all,
                          krp.at[suf_pid, suf_off].set(krq[0]))
            cs_all = put(cs_all,
                         cs.at[suf_pid, suf_off].set(cs_new[0]))
            rs_all = put(rs_all,
                         rs.at[suf_pid, suf_off].set(rs_new[0]))
        else:
            cp_all = put(cp_all, cp.at[suf_pid, suf_off].set(c_new[0]))
            krp_all = put(krp_all,
                          krp.at[suf_pid, suf_off].set(kr_new[0]))
        x_c = x_c + jnp.einsum('bsh,hd->bsd', out,
                               _d(lp['wo'], cfg.dtype))
        x_c = x_c + _ffn(x_c, lp, cfg)[0]
        return (x_c, cp_all, krp_all, cs_all, rs_all), None

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    (x, cps, krps, css, rss), _ = jax.lax.scan(
        body, (x, pcache.c_kv, pcache.k_rope, pcache.c_scale,
               pcache.r_scale),
        (params['layers'], layer_ids))
    x_last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)
    x_last = norms.rms_norm(x_last, params['final_norm'], cfg.rms_eps)
    head = (params['embed'].T if cfg.tie_embeddings else params['lm_head'])
    logits = jnp.einsum('bsd,dv->bsv', x_last, head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    length = pcache.length.at[slot].set(p + lengths[0])
    return logits[:, 0], dataclasses.replace(pcache, c_kv=cps,
                                             k_rope=krps, c_scale=css,
                                             r_scale=rss, length=length)


def decode_step(params, token: jnp.ndarray, cache: LatentCache,
                cfg: MLAConfig,
                active: Optional[jnp.ndarray] = None
                ) -> Tuple[jnp.ndarray, LatentCache]:
    """One incremental step over the latent cache. `active` [B] bool: see
    decode.decode_step — continuous-batching rows that must not advance."""
    logits, cache = verify_step(params, token[:, None], cache, cfg)
    advance = 1 if active is None else active.astype(jnp.int32)
    return logits[:, 0], LatentCache(c_kv=cache.c_kv,
                                     k_rope=cache.k_rope,
                                     length=cache.length + advance)


@functools.partial(jax.jit,
                   static_argnames=('cfg', 'max_new_tokens', 'max_len',
                                    'temperature', 'eos_id', 'top_k',
                                    'top_p'))
def generate(params, prompt: jnp.ndarray, cfg: MLAConfig,
             max_new_tokens: int, *, max_len: Optional[int] = None,
             temperature: float = 0.0, eos_id: Optional[int] = None,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             prompt_lengths: Optional[jnp.ndarray] = None,
             rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Generation over the latent cache, same surface as decode.generate
    (greedy / temperature / top-k / top-p, eos padding, ragged prompts) —
    the inference engine serves MLA models through this interchangeably."""
    b, s = prompt.shape
    if max_len is None:
        max_len = min(cfg.max_seq_len, s + max_new_tokens)
    if s + max_new_tokens > max_len:
        raise ValueError(
            f'prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds '
            f'max_len ({max_len})')
    logits, cache = prefill(params, prompt, cfg, max_len,
                            lengths=prompt_lengths)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    first = _select_token(logits, temperature, rng, top_k, top_p)
    done0 = (jnp.full((b,), False) if eos_id is None else first == eos_id)

    def body(carry, step_rng):
        tok, cache, done = carry
        logits, cache = decode_step(params, tok, cache, cfg)
        nxt = _select_token(logits, temperature, step_rng, top_k, top_p)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            done = jnp.logical_or(done, nxt == eos_id)
        return (nxt, cache, done), nxt

    step_rngs = jax.random.split(rng, max(max_new_tokens - 1, 1))
    (_, _, _), rest = jax.lax.scan(body, (first, cache, done0),
                                   step_rngs[:max_new_tokens - 1])
    return jnp.concatenate([first[:, None], rest.T], axis=1)
