"""HuggingFace checkpoint import: safetensors → native param pytrees.

The reference's flagship serve recipes point vLLM/JetStream at an HF
checkpoint directory (reference: llm/qwen/README.md:60,109 curls
/v1/chat/completions against vLLM serving Qwen2.5 weights;
examples/tpu/v6e/README.md:119-127 serves Llama HF weights). This
framework owns its model code, so the equivalent capability is a weight
importer: point the native engine at the same HF directory and serve it.

TPU-first notes:
  - Our param trees stack layers on a leading [L] axis so the forward
    runs as one `lax.scan` (llama.py:8-10); HF stores per-layer tensors.
    Import therefore gathers `model.layers.{i}.*` and stacks once.
  - torch Linear stores weights [out, in]; our einsum layouts are
    [in, out] — every projection transposes at import (a one-time cost,
    not a serving-path cost).
  - `ops/rotary.py` uses the split-halves RoPE convention, which is the
    HF-transformers convention — weights need NO head permutation.
  - safetensors are loaded through `safetensors.flax`, so bf16 shards
    load natively (numpy has no bfloat16).

Supported architectures: LlamaForCausalLM (Llama 2/3/3.1/3.2,
CodeLlama), Qwen2ForCausalLM (Qwen2/2.5 — q/k/v biases),
MixtralForCausalLM (MoE — per-expert stacks + router). Anything else
fails loudly with the architecture name.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from skypilot_tpu import sky_logging
from skypilot_tpu.models import llama

logger = sky_logging.init_logger(__name__)

# HF architecture string → config-kwarg overrides for LlamaConfig.
# MixtralForCausalLM maps onto MoEConfig (see config_from_hf); its
# router semantics match ours exactly — HF softmaxes the top-k logits,
# we softmax-all-then-renormalize-top-k, and the shared denominator
# cancels, so the gate weights are identical.
_ARCHITECTURES = {
    'LlamaForCausalLM': {},
    'Qwen2ForCausalLM': {'qkv_bias': True},
    'MixtralForCausalLM': {},
}


def config_from_hf(hf_cfg: Dict[str, Any]) -> llama.LlamaConfig:
    """Translate an HF `config.json` dict into a LlamaConfig.

    Raises ValueError on unsupported architectures or rope types rather
    than serving silently-wrong math.
    """
    archs = hf_cfg.get('architectures') or ['LlamaForCausalLM']
    arch = archs[0]
    if arch not in _ARCHITECTURES:
        raise ValueError(
            f'Unsupported HF architecture {arch!r}; supported: '
            f'{sorted(_ARCHITECTURES)}. (The MLA/DeepSeek family '
            f'imports via its own converter when added.)')
    rope_scaling = None
    rs = hf_cfg.get('rope_scaling')
    if rs:
        rope_type = rs.get('rope_type', rs.get('type', 'default'))
        if rope_type == 'llama3':
            rope_scaling = dict(
                factor=float(rs['factor']),
                low_freq_factor=float(rs.get('low_freq_factor', 1.0)),
                high_freq_factor=float(rs.get('high_freq_factor', 4.0)),
                original_max_position=int(
                    rs.get('original_max_position_embeddings', 8192)))
        elif rope_type in ('default', None):
            rope_scaling = None
        else:
            raise ValueError(
                f'Unsupported rope_scaling type {rope_type!r} (supported: '
                f"'llama3', 'default'); refusing to import with wrong "
                f'position math.')
    kwargs: Dict[str, Any] = dict(
        vocab_size=int(hf_cfg['vocab_size']),
        dim=int(hf_cfg['hidden_size']),
        n_layers=int(hf_cfg['num_hidden_layers']),
        n_heads=int(hf_cfg['num_attention_heads']),
        n_kv_heads=int(hf_cfg.get('num_key_value_heads',
                                  hf_cfg['num_attention_heads'])),
        ffn_dim=int(hf_cfg['intermediate_size']),
        rope_theta=float(hf_cfg.get('rope_theta', 10000.0)),
        rope_scaling=rope_scaling,
        rms_eps=float(hf_cfg.get('rms_norm_eps', 1e-5)),
        max_seq_len=int(hf_cfg.get('max_position_embeddings', 8192)),
        tie_embeddings=bool(hf_cfg.get('tie_word_embeddings', False)),
    )
    if hf_cfg.get('head_dim'):
        kwargs['head_dim'] = int(hf_cfg['head_dim'])
    kwargs.update(_ARCHITECTURES[arch])
    if arch == 'MixtralForCausalLM':
        from skypilot_tpu.models import moe
        if hf_cfg.get('sliding_window'):
            # Mistral-lineage windows EVERY layer — a pattern larger
            # than n_layers means "no layer is global" under
            # llama.window_active's every-pattern-th-is-global rule.
            kwargs['sliding_window'] = int(hf_cfg['sliding_window'])
            kwargs['sliding_window_pattern'] = kwargs['n_layers'] + 1
        return moe.MoEConfig(
            **kwargs,
            n_experts=int(hf_cfg['num_local_experts']),
            top_k=int(hf_cfg['num_experts_per_tok']),
            # The true model routes every token (no capacity); 2.0 keeps
            # drops negligible in our static-capacity dispatch while
            # staying static-shaped. Decode (S=1) never drops.
            capacity_factor=2.0)
    return llama.LlamaConfig(**kwargs)


def _shard_files(hf_dir: str) -> list:
    """Resolve the safetensors shard list (single-file or indexed)."""
    index = os.path.join(hf_dir, 'model.safetensors.index.json')
    if os.path.exists(index):
        with open(index, 'r', encoding='utf-8') as f:
            weight_map = json.load(f)['weight_map']
        return sorted({os.path.join(hf_dir, v)
                       for v in weight_map.values()})
    single = os.path.join(hf_dir, 'model.safetensors')
    if os.path.exists(single):
        return [single]
    raise FileNotFoundError(
        f'No model.safetensors(.index.json) under {hf_dir!r} — is this an '
        f'HF checkpoint directory? (.bin torch pickles are not supported; '
        f'convert to safetensors.)')


def _load_tensors(hf_dir: str) -> Dict[str, Any]:
    """All tensors from every shard, as jax arrays (bf16-safe)."""
    from safetensors import safe_open
    tensors: Dict[str, Any] = {}
    for path in _shard_files(hf_dir):
        with safe_open(path, framework='flax') as f:
            for key in f.keys():
                tensors[key] = f.get_tensor(key)
    return tensors


def _expect(tensors: Dict[str, Any], key: str, shape: Tuple[int, ...]):
    if key not in tensors:
        raise KeyError(f'HF checkpoint missing tensor {key!r}')
    t = tensors.pop(key)
    if tuple(t.shape) != tuple(shape):
        raise ValueError(f'{key}: shape {tuple(t.shape)} != expected '
                         f'{tuple(shape)} — config/weights mismatch')
    return t


def params_from_hf(tensors: Dict[str, Any], cfg: llama.LlamaConfig,
                   dtype: Optional[Any] = None) -> llama.Params:
    """Map HF tensor names onto the native stacked-layer pytree.

    `dtype`: optional cast target (e.g. jnp.bfloat16 for serving);
    None keeps each tensor's stored dtype.
    """
    import jax.numpy as jnp
    D, F, hd = cfg.dim, cfg.ffn_dim, cfg.hd
    H, KH, L, V = cfg.n_heads, cfg.n_kv_heads, cfg.n_layers, cfg.vocab_size

    def cast(x):
        return x.astype(dtype) if dtype is not None else x

    def stack(fmt: str, shape, transpose: bool = False):
        per_layer = [_expect(tensors, fmt.format(i=i), shape)
                     for i in range(L)]
        out = jnp.stack([t.T if transpose else t for t in per_layer])
        return cast(out)

    from skypilot_tpu.models import moe
    is_moe = isinstance(cfg, moe.MoEConfig)

    p = 'model.layers.{i}.'
    params: llama.Params = {
        'embed': cast(_expect(tensors, 'model.embed_tokens.weight',
                              (V, D))),
        'layers': {
            'attn_norm': stack(p + 'input_layernorm.weight', (D,)),
            'wq': stack(p + 'self_attn.q_proj.weight', (H * hd, D),
                        transpose=True),
            'wk': stack(p + 'self_attn.k_proj.weight', (KH * hd, D),
                        transpose=True),
            'wv': stack(p + 'self_attn.v_proj.weight', (KH * hd, D),
                        transpose=True),
            'wo': stack(p + 'self_attn.o_proj.weight', (D, H * hd),
                        transpose=True),
        },
        'final_norm': cast(_expect(tensors, 'model.norm.weight', (D,))),
    }
    if is_moe:
        # Mixtral: per-layer router + per-expert SwiGLU (w1=gate,
        # w3=up, w2=down in HF naming), stacked to [L, E, in, out].
        E = cfg.n_experts

        def stack_experts(name: str, shape, transpose: bool):
            per_layer = []
            for i in range(L):
                per_expert = [
                    _expect(tensors,
                            f'model.layers.{i}.block_sparse_moe.'
                            f'experts.{e}.{name}.weight', shape)
                    for e in range(E)]
                per_layer.append(jnp.stack(
                    [t.T if transpose else t for t in per_expert]))
            return cast(jnp.stack(per_layer))

        params['layers'].update({
            'moe_norm': stack(p + 'post_attention_layernorm.weight',
                              (D,)),
            'router': stack(p + 'block_sparse_moe.gate.weight', (E, D),
                            transpose=True),
            'w_gate': stack_experts('w1', (F, D), transpose=True),
            'w_up': stack_experts('w3', (F, D), transpose=True),
            'w_down': stack_experts('w2', (D, F), transpose=True),
        })
    else:
        params['layers'].update({
            'mlp_norm': stack(p + 'post_attention_layernorm.weight',
                              (D,)),
            'w_gate': stack(p + 'mlp.gate_proj.weight', (F, D),
                            transpose=True),
            'w_up': stack(p + 'mlp.up_proj.weight', (F, D),
                          transpose=True),
            'w_down': stack(p + 'mlp.down_proj.weight', (D, F),
                            transpose=True),
        })
    if cfg.qkv_bias:
        params['layers']['bq'] = stack(p + 'self_attn.q_proj.bias',
                                       (H * hd,))
        params['layers']['bk'] = stack(p + 'self_attn.k_proj.bias',
                                       (KH * hd,))
        params['layers']['bv'] = stack(p + 'self_attn.v_proj.bias',
                                       (KH * hd,))
    if not cfg.tie_embeddings:
        params['lm_head'] = cast(_expect(tensors, 'lm_head.weight', (V, D)).T)
    else:
        # Some exports redundantly store lm_head even when tied.
        tensors.pop('lm_head.weight', None)
    if tensors:
        leftover = sorted(tensors)[:8]
        logger.warning(f'HF import: {len(tensors)} unused tensors '
                       f'(e.g. {leftover}) — ignored.')
    return params


def load_hf_config(hf_dir: str) -> llama.LlamaConfig:
    """Just the config (cheap — no tensor reads). Used by callers that
    need the architecture before deciding whether to load weights."""
    hf_dir = os.path.expanduser(hf_dir)
    cfg_path = os.path.join(hf_dir, 'config.json')
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(f'{cfg_path} not found — --hf-dir must '
                                f'point at an HF checkpoint directory.')
    with open(cfg_path, 'r', encoding='utf-8') as f:
        return config_from_hf(json.load(f))


def load_hf_checkpoint(hf_dir: str, dtype: Optional[Any] = None
                       ) -> Tuple[llama.LlamaConfig, llama.Params]:
    """(config, params) from an HF checkpoint directory.

    Example: download `meta-llama/Llama-3.2-1B-Instruct` (or
    `Qwen/Qwen2.5-1.5B-Instruct`) and point the engine at it:
        python -m skypilot_tpu.serve.engine --hf-dir /path/to/ckpt
    """
    cfg = load_hf_config(hf_dir)
    hf_dir = os.path.expanduser(hf_dir)
    tensors = _load_tensors(hf_dir)
    params = params_from_hf(tensors, cfg, dtype=dtype)
    n = sum(int(np.prod(x.shape)) for x in
            __import__('jax').tree.leaves(params))
    logger.info(f'Imported HF checkpoint from {hf_dir}: '
                f'{type(cfg).__name__} {n / 1e9:.2f}B params.')
    return cfg, params


def hf_eos_ids(hf_dir: str) -> list:
    """EOS token id(s) from generation_config.json / config.json (HF
    stores either an int or a list — llama-3 instruct lists both
    <|end_of_text|> and <|eot_id|>)."""
    ids: list = []
    for name in ('generation_config.json', 'config.json'):
        path = os.path.join(hf_dir, name)
        if not os.path.exists(path):
            continue
        with open(path, 'r', encoding='utf-8') as f:
            eos = json.load(f).get('eos_token_id')
        if eos is None:
            continue
        ids = list(eos) if isinstance(eos, list) else [int(eos)]
        break
    return ids
