"""Offline batch inference: JSONL in → JSONL out, at TPU-batch sizes.

The reference's large-scale batch-inference recipe
(llm/batch_inference/README.md, batch_compute_vectors.py) computes text
embeddings over ~30M records by stride-partitioning the dataset across
many managed-job workers, each resuming past already-written results.
SkyPilot only orchestrates; the compute is external torch. Here the
worker itself is native and TPU-first:

  - **Stride partitioning** identical to the reference: worker j of N
    processes global lines where `idx % N == j`. Defaults ride the gang
    env contract (SKYPILOT_NODE_RANK / SKYPILOT_NUM_NODES), so
    `num_nodes: N` in a task YAML fans the file out with zero flags.
  - **Resume** by reading the worker's own output partition and
    skipping ids already present (the reference's "skip computed
    partitions" behavior) — a preempted managed job re-runs the same
    command and continues where it stopped.
  - **Length-bucketed ragged batching**: items sort by token length and
    pad to the batch max rounded to a power of two, so XLA compiles one
    program per bucket (not per shape) and `prompt_lengths` keeps the
    padding out of the math — the same contract the serving engine uses.
  - Two modes: `generate` (decode.generate — greedy/sampled completion
    per record) and `embed` (final-norm hidden states, mean- or
    last-token-pooled — the reference recipe's embedding workload).
  - `--mesh tensor=4,...` shards params by the family's param_specs for
    models bigger than one chip.

Usage:
    python -m skypilot_tpu.models.batch_infer \
        --hf-dir ~/ckpts/Qwen2.5-1.5B --input prompts.jsonl \
        --output out.jsonl --mode embed --pool mean
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger('skypilot_tpu.models.batch_infer')


def _pooled_hidden(params, tokens, lens, *, cfg, pool: str):
    """Final-norm hidden states pooled over the REAL tokens (module-level
    so the jitted callable is stable — one compile per bucket shape)."""
    import jax.numpy as jnp
    from skypilot_tpu.models import llama as llama_mod
    hidden = llama_mod.forward(params, tokens, cfg, return_hidden=True)
    mask = (jnp.arange(tokens.shape[1])[None, :]
            < lens[:, None]).astype(jnp.float32)
    if pool == 'last':
        idx = jnp.maximum(lens - 1, 0)
        return jnp.take_along_axis(hidden, idx[:, None, None],
                                   axis=1)[:, 0, :]
    return ((hidden * mask[..., None]).sum(axis=1)
            / jnp.maximum(mask.sum(axis=1), 1.0)[:, None])




def read_items(path: str, num_workers: int, worker_id: int
               ) -> List[Dict[str, Any]]:
    """This worker's stride slice of the input JSONL. Each line needs
    'prompt' or 'text'; 'id' defaults to the global line index (stable
    across workers/restarts)."""
    items = []
    with open(path, 'r', encoding='utf-8') as f:
        for idx, line in enumerate(f):
            line = line.strip()
            if not line or idx % num_workers != worker_id:
                continue
            rec = json.loads(line)
            text = rec.get('prompt', rec.get('text'))
            if text is None:
                raise ValueError(
                    f'{path}:{idx + 1}: record needs "prompt" or "text"')
            items.append({'id': rec.get('id', idx), 'text': text})
    return items


def done_ids(output_path: str) -> set:
    """Ids already present in the output partition (resume support).
    Truncated trailing lines (crash mid-write) are ignored."""
    done = set()
    if not os.path.exists(output_path):
        return done
    with open(output_path, 'r', encoding='utf-8') as f:
        for line in f:
            try:
                done.add(json.loads(line)['id'])
            except (json.JSONDecodeError, KeyError):
                continue
    return done


class BatchRunner:
    """Owns params + tokenizer + the bucketed batch loop."""

    def __init__(self, model: Optional[str] = None,
                 hf_dir: Optional[str] = None,
                 tokenizer_path: Optional[str] = None,
                 mesh_spec: Optional[Dict[str, int]] = None,
                 max_len: int = 2048):
        import jax
        from skypilot_tpu.data import tokenizer as tokenizer_lib
        from skypilot_tpu.models import get_config, mla, module_for
        from skypilot_tpu.parallel import MeshSpec, build_mesh
        from skypilot_tpu.parallel import sharding as sharding_lib

        if hf_dir:
            from skypilot_tpu.models import hf_import
            self.cfg, params = hf_import.load_hf_checkpoint(hf_dir)
            self.eos_extra = hf_import.hf_eos_ids(hf_dir)
        else:
            if model is None:
                raise ValueError('need --model or --hf-dir')
            self.cfg = get_config(model)
            params = jax.jit(
                lambda r: module_for(self.cfg).init_params(r, self.cfg))(
                    jax.random.PRNGKey(0))
            self.eos_extra = []
        self.is_mla = isinstance(self.cfg, mla.MLAConfig)
        self.mod = module_for(self.cfg)
        self.max_len = min(max_len, self.cfg.max_seq_len)

        if tokenizer_path:
            self.tokenizer = tokenizer_lib.load_tokenizer(
                tokenizer_path, eos_extra=self.eos_extra)
        elif hf_dir:
            # Raises loudly when tokenizer.json is missing — a byte
            # fallback against a real-vocab model would write millions
            # of well-formed but meaningless records with exit 0 (same
            # refusal the serving engine makes).
            self.tokenizer = tokenizer_lib.load_tokenizer(
                os.path.join(os.path.expanduser(hf_dir),
                             'tokenizer.json'),
                eos_extra=self.eos_extra)
        else:
            self.tokenizer = tokenizer_lib.ByteTokenizer()

        self.mesh = build_mesh(MeshSpec(**(mesh_spec or {})))
        specs = self.mod.param_specs(self.cfg, sharding_lib.Rules())
        shardings = sharding_lib.tree_shardings(self.mesh, specs)
        self.params = jax.tree.map(jax.device_put, params, shardings)
        self._embed_fns: Dict[str, Any] = {}   # pool → jitted fn

    # ------------------------------------------------------------------
    def _pad_batch(self, token_rows: List[List[int]], width_cap: int
                   ) -> Tuple[Any, Any, int]:
        """Pad to the batch max rounded to a power of two, capped at
        `width_cap`. Rows longer than the cap are RIGHT-TRUNCATED (the
        job must always make progress — a crash here would loop every
        managed-job restart on the same record)."""
        import jax.numpy as jnp
        import numpy as np
        from skypilot_tpu.models import decode as decode_lib
        lengths = [len(r) for r in token_rows]
        width = min(decode_lib.bucket_size(max(lengths)), width_cap)
        if any(n > width for n in lengths):
            logger.warning(
                f'{sum(n > width for n in lengths)} prompt(s) truncated '
                f'to {width} tokens (generation headroom under '
                f'--max-len {self.max_len}).')
        arr = np.zeros((len(token_rows), width), np.int32)
        for i, row in enumerate(token_rows):
            row = row[:width]
            arr[i, :len(row)] = row
            lengths[i] = len(row)
        return (jnp.asarray(arr), jnp.asarray(lengths, jnp.int32), width)

    def generate_batch(self, token_rows: List[List[int]],
                       max_new_tokens: int, temperature: float,
                       top_k: Optional[int], top_p: Optional[float],
                       seed: int) -> List[List[int]]:
        """→ per-row generated ids, truncated at the first EOS."""
        import jax
        import jax.numpy as jnp
        from skypilot_tpu.models import decode as decode_lib
        from skypilot_tpu.parallel import mesh as mesh_lib
        dec = self.mod if self.is_mla else decode_lib
        if max_new_tokens >= self.max_len:
            raise ValueError(
                f'--max-new-tokens {max_new_tokens} leaves no prompt '
                f'room under --max-len {self.max_len}')
        # Width cap reserves the generation budget by construction —
        # no batch composition can make budget <= 0.
        prompt, lengths, width = self._pad_batch(
            token_rows, self.max_len - max_new_tokens)
        budget = max_new_tokens
        eos = self.tokenizer.eos_ids[0] if getattr(
            self.tokenizer, 'eos_ids', None) else None
        with mesh_lib.use_mesh(self.mesh):
            out = dec.generate(
                self.params, prompt, self.cfg, budget,
                max_len=width + budget, temperature=temperature,
                eos_id=eos, top_k=top_k, top_p=top_p,
                prompt_lengths=lengths, rng=jax.random.PRNGKey(seed))
        out = jax.device_get(out)
        eos_set = set(getattr(self.tokenizer, 'eos_ids', []) or [])
        rows = []
        for i in range(out.shape[0]):
            ids = []
            for t in out[i].tolist():
                if t in eos_set:
                    break
                ids.append(int(t))
            rows.append(ids)
        return rows

    def embed_batch(self, token_rows: List[List[int]],
                    pool: str = 'mean') -> List[List[float]]:
        """→ per-row embedding (final-norm hidden, pooled over the real
        tokens; padding never enters the pool)."""
        import jax
        from skypilot_tpu.models import llama as llama_mod
        from skypilot_tpu.parallel import mesh as mesh_lib
        if self.mod is not llama_mod:
            # Only llama.forward implements return_hidden (covers the
            # Llama/Qwen/Gemma dense presets — the reference recipe's
            # gte-Qwen2 embedder is this architecture).
            raise ValueError(
                f'embed mode supports the dense family only, not '
                f'{type(self.cfg).__name__}')
        prompt, lengths, _ = self._pad_batch(token_rows, self.max_len)
        fn = self._embed_fns.get(pool)
        if fn is None:
            fn = self._embed_fns[pool] = jax.jit(
                functools.partial(_pooled_hidden, cfg=self.cfg,
                                  pool=pool))
        with mesh_lib.use_mesh(self.mesh):
            out = fn(self.params, prompt, lengths)
        return [row.tolist() for row in jax.device_get(out)]


def run(args) -> Dict[str, int]:
    num_workers = args.num_workers or int(
        os.environ.get('SKYPILOT_NUM_NODES', '1'))
    worker_id = (args.worker_id if args.worker_id is not None
                 else int(os.environ.get('SKYPILOT_NODE_RANK', '0')))
    if not 0 <= worker_id < num_workers:
        raise ValueError(f'worker_id {worker_id} outside [0, '
                         f'{num_workers})')
    out_path = (args.output if num_workers == 1
                else f'{args.output}.part{worker_id}')

    items = read_items(args.input, num_workers, worker_id)
    done = done_ids(out_path)
    todo = [it for it in items if it['id'] not in done]
    logger.info(f'worker {worker_id}/{num_workers}: {len(items)} items, '
                f'{len(done)} already done, {len(todo)} to run '
                f'→ {out_path}')
    if not todo:
        return {'total': len(items), 'done': len(done), 'ran': 0}

    runner = BatchRunner(model=args.model, hf_dir=args.hf_dir,
                         tokenizer_path=args.tokenizer,
                         mesh_spec=args.mesh, max_len=args.max_len)
    for it in todo:
        it['tokens'] = runner.tokenizer.encode(it['text'])
    # Length-sorted → batches are near-uniform → minimal padding waste
    # and few compiled bucket shapes.
    todo.sort(key=lambda it: len(it['tokens']))

    ran = 0
    t0 = time.perf_counter()
    with open(out_path, 'a', encoding='utf-8') as f:
        for lo in range(0, len(todo), args.batch_size):
            chunk = todo[lo:lo + args.batch_size]
            rows = [it['tokens'] for it in chunk]
            if args.mode == 'embed':
                embs = runner.embed_batch(rows, pool=args.pool)
                for it, e in zip(chunk, embs):
                    f.write(json.dumps(
                        {'id': it['id'],
                         'embedding': [round(v, 6) for v in e]}) + '\n')
            else:
                outs = runner.generate_batch(
                    rows, args.max_new_tokens, args.temperature,
                    args.top_k, args.top_p, seed=args.seed + lo)
                for it, ids in zip(chunk, outs):
                    f.write(json.dumps(
                        {'id': it['id'],
                         'completion': runner.tokenizer.decode(ids),
                         'tokens': len(ids)}) + '\n')
            f.flush()
            ran += len(chunk)
            if ran % (args.batch_size * 8) == 0 or ran == len(todo):
                rate = ran / max(time.perf_counter() - t0, 1e-9)
                logger.info(f'{ran}/{len(todo)} ({rate:.2f} items/s)')
    return {'total': len(items), 'done': len(done), 'ran': ran}


def main() -> None:
    from skypilot_tpu.utils import jax_utils
    jax_utils.pin_platform_from_env()
    parser = argparse.ArgumentParser(prog='skytpu-batch-infer')
    parser.add_argument('--input', required=True, help='JSONL of '
                        '{"prompt"|"text": ..., "id"?: ...} records.')
    parser.add_argument('--output', required=True)
    parser.add_argument('--mode', choices=('generate', 'embed'),
                        default='generate')
    parser.add_argument('--model', default=None)
    parser.add_argument('--hf-dir', default=None)
    parser.add_argument('--tokenizer', default=None)
    parser.add_argument('--mesh', default='',
                        help='axis=N comma list (e.g. tensor=4).')
    parser.add_argument('--batch-size', type=int, default=32)
    parser.add_argument('--max-len', type=int, default=2048)
    parser.add_argument('--max-new-tokens', type=int, default=128)
    parser.add_argument('--temperature', type=float, default=0.0)
    parser.add_argument('--top-k', type=int, default=None)
    parser.add_argument('--top-p', type=float, default=None)
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--pool', choices=('mean', 'last'),
                        default='mean')
    parser.add_argument('--num-workers', type=int, default=None,
                        help='Stride width (default: '
                             '$SKYPILOT_NUM_NODES).')
    parser.add_argument('--worker-id', type=int, default=None,
                        help='This worker (default: '
                             '$SKYPILOT_NODE_RANK).')
    args = parser.parse_args()
    mesh = {}
    if args.mesh:
        for part in args.mesh.split(','):
            k, v = part.split('=')
            mesh[k.strip()] = int(v)
    args.mesh = mesh
    stats = run(args)
    logger.info(json.dumps(stats))


if __name__ == '__main__':
    main()
