"""Block-paged KV cache: device-resident page pools, a host-side
refcounted free-list allocator, and the gather/scatter ops that thread
per-request page tables through the serving jits as int32 indices.

Why pages: the serving engine's contiguous layout dedicates a full
``max_len`` cache row per slot, so a finished request strands its
memory until the slot is reaped and reused, and admission is gated on
whole rows. With paging, HBM is a pool of fixed-size pages
(``[L, n_pages, page_size, ...]``); each request borrows just the
pages its (bucketed prompt + max_new + spec headroom) needs via a
fixed-shape int32 page table, releases them the moment it finishes
(collect time, not reap time), and a prefix-cache hit shares the
prefix's pages read-only instead of copying a snapshot — a hit costs
page-table entries, not HBM.

Shape discipline (the TPU contract): page COUNT is data, not shape.
Every jit sees the same ``[B, max_pages]`` int32 table regardless of
how many pages a row actually holds, so the compiled-variant matrix
stays exactly as bounded as the contiguous engine's. The skylint
``page-table-shape`` checker pins this: a page table must never reach
a jit as a Python list or a static argument.

Page 0 is the TRASH page: it is never allocated, and writes for
inactive rows (masked-out, finished, or prefilling slots) are routed
to it so a freed page can never be corrupted by a stale in-flight
step. The allocator hands out ids 1..n_pages-1.

Families: ``PagedKV`` pools the dense/GQA/MoE K/V cache
(models/decode.py); ``PagedLatent`` pools the MLA latent cache
(models/mla.py). The hot step/verify/chunk programs index pages IN
PLACE inside the attention computation (ops/paged_attention.py +
decode/mla ``paged_*`` steps — the fused default, still bit-identical
to the contiguous path and pin-tested in
tests/unit_tests/test_engine_paged.py); ``gather_view`` materializes
the contiguous per-row view only for the SKYTPU_ENGINE_ATTN=gather
regression baseline, and the cold paths (admit's scatter_prefill,
prefix snapshot/export gathers, disagg handoff) keep their
gather/scatter ops — they run once per request, not per token.

KV memory hierarchy (docs/ENGINE.md): the pools optionally quantize
to int8 (SKYTPU_ENGINE_KV_QUANT=int8) with per-vector float32 scales
in SIDECAR pools — same page geometry minus the last axis, so scales
ride every gather/scatter/spill path with their pages. Under quant
the cold scatter ops quantize fp inputs on the way in and
``gather_prefix`` dequantizes on the way out; the hot in-place paths
fuse dequant into the attention gather (ops/paged_attention.py).
``export_pages``/``import_pages`` move EXACT page contents (codes and
scales alike) for the host-RAM spill tier — a spilled page re-imports
bit-identically in either representation.
"""
from __future__ import annotations

import collections
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

TRASH_PAGE = 0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKV:
    """Paged dense K/V pool + per-slot tables.

    k/v: [L, n_pages, page_size, KH, hd] — page id indexes axis 1.
    table: [B, max_pages] int32 page ids (0 = trash / unassigned).
    length: [B] int32 valid token count per slot (same contract as
    KVCache.length).
    k_scale/v_scale: None on the fp path; under
    SKYTPU_ENGINE_KV_QUANT=int8 the [L, n_pages, page_size, KH]
    float32 per-vector scale sidecars (k/v hold int8 codes)."""
    k: jnp.ndarray
    v: jnp.ndarray
    table: jnp.ndarray
    length: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedLatent:
    """Paged MLA latent pool (models/mla.py): c_kv [L, n_pages,
    page_size, r], k_rope [L, n_pages, page_size, dr].
    c_scale/r_scale: the int8 variant's [L, n_pages, page_size]
    float32 scale sidecars (None on the fp path)."""
    c_kv: jnp.ndarray
    k_rope: jnp.ndarray
    table: jnp.ndarray
    length: jnp.ndarray
    c_scale: Optional[jnp.ndarray] = None
    r_scale: Optional[jnp.ndarray] = None


def _pools(pcache) -> Dict[str, jnp.ndarray]:
    """The per-token pool arrays of either family, by field name."""
    if isinstance(pcache, PagedKV):
        return {'k': pcache.k, 'v': pcache.v}
    return {'c_kv': pcache.c_kv, 'k_rope': pcache.k_rope}


# Pool field -> its scale-sidecar field (the spill/export naming too).
_SCALE_FIELD = {'k': 'k_scale', 'v': 'v_scale',
                'c_kv': 'c_scale', 'k_rope': 'r_scale'}


def _scale_pools(pcache) -> Optional[Dict[str, jnp.ndarray]]:
    """The scale sidecars keyed like :func:`_pools`, or None on the fp
    path (both sidecars are always set together — init_page_pool)."""
    if isinstance(pcache, PagedKV):
        if pcache.k_scale is None:
            return None
        return {'k': pcache.k_scale, 'v': pcache.v_scale}
    if pcache.c_scale is None:
        return None
    return {'c_kv': pcache.c_scale, 'k_rope': pcache.r_scale}


def quantized(pcache) -> bool:
    """True when the pool holds int8 codes + scale sidecars."""
    return _scale_pools(pcache) is not None


def page_size_of(pcache) -> int:
    return next(iter(_pools(pcache).values())).shape[2]


def max_pages_of(pcache) -> int:
    return pcache.table.shape[1]


def pages_for(n_tokens: int, page_size: int) -> int:
    """Pages covering positions [0, n_tokens)."""
    return -(-n_tokens // page_size)


def gather_view(pcache, max_len: int):
    """Materialize the contiguous [L, B, max_len, ...] per-row view:
    ``pool[:, table]`` reshaped so position ``p`` of row ``b`` reads
    ``pool[:, table[b, p // psz], p % psz]``. Rows whose table entries
    are 0 read the trash page (garbage — such rows are always masked
    inactive and their logits discarded). Returns the family's
    contiguous cache dataclass, so callers are family-blind.

    BASELINE-ONLY on the hot path: the default fused engine
    (SKYTPU_ENGINE_ATTN=fused, ops/paged_attention.py) indexes pages
    in place inside the step/verify/chunk attention and never
    materializes this view — only the SKYTPU_ENGINE_ATTN=gather
    regression baseline still routes steps through it (skylint's
    ``paged-view-materialization`` checker pins that no new hot-path
    jit does).

    Quantized pools have no contiguous fp view to materialize (and the
    engine refuses SKYTPU_ENGINE_KV_QUANT=int8 + ATTN=gather at
    startup), so this raises rather than silently hand back int8
    codes a contiguous program would misread as floats."""
    if quantized(pcache):
        raise NotImplementedError(
            'gather_view of an int8-quantized pool: the gather '
            'baseline serves fp pools only (SKYTPU_ENGINE_KV_QUANT)')
    table = pcache.table

    def g(a):
        v = a[:, table]                        # [L, B, MAXP, psz, ...]
        l, b = v.shape[0], v.shape[1]
        v = v.reshape(l, b, -1, *a.shape[3:])  # [L, B, MAXP*psz, ...]
        return v[:, :, :max_len]

    if isinstance(pcache, PagedKV):
        from skypilot_tpu.models import decode as decode_lib
        return decode_lib.KVCache(k=g(pcache.k), v=g(pcache.v),
                                  length=pcache.length)
    from skypilot_tpu.models import mla as mla_lib
    return mla_lib.LatentCache(c_kv=g(pcache.c_kv),
                               k_rope=g(pcache.k_rope),
                               length=pcache.length)


def _write_indices(pcache, pos: jnp.ndarray, active=None):
    """(page_id, offset) arrays for token positions ``pos`` (any shape
    broadcastable with [B, ...], values in [0, max_len)). Inactive rows
    route to the trash page."""
    psz = page_size_of(pcache)
    maxp = max_pages_of(pcache)
    pos = jnp.minimum(pos, maxp * psz - 1)
    pid = jnp.take_along_axis(pcache.table, pos // psz, axis=1)
    if active is not None:
        pid = jnp.where(active[:, None], pid, TRASH_PAGE)
    return pid, pos % psz


def scatter_steps(pcache, view, start: jnp.ndarray, k: int,
                  active: jnp.ndarray):
    """Write the k tokens a fused step produced back into the pool:
    positions [start, start+k) per row, read from the contiguous view
    the step math updated. ``active`` [B] bool: inactive rows' writes
    land on the trash page (their view slots hold garbage and their
    pages may already be freed)."""
    if quantized(pcache):
        raise NotImplementedError(
            'scatter_steps into an int8-quantized pool: the gather '
            'baseline serves fp pools only (SKYTPU_ENGINE_KV_QUANT)')
    pos = start[:, None] + jnp.arange(k)[None, :]          # [B, k]
    pid, off = _write_indices(pcache, pos, active)
    psz = page_size_of(pcache)
    maxp = max_pages_of(pcache)
    pos_r = jnp.minimum(pos, maxp * psz - 1)
    view_arrays = _pools_of_view(view)
    out = {}
    for name, pool_a in _pools(pcache).items():
        view_a = view_arrays[name]
        rows = jnp.arange(view_a.shape[1])
        # Clamp the read too: the view only covers max_len positions.
        rd = jnp.minimum(pos_r, view_a.shape[2] - 1)
        tok = view_a[:, rows[:, None], rd]                 # [L, B, k, ...]
        out[name] = pool_a.at[:, pid, off].set(tok)
    del psz
    return dataclasses.replace(pcache, length=view.length, **out)


def _pools_of_view(view) -> Dict[str, jnp.ndarray]:
    if hasattr(view, 'k'):
        return {'k': view.k, 'v': view.v}
    return {'c_kv': view.c_kv, 'k_rope': view.k_rope}


def scatter_prefill(pcache, rows_cache, slots: jnp.ndarray, s: int,
                    lengths: jnp.ndarray):
    """Write a grouped prefill's rows into the pool: positions [0, s)
    of each admitted row (s = the static prompt bucket) land in the
    pages its table row covers; length[slots] = lengths. The admitted
    rows' pages were just allocated, so no trash masking is needed.
    Quantized pools quantize the fp rows on the way in (scales land in
    the sidecars at the same page indices)."""
    pos = jnp.arange(s)                                    # [s]
    psz = page_size_of(pcache)
    pid = pcache.table[slots][:, pos // psz]               # [N, s]
    off = (pos % psz)[None, :]                             # [1, s]
    off = jnp.broadcast_to(off, pid.shape)
    rows_arrays = _pools_of_view(rows_cache)
    scales = _scale_pools(pcache)
    out = {}
    for name, pool_a in _pools(pcache).items():
        tok = rows_arrays[name][:, :, :s]                  # [L, N, s, ...]
        if scales is None:
            out[name] = pool_a.at[:, pid, off].set(tok)
        else:
            from skypilot_tpu.ops import paged_attention as pa
            q, sc = pa.quantize_values(tok)
            out[name] = pool_a.at[:, pid, off].set(q)
            out[_SCALE_FIELD[name]] = \
                scales[name].at[:, pid, off].set(sc)
    length = pcache.length.at[slots].set(lengths)
    return dataclasses.replace(pcache, length=length, **out)


def gather_prefix(pcache, slot, p: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The [L, 1, p, ...] contiguous prefix arrays of row ``slot``
    (p static, a multiple of the page size): the exact pair the
    family's ``prefill_extend`` takes — (k, v) for PagedKV,
    (c_kv, k_rope) for PagedLatent. Zero-copy sharing rides this: a
    prefix-cache hit points its table entries at the SHARED pages and
    gathers the same data every other holder reads.

    Quantized pools dequantize on the way out (float32 — the scale
    precision): the pair is the family's fp ``prefill_extend``
    contract either way. A disagg adopter re-quantizes on its own
    scatter, so cross-replica token identity holds at
    SKYTPU_ENGINE_KV_QUANT=none only (docs/ENGINE.md)."""
    pools = _pools(pcache)
    scales = _scale_pools(pcache)
    if p == 0:
        a, b = pools.values()
        dta = jnp.float32 if scales is not None else a.dtype
        dtb = jnp.float32 if scales is not None else b.dtype
        za = jnp.zeros((a.shape[0], 1, 0, *a.shape[3:]), dta)
        zb = jnp.zeros((b.shape[0], 1, 0, *b.shape[3:]), dtb)
        return za, zb
    psz = page_size_of(pcache)
    pos = jnp.arange(p)
    pid = pcache.table[slot, pos // psz]                   # [p]
    off = pos % psz
    if scales is not None:
        from skypilot_tpu.ops import paged_attention as pa
        a, b = [pa.dequantize_values(
                    arr[:, pid, off][:, None],
                    scales[name][:, pid, off][:, None], jnp.float32)
                for name, arr in pools.items()]
        return a, b
    a, b = [arr[:, pid, off][:, None] for arr in pools.values()]
    return a, b


def adopt_rows(pcache, a: jnp.ndarray, b: jnp.ndarray, slot, s: int,
               new_len):
    """Write a HANDED-OFF row into slot's own pages: ``(a, b)`` are the
    [L, 1, s, ...] contiguous per-token arrays in :func:`gather_prefix`
    order — (k, v) for PagedKV, (c_kv, k_rope) for PagedLatent — as
    exported by a prefill replica and shipped npy-framed across the
    wire (serve/disagg/handoff.py). Positions [0, s) land in the pages
    the slot's table covers (the adopter reserved them through its own
    allocator — page IDS never cross the wire, only page CONTENTS);
    length[slot] = new_len, so pad garbage past the real prompt length
    is never attended. The exact inverse of the export gather: adopt
    then gather_prefix round-trips bit-identically (pin-tested in
    tests/unit_tests/test_paging.py)."""
    psz = page_size_of(pcache)
    names = list(_pools(pcache))
    rows = {names[0]: a, names[1]: b}
    scales = _scale_pools(pcache)
    out = {}

    def _write(name, pool_a, tok, pid, off):
        """One pool's scatter — fp straight in, quantized via the
        codes + sidecar pair (the adopter re-quantizes: page contents
        stay exact in ITS representation)."""
        if scales is None:
            if off is None:
                return {name: pool_a.at[:, pid].set(tok)}
            return {name: pool_a.at[:, pid, off].set(tok)}
        from skypilot_tpu.ops import paged_attention as pa
        q, sc = pa.quantize_values(tok)
        if off is None:
            return {name: pool_a.at[:, pid].set(q),
                    _SCALE_FIELD[name]:
                        scales[name].at[:, pid].set(sc)}
        return {name: pool_a.at[:, pid, off].set(q),
                _SCALE_FIELD[name]:
                    scales[name].at[:, pid, off].set(sc)}

    if s % psz == 0:
        # Page-granular scatter: export buckets are page-aligned, so
        # whole pages land with s/psz scatter indices instead of s —
        # the adopt is a memory op and must stay cheap next to the
        # decode rounds it interleaves with.
        n = s // psz
        pid = pcache.table[slot, :n]                       # [n]
        for name, pool_a in _pools(pcache).items():
            tok = rows[name][:, 0, :s]                     # [L, s, ...]
            paged = tok.reshape(tok.shape[0], n, psz,
                                *tok.shape[2:])
            out.update(_write(name, pool_a, paged, pid, None))
    else:
        pos = jnp.arange(s)
        pid = pcache.table[slot, pos // psz]               # [s]
        off = pos % psz
        for name, pool_a in _pools(pcache).items():
            tok = rows[name][:, 0, :s]                     # [L, s, ...]
            out.update(_write(name, pool_a, tok, pid, off))
    length = pcache.length.at[slot].set(new_len)
    return dataclasses.replace(pcache, length=length, **out)


def scatter_suffix(pcache, row_cache, slot, p: int, s2: int, new_len):
    """Write an extend/chunk prefill's suffix — positions [p, p+s2) of
    the single returned row — into row ``slot``'s own pages, leaving
    the (possibly shared) prefix pages untouched; length[slot] =
    new_len."""
    pos = p + jnp.arange(s2)
    psz = page_size_of(pcache)
    pid = pcache.table[slot, pos // psz]                   # [s2]
    off = pos % psz
    row_arrays = _pools_of_view(row_cache)
    scales = _scale_pools(pcache)
    out = {}
    for name, pool_a in _pools(pcache).items():
        tok = row_arrays[name][:, 0, p:p + s2]             # [L, s2, ...]
        if scales is None:
            out[name] = pool_a.at[:, pid, off].set(tok)
        else:
            from skypilot_tpu.ops import paged_attention as pa
            q, sc = pa.quantize_values(tok)
            out[name] = pool_a.at[:, pid, off].set(q)
            out[_SCALE_FIELD[name]] = \
                scales[name].at[:, pid, off].set(sc)
    length = pcache.length.at[slot].set(new_len)
    return dataclasses.replace(pcache, length=length, **out)


def export_pages(pcache, pids) -> Dict[str, jnp.ndarray]:
    """EXACT contents of pages ``pids`` (int32 [n], runtime data — the
    page-table-shape discipline), for the host-RAM spill tier: one
    [L, n, psz, ...] array per pool field, INCLUDING the scale
    sidecars under quantization. No dequant, no cast — spill then
    :func:`import_pages` round-trips bit-identically in either
    representation (fp16 pages byte-for-byte; int8 codes + float32
    scales byte-for-byte), property-tested in
    tests/unit_tests/test_paging.py."""
    idx = jnp.asarray(pids, jnp.int32)
    out = {name: a[:, idx] for name, a in _pools(pcache).items()}
    scales = _scale_pools(pcache)
    if scales is not None:
        for name, a in scales.items():
            out[_SCALE_FIELD[name]] = a[:, idx]
    return out


def import_pages(pcache, arrays: Dict[str, jnp.ndarray], pids):
    """Inverse of :func:`export_pages`: land spilled page contents in
    the (freshly allocated) device pages ``pids`` — the wake half of
    the spill tier. Page IDS never persist across the round trip, only
    CONTENTS: the waker reserved its own pages through its own
    allocator, exactly the disagg adopt discipline. Tables and lengths
    are untouched — the caller re-admits through the normal paths."""
    idx = jnp.asarray(pids, jnp.int32)
    out = {name: a.at[:, idx].set(arrays[name])
           for name, a in _pools(pcache).items()}
    scales = _scale_pools(pcache)
    if scales is not None:
        for name, a in scales.items():
            out[_SCALE_FIELD[name]] = \
                a.at[:, idx].set(arrays[_SCALE_FIELD[name]])
    return dataclasses.replace(pcache, **out)


class PagesExhausted(Exception):
    """Not enough free pages — admission must wait (or evict)."""


class PageAllocator:
    """Host-side deterministic free-list allocator with refcounts.

    Determinism is load-bearing: multi-host followers must arrive at
    identical page assignments, so allocation order is FIFO over a
    deque seeded 1..n_pages-1 ascending — a follower replaying the
    leader's admit/chunk/reap op stream from mirrored state draws the
    identical ids in the identical order. The admit/chunkstart ops
    additionally carry the leader's :meth:`fingerprint`, so any drift
    fails the gang loudly before it can corrupt KV. ``take()`` claims
    explicit ids (serialized page handoff for disaggregated serving;
    exercised by the property tests).

    Refcounts implement read-only sharing: a prefix-cache entry and
    every request admitted over it each hold one ref on the prefix's
    pages; a page returns to the free list only when its last holder
    unrefs (no double-free: unref below zero raises — property-tested
    in tests/unit_tests/test_paging.py)."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(f'need >= 2 pages (1 data + trash), got '
                             f'{n_pages}')
        self.n_pages = n_pages
        self._free = collections.deque(range(1, n_pages))
        self._free_set = set(self._free)
        self._rc: Dict[int, int] = {}

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    def can_fit(self, n: int) -> bool:
        return n <= len(self._free)

    def fingerprint(self) -> Tuple[int, int]:
        """(free_count, next_free_id) — a cheap state digest for the
        multi-host lockstep cross-check. Two allocators that replayed
        the same alloc/free sequence always agree; disagreement means
        page assignments diverged."""
        return (len(self._free), self._free[0] if self._free else -1)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise PagesExhausted(
                f'need {n} pages, {len(self._free)} free')
        out = [self._free.popleft() for _ in range(n)]
        for pid in out:
            self._free_set.discard(pid)
            self._rc[pid] = 1
        return out

    def take(self, pids: Sequence[int]) -> None:
        """Claim specific pages (follower replaying the leader's plan).
        Every id must currently be free."""
        want = set(pids)
        if len(want) != len(pids):
            raise ValueError(f'duplicate page ids in plan: {pids}')
        missing = want - self._free_set
        if missing:
            raise PagesExhausted(
                f'plan pages not free: {sorted(missing)}')
        self._free = collections.deque(
            p for p in self._free if p not in want)
        self._free_set -= want
        for pid in pids:
            self._rc[pid] = 1

    def ref(self, pid: int) -> None:
        if pid not in self._rc:
            raise ValueError(f'ref of unallocated page {pid}')
        self._rc[pid] += 1

    def unref(self, pid: int) -> None:
        rc = self._rc.get(pid)
        if rc is None:
            raise ValueError(f'double free of page {pid}')
        if rc == 1:
            del self._rc[pid]
            self._free.append(pid)
            self._free_set.add(pid)
        else:
            self._rc[pid] = rc - 1

    def unref_all(self, pids: Iterable[int]) -> None:
        for pid in pids:
            self.unref(pid)

    def refcount(self, pid: int) -> int:
        return self._rc.get(pid, 0)
