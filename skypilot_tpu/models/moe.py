"""Mixture-of-Experts decoder (Mixtral-family), TPU-first with expert
parallelism.

Reference analog: the reference only *launches* MoE models via recipes
(llm/mixtral/, llm/dbrx/ — SURVEY §2.11); here the model is native.

Design: GShard/Switch-style dense dispatch — routing is expressed as
einsums against one-hot dispatch/combine tensors with a static per-expert
capacity, so the whole MoE layer is static-shaped and XLA turns the
dispatch contractions into all-to-alls over the 'expert' mesh axis.
Top-k routing with a load-balance auxiliary loss; experts are SwiGLU FFNs
stacked on a leading expert dim sharded over 'expert'.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama as llama_lib
from skypilot_tpu.ops import norms, rotary
from skypilot_tpu.parallel import sharding as sharding_lib

Params = Dict[str, Any]

# train_lib contract: forward(..., return_aux=True) yields (logits, aux).
HAS_AUX = True


@dataclasses.dataclass(frozen=True)
class MoEConfig(llama_lib.LlamaConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.02
    # Tokens are routed within fixed-size groups along the sequence (GShard),
    # so dispatch tensors stay O(S·C_group) instead of O(S²·K/E).
    router_group_size: int = 2048

    @property
    def num_params(self) -> int:
        hd = self.hd
        a = 2 + 2 * (self.n_kv_heads / self.n_heads)
        attn = int(a * self.dim * self.n_heads * hd)
        moe = self.n_experts * 3 * self.dim * self.ffn_dim
        router = self.dim * self.n_experts
        per_layer = attn + moe + router + 2 * self.dim
        embed = self.vocab_size * self.dim * (1 if self.tie_embeddings else 2)
        return self.n_layers * per_layer + embed + self.dim

    @property
    def active_params(self) -> int:
        """Params touched per token (for MFU accounting)."""
        hd = self.hd
        a = 2 + 2 * (self.n_kv_heads / self.n_heads)
        attn = int(a * self.dim * self.n_heads * hd)
        moe = self.top_k * 3 * self.dim * self.ffn_dim
        per_layer = attn + moe + self.dim * self.n_experts + 2 * self.dim
        embed = self.vocab_size * self.dim * (1 if self.tie_embeddings else 2)
        return self.n_layers * per_layer + embed + self.dim


PRESETS: Dict[str, MoEConfig] = {
    'moe-debug': MoEConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                           n_kv_heads=2, ffn_dim=128, max_seq_len=128,
                           rope_theta=10000.0, remat='none', n_experts=4,
                           top_k=2),
    'mixtral-8x7b': MoEConfig(vocab_size=32000, dim=4096, n_layers=32,
                              n_heads=32, n_kv_heads=8, ffn_dim=14336,
                              rope_theta=1e6, max_seq_len=32768, n_experts=8,
                              top_k=2),
    # ~1B-active MoE for single-chip benchmarking.
    'moe-1b': MoEConfig(vocab_size=32768, dim=1024, n_layers=12, n_heads=8,
                        n_kv_heads=4, ffn_dim=4096, max_seq_len=4096,
                        tie_embeddings=True, n_experts=8, top_k=2),
    # gpt-oss family (reference recipes: llm/gpt-oss/,
    # llm/gpt-oss-finetuning/): MoE + alternating sliding-window/full
    # attention + learned attention sinks + clamped SwiGLU + YaRN rope
    # — every knob composes from the config, no separate module.
    'gptoss-debug': MoEConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=128, rope_theta=10000.0, remat='none',
        n_experts=4, top_k=2, qkv_bias=True, attn_sinks=True,
        swiglu_limit=7.0, sliding_window=32, sliding_window_pattern=2,
        # Ample capacity: no routed token drops, so decode parity with
        # the training forward is exact (same note as
        # deepseek-moe-debug).
        capacity_factor=4.0,
        rope_scaling=dict(rope_type='yarn', factor=2.0,
                          original_max_position=64)),
    'gpt-oss-20b': MoEConfig(
        vocab_size=201088, dim=2880, n_layers=24, n_heads=64,
        n_kv_heads=8, head_dim=64, ffn_dim=2880, max_seq_len=131072,
        rope_theta=150000.0, n_experts=32, top_k=4, qkv_bias=True,
        attn_sinks=True, swiglu_limit=7.0, sliding_window=128,
        sliding_window_pattern=2,
        rope_scaling=dict(rope_type='yarn', factor=32.0,
                          original_max_position=4096)),
    'gpt-oss-120b': MoEConfig(
        vocab_size=201088, dim=2880, n_layers=36, n_heads=64,
        n_kv_heads=8, head_dim=64, ffn_dim=2880, max_seq_len=131072,
        rope_theta=150000.0, n_experts=128, top_k=4, qkv_bias=True,
        attn_sinks=True, swiglu_limit=7.0, sliding_window=128,
        sliding_window_pattern=2,
        rope_scaling=dict(rope_type='yarn', factor=32.0,
                          original_max_position=4096)),
}


def capacity(cfg: MoEConfig, seq_len: int) -> int:
    c = int(cfg.capacity_factor * seq_len * cfg.top_k / cfg.n_experts)
    return max(8, ((c + 7) // 8) * 8)


# ---------------------------------------------------------------------------
# Params
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: MoEConfig) -> Params:
    hd = cfg.hd
    k = iter(jax.random.split(rng, 16))
    init = jax.nn.initializers.normal(stddev=0.02, dtype=cfg.param_dtype)
    trunc = jax.nn.initializers.variance_scaling(
        1.0, 'fan_in', 'truncated_normal', dtype=cfg.param_dtype)
    L, D, F, E = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.n_experts
    params: Params = {
        'embed': init(next(k), (cfg.vocab_size, D)),
        'layers': {
            'attn_norm': jnp.ones((L, D), cfg.param_dtype),
            'wq': trunc(next(k), (L, D, cfg.n_heads * hd)),
            'wk': trunc(next(k), (L, D, cfg.n_kv_heads * hd)),
            'wv': trunc(next(k), (L, D, cfg.n_kv_heads * hd)),
            'wo': trunc(next(k), (L, cfg.n_heads * hd, D)),
            'moe_norm': jnp.ones((L, D), cfg.param_dtype),
            'router': init(next(k), (L, D, E)),
            'w_gate': trunc(next(k), (L, E, D, F)),
            'w_up': trunc(next(k), (L, E, D, F)),
            'w_down': trunc(next(k), (L, E, F, D)),
        },
        'final_norm': jnp.ones((D,), cfg.param_dtype),
    }
    if cfg.qkv_bias:
        params['layers']['bq'] = jnp.zeros((L, cfg.n_heads * hd),
                                           cfg.param_dtype)
        params['layers']['bk'] = jnp.zeros((L, cfg.n_kv_heads * hd),
                                           cfg.param_dtype)
        params['layers']['bv'] = jnp.zeros((L, cfg.n_kv_heads * hd),
                                           cfg.param_dtype)
    if cfg.attn_sinks:
        params['layers']['sink'] = jnp.zeros((L, cfg.n_heads),
                                             cfg.param_dtype)
    if not cfg.tie_embeddings:
        params['lm_head'] = init(next(k), (D, cfg.vocab_size))
    return params


def param_specs(cfg: MoEConfig,
                rules: Optional[sharding_lib.Rules] = None) -> Params:
    r = rules or sharding_lib.Rules()
    if cfg.pipeline_stages > 1:
        r = r.override(layers='stage')
    s = r.spec
    specs: Params = {
        'embed': s('vocab', 'embed'),
        'layers': {
            'attn_norm': s('layers', 'norm'),
            'wq': s('layers', 'embed', 'heads'),
            'wk': s('layers', 'embed', 'kv_heads'),
            'wv': s('layers', 'embed', 'kv_heads'),
            'wo': s('layers', 'heads', 'embed'),
            'moe_norm': s('layers', 'norm'),
            'router': s('layers', 'embed', 'norm'),
            'w_gate': s('layers', 'expert', 'embed', 'mlp'),
            'w_up': s('layers', 'expert', 'embed', 'mlp'),
            'w_down': s('layers', 'expert', 'mlp', 'embed'),
        },
        'final_norm': s('norm'),
    }
    if cfg.qkv_bias:
        specs['layers']['bq'] = s('layers', 'heads')
        specs['layers']['bk'] = s('layers', 'kv_heads')
        specs['layers']['bv'] = s('layers', 'kv_heads')
    if cfg.attn_sinks:
        specs['layers']['sink'] = s('layers', 'heads')
    if not cfg.tie_embeddings:
        specs['lm_head'] = s('embed', 'vocab')
    return specs


def validate_divisibility(cfg: MoEConfig, mesh_shape: Dict[str, int]):
    llama_lib.validate_divisibility(cfg, mesh_shape)
    ep = mesh_shape.get('expert', 1)
    if ep > 1 and cfg.n_experts % ep != 0:
        raise ValueError(f'n_experts={cfg.n_experts} not divisible by '
                         f'expert axis {ep}')


# ---------------------------------------------------------------------------
# MoE FFN
# ---------------------------------------------------------------------------

def moe_ffn(x: jnp.ndarray, lp: Params, cfg: MoEConfig,
            rules: sharding_lib.Rules) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x [B,S,D] → (y [B,S,D], aux_loss scalar). Routes within fixed-size
    sequence groups so all dispatch tensors are linear in S."""
    b, s_len, d = x.shape
    e, k = cfg.n_experts, cfg.top_k
    gs = min(cfg.router_group_size, s_len)
    if s_len % gs != 0:
        gs = s_len                     # fall back to one group
    g = s_len // gs
    c = capacity(cfg, gs)
    con = functools.partial(sharding_lib.constrain, rules=rules)

    xg = x.reshape(b, g, gs, d)
    logits = jnp.einsum('bgtd,de->bgte', xg.astype(jnp.float32),
                        lp['router'].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                   # [B,G,T,E]
    gate_w, gate_idx = jax.lax.top_k(probs, k)                # [B,G,T,K]
    gate_w = gate_w / jnp.maximum(gate_w.sum(-1, keepdims=True), 1e-9)

    # Load-balance aux (Switch): E · Σ_e f_e · p̄_e over the top-1 choice.
    top1 = jax.nn.one_hot(gate_idx[..., 0], e, dtype=jnp.float32)
    f_e, p_e = top1.mean((0, 1, 2)), probs.mean((0, 1, 2))
    if cfg.attention_impl == 'ring' and cfg.pipeline_stages > 1:
        # Flattened stage+sequence region (_pipelined_layers): this call
        # sees only the local sequence shard. f_e·p̄_e is a product of
        # per-token means, so averaging per-shard aux values would NOT
        # equal the full-batch aux — average the mean vectors over
        # 'sequence' first, then take the product.
        f_e = jax.lax.pmean(f_e, 'sequence')
        p_e = jax.lax.pmean(p_e, 'sequence')
    aux = e * jnp.sum(f_e * p_e)

    # Static-capacity dispatch: each (token, choice)'s buffer slot in its
    # expert comes from a cumulative count within the group.
    onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)   # [B,G,T,K,E]
    flat = onehot.reshape(b, g, gs * k, e)
    pos = jnp.cumsum(flat, axis=2) - flat
    pos = pos.reshape(b, g, gs, k, e)
    keep = (pos < c) * onehot                                 # drop overflow
    slot = jax.nn.one_hot(pos.astype(jnp.int32), c,
                          dtype=jnp.float32) * keep[..., None]
    dispatch = slot.sum(3)                                    # [B,G,T,E,C]
    combine = jnp.einsum('bgtk,bgtkec->bgtec',
                         gate_w.astype(jnp.float32), slot)

    xin = jnp.einsum('bgtec,bgtd->ebgcd', dispatch.astype(cfg.dtype), xg)
    xin = con(xin, 'expert', 'batch', None, None, 'act_embed')
    gate = jnp.einsum('ebgcd,edf->ebgcf', xin, lp['w_gate'].astype(cfg.dtype))
    up = jnp.einsum('ebgcd,edf->ebgcf', xin, lp['w_up'].astype(cfg.dtype))
    inner = cfg.glu(gate, up)
    inner = con(inner, 'expert', 'batch', None, None, 'mlp')
    out = jnp.einsum('ebgcf,efd->ebgcd', inner,
                     lp['w_down'].astype(cfg.dtype))          # [E,B,G,C,D]
    y = jnp.einsum('bgtec,ebgcd->bgtd', combine.astype(cfg.dtype), out)
    return con(y.reshape(b, s_len, d), 'batch', 'seq', 'act_embed'), aux


def _layer(carry, lp, cfg: MoEConfig, rules, sin, cos, q_offset,
           layer_idx=None):
    x, aux_sum = carry
    x = x + llama_lib.attention_block(x, lp, cfg, rules, sin, cos, q_offset,
                                      layer_idx=layer_idx)
    h = norms.rms_norm(x, lp['moe_norm'], cfg.rms_eps)
    y, aux = moe_ffn(h, lp, cfg, rules)
    return (x + y, aux_sum + aux)


def _pipelined_layers(x, layers, layer_fn, cfg: MoEConfig, sin, cos):
    """GPipe over 'stage' with the router aux loss riding each microbatch
    through the rotation (parallel/pipeline.py has_aux=True). Mirrors
    llama._pipelined_layers (incl. the flattened stage+sequence manual
    region for ring attention); the aux scalar of every microbatch is
    summed on the last stage and psum-broadcast with the activations."""
    from jax.sharding import PartitionSpec as P
    from skypilot_tpu.parallel import pipeline as pipeline_lib
    b, s_len, d = x.shape
    m = cfg.num_microbatches
    if b % m != 0:
        raise ValueError(f'batch {b} not divisible by num_microbatches {m}')
    if cfg.n_layers % cfg.pipeline_stages != 0:
        raise ValueError(f'n_layers {cfg.n_layers} not divisible by '
                         f'pipeline_stages {cfg.pipeline_stages}')
    from skypilot_tpu.ops.attention import _on_tpu
    boundary_dtype = x.dtype if _on_tpu() else jnp.float32
    xm = x.reshape(m, b // m, s_len, d).astype(boundary_dtype)
    ring = cfg.attention_impl == 'ring'
    axes = {'stage', 'sequence'} if ring else {'stage'}
    x_spec = P(None, None, 'sequence') if ring else P()
    rope_spec = P('sequence') if ring else P()

    def sm_fn(layers_local, xm_local, sin_l, cos_l):
        def fn(carry, lp):
            return layer_fn(carry, lp, sin_l, cos_l)
        out, aux = pipeline_lib.pipeline_apply(
            fn, layers_local, xm_local.astype(x.dtype), has_aux=True)
        # (aux needs no 'sequence' reduction here: moe_ffn already pmeans
        # its per-expert mean vectors across sequence shards, so the aux
        # scalar is uniform over 'sequence'.)
        return out.astype(boundary_dtype), aux

    out, aux = jax.shard_map(
        sm_fn, in_specs=(P('stage'), x_spec, rope_spec, rope_spec),
        out_specs=(x_spec, P()),
        axis_names=axes, check_vma=False)(layers, xm, sin, cos)
    # Each microbatch's aux is a mean over its own tokens; the sum over M
    # microbatches is M× the full-batch mean the scan path produces.
    return out.reshape(b, s_len, d).astype(x.dtype), aux / m


def forward(params: Params,
            tokens: jnp.ndarray,
            cfg: MoEConfig,
            rules: Optional[sharding_lib.Rules] = None,
            positions: Optional[jnp.ndarray] = None,
            q_offset: int | jnp.ndarray = 0,
            return_aux: bool = False):
    """tokens [B,S] → logits [B,S,V] fp32 (+ router aux loss if asked)."""
    rules = rules or sharding_lib.Rules()
    con = functools.partial(sharding_lib.constrain, rules=rules)
    b, s_len = tokens.shape
    tokens = con(tokens, 'batch', 'seq')
    x = jnp.take(params['embed'], tokens, axis=0).astype(cfg.dtype)
    x = con(x, 'batch', 'seq', 'act_embed')

    if cfg.qk_norm:
        raise NotImplementedError(
            'qk_norm is a dense (Gemma-3) feature; MoE layers have no '
            'q/k norm params.')
    if positions is None:
        if (cfg.attention_impl == 'ring' and
                getattr(cfg, 'ring_layout', 'seq') == 'zigzag'):
            raise ValueError(
                "ring_layout='zigzag' needs zigzag-permuted tokens and "
                "explicit `positions` — see llama.forward; train_lib's "
                "train/eval steps do the permutation automatically.")
        positions = jnp.arange(s_len) + q_offset
    # rope_tables (not raw rope_frequencies): stacks the dual rope bases
    # when local_rope_theta is set, so attention_block's per-layer
    # select_rope sees the same tables training and decode use.
    sin, cos = llama_lib.rope_tables(cfg, positions)

    if cfg.post_norms:
        raise NotImplementedError(
            'post_norms is a dense (Gemma-2) feature; MoE layers have no '
            'post-sublayer norm params.')
    layer_rules = (rules.override(seq=None)
                   if cfg.pipeline_stages > 1 and cfg.attention_impl == 'ring'
                   else rules)

    def layer_fn(carry, lp_idx, sin_l, cos_l):
        lp, idx = lp_idx
        return _layer(carry, lp, cfg, layer_rules, sin_l, cos_l, q_offset,
                      layer_idx=idx)

    policy_name = llama_lib._REMAT_POLICIES[cfg.remat]
    if policy_name is not None:
        policy = getattr(jax.checkpoint_policies, policy_name)
        layer_fn = jax.checkpoint(layer_fn, policy=policy)

    layer_ids = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    aux0 = jnp.zeros((), jnp.float32)
    if cfg.pipeline_stages > 1:
        x, aux = _pipelined_layers(x, (params['layers'], layer_ids),
                                   layer_fn, cfg, sin, cos)
    elif cfg.scan_layers:
        def body(carry, lp_idx):
            return layer_fn(carry, lp_idx, sin, cos), None
        (x, aux), _ = jax.lax.scan(body, (x, aux0),
                                   (params['layers'], layer_ids))
    else:
        carry = (x, aux0)
        for i in range(cfg.n_layers):
            lp = jax.tree.map(lambda p: p[i], params['layers'])
            carry = layer_fn(carry, (lp, jnp.int32(i)), sin, cos)
        x, aux = carry

    x = norms.rms_norm(x, params['final_norm'], cfg.rms_eps)
    head = (params['embed'].T if cfg.tie_embeddings else params['lm_head'])
    logits = jnp.einsum('bsd,dv->bsv', x, head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    logits = con(logits, 'batch', 'seq', 'vocab')
    if return_aux:
        return logits, cfg.router_aux_weight * aux / cfg.n_layers
    return logits
