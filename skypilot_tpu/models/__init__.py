"""Model zoo: TPU-first implementations (pure JAX pytrees + pjit sharding).

Reference analog: the `llm/` recipe directory — but where the reference
launches external torch code, these are native models the framework can
train/serve directly. `get_config(name)` resolves preset names;
`module_for(cfg)` maps a config to its model module (init_params /
param_specs / forward / validate_divisibility).
"""
from skypilot_tpu.models import llama
from skypilot_tpu.models import mla
from skypilot_tpu.models import moe

_PRESETS = {}
_PRESETS.update(llama.PRESETS)
_PRESETS.update(moe.PRESETS)
_PRESETS.update(mla.PRESETS)


def get_config(name: str):
    key = name.lower().replace('_', '-')
    if key not in _PRESETS:
        raise ValueError(f'Unknown model preset {name!r}; '
                         f'known: {sorted(_PRESETS)}')
    return _PRESETS[key]


def list_presets():
    return sorted(_PRESETS)


def module_for(cfg):
    """Model module implementing this config (most-derived class wins)."""
    if isinstance(cfg, mla.MLAConfig):
        return mla
    if isinstance(cfg, moe.MoEConfig):
        return moe
    if isinstance(cfg, llama.LlamaConfig):
        return llama
    raise TypeError(f'No model module for config type {type(cfg)!r}')
