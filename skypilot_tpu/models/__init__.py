"""Model zoo: TPU-first implementations (pure JAX pytrees + pjit sharding).

Reference analog: the `llm/` recipe directory — but where the reference
launches external torch code, these are native models the framework can
train/serve directly. `get_config(name)` resolves preset names.
"""
from skypilot_tpu.models import llama

_PRESETS = {}
_PRESETS.update(llama.PRESETS)


def get_config(name: str):
    key = name.lower().replace('_', '-')
    if key not in _PRESETS:
        raise ValueError(f'Unknown model preset {name!r}; '
                         f'known: {sorted(_PRESETS)}')
    return _PRESETS[key]


def list_presets():
    return sorted(_PRESETS)
