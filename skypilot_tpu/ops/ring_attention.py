"""Ring attention: exact context-parallel attention over the 'sequence' axis.

The long-context strategy the reference lacks entirely (SURVEY §2.11: SP/CP
"absent in reference") and a TPU-native design: K/V shards rotate around the
ICI ring via `lax.ppermute` while every device computes flash-attention
partials against its resident Q shard; partials merge with the numerically
stable log-sum-exp rule. Communication rides nearest-neighbour ICI links and
overlaps with the per-step kernel, so attention scales to sequence lengths
far beyond one chip's HBM.

Must be called INSIDE `shard_map` with q/k/v sharded on their sequence dim
over `axis_name`. RoPE must already be applied with *global* positions
(the model does this naturally: sin/cos are sharded alongside the tokens).

Causal layout note: plain sequential sharding makes causal load imbalanced
(shard i only attends i+1 of n steps); `zigzag=True` is reserved for the
balanced layout (future work).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -2.0 ** 30


def _combine(o: jnp.ndarray, lse: jnp.ndarray, o_i: jnp.ndarray,
             lse_i: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge two normalized attention partials via their log-sum-exps.

    o, o_i: [B,S,H,D] (f32); lse, lse_i: [B,S,H].
    """
    m = jnp.maximum(lse, lse_i)
    w = jnp.exp(lse - m)[..., None]
    w_i = jnp.exp(lse_i - m)[..., None]
    denom = w + w_i
    o_new = (o * w + o_i.astype(jnp.float32) * w_i) / denom
    lse_new = m + jnp.log(denom[..., 0])
    return o_new, lse_new


def _partial(q, k, v, causal: bool, softmax_scale, interpret: bool):
    """(out [B,S,H,D], lse [B,S,H]) for one ring step."""
    from skypilot_tpu.ops.attention import _flash_ok, xla_attention_lse
    use_flash = (not interpret and _flash_ok(q, k))
    if use_flash:
        from skypilot_tpu.ops.pallas import flash_attention as fa
        return fa.flash_attention_lse(q, k, v, causal=causal,
                                      softmax_scale=softmax_scale)
    return xla_attention_lse(q, k, v, causal=causal,
                             softmax_scale=softmax_scale)


def ring_attention(q: jnp.ndarray,
                   k: jnp.ndarray,
                   v: jnp.ndarray,
                   *,
                   axis_name: str = 'sequence',
                   causal: bool = True,
                   softmax_scale: Optional[float] = None,
                   interpret: bool = False) -> jnp.ndarray:
    """Exact attention over a sequence-sharded q/k/v. Call inside shard_map.

    q [B,Sl,H,D], k/v [B,Sl,KH,D] — Sl is the per-device shard. Returns the
    local output shard [B,Sl,H,D] in q.dtype.
    """
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, sl, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]
    part = functools.partial(_partial, softmax_scale=softmax_scale,
                             interpret=interpret)

    o0 = jnp.zeros((b, sl, h, d), jnp.float32)
    lse0 = jnp.full((b, sl, h), NEG_INF, jnp.float32)

    def body(carry, i):
        o, lse, k_c, v_c = carry
        src = (me - i) % n                     # whose kv shard we hold now

        if causal:
            def diag(_):
                return part(q, k_c, v_c, causal=True)

            def earlier(_):
                return part(q, k_c, v_c, causal=False)

            def skip(_):
                return (jnp.zeros((b, sl, h, d), q.dtype),
                        jnp.full((b, sl, h), NEG_INF, jnp.float32))

            idx = jnp.where(src == me, 1, jnp.where(src < me, 0, 2))
            o_i, lse_i = jax.lax.switch(idx, [earlier, diag, skip], None)
        else:
            o_i, lse_i = part(q, k_c, v_c, causal=False)

        o, lse = _combine(o, lse, o_i, lse_i)
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        return (o, lse, k_c, v_c), None

    (o, _, _, _), _ = jax.lax.scan(body, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype)
