"""Ring attention: exact context-parallel attention over the 'sequence' axis.

The long-context strategy the reference lacks entirely (SURVEY §2.11: SP/CP
"absent in reference") and a TPU-native design: K/V shards rotate around the
ICI ring via `lax.ppermute` while every device computes flash-attention
partials against its resident Q shard; partials merge with the numerically
stable log-sum-exp rule. Communication rides nearest-neighbour ICI links and
overlaps with the per-step kernel, so attention scales to sequence lengths
far beyond one chip's HBM.

Two entry points, both differentiable — the backward is a hand-written
forward-style ring (`jax.custom_vjp`), never a transposed collective:
  * `ring_attention(...)` — call INSIDE a manual region that binds
    `axis_name` (a shard_map, or the flattened stage+sequence pipeline
    region in models/llama.py).
  * `ring_attention_sharded(...)` — call OUTSIDE any manual region (GSPMD
    level): a plain shard_map over `ring_attention`. Shardy rejects
    opening a new manual region under a parent that binds other axes, so
    pipeline callers flatten to one stage+sequence region and use
    `ring_attention` directly instead.

RoPE must already be applied with *global* positions (the model does this
naturally: sin/cos are sharded alongside the tokens; for zigzag the caller
permutes positions with `zigzag_positions`).

Causal layouts:
  * 'seq'    — contiguous shards. Simple, but shard i only has causal work
    for i+1 of n ring steps: the last shard does ~n× the work of the first.
  * 'zigzag' — the global sequence is split into 2n chunks and shard i
    holds chunks (i, 2n-1-i), so every shard does the same causal work
    (the balanced layout from the Striped/zigzag ring-attention line of
    work). Requires the tokens to be laid out zigzag — use
    `zigzag_positions`/`zigzag_permute` on tokens, labels and positions.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.utils import knobs
from jax.sharding import PartitionSpec as P

NEG_INF = -2.0 ** 30


# ---------------------------------------------------------------------------
# Zigzag layout helpers
# ---------------------------------------------------------------------------

def zigzag_chunk_order(n_shards: int) -> list:
    """Chunk ids in device-layout order: shard i holds (i, 2n-1-i)."""
    return [c for s in range(n_shards) for c in (s, 2 * n_shards - 1 - s)]


def zigzag_positions(seq_len: int, n_shards: int) -> np.ndarray:
    """positions[j] = original sequence position stored at layout slot j.

    Doubles as the gather index that permutes a contiguous sequence into
    zigzag layout, and as the `positions` argument for RoPE. Pure numpy so
    it stays a compile-time constant under jit."""
    if seq_len % (2 * n_shards) != 0:
        raise ValueError(f'seq_len {seq_len} must divide into '
                         f'2*{n_shards} zigzag chunks.')
    chunk = seq_len // (2 * n_shards)
    order = zigzag_chunk_order(n_shards)
    return np.concatenate(
        [np.arange(c * chunk, (c + 1) * chunk) for c in order])


def zigzag_permute(x: jnp.ndarray, n_shards: int, axis: int = 1
                   ) -> jnp.ndarray:
    """Reorder a contiguous sequence dim into zigzag device layout."""
    return jnp.take(x, zigzag_positions(x.shape[axis], n_shards), axis=axis)


def zigzag_unpermute(x: jnp.ndarray, n_shards: int, axis: int = 1
                     ) -> jnp.ndarray:
    """Inverse of `zigzag_permute` (static scatter)."""
    inv = np.argsort(zigzag_positions(x.shape[axis], n_shards))
    return jnp.take(x, inv, axis=axis)


# ---------------------------------------------------------------------------
# Log-sum-exp combine + single-block partials
# ---------------------------------------------------------------------------

def _combine(o: jnp.ndarray, lse: jnp.ndarray, o_i: jnp.ndarray,
             lse_i: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge two normalized attention partials via their log-sum-exps.

    o, o_i: [B,S,H,D] (f32); lse, lse_i: [B,S,H].
    """
    m = jnp.maximum(lse, lse_i)
    w = jnp.exp(lse - m)[..., None]
    w_i = jnp.exp(lse_i - m)[..., None]
    denom = w + w_i
    o_new = (o * w + o_i.astype(jnp.float32) * w_i) / denom
    lse_new = m + jnp.log(denom[..., 0])
    return o_new, lse_new


def _partial(q, k, v, causal: bool, softmax_scale, interpret: bool):
    """(out [B,S,H,D], lse [B,S,H]) for one visible block."""
    from skypilot_tpu.ops.attention import _flash_ok, xla_attention_lse
    use_flash = (not interpret and _flash_ok(q, k))
    if use_flash:
        from skypilot_tpu.ops.pallas import flash_attention as fa
        return fa.flash_attention_lse(q, k, v, causal=causal,
                                      softmax_scale=softmax_scale)
    return xla_attention_lse(q, k, v, causal=causal,
                             softmax_scale=softmax_scale)


def _block_partial(qa, kb, vb, rel, softmax_scale, interpret):
    """Partial for one q-chunk × kv-chunk pair.

    rel (traced int32): 0 = kv chunk strictly earlier (fully visible),
    1 = same chunk (causal diagonal), 2 = kv later (skip)."""
    part = functools.partial(_partial, softmax_scale=softmax_scale,
                             interpret=interpret)
    b, sq, h, d = qa.shape

    def full(_):
        return part(qa, kb, vb, causal=False)

    def diag(_):
        return part(qa, kb, vb, causal=True)

    def skip(_):
        return (jnp.zeros((b, sq, h, d), qa.dtype),
                jnp.full((b, sq, h), NEG_INF, jnp.float32))

    return jax.lax.switch(rel, [full, diag, skip], None)


def _chunk_ids(shard_idx, n: int, layout: str):
    if layout == 'zigzag':
        return (shard_idx, 2 * n - 1 - shard_idx)
    return (shard_idx,)


def _rel(q_chunk, kv_chunk):
    return jnp.where(kv_chunk == q_chunk, 1,
                     jnp.where(kv_chunk < q_chunk, 0, 2)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Forward (inside shard_map)
# ---------------------------------------------------------------------------

def _ring_forward(q, k, v, *, axis_name, causal, softmax_scale, layout,
                  interpret):
    """(out [B,Sl,H,D] q.dtype, lse [B,Sl,H] f32). Call inside shard_map."""
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, sl, h, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]
    qcs = _chunk_ids(me, n, layout) if causal else (me,)
    ncq = len(qcs)
    csize = sl // ncq
    if causal and layout == 'zigzag' and sl % 2 != 0:
        raise ValueError(f'zigzag needs an even local shard, got {sl}')

    o0 = jnp.zeros((b, sl, h, d), jnp.float32)
    lse0 = jnp.full((b, sl, h), NEG_INF, jnp.float32)

    def body(carry, i):
        o, lse, k_c, v_c = carry
        src = (me - i) % n                     # whose kv shard we hold now

        if not causal:
            o_i, lse_i = _partial(q, k_c, v_c, causal=False,
                                  softmax_scale=softmax_scale,
                                  interpret=interpret)
        else:
            kcs = _chunk_ids(src, n, layout)
            o_rows, lse_rows = [], []
            for a in range(ncq):
                qa = q[:, a * csize:(a + 1) * csize]
                o_a = jnp.zeros((b, csize, h, d), jnp.float32)
                lse_a = jnp.full((b, csize, h), NEG_INF, jnp.float32)
                for bi in range(len(kcs)):
                    kb = k_c[:, bi * csize:(bi + 1) * csize]
                    vb = v_c[:, bi * csize:(bi + 1) * csize]
                    o_ab, lse_ab = _block_partial(
                        qa, kb, vb, _rel(qcs[a], kcs[bi]),
                        softmax_scale, interpret)
                    o_a, lse_a = _combine(o_a, lse_a, o_ab, lse_ab)
                o_rows.append(o_a)
                lse_rows.append(lse_a)
            o_i = jnp.concatenate(o_rows, axis=1)
            lse_i = jnp.concatenate(lse_rows, axis=1)

        o, lse = _combine(o, lse, o_i, lse_i)
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        return (o, lse, k_c, v_c), None

    (o, lse, _, _), _ = jax.lax.scan(body, (o0, lse0, k, v), jnp.arange(n))
    return o.astype(q.dtype), lse


class _RingOpts(NamedTuple):
    axis_name: str
    causal: bool
    softmax_scale: Optional[float]
    layout: str
    interpret: bool


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ring_local(opts: _RingOpts, q, k, v):
    out, _ = _ring_forward(q, k, v, axis_name=opts.axis_name,
                           causal=opts.causal,
                           softmax_scale=opts.softmax_scale,
                           layout=opts.layout, interpret=opts.interpret)
    return out


def _ring_local_fwd(opts, q, k, v):
    out, lse = _ring_forward(q, k, v, axis_name=opts.axis_name,
                             causal=opts.causal,
                             softmax_scale=opts.softmax_scale,
                             layout=opts.layout, interpret=opts.interpret)
    return out, (q, k, v, out, lse)


def _ring_local_bwd(opts, res, g):
    q, k, v, o, lse = res
    dq, dk, dv = _ring_backward(q, k, v, o, lse, g,
                                axis_name=opts.axis_name, causal=opts.causal,
                                softmax_scale=opts.softmax_scale,
                                layout=opts.layout,
                                interpret=opts.interpret)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_local.defvjp(_ring_local_fwd, _ring_local_bwd)


def ring_attention(q: jnp.ndarray,
                   k: jnp.ndarray,
                   v: jnp.ndarray,
                   *,
                   axis_name: str = 'sequence',
                   causal: bool = True,
                   softmax_scale: Optional[float] = None,
                   layout: str = 'seq',
                   interpret: bool = False) -> jnp.ndarray:
    """Exact attention over a sequence-sharded q/k/v. Call inside shard_map
    (or any manual region that binds `axis_name`, e.g. a flattened
    stage+sequence pipeline region).

    q [B,Sl,H,D], k/v [B,Sl,KH,D] — Sl is the per-device shard. Returns the
    local output shard [B,Sl,H,D] in q.dtype. Differentiable: the backward
    is an explicit forward-style ring (custom_vjp), never a transposed
    collective — this is what lets the ring live inside other manual
    regions without tripping Shardy's nested-manual rebind.
    """
    return _ring_local(
        _RingOpts(axis_name, causal, softmax_scale, layout, interpret),
        q, k, v)


# ---------------------------------------------------------------------------
# Backward (inside shard_map)
# ---------------------------------------------------------------------------

# Long-context backward memory bound: KV chunks larger than this are
# processed through a lax.scan, so the materialized score/probability
# block is [B,KH,G,Sq,CHUNK] f32 instead of [B,KH,G,Sq,Tk] — at 32k-token
# shards the unchunked block would be gigabytes per step. The einsums
# still land on the MXU; only peak HBM changes.
_BWD_KV_CHUNK = knobs.get_int('SKYTPU_RING_BWD_CHUNK')
# Flash-kernel backward dispatch: '' = auto (TPU + lane-aligned shapes),
# '1' = force (tests use interpret mode), '0' = always einsum path.
_BWD_FLASH = knobs.get_enum('SKYTPU_RING_BWD_FLASH')


def _flash_bwd_ok(sq: int, tk: int, d: int, interpret: bool) -> bool:
    if _BWD_FLASH == '0':
        return False
    shapes_ok = (d % 128 == 0 and sq % 128 == 0 and tk % 128 == 0)
    if _BWD_FLASH == '1':
        return shapes_ok
    return shapes_ok and not interpret


def _flash_block_grads(qa, do_a, lse_a, delta_a, kb, vb, masked, scale,
                       interpret):
    """Block gradients through the Pallas flash backward kernels
    (ops/pallas/flash_attention._bwd) — fused VMEM-blocked dq/dkv, no
    HBM score intermediates, same kernels the training step's flash
    attention backward uses.

    The kernel expects PRE-SCALED q in [B,H,S,D] layout and derives
    Δ = rowsum(out·do) − dlse internally; the ring already holds the
    global Δ, so it rides in as dlse = −Δ with out = 0 (out has no other
    use in _bwd)."""
    from skypilot_tpu.ops.pallas import flash_attention as fa
    b, sq, h, d = qa.shape
    qh = (qa * scale).swapaxes(1, 2)
    kh = kb.swapaxes(1, 2)
    vh = vb.swapaxes(1, 2)
    doh = do_a.swapaxes(1, 2)
    lse_t = jnp.broadcast_to(
        lse_a.swapaxes(1, 2)[..., None], (b, h, sq, fa.LANES))
    dq, dk, dv = fa._bwd(qh, kh, vh, jnp.zeros_like(doh), lse_t, doh,
                         causal=masked, block_q=512, block_k=512,
                         interpret=interpret,
                         dlse=-delta_a.swapaxes(1, 2),
                         # f32 partials: each block grad is accumulated
                         # across ring steps — bf16 rounding per step
                         # would compound with ring size.
                         grad_dtype=jnp.float32)
    # dq is w.r.t. the pre-scaled q → chain back through the *scale.
    dq = dq.swapaxes(1, 2) * scale
    return dq, dk.swapaxes(1, 2), dv.swapaxes(1, 2)


def _block_grads(qa, do_a, lse_a, delta_a, kb, vb, rel, scale, *,
                 interpret):
    """Flash-style block gradients for one q-chunk × kv-chunk pair.

    Uses the FINAL forward lse (global softmax normalizer) so each block's
    probabilities are already correctly normalized:
      P = exp(S - lse);  dV = Pᵀ·dO;  dP = dO·Vᵀ;
      dS = P ⊙ (dP - Δ)  with Δ = rowsum(dO ⊙ O);
      dQ = dS·K·scale;   dK = dSᵀ·Q·scale.
    Shapes: qa/do_a [B,Sq,H,D], kb/vb [B,Tk,KH,D], lse_a/delta_a [B,Sq,H].
    On TPU with lane-aligned shapes the block runs through the Pallas
    flash backward kernels; otherwise KV dims past _BWD_KV_CHUNK are
    scanned in chunks (memory-bounded einsums).
    """
    b, sq, h, d = qa.shape
    tk, kh = kb.shape[1], kb.shape[2]
    g = h // kh
    use_flash = _flash_bwd_ok(sq, tk, d, interpret)

    qg = qa.reshape(b, sq, kh, g, d).astype(jnp.float32)
    dog = do_a.reshape(b, sq, kh, g, d).astype(jnp.float32)
    lse_g = lse_a.reshape(b, sq, kh, g).transpose(0, 2, 3, 1)
    delta_g = delta_a.reshape(b, sq, kh, g).transpose(0, 2, 3, 1)

    def grads_vs_kv_chunk(kf, vf, kv_off, masked):
        """(dq_contrib, dk_chunk, dv_chunk) against kv[kv_off:kv_off+ck]."""
        s = jnp.einsum('bskgd,btkd->bkgst', qg, kf) * scale
        if masked:
            ck = kf.shape[1]
            causal_mask = (jnp.arange(sq)[:, None] >=
                           jnp.arange(ck)[None, :] + kv_off)
            s = jnp.where(causal_mask[None, None, None], s, NEG_INF)
        p = jnp.exp(s - lse_g[..., None])
        dv = jnp.einsum('bkgst,bskgd->btkd', p, dog)
        dp = jnp.einsum('bskgd,btkd->bkgst', dog, vf)
        ds = p * (dp - delta_g[..., None])
        dq = jnp.einsum('bkgst,btkd->bskgd', ds, kf).reshape(
            b, sq, h, d) * scale
        dk = jnp.einsum('bkgst,bskgd->btkd', ds, qg) * scale
        return dq, dk, dv

    def compute(masked):
        if use_flash:
            return _flash_block_grads(qa, do_a, lse_a, delta_a, kb, vb,
                                      masked, scale, interpret)
        kf_all = kb.astype(jnp.float32)
        vf_all = vb.astype(jnp.float32)
        # Largest divisor of tk <= the target chunk, so the memory bound
        # holds for non-power-of-two shard sizes too (equal-size chunks
        # keep the scan body static-shaped).
        ck = min(_BWD_KV_CHUNK, tk)
        while tk % ck != 0:
            ck -= 1
        if tk <= ck:
            return grads_vs_kv_chunk(kf_all, vf_all, 0, masked)

        def chunk_body(dq_acc, idx):
            kc = jax.lax.dynamic_slice_in_dim(kf_all, idx * ck, ck, 1)
            vc = jax.lax.dynamic_slice_in_dim(vf_all, idx * ck, ck, 1)
            dq_c, dk_c, dv_c = grads_vs_kv_chunk(kc, vc, idx * ck, masked)
            return dq_acc + dq_c, (dk_c, dv_c)

        dq0 = jnp.zeros((b, sq, h, d), jnp.float32)
        dq, (dks, dvs) = jax.lax.scan(chunk_body, dq0,
                                      jnp.arange(tk // ck))
        dk = dks.transpose(1, 0, 2, 3, 4).reshape(b, tk, kh, d)
        dv = dvs.transpose(1, 0, 2, 3, 4).reshape(b, tk, kh, d)
        return dq, dk, dv

    def full(_):
        return compute(masked=False)

    def diag(_):
        return compute(masked=True)

    def skip(_):
        return (jnp.zeros((b, sq, h, d), jnp.float32),
                jnp.zeros((b, tk, kh, d), jnp.float32),
                jnp.zeros((b, tk, kh, d), jnp.float32))

    return jax.lax.switch(rel, [full, diag, skip], None)


def _ring_backward(q, k, v, o, lse, do, *, axis_name, causal, softmax_scale,
                   layout, interpret):
    """(dq, dk, dv) local shards (f32). Call inside shard_map.

    The kv shards rotate exactly as in forward, with their gradient
    accumulators travelling alongside: after n steps each (dk, dv) has
    collected every device's contribution and is home again."""
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, sl, h, d = q.shape
    kh = k.shape[2]
    perm = [(j, (j + 1) % n) for j in range(n)]
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    qcs = _chunk_ids(me, n, layout) if causal else (me,)
    ncq = len(qcs)
    csize = sl // ncq

    # Δ = rowsum(dO ⊙ O): one vector per q position, shared by every block.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    dq0 = jnp.zeros((b, sl, h, d), jnp.float32)
    dk0 = jnp.zeros((b, sl, kh, d), jnp.float32)
    dv0 = jnp.zeros((b, sl, kh, d), jnp.float32)

    def body(carry, i):
        dq, k_c, v_c, dk_c, dv_c = carry
        src = (me - i) % n

        if not causal:
            dq_i, dk_i, dv_i = _block_grads(
                q, do, lse, delta, k_c, v_c, jnp.int32(0), scale,
                interpret=interpret)
            dq = dq + dq_i
            dk_c = dk_c + dk_i
            dv_c = dv_c + dv_i
        else:
            kcs = _chunk_ids(src, n, layout)
            for a in range(ncq):
                sla = slice(a * csize, (a + 1) * csize)
                for bi in range(len(kcs)):
                    slb = slice(bi * csize, (bi + 1) * csize)
                    dq_ab, dk_ab, dv_ab = _block_grads(
                        q[:, sla], do[:, sla], lse[:, sla], delta[:, sla],
                        k_c[:, slb], v_c[:, slb],
                        _rel(qcs[a], kcs[bi]), scale,
                        interpret=interpret)
                    dq = dq.at[:, sla].add(dq_ab)
                    dk_c = dk_c.at[:, slb].add(dk_ab)
                    dv_c = dv_c.at[:, slb].add(dv_ab)

        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        dk_c = jax.lax.ppermute(dk_c, axis_name, perm)
        dv_c = jax.lax.ppermute(dv_c, axis_name, perm)
        return (dq, k_c, v_c, dk_c, dv_c), None

    (dq, _, _, dk, dv), _ = jax.lax.scan(
        body, (dq0, k, v, dk0, dv0), jnp.arange(n))
    return dq, dk, dv


# ---------------------------------------------------------------------------
# GSPMD-level entry point with custom VJP
# ---------------------------------------------------------------------------

def ring_attention_sharded(q: jnp.ndarray,
                           k: jnp.ndarray,
                           v: jnp.ndarray,
                           *,
                           axis_name: str = 'sequence',
                           causal: bool = True,
                           softmax_scale: Optional[float] = None,
                           layout: str = 'seq',
                           interpret: bool = False) -> jnp.ndarray:
    """Context-parallel attention at the GSPMD level (call OUTSIDE any
    manual region; q/k/v are globally-shaped arrays sharded on dim 1).

    A plain shard_map over `ring_attention`; autodiff goes through the
    local custom_vjp (explicit ring backward), so no collective is ever
    transposed. Callers already inside a manual region that binds
    `axis_name` (e.g. the flattened stage+sequence pipeline region) should
    call `ring_attention` directly instead — Shardy rejects opening a new
    manual region for an axis under a parent that already binds others."""
    spec = P(None, axis_name)
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, softmax_scale=softmax_scale,
                           layout=layout, interpret=interpret)
    # check_vma=False: the causal 'skip' branch returns constants that the
    # varying-axis checker would reject; semantics are still per-shard.
    return jax.shard_map(fn, in_specs=(spec, spec, spec), out_specs=spec,
                         axis_names={axis_name}, check_vma=False)(q, k, v)
