"""TPU-native op library: norms, rotary embeddings, attention dispatch.

The hot ops are written so XLA tiles them onto the MXU (bf16 einsums, static
shapes) with fp32 accumulation where it matters; Pallas kernels
(flash/ring attention) live beside the XLA reference implementations and are
selected via `attention(..., impl=...)`.
"""
from skypilot_tpu.ops.norms import rms_norm
from skypilot_tpu.ops.rotary import apply_rope, rope_frequencies
from skypilot_tpu.ops.attention import attention

__all__ = ['rms_norm', 'apply_rope', 'rope_frequencies', 'attention']
