"""Attention dispatch: XLA reference impl, Pallas flash kernel, ring (CP).

Layouts: q [B, S, H, D]; k, v [B, T, KH, D] with H = KH * G (grouped-query).
Scores accumulate in fp32; output is returned in q.dtype (bf16 on TPU so the
MXU does the contractions).

impl:
  'xla'   — einsum + masked softmax; XLA fuses well for moderate S.
  'flash' — Pallas TPU flash-attention kernel (ops/pallas/flash_attention.py);
            falls back to 'xla' off-TPU.
  'ring'  — context-parallel ring attention over the 'sequence' mesh axis
            (ops/ring_attention.py); requires being inside shard_map.
  'auto'  — 'flash' on TPU when shapes allow, else 'xla'.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -2.0 ** 30  # large-but-finite: keeps softmax fp32-safe


def _xla_attention_impl(q, k, v, causal, q_offset, kv_offset, segment_ids,
                        softmax_scale, return_lse, logit_softcap=None,
                        window=None, window_active=None, sinks=None):
    b, s, h, d = q.shape
    t, kh = k.shape[1], k.shape[2]
    groups = h // kh
    scale = softmax_scale if softmax_scale is not None else d ** -0.5

    qf = (q * scale).astype(q.dtype)
    # [B,S,KH,G,D] x [B,T,KH,D] -> [B,KH,G,S,T]
    qg = qf.reshape(b, s, kh, groups, d)
    scores = jnp.einsum('bskgd,btkd->bkgst', qg, k,
                        preferred_element_type=jnp.float32)
    if logit_softcap:
        # Gemma-2 style: bound attention logits with cap·tanh(s/cap)
        # BEFORE masking (the mask's NEG_INF must stay -inf-like).
        scores = logit_softcap * jnp.tanh(scores / logit_softcap)

    mask = None
    win_mask = None
    if causal:
        q_off = jnp.asarray(q_offset)
        kv_pos = jnp.arange(t) + kv_offset
        if q_off.ndim == 1:
            # Per-row offsets [B] (ragged decode: each row's new token
            # sits at its own cache length).
            q_pos = jnp.arange(s)[None, :] + q_off[:, None]    # [B,S]
            mask = (q_pos[:, :, None] >= kv_pos[None, None, :])
            if window is not None:
                win_mask = (q_pos[:, :, None] - kv_pos[None, None, :] <
                            window)
                win_mask = win_mask[:, None, None, :, :]
            mask = mask[:, None, None, :, :]                   # [B,1,1,S,T]
        else:
            q_pos = jnp.arange(s) + q_off
            mask = q_pos[:, None] >= kv_pos[None, :]           # [S,T]
            if window is not None:
                win_mask = (q_pos[:, None] - kv_pos[None, :] < window)
                win_mask = win_mask[None, None, None, :, :]
            mask = mask[None, None, None, :, :]
        if win_mask is not None:
            if window_active is not None:
                # Traced per-layer flag (alternating local/global layers
                # under one lax.scan): blend the window in only when set.
                win_mask = jnp.logical_or(
                    win_mask, jnp.logical_not(window_active))
            mask = jnp.logical_and(mask, win_mask)
    if segment_ids is not None:
        q_seg, kv_seg = segment_ids
        seg_mask = (q_seg[:, :, None] == kv_seg[:, None, :])  # [B,S,T]
        seg_mask = seg_mask[:, None, None, :, :]
        mask = seg_mask if mask is None else jnp.logical_and(mask, seg_mask)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)

    if sinks is not None:
        # Attention sinks (gpt-oss): a learned per-head logit joins the
        # softmax as a phantom key — it absorbs probability mass (the
        # denominator grows by exp(sink)) but contributes no value.
        # Never masked: it is exactly the always-visible "sink token".
        assert not return_lse, 'sinks not supported on the lse path (ring)'
        sink_col = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(1, kh, groups, 1, 1),
            (b, kh, groups, s, 1))
        scores = jnp.concatenate([scores, sink_col], axis=-1)

    if return_lse:
        lse = jax.nn.logsumexp(scores, axis=-1)           # [B,KH,G,S]
        probs = jnp.exp(scores - lse[..., None]).astype(q.dtype)
    else:
        lse = None
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if sinks is not None:
        probs = probs[..., :t]   # drop the phantom column (no value)
    out = jnp.einsum('bkgst,btkd->bskgd', probs, v).reshape(b, s, h, d)
    if return_lse:
        return out, lse.transpose(0, 3, 1, 2).reshape(b, s, h)
    return out


def xla_attention(q: jnp.ndarray,
                  k: jnp.ndarray,
                  v: jnp.ndarray,
                  *,
                  causal: bool = True,
                  q_offset: int | jnp.ndarray = 0,
                  kv_offset: int | jnp.ndarray = 0,
                  segment_ids: Optional[jnp.ndarray] = None,
                  softmax_scale: Optional[float] = None,
                  logit_softcap: Optional[float] = None,
                  window: Optional[int] = None,
                  window_active=None,
                  sinks: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Reference attention. q [B,S,H,D], k/v [B,T,KH,D] → [B,S,H,D].

    q_offset/kv_offset are the global positions of q[:,0]/k[:,0] — used both
    for decode (q_offset=cache_len) and for context-parallel shards.
    `logit_softcap` bounds attention logits (Gemma-2); `window` masks keys
    older than `window` positions, gated by the (possibly traced)
    `window_active` flag so alternating local/global layers share one
    compiled scan body. `sinks` [H] adds a learned per-head phantom-key
    logit to the softmax (gpt-oss attention sinks).
    """
    return _xla_attention_impl(q, k, v, causal, q_offset, kv_offset,
                               segment_ids, softmax_scale, return_lse=False,
                               logit_softcap=logit_softcap, window=window,
                               window_active=window_active, sinks=sinks)


def xla_attention_lse(q, k, v, *, causal: bool = True, softmax_scale=None):
    """Reference attention that also returns lse [B,S,H] (for ring/CP)."""
    return _xla_attention_impl(q, k, v, causal, 0, 0, None, softmax_scale,
                               return_lse=True)


def attention(q: jnp.ndarray,
              k: jnp.ndarray,
              v: jnp.ndarray,
              *,
              impl: str = 'auto',
              causal: bool = True,
              q_offset: int | jnp.ndarray = 0,
              kv_offset: int | jnp.ndarray = 0,
              segment_ids: Optional[jnp.ndarray] = None,
              softmax_scale: Optional[float] = None,
              logit_softcap: Optional[float] = None,
              window: Optional[int] = None,
              window_active=None,
              sinks: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    # The Pallas kernel supports neither position offsets, segment ids,
    # logit softcaps, sliding windows nor attention sinks; anything
    # non-trivial routes to the XLA reference implementation.
    trivial = (isinstance(q_offset, int) and q_offset == 0 and
               isinstance(kv_offset, int) and kv_offset == 0 and
               segment_ids is None and logit_softcap is None and
               window is None and sinks is None)
    if impl == 'auto':
        impl = 'flash' if (_on_tpu() and _flash_ok(q, k) and trivial) \
            else 'xla'
    elif impl == 'flash' and not trivial:
        impl = 'xla'
    if impl == 'xla':
        return xla_attention(q, k, v, causal=causal, q_offset=q_offset,
                             kv_offset=kv_offset, segment_ids=segment_ids,
                             softmax_scale=softmax_scale,
                             logit_softcap=logit_softcap, window=window,
                             window_active=window_active, sinks=sinks)
    if impl == 'flash':
        from skypilot_tpu.ops.pallas import flash_attention
        return flash_attention.flash_attention(
            q, k, v, causal=causal, softmax_scale=softmax_scale,
            interpret=not _on_tpu())
    if impl == 'ring':
        try:
            from skypilot_tpu.ops import ring_attention
        except ImportError as e:
            raise NotImplementedError(
                'ring attention requires skypilot_tpu.ops.ring_attention '
                '(context-parallel path)') from e
        return ring_attention.ring_attention(
            q, k, v, axis_name='sequence', causal=causal,
            softmax_scale=softmax_scale)
    raise ValueError(f'Unknown attention impl {impl!r}')


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == 'tpu'
    except Exception:  # pylint: disable=broad-except
        return False


def _flash_ok(q, k) -> bool:
    # Pallas kernel wants lane-aligned head_dim and block-divisible seq lens.
    d = q.shape[-1]
    return d % 128 == 0 and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
