"""Flash attention (fwd + bwd) as Pallas TPU kernels.

Own-design kernels following the standard online-softmax tiling:
  - fwd: grid (B, H, nq, nk), kv innermost; VMEM scratch accumulators
    (acc, m, l) persist across the kv dimension; causal blocks above the
    diagonal are skipped with `pl.when`.
  - bwd: two kernels — dq with grid (B, H, nq, nk) and dkv with grid
    (B, H, nk, nq) — both recompute p = exp(s - lse) from the saved
    log-sum-exp, so no S×S tensor ever hits HBM.
  - GQA: kv blocks are index-mapped per q-head (h → h // group) in fwd/dq;
    dkv produces per-q-head dk/dv which the wrapper group-sums.

Layouts: wrapper takes [B, S, H, D] (model layout), kernels run [B, H, S, D].
Row statistics (m, l, lse, delta) are lane-replicated [.., S, 128] f32 —
the Mosaic-friendly layout for per-row scalars.

Residual memory: lse + delta cost 2·B·H·S·128·4 bytes; for context-parallel
long sequences each device only holds its S/cp shard (ring attention calls
this kernel per shard), keeping that bounded.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
NEG_INF = -2.0 ** 30

# jax renamed TPUCompilerParams → CompilerParams across versions;
# serve both spellings so the kernels load on either.
COMPILER_PARAMS = getattr(pltpu, 'CompilerParams', None) or \
    getattr(pltpu, 'TPUCompilerParams')


def _block_size(s: int, preferred: int) -> int:
    for cand in (preferred, 512, 256, 128):
        if cand <= s and s % cand == 0:
            return cand
    raise ValueError(f'seq len {s} must be a multiple of 128')


def _lane_tile(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """Tile a (rows, LANES) lane-replicated stat out to (rows, n)."""
    assert n % LANES == 0
    return jnp.tile(x, (1, n // LANES))


def _causal_mask(s: jnp.ndarray, qi, ki, block_q: int, block_k: int):
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + qi * block_q
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ki * block_k
    return jnp.where(cols <= rows, s, NEG_INF)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, causal: bool, block_q: int, block_k: int, nk: int):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    if causal:
        relevant = (qi + 1) * block_q > ki * block_k
        last_ki = ((qi + 1) * block_q - 1) // block_k
    else:
        relevant = True
        last_ki = nk - 1

    @pl.when(relevant)
    def _body():
        q = q_ref[0, 0]                                    # (Bq, D)
        k = k_ref[0, 0]                                    # (Bk, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)

        m_prev = m_scr[...]                                # (Bq, LANES)
        l_prev = l_scr[...]
        m_cur = jnp.max(s, axis=1)[:, None]                # (Bq, 1)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - _lane_tile(m_next, s.shape[1]))
        l_next = l_prev * alpha + jnp.sum(p, axis=1)[:, None]
        m_scr[...] = m_next
        l_scr[...] = l_next

        v = v_ref[0, 0]                                    # (Bk, D)
        pv = jax.lax.dot(p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * _lane_tile(alpha, acc_scr.shape[1]) + pv

    @pl.when(ki == last_ki)
    def _finalize():
        l = l_scr[...]
        l_inv = jnp.where(l == 0.0, 1.0, 1.0 / l)
        o_ref[0, 0] = (acc_scr[...] *
                       _lane_tile(l_inv, acc_scr.shape[1])).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[...] + jnp.log(jnp.maximum(l, 1e-30))


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    b, h, s, d = q.shape
    kh, t = k.shape[1], k.shape[2]
    g = h // kh
    bq, bk = _block_size(s, block_q), _block_size(t, block_k)
    nq, nk = s // bq, t // bk

    grid = (b, h, nq, nk)
    kernel = functools.partial(_fwd_kernel, causal=causal, block_q=bq,
                               block_k=bk, nk=nk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, qi, ki: (b_, h_ // g, ki, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, qi, ki: (b_, h_ // g, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, bq, LANES),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, causal: bool, block_q: int, block_k: int, nk: int):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    if causal:
        relevant = (qi + 1) * block_q > ki * block_k
        last_ki = ((qi + 1) * block_q - 1) // block_k
    else:
        relevant = True
        last_ki = nk - 1

    @pl.when(relevant)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - _lane_tile(lse_ref[0, 0], s.shape[1]))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - _lane_tile(delta_ref[0, 0], s.shape[1]))
        dq_scr[...] += jax.lax.dot(ds.astype(k.dtype), k,
                                   preferred_element_type=jnp.float32)

    @pl.when(ki == last_ki)
    def _finalize():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, causal: bool, block_q: int, block_k: int, nq: int):
    ki, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == (ki * block_k) // block_q if causal else qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    relevant = (qi + 1) * block_q > ki * block_k if causal else True

    @pl.when(relevant)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - _lane_tile(lse_ref[0, 0], s.shape[1]))
        # dv += pᵀ · do  (contract the q dim without materialising pᵀ)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - _lane_tile(delta_ref[0, 0], s.shape[1]))
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, causal, block_q, block_k, interpret,
         dlse=None, grad_dtype=None):
    """grad_dtype overrides the dq/dk/dv output dtype (ring attention
    accumulates block grads across ring steps and wants f32 partials;
    the training custom-vjp path keeps operand dtypes)."""
    b, h, s, d = q.shape
    kh, t = k.shape[1], k.shape[2]
    g = h // kh
    dq_dt = grad_dtype or q.dtype
    dk_dt = grad_dtype or k.dtype
    dv_dt = grad_dtype or v.dtype
    bq, bk = _block_size(s, block_q), _block_size(t, block_k)
    nq, nk = s // bq, t // bk

    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)                  # [B,H,S,1]
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)[..., None]
    delta = jnp.broadcast_to(delta, (b, h, s, LANES))

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d),
                           lambda b_, h_, qi, ki: (b_, h_ // g, ki, 0))
    stat_spec = pl.BlockSpec((1, 1, bq, LANES),
                             lambda b_, h_, qi, ki: (b_, h_, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, block_q=bq, block_k=bk,
                          nk=nk),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, stat_spec, stat_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, s, d), dq_dt)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, k, v, do, lse, delta)[0]

    # dkv grid: (B, H, nk, nq) — q innermost, kv-block accumulators.
    q_spec2 = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, ki, qi: (b_, h_, qi, 0))
    kv_spec2 = pl.BlockSpec((1, 1, bk, d),
                            lambda b_, h_, ki, qi: (b_, h_ // g, ki, 0))
    kv_out_spec2 = pl.BlockSpec((1, 1, bk, d),
                                lambda b_, h_, ki, qi: (b_, h_, ki, 0))
    stat_spec2 = pl.BlockSpec((1, 1, bq, LANES),
                              lambda b_, h_, ki, qi: (b_, h_, qi, 0))
    dk_exp, dv_exp = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, block_q=bq, block_k=bk,
                          nq=nq),
        grid=(b, h, nk, nq),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, stat_spec2,
                  stat_spec2],
        out_specs=[kv_out_spec2, kv_out_spec2],
        out_shape=[jax.ShapeDtypeStruct((b, h, t, d), dk_dt),
                   jax.ShapeDtypeStruct((b, h, t, d), dv_dt)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    if g > 1:
        dk = dk_exp.reshape(b, kh, g, t, d).sum(axis=2)
        dv = dv_exp.reshape(b, kh, g, t, d).sum(axis=2)
    else:
        dk, dv = dk_exp, dv_exp
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public wrappers ([B, S, H, D] layout, custom VJP)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, causal, block_q, block_k, interpret)
    return out, lse[..., 0]                         # lse compact [B,H,S]


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, causal, block_q, block_k, interpret)
    return (out, lse[..., 0]), (q, k, v, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, cts):
    q, k, v, out, lse = res
    do, dlse = cts
    # d lse_i enters as ds += p · dlse_i, which folds into the delta term:
    # ds = p (dp − (delta − dlse)).
    dq, dk, dv = _bwd(q, k, v, out, lse, do, causal, block_q, block_k,
                      interpret, dlse=dlse)
    return dq, dk, dv


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_lse(q: jnp.ndarray,
                        k: jnp.ndarray,
                        v: jnp.ndarray,
                        *,
                        causal: bool = True,
                        softmax_scale: Optional[float] = None,
                        block_q: int = 512,
                        block_k: int = 512,
                        interpret: bool = False):
    """Like flash_attention but also returns lse [B,S,H] (f32) — the
    per-row log-sum-exp needed to combine partial attentions (ring/CP)."""
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    # Pre-scale q: s = (scale·q)·kᵀ, and dk = dsᵀ·(scale·q) comes out right;
    # dq needs the extra `scale` which the chain rule applies automatically
    # through this multiplication.
    qh = (q * scale).swapaxes(1, 2)                 # [B,H,S,D]
    kh_ = k.swapaxes(1, 2)                          # [B,KH,T,D]
    vh = v.swapaxes(1, 2)
    out, lse = _flash(qh, kh_, vh, causal, block_q, block_k, interpret)
    return out.swapaxes(1, 2), lse.swapaxes(1, 2)


def flash_attention(q: jnp.ndarray,
                    k: jnp.ndarray,
                    v: jnp.ndarray,
                    *,
                    causal: bool = True,
                    softmax_scale: Optional[float] = None,
                    block_q: int = 512,
                    block_k: int = 512,
                    interpret: bool = False) -> jnp.ndarray:
    """q [B,S,H,D], k/v [B,T,KH,D] → [B,S,H,D]; differentiable."""
    out, _ = flash_attention_lse(q, k, v, causal=causal,
                                 softmax_scale=softmax_scale,
                                 block_q=block_q, block_k=block_k,
                                 interpret=interpret)
    return out
