"""Pallas TPU kernels — the framework's native compute components.

These replace what GPU frameworks ship as CUDA kernels: flash attention
(fwd+bwd), and the building blocks for ring attention's per-step compute.
All kernels run in interpret mode on CPU for hermetic tests.
"""
