"""Table-driven paged decode attention as a Pallas TPU kernel.

The dense/GQA half of the in-place paged attention entry point
(ops/paged_attention.py): q is a handful of decode/verify positions
per row ([B, S, H, hd], S = the fused step width), K/V live in the
block-paged pool ([n_pages, page_size, KH, hd]) and the per-row page
table ([B, max_pages] int32) says which page backs which position
span. Instead of materializing a contiguous per-row view, the kernel
STREAMS one page block per grid step straight from the pool:

  - grid (B, H, max_pages), page index innermost; the page table and
    per-row lengths ride as SCALAR-PREFETCH operands so the K/V
    BlockSpec index maps resolve ``table[b, j]`` while the pipeline
    prefetches — the JetStream/vLLM paged-attention structure;
  - online softmax across page blocks (VMEM scratch m/l/acc persists
    over the page dimension, flash-attention style); pages past the
    row's content (``j*psz > length+S-1``) are skipped with pl.when —
    their table entries are 0 (the trash page) and never loaded;
  - causality inside a block is positional: page j covers row
    positions [j*psz, (j+1)*psz), so the mask is
    ``length + s >= j*psz + offset`` — no view, no position clamp.

The caller writes the step's new K/V into the pool FIRST (the same
trash-routed scatter the fused lax path uses), so the kernel only ever
reads pages. Gated like the flash kernel: interpret-mode allclose
against the fused lax formulation in tests/unit_tests/
test_paged_attention.py, selected on real TPUs only
(ops/paged_attention._pallas_ok).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
NEG_INF = -2.0 ** 30

# The TPUCompilerParams → CompilerParams rename alias lives with the
# flash kernel; one definition serves every pallas kernel here.
from skypilot_tpu.ops.pallas.flash_attention import COMPILER_PARAMS


def _kernel(table_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
            m_scr, l_scr, acc_scr, *, psz: int, s: int, nk: int):
    b, j = pl.program_id(0), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[b]
    q_pos_max = length + s - 1
    # Pages wholly past the row's content hold table entry 0 (trash):
    # skip them — the online stats simply don't advance.
    relevant = j * psz <= q_pos_max
    last_j = jnp.minimum(q_pos_max // psz, nk - 1)

    @pl.when(relevant)
    def _body():
        q = q_ref[0, :, 0, :]                              # (S, hd)
        k = k_ref[0, :, 0, :]                              # (psz, hd)
        v = v_ref[0, :, 0, :]
        s_ij = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (S, psz)
        q_pos = length + jax.lax.broadcasted_iota(
            jnp.int32, s_ij.shape, 0)
        kv_pos = j * psz + jax.lax.broadcasted_iota(
            jnp.int32, s_ij.shape, 1)
        s_ij = jnp.where(q_pos >= kv_pos, s_ij, NEG_INF)

        m_prev = m_scr[...][:, :1]                         # (S, 1)
        l_prev = l_scr[...][:, :1]
        m_cur = jnp.max(s_ij, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s_ij - m_next)                         # (S, psz)
        l_next = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = jnp.broadcast_to(m_next, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_next, l_scr.shape)
        pv = jax.lax.dot(p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + pv

    @pl.when(j == last_j)
    def _finalize():
        l = l_scr[...][:, :1]
        l_inv = jnp.where(l == 0.0, 1.0, 1.0 / l)
        o_ref[0, :, 0, :] = (acc_scr[...] * l_inv).astype(o_ref.dtype)


def paged_decode_attention(q: jnp.ndarray,
                           kp: jnp.ndarray,
                           vp: jnp.ndarray,
                           table: jnp.ndarray,
                           length: jnp.ndarray,
                           *,
                           softmax_scale: Optional[float] = None,
                           interpret: bool = False) -> jnp.ndarray:
    """q [B, S, H, hd] at per-row offsets `length` [B], pools kp/vp
    [n_pages, psz, KH, hd] addressed through table [B, max_pages] →
    out [B, S, H, hd]. Causal over positions [0, length+S) per row;
    positions [length, length+S) must already be written to the pool
    (the caller's in-place scatter precedes the call)."""
    b, s, h, hd = q.shape
    kh, psz = kp.shape[2], kp.shape[1]
    g = h // kh
    nk = table.shape[1]
    scale = softmax_scale if softmax_scale is not None else hd ** -0.5
    qs = (q * scale).astype(q.dtype)
    grid = (b, h, nk)
    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, s, 1, hd),
                         lambda b_, h_, j, tref, lref: (b_, 0, h_, 0)),
            pl.BlockSpec((1, psz, 1, hd),
                         lambda b_, h_, j, tref, lref:
                         (tref[b_, j], 0, h_ // g, 0)),
            pl.BlockSpec((1, psz, 1, hd),
                         lambda b_, h_, j, tref, lref:
                         (tref[b_, j], 0, h_ // g, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, s, 1, hd),
            lambda b_, h_, j, tref, lref: (b_, 0, h_, 0)),
        scratch_shapes=[
            pltpu.VMEM((s, LANES), jnp.float32),
            pltpu.VMEM((s, LANES), jnp.float32),
            pltpu.VMEM((s, hd), jnp.float32),
        ],
    )
    kernel = functools.partial(_kernel, psz=psz, s=s, nk=nk)
    return pl.pallas_call(
        kernel,
        grid_spec=spec,
        out_shape=jax.ShapeDtypeStruct((b, s, h, hd), q.dtype),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret,
    )(table, length.astype(jnp.int32), qs, kp, vp)
