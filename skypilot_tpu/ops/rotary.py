"""Rotary position embeddings (RoPE), Llama-3 scaling supported.

Computed on the fly from integer positions so context-parallel shards can
pass their own (global) position offsets — required by ring attention where
each sequence shard sees positions [i*S/cp, (i+1)*S/cp).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def rope_frequencies(head_dim: int,
                     positions: jnp.ndarray,
                     theta: float = 10000.0,
                     scaling: Optional[dict] = None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Return (sin, cos) of shape positions.shape + (head_dim // 2,), fp32.

    `scaling`: optional rope-scaling config. `rope_type` selects:
      - 'llama3' (default): NTK-by-parts with keys {factor,
        low_freq_factor, high_freq_factor, original_max_position}.
      - 'yarn' (gpt-oss, DeepSeek long-context): keys {factor,
        beta_fast=32, beta_slow=1, original_max_position,
        attention_factor} — low-frequency dims interpolate by `factor`,
        high-frequency dims extrapolate, with a linear ramp between the
        beta_fast/beta_slow correction dims; the attention
        (concentration) factor 0.1·ln(factor)+1 scales the tables.
    """
    import math
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    mscale = 1.0
    if scaling:
        if not isinstance(scaling, dict):   # models.llama.RopeScaling
            import dataclasses as _dc
            scaling = _dc.asdict(scaling)
        factor = float(scaling['factor'])
        orig = float(scaling.get('original_max_position', 8192))
        rope_type = scaling.get('rope_type', 'llama3')
        if rope_type == 'yarn':
            beta_fast = float(scaling.get('beta_fast', 32.0))
            beta_slow = float(scaling.get('beta_slow', 1.0))

            def correction_dim(num_rotations: float) -> float:
                # The dim index whose wavelength completes
                # `num_rotations` turns over the original context:
                # freqs_i = θ^(-i/half), so orig·freqs_i/(2π) = n at
                # i = half·ln(orig/(2πn))/ln θ. (HF writes the same as
                # dim·ln(...)/(2·ln θ) with dim = FULL head size.)
                return (half * math.log(orig /
                                        (num_rotations * 2 * math.pi))
                        / math.log(theta))

            low = max(math.floor(correction_dim(beta_fast)), 0)
            high = min(math.ceil(correction_dim(beta_slow)), half - 1)
            ramp = jnp.clip(
                (jnp.arange(half, dtype=jnp.float32) - low)
                / max(high - low, 1e-3), 0.0, 1.0)
            # ramp 0 → high-frequency (extrapolate, keep freqs);
            # ramp 1 → low-frequency (interpolate, freqs/factor).
            freqs = freqs * (1 - ramp) + (freqs / factor) * ramp
            af = scaling.get('attention_factor')
            mscale = (float(af) if af is not None
                      else 0.1 * math.log(factor) + 1.0)
        else:
            low = float(scaling.get('low_freq_factor', 1.0))
            high = float(scaling.get('high_freq_factor', 4.0))
            wavelen = 2.0 * jnp.pi / freqs
            ratio = orig / wavelen
            smooth = jnp.clip((ratio - low) / (high - low), 0.0, 1.0)
            scaled = freqs / factor
            freqs = jnp.where(ratio < low, scaled,
                              jnp.where(ratio > high, freqs,
                                        (1 - smooth) * scaled
                                        + smooth * freqs))
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.sin(angles) * mscale, jnp.cos(angles) * mscale


def apply_rope(x: jnp.ndarray, sin: jnp.ndarray,
               cos: jnp.ndarray) -> jnp.ndarray:
    """Rotate x [..., S, H, D] by per-position (sin, cos) [..., S, D/2].

    Uses the split-halves convention (HF Llama): x = [x1, x2],
    out = [x1*cos - x2*sin, x2*cos + x1*sin]. fp32 rotate, cast back.
    """
    dtype = x.dtype
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    # sin/cos are [..., S, D/2]; insert the heads axis.
    s = sin[..., None, :]
    c = cos[..., None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
