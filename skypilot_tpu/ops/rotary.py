"""Rotary position embeddings (RoPE), Llama-3 scaling supported.

Computed on the fly from integer positions so context-parallel shards can
pass their own (global) position offsets — required by ring attention where
each sequence shard sees positions [i*S/cp, (i+1)*S/cp).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def rope_frequencies(head_dim: int,
                     positions: jnp.ndarray,
                     theta: float = 10000.0,
                     scaling: Optional[dict] = None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Return (sin, cos) of shape positions.shape + (head_dim // 2,), fp32.

    `scaling`: optional llama-3.1 style NTK config with keys
    {factor, low_freq_factor, high_freq_factor, original_max_position}.
    """
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if scaling:
        if not isinstance(scaling, dict):   # models.llama.RopeScaling
            import dataclasses as _dc
            scaling = _dc.asdict(scaling)
        factor = float(scaling['factor'])
        low = float(scaling.get('low_freq_factor', 1.0))
        high = float(scaling.get('high_freq_factor', 4.0))
        orig = float(scaling.get('original_max_position', 8192))
        wavelen = 2.0 * jnp.pi / freqs
        ratio = orig / wavelen
        smooth = jnp.clip((ratio - low) / (high - low), 0.0, 1.0)
        scaled = freqs / factor
        freqs = jnp.where(ratio < low, scaled,
                          jnp.where(ratio > high, freqs,
                                    (1 - smooth) * scaled + smooth * freqs))
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x: jnp.ndarray, sin: jnp.ndarray,
               cos: jnp.ndarray) -> jnp.ndarray:
    """Rotate x [..., S, H, D] by per-position (sin, cos) [..., S, D/2].

    Uses the split-halves convention (HF Llama): x = [x1, x2],
    out = [x1*cos - x2*sin, x2*cos + x1*sin]. fp32 rotate, cast back.
    """
    dtype = x.dtype
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    # sin/cos are [..., S, D/2]; insert the heads axis.
    s = sin[..., None, :]
    c = cos[..., None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
