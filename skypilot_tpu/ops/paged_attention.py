"""In-place paged attention: index KV pages inside the attention
computation instead of materializing a contiguous per-row view.

The block-paged engine (models/paging.py, docs/ENGINE.md) originally
ran every fused step as gather → contiguous math → scatter:
``paging.gather_view`` materialized the full ``[L, B, max_len, ...]``
view in HBM before attention and ``scatter_steps``/``scatter_suffix``
wrote results back — roughly 2/k extra full-cache traversals per
decoded token on an HBM-bandwidth-bound decode path. This module is
the JetStream/vLLM-style fix: the step/verify/chunked-prefill programs
read ``pool[table[b, p // psz], p % psz]`` per LAYER inside the
attention computation and write the k new token positions straight
into the pool, so the only full-cache traffic left is the attention
read itself.

Two formulations behind one entry point (:func:`paged_attention_step`):

  - ``fused`` (default, CPU-runnable, the correctness anchor): a
    lax-level blockwise path — gather THIS layer's pages, overlay the
    step's new K/V at each row's write positions exactly like the
    contiguous ``verify_step`` does, and run the unchanged
    ``ops.attention`` reduction. Page order equals position order, so
    the reduction order (and the NEG_INF-underflow masking of
    trash-page garbage) is preserved bit-for-bit: the paged engine
    stays token-identical to the contiguous path by construction
    (pin-tested in tests/unit_tests/test_engine_paged.py, property-
    tested against the gather/scatter formulation in
    tests/unit_tests/test_paging.py).
  - ``pallas`` (TPU): a table-driven kernel
    (ops/pallas/paged_attention.py) streaming per-page K/V blocks from
    the pool with the page table scalar-prefetched into the index
    maps. Gated like the flash path: allclose-tested in interpret mode
    against the fused formulation, selected on TPU only — off-TPU (or
    for the MLA latent family, whose absorbed attention has no kernel
    yet) it falls back to ``fused``.

``gather`` keeps yesterday's gather/scatter programs compiled as the
regression baseline (serve/engine.py selects it per
``SKYTPU_ENGINE_ATTN``); skylint's ``paged-view-materialization``
checker pins that no NEW hot-path jit reaches for ``gather_view``.

Layout contract (both cache families): pools are
``[n_pages, page_size, ...]`` per layer, tables ``[B, max_pages]``
int32 runtime data (page COUNT is data, not shape — the
``page-table-shape`` discipline), page 0 is the trash page.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.ops.attention import _on_tpu
from skypilot_tpu.utils import knobs
from skypilot_tpu.ops.attention import attention as _attention

BACKENDS = ('fused', 'pallas', 'gather')
DEFAULT_BACKEND = 'fused'
ENV_VAR = 'SKYTPU_ENGINE_ATTN'


def backend_from_env() -> str:
    """The engine's attention-backend selection
    (``SKYTPU_ENGINE_ATTN=fused|pallas|gather``; default ``fused``).
    Garbage fails loudly at startup — a typo silently serving the slow
    gather baseline would be an invisible perf regression."""
    return knobs.get_enum(ENV_VAR)


def gather_pages(pool_layer: jnp.ndarray, table: jnp.ndarray,
                 max_len: int) -> jnp.ndarray:
    """One layer's contiguous view, straight from the pages: position
    ``p`` of row ``b`` reads ``pool_layer[table[b, p // psz], p % psz]``.
    pool_layer [n_pages, psz, ...], table [B, max_pages] →
    [B, max_len, ...]. Rows whose table entries are 0 read the trash
    page (garbage — always causally masked or overwritten before it is
    attended). Pages concatenate in position order, so the attention
    reduction order equals the materialized gather_view's exactly."""
    v = pool_layer[table]                       # [B, MAXP, psz, ...]
    b = v.shape[0]
    v = v.reshape(b, -1, *pool_layer.shape[2:])
    return v[:, :max_len]


def write_pages(pool_layer: jnp.ndarray, new: jnp.ndarray,
                pid: jnp.ndarray, off: jnp.ndarray) -> jnp.ndarray:
    """Write this step's new per-token values straight into the pool:
    new [B, S, ...] lands at (pid, off) [B, S] — indices the caller
    derives from the page table with inactive rows routed to the trash
    page (paging._write_indices), so a freed page can never be
    corrupted by a stale in-flight step."""
    return pool_layer.at[pid, off].set(new)


# Guard against all-zero vectors (fresh pool pages, padding tokens):
# a zero amax would divide by zero; QUANT_EPS keeps the scale finite
# and the round trip exactly zero (0 / eps rounds to 0, 0 * eps = 0).
QUANT_EPS = 1e-8


def quantize_values(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-vector int8 quantization over the LAST axis — the
    head dim for the dense K/V family, the latent rank for MLA. One
    float32 scale per vector lands in the sidecar scale pool (shape =
    value shape minus the last axis), so a page's scales travel with
    the page through every gather/scatter/spill path. amax/127 keeps
    the codebook symmetric (no zero-point): K/V activations are
    zero-centered post-norm, and symmetry means dequant is one fused
    multiply inside the attention gather. Error bound per element is
    scale/2 — property-tested in tests/unit_tests/test_paging.py."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, QUANT_EPS) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_values(q: jnp.ndarray, scale: jnp.ndarray,
                      dtype) -> jnp.ndarray:
    """Inverse of :func:`quantize_values`: q [..., d] int8 with scale
    [...] float32 back to ``dtype``. The multiply happens in float32 so
    bf16/fp16 targets round once, not twice."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def paged_attention_step(q: jnp.ndarray,
                         kp: jnp.ndarray,
                         vp: jnp.ndarray,
                         table: jnp.ndarray,
                         length: jnp.ndarray,
                         k_new: jnp.ndarray,
                         v_new: jnp.ndarray,
                         pid: jnp.ndarray,
                         off: jnp.ndarray,
                         *,
                         max_len: int,
                         impl: str = 'fused',
                         logit_softcap: Optional[float] = None,
                         window: Optional[int] = None,
                         window_active=None,
                         sinks: Optional[jnp.ndarray] = None,
                         k_scale: Optional[jnp.ndarray] = None,
                         v_scale: Optional[jnp.ndarray] = None):
    """One layer of in-place paged decode/verify attention for the
    dense/GQA K/V family: q [B, S, H, hd] at per-row offsets `length`
    ([B] int32), pools kp/vp [n_pages, psz, KH, hd], the step's new
    K/V [B, S, KH, hd] written at (pid, off). Returns
    (out [B, S, H, hd], kp', vp') with the pools updated in place —
    no contiguous view is ever materialized across layers.

    ``impl='fused'`` reproduces the contiguous verify_step bit-for-bit:
    gather this layer's view from the PRE-WRITE pool, overlay the new
    K/V at positions [length, length+S) for every row (exactly the
    ``.at[rows, positions].set`` the contiguous path does — inactive
    rows attend their own overlay too, so even their discarded logits
    match), attend with the unchanged XLA reduction. ``impl='pallas'``
    writes the pool first and streams page blocks through the
    table-driven kernel — TPU only; off-TPU, and whenever the kernel's
    shape/feature guard declines (softcap/window/sinks, lane-unaligned
    head dims), it falls back to the fused formulation.

    ``k_scale``/``v_scale`` [n_pages, psz, KH] select the int8 page
    pool (SKYTPU_ENGINE_KV_QUANT=int8): kp/vp hold int8 codes, the
    gather dequantizes in place, and the step's new K/V quantize on
    the way in. The overlay uses the DEQUANTIZED new values — this
    step's attention sees exactly what every future gather of these
    positions will read, so decode is replay-consistent under
    quantization (the fp path's bit-identity relaxes to allclose,
    gated by the pinned quality eval — QUALITY_LAST_GOOD.json).
    Returns a 5-tuple (out, kp', vp', k_scale', v_scale') on this
    path; the pallas kernel declines it (fused lax serves)."""
    b, s = q.shape[0], q.shape[1]
    rows = jnp.arange(b)
    positions = length[:, None] + jnp.arange(s)            # [B, S]
    if impl == 'pallas' and k_scale is None and \
            _pallas_ok(q, kp, logit_softcap, window, sinks):
        from skypilot_tpu.ops.pallas import paged_attention as pk
        kp2 = write_pages(kp, k_new, pid, off)
        vp2 = write_pages(vp, v_new, pid, off)
        # _pallas_ok gated on a real TPU, so the kernel always compiles
        # here; interpret mode is the TESTS' entry (they call
        # paged_decode_attention directly).
        out = pk.paged_decode_attention(q, kp2, vp2, table, length)
        return out, kp2, vp2
    # Fused lax path (and the pallas fallback): overlay-then-attend,
    # preserving the contiguous reduction order exactly.
    if k_scale is not None:
        kq, ks_new = quantize_values(k_new)
        vq, vs_new = quantize_values(v_new)
        k_l = dequantize_values(gather_pages(kp, table, max_len),
                                gather_pages(k_scale, table, max_len),
                                q.dtype)
        v_l = dequantize_values(gather_pages(vp, table, max_len),
                                gather_pages(v_scale, table, max_len),
                                q.dtype)
        k_l = k_l.at[rows[:, None], positions].set(
            dequantize_values(kq, ks_new, q.dtype))
        v_l = v_l.at[rows[:, None], positions].set(
            dequantize_values(vq, vs_new, q.dtype))
        out = _attention(
            q, k_l, v_l, impl='xla', causal=True, q_offset=length,
            kv_offset=0, logit_softcap=logit_softcap, window=window,
            window_active=window_active, sinks=sinks)
        return (out, write_pages(kp, kq, pid, off),
                write_pages(vp, vq, pid, off),
                write_pages(k_scale, ks_new, pid, off),
                write_pages(v_scale, vs_new, pid, off))
    k_l = gather_pages(kp, table, max_len)
    v_l = gather_pages(vp, table, max_len)
    k_l = k_l.at[rows[:, None], positions].set(k_new)
    v_l = v_l.at[rows[:, None], positions].set(v_new)
    out = _attention(
        q, k_l, v_l, impl='xla', causal=True, q_offset=length,
        kv_offset=0, logit_softcap=logit_softcap, window=window,
        window_active=window_active, sinks=sinks)
    kp2 = write_pages(kp, k_new, pid, off)
    vp2 = write_pages(vp, v_new, pid, off)
    return out, kp2, vp2


def _pallas_ok(q, kp, logit_softcap, window, sinks) -> bool:
    """The kernel guard, mirroring ops/attention's flash gating: TPU
    only, plain causal attention only (no softcap/window/sinks — those
    route to the fused lax path, like non-trivial shapes route flash
    to xla)."""
    if logit_softcap is not None or window is not None or \
            sinks is not None:
        return False
    if not _on_tpu():
        return False
    # Lane alignment: head_dim multiples of 128 stream cleanly; the
    # fused path serves everything else.
    return q.shape[-1] % 128 == 0 and kp.shape[1] % 8 == 0
