"""RMSNorm with fp32 statistics, bf16 in/out (XLA fuses this into one pass)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float = 1e-5,
             scale_plus_one: bool = False) -> jnp.ndarray:
    """y = x / rms(x) * scale, computed in fp32, returned in x.dtype."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    s = scale.astype(jnp.float32)
    if scale_plus_one:
        s = s + 1.0
    return (normed * s).astype(dtype)
