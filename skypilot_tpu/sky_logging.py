"""Logger setup with env-controlled verbosity.

Reference analog: sky/sky_logging.py (init_logger, is_silent).
"""
from __future__ import annotations

import contextlib
import logging
import sys
import threading

FORMAT = '%(levelname).1s %(asctime)s %(filename)s:%(lineno)d] %(message)s'
DATE_FORMAT = '%m-%d %H:%M:%S'

_root_name = 'skypilot_tpu'
_setup_lock = threading.Lock()
_initialized = False

_silent = threading.local()


def _debug_enabled() -> bool:
    # Shares the one registry bool grammar with env_options
    # SHOW_DEBUG_INFO — the two SKYTPU_DEBUG readers used to disagree
    # (this one accepted only '1'; 'true'/'yes' toggled the other).
    # Lazy import: sky_logging sits below utils in the layer DAG.
    from skypilot_tpu.utils import knobs
    return knobs.get_bool('SKYTPU_DEBUG')


class _NoPrefixFormatter(logging.Formatter):
    """INFO lines go out bare (user-facing); others keep the full prefix."""

    def format(self, record: logging.LogRecord) -> str:
        if record.levelno == logging.INFO and not _debug_enabled():
            return record.getMessage()
        return super().format(record)


def _setup_root() -> None:
    global _initialized
    with _setup_lock:
        if _initialized:
            return
        root = logging.getLogger(_root_name)
        root.setLevel(logging.DEBUG if _debug_enabled() else logging.INFO)
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(_NoPrefixFormatter(FORMAT, DATE_FORMAT))
        handler.setLevel(logging.DEBUG if _debug_enabled() else logging.INFO)
        root.addHandler(handler)
        root.propagate = False
        _initialized = True


def init_logger(name: str) -> logging.Logger:
    _setup_root()
    return logging.getLogger(name)


def is_silent() -> bool:
    return getattr(_silent, 'value', False)


@contextlib.contextmanager
def silent():
    """Suppress INFO output inside the block (used by nested SDK calls)."""
    old = getattr(_silent, 'value', False)
    _silent.value = True
    root = logging.getLogger(_root_name)
    old_level = root.level
    root.setLevel(logging.WARNING)
    try:
        yield
    finally:
        _silent.value = old
        root.setLevel(old_level)


def print_status(msg: str) -> None:
    if not is_silent():
        print(msg, flush=True)
