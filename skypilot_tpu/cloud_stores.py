"""CloudStorage helpers: fetch a URL source onto cluster hosts.

Reference analog: sky/cloud_stores.py (gsutil/aws-s3-cp/curl command
builders used by file_mounts with bucket/URL sources). The seam is a
command string executed on each host, so one implementation serves SSH and
local clusters alike.
"""
from __future__ import annotations

import shlex
from typing import Optional

from skypilot_tpu import exceptions


class CloudStorage:
    """Command builders for one URL scheme."""

    def make_sync_command(self, source: str, destination: str) -> str:
        """One command that works whether `source` is an object or a
        prefix — string heuristics can't tell them apart, the storage
        service can (the reference resolves this by listing; here the
        object-copy attempt is the existence probe: it fails fast on a
        prefix, and the dir sync fails fast on an object)."""
        raise NotImplementedError


class GcsCloudStorage(CloudStorage):

    def make_sync_command(self, source: str, destination: str) -> str:
        src = shlex.quote(source.rstrip('/'))
        dst = shlex.quote(destination)
        return (f'mkdir -p $(dirname {dst}) && '
                f'(gsutil cp {src} {dst} 2>/dev/null || '
                f'(mkdir -p {dst} && gsutil -m rsync -r {src} {dst}))')


class S3CloudStorage(CloudStorage):
    """The whole S3-compatible family: plain s3:// plus endpoint-
    parameterized providers (r2://, nebius:// — data/s3_compat.py),
    mirroring reference sky/data/storage.py:1468's S3CompatibleStore."""

    def make_sync_command(self, source: str, destination: str) -> str:
        from skypilot_tpu.data import s3_compat
        # cp first: `aws s3 sync` on an object key silently copies nothing,
        # so it must be the fallback, never the probe.
        ep_arg = s3_compat.aws_cli_flag(source)
        src = shlex.quote(s3_compat.to_s3_url(source.rstrip('/')))
        dst = shlex.quote(destination)
        return (f'mkdir -p $(dirname {dst}) && '
                f'(aws s3{ep_arg} cp {src} {dst} 2>/dev/null || '
                f'(mkdir -p {dst} && aws s3{ep_arg} sync {src} {dst}))')


class AzureBlobCloudStorage(CloudStorage):
    """Azure blob URLs (https://ACCOUNT.blob.core.windows.net/...) —
    matched by HOST, before the generic https handler (reference analog:
    sky/data/storage.py:2680 AzureBlobStore)."""

    def make_sync_command(self, source: str, destination: str) -> str:
        from skypilot_tpu.data import azure_blob
        return azure_blob.azcopy_copy_command(source, destination)


class HttpCloudStorage(CloudStorage):

    def make_sync_command(self, source: str, destination: str) -> str:
        dst = shlex.quote(destination)
        return (f'mkdir -p $(dirname {dst}) && '
                f'(command -v curl >/dev/null && '
                f'curl -fsSL {shlex.quote(source)} -o {dst} || '
                f'wget -q {shlex.quote(source)} -O {dst})')


def _build_registry():
    # The S3-family entries derive from the provider table so a new
    # provider in data/s3_compat.py is reachable here automatically.
    from skypilot_tpu.data import s3_compat
    s3_store = S3CloudStorage()
    registry = {'gs://': GcsCloudStorage()}
    registry.update({scheme: s3_store for scheme in s3_compat.SCHEMES})
    registry.update({'http://': HttpCloudStorage(),
                     'https://': HttpCloudStorage()})
    return registry


_REGISTRY = _build_registry()


def get_storage_from_path(url: str) -> Optional[CloudStorage]:
    """The CloudStorage for a URL, or None for plain local paths."""
    from skypilot_tpu.data import azure_blob
    if azure_blob.is_azure_url(url):
        return AzureBlobCloudStorage()
    for prefix, store in _REGISTRY.items():
        if url.startswith(prefix):
            return store
    return None
