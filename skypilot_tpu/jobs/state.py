"""Managed-job state machine + sqlite store (control-plane side).

Reference analog: sky/jobs/state.py (`ManagedJobStatus:377`, the spot table,
schedule state). One row per managed job; the controller process drives the
status through PENDING → STARTING → RUNNING → (RECOVERING → RUNNING)* →
terminal. Unlike the on-cluster JobStatus (skylet/job_lib.py), which resets
on every recovery, a managed job has exactly one ManagedJobStatus for its
whole life.
"""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.analysis import state_machines
from skypilot_tpu.utils import knobs
from skypilot_tpu.observe import journal as journal_lib
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.observe import trace as trace_lib
from skypilot_tpu.utils import sqlite_utils

logger = sky_logging.init_logger(__name__)

_DB_PATH_ENV = 'SKYTPU_JOBS_DB'


class ManagedJobStatus(enum.Enum):
    """Serverless-style status of a managed job (state.py:377 analog).

    Mapping from the on-cluster JobStatus each time the cluster is alive:
      INIT/PENDING/SETTING_UP → RUNNING (cluster is dedicated to the job)
      RUNNING                 → RUNNING
      SUCCEEDED               → SUCCEEDED
      FAILED / FAILED_SETUP   → FAILED / FAILED_SETUP
    Cluster gone while non-terminal → RECOVERING.
    """
    # Waiting for a controller slot (scheduler parallelism limit).
    PENDING = 'PENDING'
    # Controller is provisioning the cluster for the first time.
    STARTING = 'STARTING'
    # Submitted to the cluster; setting up or running.
    RUNNING = 'RUNNING'
    # Cluster was preempted/lost; controller is relaunching (failover).
    RECOVERING = 'RECOVERING'
    # User requested cancel; controller is tearing down.
    CANCELLING = 'CANCELLING'
    # Terminal:
    SUCCEEDED = 'SUCCEEDED'
    CANCELLED = 'CANCELLED'
    FAILED = 'FAILED'                    # user code exited non-zero
    FAILED_SETUP = 'FAILED_SETUP'        # setup section failed
    FAILED_PRECHECKS = 'FAILED_PRECHECKS'  # task invalid / optimizer error
    FAILED_NO_RESOURCE = 'FAILED_NO_RESOURCE'  # exhausted every failover target
    FAILED_CONTROLLER = 'FAILED_CONTROLLER'  # controller itself crashed

    def is_terminal(self) -> bool:
        return self in _TERMINAL

    def is_failed(self) -> bool:
        return self in _FAILED

    def colored_str(self) -> str:
        if self is ManagedJobStatus.SUCCEEDED:
            color = '\x1b[32m'
        elif self in _FAILED or self is ManagedJobStatus.CANCELLED:
            color = '\x1b[31m'
        else:
            color = '\x1b[33m'
        return f'{color}{self.value}\x1b[0m'


_TERMINAL = frozenset({
    ManagedJobStatus.SUCCEEDED,
    ManagedJobStatus.CANCELLED,
    ManagedJobStatus.FAILED,
    ManagedJobStatus.FAILED_SETUP,
    ManagedJobStatus.FAILED_PRECHECKS,
    ManagedJobStatus.FAILED_NO_RESOURCE,
    ManagedJobStatus.FAILED_CONTROLLER,
})
_FAILED = frozenset({
    ManagedJobStatus.FAILED,
    ManagedJobStatus.FAILED_SETUP,
    ManagedJobStatus.FAILED_PRECHECKS,
    ManagedJobStatus.FAILED_NO_RESOURCE,
    ManagedJobStatus.FAILED_CONTROLLER,
})

# Transition telemetry: label values are the declared enum — bounded by
# construction (the metric-discipline contract).
_TRANSITIONS_METRIC = metrics_lib.counter(
    'skytpu_jobs_transitions_total',
    'Managed-job status transitions committed, by target status.',
    labels={'to': tuple(s.value for s in ManagedJobStatus)})
_RECOVERIES_METRIC = metrics_lib.counter(
    'skytpu_jobs_recoveries_total',
    'Managed-job recoveries completed (RECOVERING -> RUNNING).')


def _journal_transition(job_id: int, old: Optional[ManagedJobStatus],
                        new: ManagedJobStatus,
                        reason: Optional[str] = None,
                        trace_id: Optional[str] = None) -> None:
    """Publish one WINNING job transition (callers invoke this only
    after their guarded UPDATE committed, and never for self-loops)."""
    journal_lib.record_transition(
        'job', str(job_id), old.value if old else None, new.value,
        reason=reason, trace_id=trace_id)
    if old is not None:
        # Entry into PENDING is row creation, not a transition — the
        # journal classes it as KIND_ENTRY; the counter must agree.
        _TRANSITIONS_METRIC.inc(to=new.value)


def _db_path() -> str:
    path = os.path.expanduser(knobs.get_str(_DB_PATH_ENV))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    return path


def _conn() -> sqlite3.Connection:
    conn = sqlite_utils.connect_wal(_db_path())
    conn.execute("""
        CREATE TABLE IF NOT EXISTS jobs (
            job_id INTEGER PRIMARY KEY AUTOINCREMENT,
            name TEXT,
            task_config TEXT,
            status TEXT,
            strategy TEXT,
            submitted_at REAL,
            started_at REAL,
            ended_at REAL,
            last_recovered_at REAL,
            recovery_count INTEGER DEFAULT 0,
            restarts_on_errors INTEGER DEFAULT 0,
            max_restarts_on_errors INTEGER DEFAULT 0,
            cluster_name TEXT,
            cluster_job_id INTEGER,
            failure_reason TEXT,
            controller_pid INTEGER,
            cancel_requested INTEGER DEFAULT 0,
            current_task INTEGER DEFAULT 0,
            num_tasks INTEGER DEFAULT 1,
            pool TEXT,
            trace_id TEXT
        )""")
    # Older DBs predate the pipeline columns.
    for col, default in (('current_task', 0), ('num_tasks', 1)):
        try:
            conn.execute(f'ALTER TABLE jobs ADD COLUMN {col} INTEGER '
                         f'DEFAULT {default}')
        except sqlite3.OperationalError:
            pass   # already present
    for col in ('pool TEXT', 'controller_restarts INTEGER DEFAULT 0',
                'trace_id TEXT'):
        try:
            conn.execute(f'ALTER TABLE jobs ADD COLUMN {col}')
        except sqlite3.OperationalError:
            pass
    return conn


def controller_log_path(job_id: int) -> str:
    d = os.path.expanduser('~/.skytpu/jobs')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'controller_{job_id}.log')


def job_log_path(job_id: int) -> str:
    """Mirrored user-job output (rank-0), streamed by `jobs logs`."""
    d = os.path.expanduser('~/.skytpu/jobs')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'run_{job_id}.log')


# ---------------------------------------------------------------------------
# Writes
# ---------------------------------------------------------------------------
def submit(name: str, task_config: Dict[str, Any], strategy: str,
           max_restarts_on_errors: int = 0, num_tasks: int = 1,
           pool: Optional[str] = None) -> int:
    """task_config: one task dict, or {'pipeline': [task dicts]} for
    chained multi-task jobs (reference: pipeline managed jobs). `pool`
    routes the job onto a worker of that pool instead of a dedicated
    cluster."""
    # The trace minted at API-request ingress sticks to the job row, so
    # a resumed controller (fresh process, no contextvar) still journals
    # under the original correlation id.
    trace_id = trace_lib.get()
    with _conn() as conn:
        cur = conn.execute(
            'INSERT INTO jobs (name, task_config, status, strategy, '
            'submitted_at, max_restarts_on_errors, num_tasks, pool, '
            'trace_id) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)',
            (name, json.dumps(task_config), ManagedJobStatus.PENDING.value,
             strategy, time.time(), max_restarts_on_errors, num_tasks,
             pool, trace_id))
        assert cur.lastrowid is not None
        job_id = cur.lastrowid
    _journal_transition(job_id, None, ManagedJobStatus.PENDING,
                        trace_id=trace_id)
    return job_id


def set_current_task(job_id: int, index: int,
                     cluster_name: Optional[str] = None) -> None:
    """Advance the pipeline stage pointer; cluster_name must track the
    stage's cluster or orphan-teardown and log streaming act on a dead
    name."""
    if cluster_name is not None:
        _update(job_id, current_task=index, cluster_name=cluster_name)
    else:
        _update(job_id, current_task=index)


def _update(job_id: int, **cols: Any) -> None:
    sets = ', '.join(f'{k} = ?' for k in cols)
    with _conn() as conn:
        conn.execute(f'UPDATE jobs SET {sets} WHERE job_id = ?',
                     (*cols.values(), job_id))


def set_status_nonterminal(job_id: int, status: ManagedJobStatus,
                           exprs: Optional[Dict[str, str]] = None,
                           **cols: Any) -> bool:
    """Guarded live transition: applies iff the declared state machine
    (analysis/state_machines.py JOB_TRANSITIONS) allows current->status.

    The read-check-write runs under BEGIN IMMEDIATE, so a concurrent
    terminal writer cannot slip between the check and the UPDATE: a job
    cancelled while PENDING can never be resurrected to RUNNING by its
    late-spawning controller, no matter the interleaving. Returns False
    when the transition was refused (row gone, already terminal, or an
    undeclared edge).

    ``exprs`` maps column -> raw SQL expression evaluated inside the
    same transaction (e.g. ``recovery_count + 1``) — the read half of a
    read-modify-write must live in here, not in a caller-side SELECT
    that races other writers.
    """
    assert not status.is_terminal(), status
    conn = _conn()
    with sqlite_utils.immediate(conn):
        row = conn.execute(
            'SELECT status, trace_id FROM jobs WHERE job_id = ?',
            (job_id,)).fetchone()
        if row is None:
            return False
        cur = ManagedJobStatus(row[0])
        if not state_machines.can_transition(
                state_machines.JOB_TRANSITIONS, cur.name, status.name):
            logger.warning(
                f'[job {job_id}] refusing undeclared transition '
                f'{cur.value} -> {status.value} (see '
                f'analysis/state_machines.py).')
            return False
        sets = ''.join(f', {k} = {sql}'
                       for k, sql in (exprs or {}).items())
        sets += ''.join(f', {k} = ?' for k in cols)
        conn.execute(f'UPDATE jobs SET status = ?{sets} '
                     f'WHERE job_id = ?',
                     (status.value, *cols.values(), job_id))
        # Journal INSIDE the write lock (the journal is a different DB
        # file — no deadlock) so journal order matches commit order:
        # outside it, a preempted winner could journal its edge after
        # a later writer's, inverting the chain readers see. Only a
        # real edge is journaled — a self-loop re-write is not a
        # transition.
        if cur is not status:
            _journal_transition(job_id, cur, status, trace_id=row[1])
    return True


def set_controller_pid(job_id: int, pid: int) -> None:
    _update(job_id, controller_pid=pid)


def bump_controller_restarts(job_id: int) -> int:
    return _bump(job_id, 'controller_restarts')


def _bump(job_id: int, col: str) -> int:
    """Atomic counter increment (UPDATE-then-read under BEGIN
    IMMEDIATE — two concurrent bumpers must not read the same base)."""
    conn = _conn()
    with sqlite_utils.immediate(conn):
        conn.execute(f'UPDATE jobs SET {col} = COALESCE({col}, 0) + 1 '
                     f'WHERE job_id = ?', (job_id,))
        row = conn.execute(f'SELECT {col} FROM jobs WHERE job_id = ?',
                           (job_id,)).fetchone()
        return int(row[0]) if row else 1


def set_starting(job_id: int, cluster_name: str) -> bool:
    return set_status_nonterminal(job_id, ManagedJobStatus.STARTING,
                                  cluster_name=cluster_name)


def set_started(job_id: int, cluster_job_id: Optional[int]) -> bool:
    # started_at is sticky across recoveries: COALESCE keeps the first
    # value, computed inside the guarded transaction (a caller-side
    # SELECT would race concurrent writers).
    return set_status_nonterminal(
        job_id, ManagedJobStatus.RUNNING,
        exprs={'started_at': f'COALESCE(started_at, {time.time()!r})'},
        cluster_job_id=cluster_job_id)


def set_recovering(job_id: int) -> bool:
    return set_status_nonterminal(job_id, ManagedJobStatus.RECOVERING)


def set_recovered(job_id: int, cluster_job_id: Optional[int]) -> bool:
    ok = set_status_nonterminal(
        job_id, ManagedJobStatus.RUNNING,
        exprs={'recovery_count': 'COALESCE(recovery_count, 0) + 1'},
        last_recovered_at=time.time(),
        cluster_job_id=cluster_job_id)
    if ok:
        _RECOVERIES_METRIC.inc()
    return ok


def bump_restart_on_error(job_id: int) -> int:
    return _bump(job_id, 'restarts_on_errors')


def set_cancelling(job_id: int) -> bool:
    return set_status_nonterminal(job_id, ManagedJobStatus.CANCELLING)


def set_terminal(job_id: int, status: ManagedJobStatus,
                 failure_reason: Optional[str] = None) -> bool:
    """First terminal status wins; a later writer cannot overwrite it.

    The read-check-write runs under BEGIN IMMEDIATE (sqlite's single
    write lock), so N concurrent terminal writers commit exactly one
    transition — and that winning writer (alone) journals the edge
    old -> terminal, so docs/STATE_MACHINES.md is observable at
    runtime with exactly one event per committed transition.
    """
    assert status.is_terminal(), status
    conn = _conn()
    with sqlite_utils.immediate(conn):
        row = conn.execute(
            'SELECT status, trace_id FROM jobs WHERE job_id = ?',
            (job_id,)).fetchone()
        if row is None:
            return False
        cur = ManagedJobStatus(row[0])
        if cur.is_terminal():
            return False
        conn.execute(
            'UPDATE jobs SET status = ?, ended_at = ?, '
            'failure_reason = ? WHERE job_id = ?',
            (status.value, time.time(), failure_reason, job_id))
        # Inside the lock: journal order == commit order (see
        # set_status_nonterminal).
        _journal_transition(job_id, cur, status, reason=failure_reason,
                            trace_id=row[1])
    return True


def request_cancel(job_id: int) -> None:
    _update(job_id, cancel_requested=1)


# ---------------------------------------------------------------------------
# Reads
# ---------------------------------------------------------------------------
def _row_to_dict(row: sqlite3.Row) -> Dict[str, Any]:
    d = dict(row)
    d['status'] = ManagedJobStatus(d['status'])
    d['task_config'] = (json.loads(d['task_config'])
                        if d.get('task_config') else {})
    return d


def get_job(job_id: int) -> Optional[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        row = conn.execute('SELECT * FROM jobs WHERE job_id = ?',
                           (job_id,)).fetchone()
        return _row_to_dict(row) if row else None


def get_jobs(name: Optional[str] = None) -> List[Dict[str, Any]]:
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        if name is None:
            rows = conn.execute(
                'SELECT * FROM jobs ORDER BY job_id DESC').fetchall()
        else:
            rows = conn.execute(
                'SELECT * FROM jobs WHERE name = ? ORDER BY job_id DESC',
                (name,)).fetchall()
        return [_row_to_dict(r) for r in rows]


def cancel_was_requested(job_id: int) -> bool:
    with _conn() as conn:
        row = conn.execute(
            'SELECT cancel_requested FROM jobs WHERE job_id = ?',
            (job_id,)).fetchone()
    return bool(row and row[0])


def nonterminal_jobs() -> List[Dict[str, Any]]:
    terminal = tuple(s.value for s in _TERMINAL)
    with _conn() as conn:
        conn.row_factory = sqlite3.Row
        ph = ','.join('?' * len(terminal))
        rows = conn.execute(
            f'SELECT * FROM jobs WHERE status NOT IN ({ph}) '
            f'ORDER BY job_id', terminal).fetchall()
        return [_row_to_dict(r) for r in rows]
