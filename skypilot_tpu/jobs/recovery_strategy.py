"""Launch/recovery/termination strategies for managed-job clusters.

Reference analog: sky/jobs/recovery_strategy.py (`StrategyExecutor:60`,
`FailoverStrategyExecutor:606`, `EagerFailoverStrategyExecutor:706`).

Strategy selection comes from the task's resources
(`job_recovery`/`spot_recovery: FAILOVER | EAGER_NEXT_REGION`). The TPU
wrinkle baked into `recover()`: a preempted spot TPU slice is NOT reusable —
GCP leaves the dead slice resource behind and it must be deleted before a
slice with the same name can be recreated (sky/clouds/gcp.py:1095-1101), so
every recovery is terminate-then-relaunch, never restart.
"""
from __future__ import annotations

import os
import time
import typing
from typing import Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.jobs import state
from skypilot_tpu.observe import journal as journal_lib
from skypilot_tpu.observe import metrics as metrics_lib
from skypilot_tpu.utils import backoff as backoff_lib
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)

DEFAULT_RECOVERY_STRATEGY = 'failover'


class JobCancelledDuringRecovery(Exception):
    """Raised out of recover() when the user cancels mid-failover, so the
    controller can stop burning provisioning attempts immediately."""

# Base gap between failed relaunch attempts while recovering — grows
# exponentially with per-job seeded jitter (utils/backoff.py), capped at
# RETRY_GAP_CAP_SECONDS. Tests shrink these via the env knobs below.
RETRY_GAP_SECONDS = 20
RETRY_GAP_CAP_SECONDS = 300
# Max full failover rounds while recovering before giving up; None = forever
# (the reference retries forever; we bound it but keep it high).
MAX_RECOVERY_ROUNDS = 720

# Recovery budget knobs (read per recover() call so tests/operators can
# retune without a controller restart):
#   SKYTPU_JOBS_RECOVERY_MAX_ROUNDS    max failover rounds (default 720)
#   SKYTPU_JOBS_RECOVERY_BUDGET_SECONDS  wall-clock budget for one
#       recovery, 0 = unlimited (default 0)
#   SKYTPU_JOBS_RECOVERY_BASE_SECONDS / _CAP_SECONDS  backoff shape
_MAX_ROUNDS_ENV = 'SKYTPU_JOBS_RECOVERY_MAX_ROUNDS'
_BUDGET_ENV = 'SKYTPU_JOBS_RECOVERY_BUDGET_SECONDS'
_BASE_ENV = 'SKYTPU_JOBS_RECOVERY_BASE_SECONDS'
_CAP_ENV = 'SKYTPU_JOBS_RECOVERY_CAP_SECONDS'

_RECOVERY_ATTEMPTS = metrics_lib.counter(
    'skytpu_jobs_recovery_attempts_total',
    'Managed-job recovery relaunch attempts, by outcome.',
    labels={'outcome': ('ok', 'no_capacity', 'fault')})
_RECOVERY_SECONDS = metrics_lib.histogram(
    'skytpu_jobs_recovery_seconds',
    'Wall-clock duration of one full recovery (cluster lost -> '
    'relaunched), failover strategies only.')


class StrategyExecutor:
    """Handles launching, recovery and termination of one job's cluster."""

    def __init__(self, cluster_name: str, task: 'task_lib.Task',
                 job_id: int) -> None:
        self.cluster_name = cluster_name
        self.task = task
        self.job_id = job_id
        self.handle: Optional[slice_backend.SliceResourceHandle] = None
        self.backend = slice_backend.TpuSliceBackend()

    # ------------------------------------------------------------------
    @classmethod
    def make(cls, cluster_name: str, task: 'task_lib.Task',
             job_id: int) -> 'StrategyExecutor':
        """Pick the strategy from the task's resources (job_recovery)."""
        from skypilot_tpu import resources as resources_lib
        name = None
        for res in task.resources_list():
            assert isinstance(res, resources_lib.Resources)
            if res.spot_recovery is not None:
                if name is not None and name != res.spot_recovery:
                    raise ValueError(
                        'All resource candidates must agree on job_recovery; '
                        f'got {name!r} and {res.spot_recovery!r}.')
                name = res.spot_recovery
        name = name or DEFAULT_RECOVERY_STRATEGY
        strategy_cls = registry.JOBS_RECOVERY_STRATEGY_REGISTRY.type_from_str(
            name)
        return strategy_cls(cluster_name, task, job_id)

    # ------------------------------------------------------------------
    def launch(self) -> Optional[int]:
        """First launch. Returns the on-cluster job id.

        Raises ResourcesUnavailableError if every failover target is
        exhausted (→ FAILED_NO_RESOURCE) and other exceptions for
        precheck-class failures (→ FAILED_PRECHECKS).
        """
        job_id_on_cluster = self._launch_once()
        return job_id_on_cluster

    def recover(self) -> Optional[int]:
        """Relaunch after preemption. Returns the new on-cluster job id.

        Subclasses encode *where* to retry first. Common contract:
        terminate the dead slice, then relaunch (possibly elsewhere).
        """
        raise NotImplementedError

    # ------------------------------------------------------------------
    def _launch_once(self,
                     resources_override: Optional[dict] = None,
                     blocked_resources: Optional[list] = None
                     ) -> Optional[int]:
        """One launch attempt end-to-end (provision → sync → setup → exec)."""
        from skypilot_tpu import execution
        from skypilot_tpu import task as task_lib_mod
        task = self.task
        if resources_override:
            # Clone the task with pinned/relaxed placement for this attempt.
            cfg = task.to_yaml_config()
            task = task_lib_mod.Task.from_yaml_config(cfg)
            new_res = [
                r.copy(**resources_override) for r in task.resources_list()
            ]
            task.set_resources(new_res if len(new_res) > 1 else new_res[0])
        if failpoints.ACTIVE:
            failpoints.fire('jobs.launch')
        job_id, handle = execution.launch(
            task, cluster_name=self.cluster_name, detach_run=True,
            blocked_resources=blocked_resources)
        assert handle is not None
        self.handle = handle
        return job_id

    def terminate_cluster(self, max_retries: int = 3) -> None:
        """Delete the job's slice. Preempted spot TPUs MUST be deleted
        before a relaunch can reuse the name (clouds/gcp.py:1095-1101);
        termination of an already-gone cluster is a no-op. Retries ride
        the shared jittered backoff; the FINAL failure is journaled with
        its failure class — a leaked slice blocks name reuse at the next
        relaunch and keeps billing, so the evidence must outlive this
        process."""
        from skypilot_tpu import global_state
        retry_backoff = backoff_lib.Backoff(base=1.0, cap=10.0,
                                            seed=self.job_id)
        last_exc: Optional[BaseException] = None
        for attempt in range(max_retries):
            try:
                if failpoints.ACTIVE:
                    failpoints.fire('jobs.terminate')
                record = global_state.get_cluster(self.cluster_name)
                if record is None:
                    return
                handle = slice_backend.SliceResourceHandle.from_dict(
                    record['handle'])
                self.backend.teardown(handle, terminate=True)
                return
            except Exception as e:  # pylint: disable=broad-except
                last_exc = e
                if attempt < max_retries - 1:
                    retry_backoff.sleep()
        failure_reason = f'{type(last_exc).__name__}: {last_exc}'
        logger.warning(f'Failed to terminate {self.cluster_name} after '
                       f'{max_retries} attempts: {failure_reason}')
        journal_lib.record_event(
            'jobs_terminate_failed', entity=str(self.job_id),
            reason=failure_reason,
            data={'cluster': self.cluster_name, 'attempts': max_retries,
                  'failure_class': type(last_exc).__name__})

    def _check_cancel(self) -> None:
        if self.job_id and state.cancel_was_requested(self.job_id):
            raise JobCancelledDuringRecovery(
                f'job {self.job_id} cancelled during recovery')

    def _recovery_attempt(self, round_idx: int, phase: str,
                          target: dict, **launch_kwargs) -> Optional[int]:
        """One journaled relaunch attempt. Injected faults
        (FailpointError out of jobs.launch/jobs.setup) are classed and
        re-raised as no-capacity so the loop's containment — backoff,
        budget, failover — applies to them identically."""
        t0 = time.monotonic()
        outcome = 'error'
        try:
            result = self._launch_once(**launch_kwargs)
            outcome = 'ok'
            return result
        except exceptions.ResourcesUnavailableError:
            outcome = 'no_capacity'
            raise
        except failpoints.FailpointError as e:
            outcome = 'fault'
            raise exceptions.ResourcesUnavailableError(
                f'injected fault: {e}') from e
        finally:
            if outcome in ('ok', 'no_capacity', 'fault'):
                # 'error' (an unexpected exception class) is journaled
                # below but kept out of the bounded metric label set.
                _RECOVERY_ATTEMPTS.inc(outcome=outcome)
            landed = self.handle if outcome == 'ok' else None
            journal_lib.record_event(
                'jobs_recovery_attempt', entity=str(self.job_id),
                data={'round': round_idx + 1, 'phase': phase,
                      'outcome': outcome,
                      'duration': round(time.monotonic() - t0, 3),
                      'target': target,
                      'zone': landed.zone if landed is not None else None,
                      'region': (landed.region if landed is not None
                                 else None)})

    def _relaunch_with_failover(
            self, try_same_placement_first: bool) -> Optional[int]:
        """Shared recovery loop: optional same-placement fast path, then
        avoid-the-preempted-region, then unconstrained, retrying under
        an exponential per-job-jittered backoff and a bounded budget
        (rounds + optional wall-clock) until something lands. Every
        attempt is journaled with its placement target and outcome;
        aborts promptly on user cancel."""
        t_recover = time.monotonic()
        result = self._failover_rounds(try_same_placement_first)
        _RECOVERY_SECONDS.observe(time.monotonic() - t_recover)
        return result

    def _failover_rounds(
            self, try_same_placement_first: bool) -> Optional[int]:
        launched_cloud = self.handle.cloud if self.handle else None
        launched_region = self.handle.region if self.handle else None
        launched_zone = self.handle.zone if self.handle else None
        max_rounds = knobs.get_int(_MAX_ROUNDS_ENV)
        budget_seconds = knobs.get_float(_BUDGET_ENV)
        retry_backoff = backoff_lib.Backoff(
            base=knobs.get_float(_BASE_ENV),
            cap=knobs.get_float(_CAP_ENV),
            seed=self.job_id)
        t_start = time.monotonic()

        def _exhausted(why: str, rounds: int
                       ) -> exceptions.ManagedJobReachedMaxRetriesError:
            msg = (f'Recovery of job {self.job_id} gave up: {why} '
                   f'(rounds={rounds}, elapsed='
                   f'{time.monotonic() - t_start:.1f}s).')
            journal_lib.record_event(
                'jobs_recovery_exhausted', entity=str(self.job_id),
                reason=why,
                data={'rounds': rounds,
                      'elapsed': round(time.monotonic() - t_start, 3),
                      'budget_seconds': budget_seconds,
                      'max_rounds': max_rounds})
            return exceptions.ManagedJobReachedMaxRetriesError(msg)

        for round_idx in range(max_rounds):
            self._check_cancel()
            if budget_seconds and time.monotonic() - t_start > budget_seconds:
                raise _exhausted(
                    f'recovery budget of {budget_seconds:.0f}s exhausted',
                    round_idx)
            # The dead slice blocks name reuse: always delete first.
            self.terminate_cluster()
            if try_same_placement_first and launched_region is not None:
                # Same region/zone first: data/ckpt egress stays local and
                # capacity often returns to the same zone first.
                try:
                    # Pin cloud too: region/zone names only validate against
                    # the cloud that owns them.
                    return self._recovery_attempt(
                        round_idx, 'same_placement',
                        {'cloud': launched_cloud, 'region': launched_region,
                         'zone': launched_zone},
                        resources_override={
                            'cloud': launched_cloud,
                            'region': launched_region,
                            'zone': launched_zone,
                        })
                except exceptions.ResourcesUnavailableError:
                    logger.info(
                        f'[job {self.job_id}] same-placement relaunch in '
                        f'{launched_region} failed; trying full failover.')
                    self.terminate_cluster()
            elif launched_region is not None:
                # Eager next-region: exclude the placement that just
                # preempted us — it is the least likely to have spot
                # capacity right now (recovery_strategy.py:706 analog).
                from skypilot_tpu import resources as resources_lib
                blocked = [resources_lib.Resources(cloud=launched_cloud,
                                                   region=launched_region)]
                try:
                    return self._recovery_attempt(
                        round_idx, 'blocked_region',
                        {'blocked_cloud': launched_cloud,
                         'blocked_region': launched_region},
                        resources_override={'region': None, 'zone': None},
                        blocked_resources=blocked)
                except exceptions.ResourcesUnavailableError:
                    logger.info(
                        f'[job {self.job_id}] no capacity outside '
                        f'{launched_region}; allowing it again.')
                    self.terminate_cluster()
            self._check_cancel()
            try:
                # Unconstrained: let the optimizer pick anywhere feasible.
                return self._recovery_attempt(
                    round_idx, 'unconstrained', {},
                    resources_override={'region': None, 'zone': None})
            except exceptions.ResourcesUnavailableError:
                gap = retry_backoff.next()
                logger.info(
                    f'[job {self.job_id}] recovery round {round_idx + 1} '
                    f'found no capacity anywhere; retrying in {gap:.1f}s.')
                time.sleep(gap)
        raise _exhausted(f'no capacity after {max_rounds} failover rounds',
                         max_rounds)


class PoolStrategyExecutor(StrategyExecutor):
    """Run the job on a worker of a pre-provisioned pool (jobs/pool.py).

    Instead of launching a dedicated cluster, `launch` claims a READY idle
    worker (serve_state.acquire_worker) and execs the task onto it —
    seconds instead of minutes, no provisioning risk. Recovery releases
    the (dead) worker — the pool's replica manager replaces it — and
    claims a different one. Termination releases the worker; the cluster
    itself belongs to the pool. Reference: sky/jobs/recovery_strategy.py
    pool path (job_id_on_pool_cluster) + scheduler.py:396.

    Not in the strategy registry: selection is by the job's `pool` field,
    not by `job_recovery:` (any recovery name combined with --pool means
    "reacquire a worker").
    """

    # How long launch() waits for a free worker before giving up entirely.
    ACQUIRE_TIMEOUT_SECONDS = knobs.get_float('SKYTPU_POOL_ACQUIRE_TIMEOUT')
    ACQUIRE_POLL_SECONDS = knobs.get_float('SKYTPU_POOL_ACQUIRE_POLL')

    def __init__(self, cluster_name: str, task: 'task_lib.Task',
                 job_id: int, pool: str) -> None:
        super().__init__(cluster_name, task, job_id)
        self.pool = pool

    def _pool_alive(self) -> bool:
        from skypilot_tpu.serve import serve_state
        record = serve_state.get_service(self.pool)
        return record is not None and not record['status'].is_terminal()

    def launch(self) -> Optional[int]:
        """Claim a worker, exec the task on it. Queues (rather than fails)
        while every worker is busy — that is the pool contract."""
        from skypilot_tpu import execution
        from skypilot_tpu.serve import serve_state
        deadline = time.time() + self.ACQUIRE_TIMEOUT_SECONDS
        while True:
            self._check_cancel()
            if not self._pool_alive():
                raise exceptions.ResourcesUnavailableError(
                    f'Pool {self.pool!r} is gone or failed; cannot place '
                    f'job {self.job_id}.')
            worker = serve_state.acquire_worker(self.pool, self.job_id)
            if worker is not None:
                cluster = worker['cluster_name']
                try:
                    job_id_on_cluster, handle = execution.exec(
                        self.task, cluster_name=cluster, detach_run=True)
                except Exception:
                    # Worker unusable (e.g. preempted between READY and
                    # exec): return it NOT_READY so reconcile re-vets it,
                    # and try another.
                    serve_state.release_worker(self.pool, self.job_id)
                    serve_state.set_replica_status(
                        self.pool, worker['replica_id'],
                        serve_state.ReplicaStatus.NOT_READY)
                    logger.warning(
                        f'[job {self.job_id}] exec on worker '
                        f'{worker["replica_id"]} ({cluster}) failed; '
                        f'trying another.', exc_info=True)
                    continue
                self.handle = handle
                self.cluster_name = cluster
                logger.info(f'[job {self.job_id}] running on pool '
                            f'{self.pool!r} worker {worker["replica_id"]} '
                            f'({cluster}).')
                return job_id_on_cluster
            if time.time() > deadline:
                raise exceptions.ResourcesUnavailableError(
                    f'No worker of pool {self.pool!r} became free within '
                    f'{self.ACQUIRE_TIMEOUT_SECONDS:.0f}s.')
            time.sleep(self.ACQUIRE_POLL_SECONDS)

    def recover(self) -> Optional[int]:
        """The worker died (or the job's cluster check failed): release it
        and claim a different one. The pool's own replica manager deals
        with replacing the dead worker."""
        from skypilot_tpu.serve import serve_state
        serve_state.release_worker(self.pool, self.job_id)
        self.handle = None
        return self.launch()

    def terminate_cluster(self, max_retries: int = 3) -> None:
        """Jobs never tear down pool workers — just hand the claim back."""
        from skypilot_tpu.serve import serve_state
        serve_state.release_worker(self.pool, self.job_id)


@registry.JOBS_RECOVERY_STRATEGY_REGISTRY.register(name='failover')
class FailoverStrategyExecutor(StrategyExecutor):
    """Retry the original placement first, then fail over anywhere
    (reference default: recovery_strategy.py:606)."""

    def recover(self) -> Optional[int]:
        return self._relaunch_with_failover(try_same_placement_first=True)


@registry.JOBS_RECOVERY_STRATEGY_REGISTRY.register(name='eager_next_region')
class EagerFailoverStrategyExecutor(StrategyExecutor):
    """Skip the preempted placement: a zone that just preempted us is the
    least likely to have spot capacity (recovery_strategy.py:706)."""

    def recover(self) -> Optional[int]:
        return self._relaunch_with_failover(try_same_placement_first=False)
