"""Managed jobs plane: submit → controller → launch/monitor/recover.

Reference analog: sky/jobs/ (controller.py, recovery_strategy.py,
scheduler.py, state.py). TPU-first redesign: controllers are detached local
processes next to the API server (no dedicated controller cluster to
provision), and preemption recovery knows the TPU wrinkle that a preempted
spot slice must be deleted before it can be recreated
(sky/clouds/gcp.py:1095-1101).
"""
from skypilot_tpu.jobs.core import cancel
from skypilot_tpu.jobs.core import launch
from skypilot_tpu.jobs.core import queue
from skypilot_tpu.jobs.core import tail_logs
from skypilot_tpu.jobs.pool import apply as pool_apply
from skypilot_tpu.jobs.pool import down as pool_down
from skypilot_tpu.jobs.pool import status as pool_status
from skypilot_tpu.jobs.state import ManagedJobStatus

__all__ = ['launch', 'queue', 'cancel', 'tail_logs', 'ManagedJobStatus',
           'pool_apply', 'pool_down', 'pool_status']
