"""User-facing managed-jobs API: launch/queue/cancel/logs.

Reference analog: sky/jobs/ client+server core (jobs launch wraps the task
for the controller; queue/cancel/logs talk to controller state).
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Union

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import scheduler
from skypilot_tpu.utils import knobs
from skypilot_tpu.jobs import state

logger = sky_logging.init_logger(__name__)


from skypilot_tpu.usage import usage_lib


@usage_lib.tracked('jobs.launch')
def launch(entrypoint: Union[task_lib.Task, dag_lib.Dag],
           name: Optional[str] = None, pool: Optional[str] = None) -> int:
    """Submit a managed job; returns its managed-job id immediately.

    The controller process owns the whole lifecycle from here: provisioning
    (with failover), monitoring, preemption recovery, teardown. With
    `pool`, the job runs on a claimed worker of that pool (jobs/pool.py)
    instead of a dedicated cluster.
    """
    from skypilot_tpu import admin_policy
    if pool is not None:
        from skypilot_tpu.serve import serve_state
        record = serve_state.get_service(pool)
        if record is None or not (record['spec'] or {}).get('pool'):
            raise ValueError(
                f'Pool {pool!r} does not exist; create it with '
                f'`skytpu jobs pool apply`.')
        if record['status'].is_terminal():
            raise ValueError(f'Pool {pool!r} is {record["status"].value}.')
    if isinstance(entrypoint, dag_lib.Dag):
        if not entrypoint.is_chain():
            raise NotImplementedError(
                'Managed pipelines must be linear chains; general DAGs '
                'are not supported.')
        tasks = entrypoint.topological_order() or entrypoint.tasks
        pipeline_name = entrypoint.name
    else:
        tasks = [entrypoint]
        pipeline_name = None
    tasks = [admin_policy.apply(t, 'jobs.launch') for t in tasks]
    for t in tasks:
        t.validate()
        # Fail fast on an unknown recovery strategy (before the controller
        # is off in its own process, where errors are only visible in logs).
        recovery_strategy.StrategyExecutor.make('prevalidate', t, job_id=0)
    job_name = (name or pipeline_name or tasks[0].name or 'unnamed')
    if len(tasks) == 1:
        task_config = tasks[0].to_yaml_config()
    else:
        task_config = {'pipeline': [t.to_yaml_config() for t in tasks]}
    job_id = state.submit(
        job_name, task_config,
        strategy=_strategy_name(tasks[0]),
        max_restarts_on_errors=_max_restarts(tasks[0]),
        num_tasks=len(tasks), pool=pool)
    scheduler.maybe_schedule()
    logger.info(f'Managed job {job_id} ({job_name!r}) submitted.')
    return job_id


def _strategy_name(task: task_lib.Task) -> str:
    for res in task.resources_list():
        if res.spot_recovery is not None:
            return res.spot_recovery.lower()
    return recovery_strategy.DEFAULT_RECOVERY_STRATEGY


def _max_restarts(task: task_lib.Task) -> int:
    # YAML: resources.job_recovery could grow {max_restarts_on_errors: N};
    # until then a task env opt-in keeps the knob reachable. Parsed
    # against the registry so garbage fails at submit time, loudly.
    return knobs.parse('SKYTPU_MAX_RESTARTS_ON_ERRORS',
                       task.envs_and_secrets.get(
                           'SKYTPU_MAX_RESTARTS_ON_ERRORS'))


def queue(name: Optional[str] = None,
          skip_finished: bool = False) -> List[Dict[str, Any]]:
    # Piggyback the crash watchdog on inspection: a job whose controller
    # died hard gets its controller resumed the next time anyone looks
    # (scheduler.maybe_schedule is idempotent and cheap). Log GC rides
    # the same path, rate-limited (jobs/log_gc.py).
    scheduler.maybe_schedule()
    from skypilot_tpu.jobs import log_gc
    log_gc.maybe_collect()
    jobs = state.get_jobs(name)
    if skip_finished:
        jobs = [j for j in jobs if not j['status'].is_terminal()]
    return jobs


def cancel(job_ids: Optional[List[int]] = None,
           name: Optional[str] = None,
           all_jobs: bool = False) -> List[int]:
    """Request cancellation; controllers notice within one poll interval."""
    if not (job_ids or name or all_jobs):
        raise ValueError('Specify job ids, a name, or all_jobs=True.')
    targets: List[Dict[str, Any]] = []
    if all_jobs:
        targets = state.nonterminal_jobs()
    else:
        if job_ids:
            for jid in job_ids:
                job = state.get_job(jid)
                if job is None:
                    raise exceptions.JobNotFoundError(
                        f'Managed job {jid} not found.')
                targets.append(job)
        if name:
            targets.extend(j for j in state.get_jobs(name)
                           if not j['status'].is_terminal())
    cancelled = []
    cancelled_set = set()
    for job in targets:
        if job['status'].is_terminal() or job['job_id'] in cancelled_set:
            continue
        # Set the flag first: a controller that won the PENDING→STARTING
        # race still sees it on its next poll.
        state.request_cancel(job['job_id'])
        if job['status'] is state.ManagedJobStatus.PENDING:
            # No controller yet (usually): terminal-ize directly. If a
            # controller slipped in, the guarded write is a no-op and the
            # flag above does the job.
            state.set_terminal(job['job_id'],
                               state.ManagedJobStatus.CANCELLED)
        cancelled_set.add(job['job_id'])
        cancelled.append(job['job_id'])
    return cancelled


def tail_logs(job_id: Optional[int] = None, follow: bool = True,
              controller: bool = False) -> int:
    """Stream a managed job's logs.

    While the cluster is up this streams live from the cluster; otherwise it
    falls back to the controller-mirrored copy (which survives preemption
    and teardown). `controller=True` shows the controller's own log.
    """
    if job_id is None:
        jobs = state.get_jobs()
        if not jobs:
            logger.info('No managed jobs.')
            return 0
        job_id = jobs[0]['job_id']
    job = state.get_job(job_id)
    if job is None:
        raise exceptions.JobNotFoundError(f'Managed job {job_id} not found.')

    if controller:
        return _tail_file(state.controller_log_path(job_id), follow=follow,
                          job_id=job_id)
    from skypilot_tpu import core as core_lib
    while True:
        job = state.get_job(job_id)
        assert job is not None
        if (job['status'] is state.ManagedJobStatus.RUNNING and
                job['cluster_job_id'] is not None):
            recoveries_before = job['recovery_count']
            try:
                # Live stream from the cluster; blocks until the on-cluster
                # job ends (or the slice is preempted mid-stream).
                rc = core_lib.tail_logs(job['cluster_name'],
                                        job['cluster_job_id'], follow=follow)
                job = state.get_job(job_id)
                if not follow or job is None or job['status'].is_terminal():
                    return rc
                # The on-cluster job ended but the managed job hasn't been
                # finalised yet (controller polls every POLL_SECONDS). Wait
                # for either the terminal flip or a recovery — re-streaming
                # immediately would replay the whole log in a tight loop.
                while (job is not None and not job['status'].is_terminal()
                       and job['recovery_count'] == recoveries_before):
                    time.sleep(0.5)
                    job = state.get_job(job_id)
                if job is None or job['status'].is_terminal():
                    return rc
                continue  # recovered onto a fresh cluster: stream it
            except exceptions.SkyTpuError:
                pass  # cluster just went away — recovery or teardown
        if job['status'].is_terminal() or not follow:
            # Mirrored copy survives preemption and teardown.
            return _tail_file(state.job_log_path(job_id), follow=False,
                              job_id=job_id)
        time.sleep(0.5)  # PENDING/STARTING/RECOVERING: wait for a cluster


def _tail_file(path: str, follow: bool, job_id: int) -> int:
    # In follow mode the file may not exist yet (controller log right after
    # submit): wait for it instead of returning before the job even starts.
    while follow and not os.path.exists(path):
        job = state.get_job(job_id)
        if job is None or job['status'].is_terminal():
            break
        time.sleep(0.5)
    if not os.path.exists(path):
        logger.info(f'No logs yet for managed job {job_id}.')
        return 0
    with open(path, 'r', encoding='utf-8', errors='replace') as f:
        while True:
            chunk = f.read()
            if chunk:
                print(chunk, end='', flush=True)
            if not follow:
                return 0
            job = state.get_job(job_id)
            if job is None or job['status'].is_terminal():
                print(f.read(), end='', flush=True)
                return 0
            time.sleep(0.5)
