"""Worker pools for managed jobs: pre-provisioned clusters jobs exec onto.

Reference analog: sky jobs pool (sky/jobs/server/core.py:1155
`pool_apply/pool_down/pool_status`, sky/serve/service_spec.py:40-64 pool
mode). A pool reuses the serve plane wholesale — it IS a service whose
spec has `pool: true`: the same controller reconciles workers (launch,
liveness, preemption replacement, spot placement), with no load balancer
and no HTTP probes. What pools add on top:

  - workers idle after setup (`run:` is rejected at apply);
  - `jobs launch --pool NAME` claims a READY worker
    (serve_state.acquire_worker) and execs the job onto it — startup in
    seconds, cluster reuse across jobs, queueing when all workers are
    busy (jobs/recovery_strategy.py `PoolStrategyExecutor`).

Pool YAML (task file):

    pool:
      workers: 2
    resources:
      accelerators: tpu-v5e-8
    setup: pip install -r requirements.txt
"""
from __future__ import annotations

import json
import typing
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.usage import usage_lib

if typing.TYPE_CHECKING:
    from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)


def _serve():
    """Lazy cross-plane bridge into the serve plane (skylint layer
    contract: jobs and serve are peers, so the dependency a pool has on
    the serve controller stays function-level, same as
    recovery_strategy's)."""
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu.serve import serve_state
    return serve_core, serve_state


@usage_lib.tracked('jobs.pool_apply')
def apply(task: 'task_lib.Task', pool_name: Optional[str] = None,
          workers: Optional[int] = None) -> Dict[str, Any]:
    """Create a pool (or resize an existing one) from a `pool:` task.

    `workers` overrides the YAML's `pool.workers`. Resizing an existing
    pool updates the worker target in place — the running controller's
    reconcile loop scales toward it without touching busy workers.
    """
    if task.service_spec is None:
        task.service_spec = {'pool': True}
    if not task.service_spec.get('pool'):
        raise ValueError("Task has a 'service:' section; use `serve up` "
                         'for services and a `pool:` section for pools.')
    if workers is not None:
        task.service_spec = {**task.service_spec, 'workers': int(workers)}
    serve_core, serve_state = _serve()
    name = pool_name or task.name or 'pool'
    existing = serve_state.get_service(name)
    if existing is not None and not existing['status'].is_terminal():
        if not (existing['spec'] or {}).get('pool'):
            raise ValueError(f'{name!r} is a service, not a pool.')
        # In-place resize: only the worker count may change (the live
        # controller re-reads it every reconcile pass); anything else
        # requires a down/apply cycle.
        return _resize(name, existing, task)
    return serve_core.up(task, service_name=name)


def _resize(name: str, record: Dict[str, Any],
            task: 'task_lib.Task') -> Dict[str, Any]:
    from skypilot_tpu.serve import service_spec as spec_lib
    _, serve_state = _serve()
    new_spec = spec_lib.ServiceSpec.from_yaml_config(task.service_spec)
    old_cfg = dict(record['spec'])
    new_cfg = new_spec.to_yaml_config()
    if {k: v for k, v in old_cfg.items() if k != 'workers'} != \
            {k: v for k, v in new_cfg.items() if k != 'workers'}:
        raise ValueError(
            f'Pool {name!r} exists with a different spec; only the worker '
            f'count can change in place. `jobs pool down {name}` first.')
    if record['task_config'].get('setup') != task.to_yaml_config().get(
            'setup'):
        raise ValueError(
            f"Pool {name!r} exists with a different 'setup'; tear it down "
            f'first (`jobs pool down {name}`).')
    serve_state.update_service(name, spec=json.dumps(new_cfg))
    logger.info(f'Pool {name!r} resized to {new_cfg["workers"]} worker(s).')
    return {'name': name, 'endpoint': None}


def status(pool_names: Optional[List[str]] = None) -> List[Dict[str, Any]]:
    """Pool records only (services are `serve status`)."""
    serve_core, _ = _serve()
    return serve_core.status(pool_names, pool=True)


@usage_lib.tracked('jobs.pool_down')
def down(pool_name: str, purge: bool = False) -> None:
    """Tear a pool down. Jobs still running on its workers lose their
    clusters and will fail recovery (pool gone → FAILED_NO_RESOURCE)."""
    serve_core, serve_state = _serve()
    record = serve_state.get_service(pool_name)
    if record is not None and not (record['spec'] or {}).get('pool'):
        raise ValueError(f'{pool_name!r} is a service; use `serve down`.')
    serve_core.down(pool_name, purge=purge)
