"""Controller-process scheduler: parallelism cap + spawn.

Reference analog: sky/jobs/scheduler.py (`maybe_start_controllers:267`,
`submit_job:323`) — there, controller *coroutines* inside a controller
cluster; here, detached local processes (see controller.py docstring for
why). The cap bounds concurrent provisioning fan-out, not job count: PENDING
jobs wait in the DB and every controller exit re-runs the scheduler.
"""
from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils import locks

logger = sky_logging.init_logger(__name__)

# A job whose controller keeps dying (poisoned record, OOM-looping box)
# stops being resumed after this many restarts.
MAX_CONTROLLER_RESTARTS = knobs.get_int('SKYTPU_JOBS_MAX_CONTROLLER_RESTARTS')


def _max_parallel() -> int:
    from skypilot_tpu import config as config_lib
    return knobs.get_int(
        'SKYTPU_JOBS_MAX_PARALLEL',
        default=int(config_lib.get_nested(('jobs', 'max_parallel'), 8)))


from skypilot_tpu.utils.proc import pid_alive as _pid_alive


def _spawn_controller(job_id: int) -> int:
    log_path = state.controller_log_path(job_id)
    env = dict(os.environ)
    # Controllers import the package the same way this process does.
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    pp = env.get('PYTHONPATH', '')
    if repo_root not in pp.split(os.pathsep):
        env['PYTHONPATH'] = f'{repo_root}{os.pathsep}{pp}' if pp else repo_root
    # The controller carries the JOB's trace, not whatever trace this
    # scheduler invocation happens to run under (a controller-exit
    # rescheduling pass services many jobs).
    record = state.get_job(job_id)
    if record and record.get('trace_id'):
        env['SKYTPU_TRACE_ID'] = record['trace_id']
    else:
        env.pop('SKYTPU_TRACE_ID', None)
    with open(log_path, 'ab') as log_file:
        proc = subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.jobs.controller',
             '--job-id', str(job_id)],
            stdout=log_file, stderr=subprocess.STDOUT, env=env,
            start_new_session=True)
    return proc.pid


def _teardown_orphan(cluster_name: Optional[str]) -> None:
    """Best-effort teardown of a cluster whose controller died."""
    if not cluster_name:
        return
    try:
        from skypilot_tpu import global_state
        from skypilot_tpu.backends import slice_backend
        record = global_state.get_cluster(cluster_name)
        if record is None:
            return
        handle = slice_backend.SliceResourceHandle.from_dict(
            record['handle'])
        slice_backend.TpuSliceBackend().teardown(handle, terminate=True)
        logger.info(f'Tore down orphaned cluster {cluster_name!r}.')
    except Exception as e:  # pylint: disable=broad-except
        logger.warning(f'Failed to tear down orphan {cluster_name!r}: {e}')


def maybe_schedule() -> None:
    """Start controllers for PENDING jobs up to the parallelism cap.

    Called after every submit and from every controller's exit path, so a
    full queue drains itself without a daemon. Idempotent and cheap.
    """
    with locks.cluster_status_lock('jobs-scheduler', timeout=60):
        alive = 0
        pending = []
        for job in state.nonterminal_jobs():
            if job['status'] is state.ManagedJobStatus.PENDING:
                if _pid_alive(job['controller_pid']):
                    alive += 1  # spawned, controller hasn't set STARTING yet
                else:
                    pending.append(job)
            elif _pid_alive(job['controller_pid']):
                alive += 1
            # Non-terminal with a dead controller and not PENDING: the
            # controller crashed hard (kill -9 / host reboot). RESUME it —
            # a fresh controller re-attaches to the still-running cluster
            # job (controller.py resume path) so the user's job survives
            # control-plane crashes (reference analog: HA recovery,
            # serve_utils.ha_recovery_for_consolidation_mode). Repeated
            # crashes (a poisoned record crashing every controller) are
            # bounded; past the cap the job fails and the cluster is
            # reclaimed so an orphaned slice can't bill forever.
            elif job['status'] is not state.ManagedJobStatus.PENDING:
                restarts = state.bump_controller_restarts(job['job_id'])
                if restarts > MAX_CONTROLLER_RESTARTS:
                    state.set_terminal(
                        job['job_id'],
                        state.ManagedJobStatus.FAILED_CONTROLLER,
                        failure_reason=f'controller died {restarts} times')
                    _teardown_orphan(job.get('cluster_name'))
                    continue
                pid = _spawn_controller(job['job_id'])
                state.set_controller_pid(job['job_id'], pid)
                alive += 1
                logger.warning(
                    f'Controller of job {job["job_id"]} died; resumed with '
                    f'pid={pid} (restart {restarts}).')
        cap = _max_parallel()
        for job in pending:
            if alive >= cap:
                break
            pid = _spawn_controller(job['job_id'])
            state.set_controller_pid(job['job_id'], pid)
            alive += 1
            logger.info(f'Started controller pid={pid} for managed job '
                        f'{job["job_id"]}.')
