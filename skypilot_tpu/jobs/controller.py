"""Per-job controller: launch → monitor → recover loop.

Reference analog: sky/jobs/controller.py (the asyncio controller driving
launch/monitor/recover on a controller cluster). Redesigned as one plain
detached process per managed job running next to the API server: TPU slices
are atomic gang resources, so there is no per-node bookkeeping that would
justify an asyncio fan-out, and a process boundary means a crashed
controller can never corrupt its siblings (the scheduler enforces the
parallelism cap, scheduler.py).

The monitor loop's liveness check is two-level, in this order:
1. cluster liveness via provision.query_instances — a preempted/deleted
   slice (the spot case) means RECOVERING regardless of last job status;
2. on-cluster job status via the skylet queue — SUCCEEDED/FAILED only count
   when the cluster itself is still alive.
"""
from __future__ import annotations

import argparse
import os
import time
import traceback
from typing import Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_state
from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.jobs import recovery_strategy
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import failpoints
from skypilot_tpu.utils import knobs
from skypilot_tpu.utils.status_lib import JobStatus

logger = sky_logging.init_logger(__name__)

# Seconds between monitor polls (reference: JOB_STATUS_CHECK_GAP ~ 15-30s;
# kept low and env-tunable so hermetic tests run in seconds).
POLL_SECONDS = knobs.get_float('SKYTPU_JOBS_POLL_SECONDS')


def _generate_cluster_name(job_id: int, name: str) -> str:
    safe = ''.join(c if c.isalnum() or c == '-' else '-' for c in name.lower())
    return f'jobs-{safe[:20].strip("-") or "job"}-{job_id}'


class JobsController:
    """Drives one managed job to a terminal state."""

    def __init__(self, job_id: int):
        self.job_id = job_id
        record = state.get_job(job_id)
        if record is None:
            raise exceptions.ManagedJobStatusError(
                f'Managed job {job_id} not found.')
        self.record = record
        # Whole-process trace adoption (this process exists for exactly
        # one job): journal/timeline writes and every child —
        # provisioning runners, the slice driver — carry the trace
        # minted when the launch request entered the API server.
        from skypilot_tpu.observe import trace
        trace.adopt(record.get('trace_id'))
        cfg = record['task_config']
        if 'pipeline' in cfg:
            # Chained multi-task job (reference: pipeline managed jobs):
            # stages run in order, each on its own (possibly differently
            # shaped) cluster, all under ONE ManagedJobStatus.
            self.tasks = [task_lib.Task.from_yaml_config(c)
                          for c in cfg['pipeline']]
        else:
            self.tasks = [task_lib.Task.from_yaml_config(cfg)]
        base = _generate_cluster_name(job_id, record['name'] or 'job')
        self._base_cluster_name = record['cluster_name'] or base
        # Cross-stage exports: <STAGE_NAME>_HEAD_IP per launched stage,
        # injected into every LATER stage's envs (run()). Replaces the
        # hand-exported `${DATA_PLANE_HEAD_IP:?...}` dance in chained
        # DAGs — the controller already knows every stage's head node.
        self._stage_exports: Dict[str, str] = {}
        # task/cluster_name/strategy are per-stage state, owned by run().

    def _stage_cluster_name(self, index: int) -> str:
        if len(self.tasks) == 1:
            return self._base_cluster_name
        return f'{self._base_cluster_name}-t{index}'

    # ------------------------------------------------------------------
    def _sync_cluster_name(self) -> None:
        """Pool jobs land on a worker cluster the strategy picked; keep the
        controller's (and the queue display's) cluster name in step."""
        if self.strategy.cluster_name != self.cluster_name:
            self.cluster_name = self.strategy.cluster_name
            state.set_current_task(self.job_id,
                                   state.get_job(self.job_id)['current_task'],
                                   self.cluster_name)

    def _record_stage_export(self) -> None:
        """Publish this stage's head-node IP for later pipeline stages.

        The data-service example's train stage needs the data plane's
        dispatcher address; the gang env already carries the head IP
        WITHIN a gang (skylet/constants.py gang_env), and this is the
        cross-STAGE analog: after a stage launches (or recovers onto a
        new slice), `<STAGE_NAME>_HEAD_IP` becomes visible to every
        later stage's envs. Internal IP preferred — stages of one
        pipeline share a network; the external IP is the fallback."""
        if len(self.tasks) <= 1 or not self.task.name:
            return
        handle = self.strategy.handle
        if handle is None:
            return
        try:
            head = handle.get_cluster_info().get_head_instance()
        except Exception as e:  # pylint: disable=broad-except
            logger.debug(f'[job {self.job_id}] head-IP export skipped: {e}')
            return
        if head is None:
            return
        ip = head.internal_ip or head.external_ip
        if not ip:
            return
        key = ''.join(c if c.isalnum() else '_'
                      for c in self.task.name.upper()) + '_HEAD_IP'
        self._stage_exports[key] = ip
        logger.info(f'[job {self.job_id}] exporting {key}={ip} to later '
                    f'pipeline stages.')

    def _cluster_alive(self) -> bool:
        """Cloud-truth liveness of the job's slice (preemption detector)."""
        if failpoints.ACTIVE:
            # Deterministic preemption injection: a firing is classed
            # exactly like a dead slice, so a chaos schedule drives the
            # real RECOVERING -> recover() -> RECOVERED containment arc
            # without touching a cloud.
            try:
                failpoints.fire('jobs.preempt')
            except failpoints.FailpointError:
                return False
        record = global_state.get_cluster(self.cluster_name)
        if record is None:
            return False
        handle = slice_backend.SliceResourceHandle.from_dict(record['handle'])
        try:
            statuses = provision.query_instances(handle.cloud, handle.region,
                                                 self.cluster_name,
                                                 handle.provider_config)
        except exceptions.ClusterDoesNotExist:
            return False
        except Exception as e:  # pylint: disable=broad-except
            # Transient cloud API failure: do NOT treat as preemption — a
            # false positive would tear down a healthy (billing) slice.
            logger.warning(f'liveness probe failed (assuming alive): {e}')
            return True
        if not statuses:
            return False
        return all(s in ('running', 'READY') for s in statuses.values())

    def _job_status(self, cluster_job_id: Optional[int]
                    ) -> Optional[JobStatus]:
        if cluster_job_id is None or self.strategy.handle is None:
            return None
        try:
            return self.strategy.backend.job_status(self.strategy.handle,
                                                    cluster_job_id)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'job status probe failed: {e}')
            return None

    def _mirror_logs(self, cluster_job_id: Optional[int]) -> None:
        """Copy the aggregated run log off the cluster so `jobs logs` works
        even after the slice is preempted/torn down."""
        if cluster_job_id is None or self.strategy.handle is None:
            return
        try:
            info = self.strategy.handle.get_cluster_info()
            from skypilot_tpu.provision import provisioner as provisioner_lib
            runner = provisioner_lib.get_command_runners(info)[0]
            remote = (f'.skytpu_runtime/logs/{cluster_job_id}/run.log'
                      if info.provider_name == 'local' else
                      f'~/.skytpu_runtime/logs/{cluster_job_id}/run.log')
            runner.rsync(remote, state.job_log_path(self.job_id), up=False)
        except Exception as e:  # pylint: disable=broad-except
            # Best-effort (the log may not exist yet), but say so: a
            # permanently failing mirror means `jobs logs` serves stale
            # output after preemption and nobody knows why.
            logger.debug(f'[job {self.job_id}] log mirror skipped: {e}')

    # ------------------------------------------------------------------
    def _do_cancel(self, cluster_job_id) -> None:
        state.set_cancelling(self.job_id)
        logger.info(f'[job {self.job_id}] cancelling')
        try:
            if self.strategy.handle is not None:
                self.strategy.backend.cancel_jobs(
                    self.strategy.handle,
                    [cluster_job_id] if cluster_job_id is not None else None)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'[job {self.job_id}] on-cluster cancel '
                           f'failed (continuing teardown): {e}')
        self.strategy.terminate_cluster()
        state.set_terminal(self.job_id, state.ManagedJobStatus.CANCELLED)

    def _handle_user_code_failure(self, job_status: JobStatus,
                                  cluster_job_id):
        """(restarted, new_cluster_job_id) under max_restarts_on_errors."""
        max_restarts = self.record['max_restarts_on_errors'] or 0
        if (job_status is JobStatus.FAILED and
                state.bump_restart_on_error(self.job_id) <= max_restarts):
            logger.info(f'[job {self.job_id}] user code failed; restarting '
                        f'(max_restarts_on_errors={max_restarts}).')
            state.set_recovering(self.job_id)
            from skypilot_tpu.observe import spans
            with spans.span('jobs.recover',
                            attrs={'job_id': self.job_id,
                                   'why': 'user_code_failure'}):
                new_id = self.strategy.recover()
            state.set_recovered(self.job_id, new_id)
            return True, new_id
        return False, cluster_job_id

    def run(self) -> None:
        job_id = self.job_id
        # Resume path: a controller respawned by the scheduler after a
        # hard crash re-attaches to the in-flight stage instead of
        # relaunching from scratch (the cluster job kept running the
        # whole time — only the monitor died).
        resume_from = None
        if self.record['status'] in (state.ManagedJobStatus.STARTING,
                                     state.ManagedJobStatus.RUNNING,
                                     state.ManagedJobStatus.RECOVERING,
                                     state.ManagedJobStatus.CANCELLING):
            if state.cancel_was_requested(job_id):
                # A controller that died mid-cancel must FINISH the
                # cancel, not re-enter the launch loop (which would
                # provision a fresh slice for a cancelled job).
                self.task = self.tasks[
                    int(self.record.get('current_task') or 0)]
                self.cluster_name = (self.record.get('cluster_name') or
                                     self._stage_cluster_name(0))
                pool_name = self.record.get('pool')
                if pool_name:
                    self.strategy = recovery_strategy.PoolStrategyExecutor(
                        self.cluster_name, self.task, job_id, pool_name)
                else:
                    self.strategy = recovery_strategy.StrategyExecutor.make(
                        self.cluster_name, self.task, job_id)
                self._try_reattach()
                self._do_cancel(self.record.get('cluster_job_id'))
                return
            resume_from = int(self.record.get('current_task') or 0)
            logger.info(f'[job {job_id}] resuming mid-flight at stage '
                        f'{resume_from} ({self.record["status"].value}).')
        elif not state.set_starting(job_id, self._stage_cluster_name(0)):
            # The job reached a terminal state (e.g. cancelled while
            # PENDING) before this controller got going: nothing to do.
            logger.info(f'[job {job_id}] already terminal; controller exits.')
            return
        pool = self.record.get('pool')
        for index, task in enumerate(self.tasks):
            if resume_from is not None and index < resume_from:
                continue
            self.task = task
            if self._stage_exports:
                # Earlier stages' head IPs; a user-set env wins.
                task.update_envs({k: v
                                  for k, v in self._stage_exports.items()
                                  if k not in task.envs})
            reattach = (resume_from == index)
            if reattach and self.record.get('cluster_name'):
                # Keep the in-flight stage's cluster (pool jobs: the
                # claimed worker's name was synced into the record).
                self.cluster_name = self.record['cluster_name']
            else:
                self.cluster_name = self._stage_cluster_name(index)
                state.set_current_task(job_id, index, self.cluster_name)
            if pool:
                # Pool jobs run on a claimed worker instead of a dedicated
                # cluster; the real cluster name is known after acquire.
                self.strategy = recovery_strategy.PoolStrategyExecutor(
                    self.cluster_name, task, job_id, pool)
            else:
                self.strategy = recovery_strategy.StrategyExecutor.make(
                    self.cluster_name, task, job_id)
            if len(self.tasks) > 1:
                logger.info(f'[job {job_id}] pipeline stage '
                            f'{index + 1}/{len(self.tasks)}')
            if not self._run_one_task(reattach=reattach):
                return   # terminal status already recorded
        state.set_terminal(job_id, state.ManagedJobStatus.SUCCEEDED)

    def _try_reattach(self) -> Optional[int]:
        """Adopt the crashed controller's in-flight cluster job: restore
        the strategy's handle from the cluster record and reuse the
        recorded on-cluster job id. Returns None when there is nothing to
        re-attach to (the monitor loop's liveness check then drives a
        normal recovery)."""
        record = global_state.get_cluster(self.cluster_name)
        if record is None:
            return None
        self.strategy.handle = slice_backend.SliceResourceHandle.from_dict(
            record['handle'])
        self.strategy.cluster_name = self.cluster_name
        return self.record.get('cluster_job_id')

    def _run_one_task(self, reattach: bool = False) -> bool:
        """Drive one (stage's) task to completion on its own cluster.

        Returns True when the stage SUCCEEDED (pipeline continues); False
        when a terminal ManagedJobStatus was already recorded.
        """
        job_id = self.job_id
        cluster_job_id = self._try_reattach() if reattach else None
        if cluster_job_id is not None:
            logger.info(f'[job {job_id}] re-attached to '
                        f'{self.cluster_name!r} (cluster job '
                        f'{cluster_job_id}).')
        else:
            logger.info(f'[job {job_id}] launching as '
                        f'{self.cluster_name!r}')
            try:
                # The stage-launch span: optimizer/provision/driver
                # child spans (same process + subprocess env carrier)
                # nest under it in /v1/traces.
                from skypilot_tpu.observe import spans
                with spans.span('jobs.launch',
                                attrs={'job_id': job_id,
                                       'cluster': self.cluster_name}):
                    cluster_job_id = self.strategy.launch()
                self._sync_cluster_name()
                self._record_stage_export()
            except recovery_strategy.JobCancelledDuringRecovery:
                # Cancelled while queued for a pool worker.
                self._do_cancel(None)
                return False
            except exceptions.ResourcesUnavailableError as e:
                state.set_terminal(job_id, state.ManagedJobStatus.
                                   FAILED_NO_RESOURCE, failure_reason=str(e))
                return False
            except Exception as e:  # pylint: disable=broad-except
                state.set_terminal(job_id,
                                   state.ManagedJobStatus.FAILED_PRECHECKS,
                                   failure_reason=f'{type(e).__name__}: {e}')
                return False
        if not state.set_started(job_id, cluster_job_id):
            # Cancelled while we were provisioning: clean up and bow out.
            self.strategy.terminate_cluster()
            return False

        while True:
            time.sleep(POLL_SECONDS)

            if state.cancel_was_requested(job_id):
                self._do_cancel(cluster_job_id)
                return False

            if not self._cluster_alive():
                # Preemption (or external down). Recover: delete the dead
                # slice, relaunch with the strategy's placement policy.
                logger.info(f'[job {job_id}] cluster lost — recovering')
                state.set_recovering(job_id)
                try:
                    from skypilot_tpu.observe import spans
                    with spans.span('jobs.recover',
                                    attrs={'job_id': job_id,
                                           'why': 'cluster_lost'}):
                        cluster_job_id = self.strategy.recover()
                except exceptions.ManagedJobReachedMaxRetriesError as e:
                    state.set_terminal(
                        job_id, state.ManagedJobStatus.FAILED_NO_RESOURCE,
                        failure_reason=str(e))
                    return False
                except recovery_strategy.JobCancelledDuringRecovery:
                    self._do_cancel(cluster_job_id)
                    return False
                state.set_recovered(job_id, cluster_job_id)
                self._sync_cluster_name()
                # Recovery may land on a new slice: re-export the IP.
                self._record_stage_export()
                continue

            job_status = self._job_status(cluster_job_id)
            # Mirror logs every poll: after a preemption the slice (and its
            # logs) are gone, so the last pre-preemption copy is what
            # `jobs logs` can still serve.
            self._mirror_logs(cluster_job_id)
            if job_status is None or not job_status.is_terminal():
                continue
            if job_status is JobStatus.SUCCEEDED:
                self.strategy.terminate_cluster()
                return True
            if job_status is JobStatus.CANCELLED:
                self.strategy.terminate_cluster()
                state.set_terminal(job_id, state.ManagedJobStatus.CANCELLED)
                return False
            try:
                restarted, cluster_job_id = self._handle_user_code_failure(
                    job_status, cluster_job_id)
            except recovery_strategy.JobCancelledDuringRecovery:
                self._do_cancel(cluster_job_id)
                return False
            if restarted:
                continue
            # Real failure on a live cluster: keep the cluster for debugging
            # only if the user asked (not yet supported) — default teardown.
            self.strategy.terminate_cluster()
            failed_status = (state.ManagedJobStatus.FAILED_SETUP
                             if job_status is JobStatus.FAILED_SETUP else
                             state.ManagedJobStatus.FAILED)
            state.set_terminal(
                job_id, failed_status,
                failure_reason=f'on-cluster job status: {job_status.value}')
            return False


def main(job_id: int) -> None:
    try:
        JobsController(job_id).run()
    except Exception as e:  # pylint: disable=broad-except
        traceback.print_exc()
        try:
            state.set_terminal(job_id,
                               state.ManagedJobStatus.FAILED_CONTROLLER,
                               failure_reason=f'{type(e).__name__}: {e}')
        except Exception as db_err:  # pylint: disable=broad-except
            # The crash above is already on stderr; the DB write failing
            # too means the job will show non-terminal forever — leave
            # a trace of WHY.
            logger.warning(f'[job {job_id}] could not record '
                           f'FAILED_CONTROLLER: {db_err}')
    finally:
        # Free our scheduler slot and let the next PENDING job start.
        from skypilot_tpu.jobs import scheduler
        scheduler.maybe_schedule()


if __name__ == '__main__':
    parser = argparse.ArgumentParser()
    parser.add_argument('--job-id', type=int, required=True)
    main(parser.parse_args().job_id)
