"""Controller/run log garbage collection for managed jobs.

Reference analog: sky/jobs/log_gc.py:1-201 — an asyncio daemon with
leader-election filelock and per-kind retention config. Redesigned to
match this framework's daemonless jobs plane: collection is a cheap,
idempotent pass piggybacked on `scheduler.maybe_schedule` (the same
trick the crash watchdog uses), rate-limited by a marker file's mtime, so
logs age out as long as ANYONE looks at the queue — no long-lived
process required.

Config (skypilot config, hours; negative disables):
  jobs.controller_logs_gc_retention_hours   (default 168 = 7 days)
  jobs.task_logs_gc_retention_hours         (default 168)
Only logs of TERMINAL jobs are ever collected.
"""
from __future__ import annotations

import os
import time
from typing import List

from skypilot_tpu import config as config_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.jobs import state
from skypilot_tpu.utils import knobs

logger = sky_logging.init_logger(__name__)

DEFAULT_RETENTION_HOURS = 24 * 7
# At most one sweep per this interval (marker-file mtime).
SWEEP_INTERVAL_SECONDS = knobs.get_int('SKYTPU_JOBS_LOG_GC_INTERVAL')


def _marker_path() -> str:
    return os.path.join(os.path.dirname(state.controller_log_path(0)),
                        '.log_gc_last_sweep')


def _retention_seconds(key: str) -> float:
    hours = config_lib.get_nested(('jobs', key), DEFAULT_RETENTION_HOURS)
    return float(hours) * 3600.0


def collect(now: float = None) -> List[str]:
    """One sweep: delete logs of terminal jobs older than retention.

    Age is the log file's mtime (terminal jobs stop writing, so mtime ≈
    finish time without a schema change). Returns removed paths."""
    now = time.time() if now is None else now
    ret_ctrl = _retention_seconds('controller_logs_gc_retention_hours')
    ret_task = _retention_seconds('task_logs_gc_retention_hours')
    removed: List[str] = []
    for job in state.get_jobs(None):
        if not job['status'].is_terminal():
            continue
        jid = job['job_id']
        for path, retention in (
                (state.controller_log_path(jid), ret_ctrl),
                (state.job_log_path(jid), ret_task)):
            if retention < 0:
                continue
            try:
                if now - os.path.getmtime(path) > retention:
                    os.remove(path)
                    removed.append(path)
            except OSError:
                continue
    if removed:
        logger.info(f'Log GC removed {len(removed)} file(s) of terminal '
                    f'jobs past retention.')
    return removed


def maybe_collect() -> None:
    """Rate-limited sweep; safe to call from any inspection path."""
    marker = _marker_path()
    try:
        if time.time() - os.path.getmtime(marker) < SWEEP_INTERVAL_SECONDS:
            return
    except OSError:
        pass
    try:
        os.makedirs(os.path.dirname(marker), exist_ok=True)
        with open(marker, 'w', encoding='utf-8') as f:
            f.write(str(time.time()))
        collect()
    except Exception as e:  # pylint: disable=broad-except
        logger.debug(f'log GC sweep failed: {e}')
