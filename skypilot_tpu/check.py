"""Credential probing + enabled-cloud cache.

Reference analog: sky/check.py (`check_capability`,
`get_cached_enabled_clouds_or_refresh`).
"""
from __future__ import annotations

import json
import os
import time
from typing import List, Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

logger = sky_logging.init_logger(__name__)

_CACHE_PATH = os.path.expanduser('~/.skytpu/enabled_clouds.json')
_CACHE_TTL_SECONDS = 12 * 3600


def check(quiet: bool = False, clouds: Optional[List[str]] = None
          ) -> List[str]:
    """Probe credentials for each registered cloud; persist enabled list."""
    results: List[Tuple[str, bool, Optional[str]]] = []
    names = clouds or registry.CLOUD_REGISTRY.keys()
    for name in names:
        cloud_cls = registry.CLOUD_REGISTRY.type_from_str(name)
        try:
            ok, reason = cloud_cls.check_credentials()
        except Exception as e:  # pylint: disable=broad-except
            ok, reason = False, str(e)
        results.append((name, ok, reason))
    enabled = [name for name, ok, _ in results if ok]
    os.makedirs(os.path.dirname(_CACHE_PATH), exist_ok=True)
    with open(_CACHE_PATH, 'w', encoding='utf-8') as f:
        json.dump({'enabled': enabled, 'ts': time.time()}, f)
    if not quiet:
        for name, ok, reason in results:
            mark = '\x1b[32m✔\x1b[0m' if ok else '\x1b[31m✗\x1b[0m'
            line = f'  {mark} {name}'
            if not ok and reason:
                line += f': {reason}'
            sky_logging.print_status(line)
    return enabled


def get_cached_enabled_clouds_or_refresh(
        raise_if_no_cloud_access: bool = False) -> List[cloud_lib.Cloud]:
    enabled: Optional[List[str]] = None
    if os.path.exists(_CACHE_PATH):
        try:
            with open(_CACHE_PATH, 'r', encoding='utf-8') as f:
                payload = json.load(f)
            if time.time() - payload.get('ts', 0) < _CACHE_TTL_SECONDS:
                enabled = payload.get('enabled')
        except (json.JSONDecodeError, OSError):
            enabled = None
    if enabled is None:
        enabled = check(quiet=True)
    clouds = []
    for name in enabled:
        if name in registry.CLOUD_REGISTRY:
            c = registry.CLOUD_REGISTRY.from_str(name)
            assert c is not None
            clouds.append(c)
    if raise_if_no_cloud_access and not clouds:
        raise exceptions.NoCloudAccessError(
            'No cloud is enabled. Run `skytpu check` for details.')
    return clouds
