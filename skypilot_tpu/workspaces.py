"""Workspaces: namespace clusters/jobs/services per project or team.

Reference analog: sky/workspaces/ (812 LoC multi-tenant admin). Lean
redesign: the active workspace is a config value (`workspace:` in
~/.skytpu/config.yaml, or SKYTPU_WORKSPACE env — env wins so one shell can
switch per-command); every cluster launched is stamped with it, and
status/listings filter to the active workspace by default. 'default' is
the workspace when none is configured, so single-tenant users never see
the feature.
"""
from __future__ import annotations

from skypilot_tpu.utils import knobs

DEFAULT_WORKSPACE = 'default'


def get_active_workspace() -> str:
    env = knobs.get_str('SKYTPU_WORKSPACE')
    if env:
        return env
    from skypilot_tpu import config as config_lib
    return str(config_lib.get_nested(('workspace',), DEFAULT_WORKSPACE))


def filter_records(records, all_workspaces: bool = False,
                   workspace=None):
    """Keep records belonging to the active (or given) workspace. Records
    written before workspaces existed (workspace=None) always show."""
    if all_workspaces:
        return records
    active = workspace or get_active_workspace()
    return [r for r in records
            if r.get('workspace') is None          # pre-workspace records
            or r['workspace'] == active]
