"""skypilot_tpu: a TPU-native AI-infrastructure control plane.

Public SDK surface (reference analog: sky/__init__.py:90-120 re-exports).
"""
from skypilot_tpu.dag import Dag
from skypilot_tpu.execution import exec  # pylint: disable=redefined-builtin
from skypilot_tpu.execution import launch
from skypilot_tpu.optimizer import Optimizer, OptimizeTarget
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task
from skypilot_tpu.core import (
    autostop,
    cancel,
    cost_report,
    down,
    job_status,
    queue,
    start,
    status,
    stop,
    tail_logs,
)
# `skypilot_tpu.check` stays a module (skypilot_tpu.check.check() to probe
# credentials) — mirroring the reference, where sky.check is the module.
from skypilot_tpu import check  # noqa: F401
from skypilot_tpu.tpu import TpuSlice, parse_tpu_accelerator

__version__ = '0.1.0'

__all__ = [
    'Dag',
    'Optimizer',
    'OptimizeTarget',
    'Resources',
    'Task',
    'TpuSlice',
    'autostop',
    'cancel',
    'check',
    'cost_report',
    'down',
    'exec',
    'job_status',
    'launch',
    'parse_tpu_accelerator',
    'queue',
    'start',
    'status',
    'stop',
    'tail_logs',
]
