"""Log tailing/following for on-cluster job logs.

Reference analog: sky/skylet/log_lib.py (tailing used by `sky logs`). Invoked
remotely via `python -m skypilot_tpu.skylet.log_lib --job-id N [--follow]`,
which streams logs/<job>/run.log to stdout until the job reaches a terminal
state.
"""
from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional

from skypilot_tpu.skylet import job_lib
from skypilot_tpu.utils.status_lib import JobStatus

_POLL_SECONDS = 0.25
_WAIT_FOR_LOG_SECONDS = 30


def tail_job_logs(job_id: int, follow: bool = True,
                  out=sys.stdout,
                  tail: Optional[int] = None) -> Optional[JobStatus]:
    """Stream (or dump) one job's run.log. `tail` (non-follow only):
    emit just the last N lines — the dashboard polls this, and shipping
    a multi-GB log across the wire to keep 200 lines would be absurd."""
    log_path = os.path.join(job_lib.log_dir_for(job_id), 'run.log')
    deadline = time.time() + _WAIT_FOR_LOG_SECONDS
    while not os.path.exists(log_path):
        status = job_lib.get_status(job_id)
        if status is not None and status.is_terminal():
            break
        if not follow or time.time() > deadline:
            break
        time.sleep(_POLL_SECONDS)
    if not os.path.exists(log_path):
        print(f'[skytpu] no logs for job {job_id}.', file=out)
        return job_lib.get_status(job_id)
    if tail is not None and not follow:
        import collections
        with open(log_path, 'r', encoding='utf-8',
                  errors='replace') as f:
            for line in collections.deque(f, maxlen=tail):
                out.write(line)
        out.flush()
        return job_lib.get_status(job_id)
    with open(log_path, 'r', encoding='utf-8', errors='replace') as f:
        while True:
            line = f.readline()
            if line:
                out.write(line)
                out.flush()
                continue
            status = job_lib.get_status(job_id)
            if not follow:
                return status
            if status is None or status.is_terminal():
                # Drain whatever raced in after the status flip.
                rest = f.read()
                if rest:
                    out.write(rest)
                    out.flush()
                return status
            time.sleep(_POLL_SECONDS)


def main() -> None:
    parser = argparse.ArgumentParser(prog='log_lib')
    parser.add_argument('--job-id', type=int, required=True)
    parser.add_argument('--follow', action='store_true')
    parser.add_argument('--tail', type=int, default=None,
                        help='Emit only the last N lines (non-follow).')
    args = parser.parse_args()
    status = tail_job_logs(args.job_id, follow=args.follow,
                           tail=args.tail)
    if status is not None:
        print(f'[skytpu] job {args.job_id} finished: {status.value}',
              file=sys.stderr)
    sys.exit(0 if status in (JobStatus.SUCCEEDED, None) else 100)


if __name__ == '__main__':
    main()
