"""In-cluster exec agent: kubectl-free rank fan-out for k8s pods.

Stock pod images cannot run multi-host gangs the kubectl way (the image
must ship kubectl AND the pod's service account must grant pods/exec —
backends/slice_backend.py r2 limitation). This agent removes both
requirements: post-provision runtime setup starts `serve` on every worker
pod (plain python, shipped with the package tree), and the head-pod
slice driver reaches workers over the pod network with `client` — no
kubectl binary, no RBAC, no sshd in the image.

Protocol (newline-delimited JSON over one TCP connection):
  client → {'token': <cluster secret>, 'cmd': <bash command line>}
  server → {'out': <merged stdout/stderr line>}*   then   {'rc': <int>}

Teardown rides the socket: the rank command runs in its own process
group and the server kills the whole group the moment the connection
drops — so the driver's first-failure gang teardown (killing its local
client process) reaps the remote rank, same contract as the ssh -tt
path.

Auth: a per-cluster random token written to ~/.skytpu_runtime by runtime
setup on every pod; both sides read their local copy. The pod network is
flat, so the token (not reachability) is the auth boundary.

Reference analog: none — the reference's k8s path needs its image
(kubectl included) and pods/exec RBAC; this is the native replacement.
"""
from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import socket
import socketserver
import subprocess
import sys
import threading

from skypilot_tpu.utils import knobs

DEFAULT_PORT = 17077
TOKEN_PATH = os.path.join(
    os.path.expanduser(knobs.get_str('SKYTPU_RUNTIME_DIR')),
    'exec_agent.token')


def read_token(path: str = None) -> str:
    with open(path or TOKEN_PATH, 'r', encoding='utf-8') as f:
        return f.read().strip()


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

class _Handler(socketserver.StreamRequestHandler):

    def handle(self):  # noqa: D102
        try:
            line = self.rfile.readline()
            req = json.loads(line)
        except (json.JSONDecodeError, UnicodeDecodeError):
            self._send({'rc': 97, 'error': 'bad request'})
            return
        if req.get('token') != self.server.token:  # type: ignore[attr-defined]
            self._send({'rc': 98, 'error': 'bad token'})
            return
        cmd = req.get('cmd')
        if not isinstance(cmd, str) or not cmd:
            self._send({'rc': 97, 'error': 'missing cmd'})
            return
        proc = subprocess.Popen(['bash', '-c', cmd],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT,
                                text=True, bufsize=1,
                                start_new_session=True)

        # If the client goes away (gang teardown killed it), kill the
        # whole remote process group.
        stop = threading.Event()

        def _watch_peer():
            try:
                # recv returns b'' on orderly close; raises on reset.
                self.connection.settimeout(None)
                data = self.connection.recv(1, socket.MSG_PEEK)
                if data == b'' and proc.poll() is None:
                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass    # exited between poll() and killpg

            except OSError:
                if proc.poll() is None:
                    try:
                        os.killpg(proc.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
            finally:
                stop.set()

        watcher = threading.Thread(target=_watch_peer, daemon=True)
        watcher.start()
        try:
            for out_line in proc.stdout:
                self._send({'out': out_line.rstrip('\n')})
            rc = proc.wait()
            self._send({'rc': rc})
        except (BrokenPipeError, ConnectionResetError):
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
            proc.wait()

    def _send(self, obj) -> None:
        self.wfile.write((json.dumps(obj) + '\n').encode())
        self.wfile.flush()


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def serve(port: int, token: str, host: str = '0.0.0.0') -> None:
    srv = _Server((host, port), _Handler)
    srv.token = token  # type: ignore[attr-defined]
    srv.serve_forever()


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

def run_client(ip: str, port: int, token: str, cmd: str) -> int:
    """Submit `cmd`, stream its output to stdout, return its exit code.

    Killing this client closes the socket, which makes the server kill
    the remote process group."""
    with socket.create_connection((ip, port), timeout=30) as sock:
        # Connect bounded, reads unbounded: a training rank may be silent
        # for minutes — a lingering read timeout would kill the gang.
        sock.settimeout(None)
        sock.sendall((json.dumps({'token': token, 'cmd': cmd}) +
                      '\n').encode())
        f = sock.makefile('r', encoding='utf-8')
        for line in f:
            msg = json.loads(line)
            if 'out' in msg:
                print(msg['out'], flush=True)
            if 'rc' in msg:
                if msg.get('error'):
                    print(f'exec-agent: {msg["error"]}', file=sys.stderr)
                return int(msg['rc'])
    return 99    # connection closed without a result


def main() -> None:
    parser = argparse.ArgumentParser(prog='skytpu-exec-agent')
    sub = parser.add_subparsers(dest='mode', required=True)
    s = sub.add_parser('serve')
    s.add_argument('--port', type=int, default=DEFAULT_PORT)
    s.add_argument('--token-file', default=TOKEN_PATH)
    s.add_argument('--host', default='0.0.0.0')
    c = sub.add_parser('client')
    c.add_argument('--ip', required=True)
    c.add_argument('--port', type=int, default=DEFAULT_PORT)
    c.add_argument('--token-file', default=TOKEN_PATH)
    c.add_argument('--cmd-b64', required=True,
                   help='base64 of the bash command line to run remotely.')
    args = parser.parse_args()
    if args.mode == 'serve':
        serve(args.port, read_token(args.token_file), host=args.host)
    else:
        cmd = base64.b64decode(args.cmd_b64).decode()
        sys.exit(run_client(args.ip, args.port,
                            read_token(args.token_file), cmd))


if __name__ == '__main__':
    main()
