"""Periodic skylet events (reference analog: sky/skylet/events.py)."""
from __future__ import annotations

import os
import time
import traceback
from typing import Any, Dict

from skypilot_tpu import sky_logging
from skypilot_tpu.skylet import autostop_lib
from skypilot_tpu.skylet import job_lib

logger = sky_logging.init_logger(__name__)


class SkyletEvent:
    """Base periodic event (events.py:37-ish in the reference)."""
    EVENT_INTERVAL_SECONDS = 60

    def __init__(self) -> None:
        self._last_run = 0.0

    def maybe_run(self) -> None:
        now = time.time()
        if now - self._last_run < self.EVENT_INTERVAL_SECONDS:
            return
        self._last_run = now
        try:
            self._run()
        except Exception:  # pylint: disable=broad-except
            logger.error(f'{type(self).__name__} failed:\n'
                         f'{traceback.format_exc()}')

    def _run(self) -> None:
        raise NotImplementedError


class AutostopEvent(SkyletEvent):
    """Self-teardown when idle (reference analog: events.py:160)."""
    EVENT_INTERVAL_SECONDS = 60

    def _run(self) -> None:
        cfg = autostop_lib.get_autostop_config()
        if cfg is None or not autostop_lib.is_idle_past_threshold():
            return
        logger.info(
            f'Cluster idle past {cfg["idle_minutes"]}min; '
            f'{"terminating" if cfg.get("down") else "stopping"}.')
        self._teardown(cfg)

    def _teardown(self, cfg: Dict[str, Any]) -> None:
        from skypilot_tpu import provision
        cloud = cfg['cloud']
        region = cfg['region']
        cluster = cfg['cluster_name']
        pc = cfg.get('provider_config') or None
        if cfg.get('down'):
            provision.terminate_instances(cloud, region, cluster, pc)
        else:
            provision.stop_instances(cloud, region, cluster, pc)


class JobHeartbeatEvent(SkyletEvent):
    """Touch a heartbeat file so the control plane can detect dead agents
    (backs the failure-detection path of managed jobs)."""
    EVENT_INTERVAL_SECONDS = 30

    def _run(self) -> None:
        path = os.path.join(job_lib.runtime_dir(), 'skylet.heartbeat')
        with open(path, 'w', encoding='utf-8') as f:
            f.write(str(time.time()))
